"""PlaneExecutor seam: where sharded broadcast drain work runs.

The sharded plane (broadcast/shards.py) partitions slot state by origin
key and needs two things from the runtime: a place to run each shard's
drain closure, and a bounded handoff lane for the effects a shard
produces (outbound frames, delivered payloads, stall kicks) that must be
applied on the owner event loop. This module provides both behind a
seam small enough that the sim can substitute a synchronous executor
and keep the whole plane deterministic:

- ``InlinePlaneExecutor`` runs shard closures synchronously on the
  caller. One logical worker, no threads, no reordering — this is what
  ``SimScheduler``-driven nodes use, and why the same-seed campaign
  hash is identical at shards=1 and shards=4.
- ``ThreadPlaneExecutor`` pins one OS thread per shard (single-thread
  pool each, so shard state is confined to exactly one thread for its
  lifetime). Python-level work still serializes on the GIL; the
  scaling comes from the native quorum/parse kernels releasing it.
- ``ProcessPlaneExecutor`` breaks the GIL outright: one spawn worker
  process per shard, each owning its whole shard core
  (parallel/plane_worker.py), with a pair of fixed-slot shared-memory
  rings per shard (parallel/ring.py) as the only channel. The owner
  loop routes flat wire records in and applies flat effect records
  out; Python-level shard work (admission, quorum transitions, the
  verify term itself) runs on genuinely independent cores.
- ``SPSCQueue`` is the bounded single-producer single-consumer lane a
  THREAD shard uses to hand effects back to the owner loop: same
  address space, so records are plain object references and the GIL
  makes deque ops atomic — serializing them through a byte ring would
  only add copies. Process shards use ``ShmRing``, the cross-address-
  space twin with the same bounded/drop-accounted/latency-instrumented
  contract. Both are bounded so a stalled owner exerts backpressure
  instead of growing without limit; both feed the same
  ``plane_shard_handoff_ns`` histogram and ``effects_dropped`` export.
"""

from __future__ import annotations

import concurrent.futures
import multiprocessing
import os
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

from .ring import ShmRing


class SPSCQueue:
    """Bounded single-producer single-consumer handoff queue.

    One shard thread puts, the owner loop drains. Under CPython's GIL a
    deque's append/popleft are atomic, so no lock is needed for the
    1-producer/1-consumer discipline this class documents. ``put``
    returns False when the queue is full — the producer decides whether
    to spin, drop, or run the effect degraded; it must not block the
    shard drain loop on the owner.
    """

    __slots__ = ("_q", "_cap", "_dropped")

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError("SPSCQueue capacity must be positive")
        self._q: deque = deque()
        self._cap = capacity
        self._dropped = 0

    def put(self, item: Any) -> bool:
        if len(self._q) >= self._cap:
            self._dropped += 1
            return False
        self._q.append((time.perf_counter_ns(), item))
        return True

    def drain(self, max_items: int = 0) -> Tuple[List[Any], int]:
        """Pop up to ``max_items`` entries (0 = all currently visible).

        Returns ``(items, max_handoff_ns)`` where the second element is
        the oldest enqueue-to-drain latency seen in this drain — the
        number /metrics reports as ``plane_shard_handoff_ns``.
        """
        out: List[Any] = []
        worst = 0
        now = time.perf_counter_ns()
        n = len(self._q) if max_items <= 0 else min(max_items, len(self._q))
        for _ in range(n):
            try:
                t0, item = self._q.popleft()
            except IndexError:  # racing producer-side len() snapshot
                break
            dt = now - t0
            if dt > worst:
                worst = dt
            out.append(item)
        return out, worst

    def __len__(self) -> int:
        return len(self._q)

    @property
    def dropped(self) -> int:
        return self._dropped


class InlinePlaneExecutor:
    """Synchronous executor: shard closures run on the caller, in call
    order. This is the deterministic path — the sim drives every shard
    from one logical worker, so wire behavior is byte-identical to the
    monolithic plane."""

    name = "inline"

    def __init__(self, shards: int = 1):
        self.shards = shards

    def submit(
        self, shard_id: int, fn: Callable[..., Any], *args: Any
    ) -> "concurrent.futures.Future":
        fut: concurrent.futures.Future = concurrent.futures.Future()
        try:
            fut.set_result(fn(*args))
        except BaseException as exc:  # noqa: BLE001 - mirrored to future
            fut.set_exception(exc)
        return fut

    def shutdown(self) -> None:
        pass


class ThreadPlaneExecutor:
    """One OS thread per shard. Each shard gets its own single-thread
    pool so its slot state is only ever touched from that thread —
    confinement, not locking, is the memory model. The owner loop
    awaits the returned futures (wrapped via asyncio) and applies the
    shard's queued effects afterwards."""

    name = "thread"

    def __init__(self, shards: int):
        if shards <= 0:
            raise ValueError("ThreadPlaneExecutor needs >= 1 shard")
        self.shards = shards
        self._pools = [
            concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"plane-shard-{i}"
            )
            for i in range(shards)
        ]

    def submit(
        self, shard_id: int, fn: Callable[..., Any], *args: Any
    ) -> "concurrent.futures.Future":
        return self._pools[shard_id].submit(fn, *args)

    def shutdown(self) -> None:
        for p in self._pools:
            p.shutdown(wait=False, cancel_futures=True)


class ProcessPlaneExecutor:
    """One spawn worker PROCESS per shard — true parallelism.

    The executor owns the per-shard ring pair (actions owner->worker,
    effects worker->owner) and the worker lifecycle; the sharded plane
    owns the protocol (what goes into the rings and how effects apply).
    Spawn, not fork: the owner runs an event loop, executor threads and
    (on TPU hosts) a JAX runtime, none of which survive a fork — spawn
    children import fresh from a picklable :class:`WorkerSpec`.

    Lifecycle contract (production-shaped):

    * ``shutdown()`` sends every live worker a SHUTDOWN record, joins
      with a bounded timeout, terminates stragglers, and unlinks the
      rings — a clean exit leaves nothing in /dev/shm;
    * ``poll_crashed()`` reports workers that died UNINVITED (exitcode
      without a shutdown in flight) exactly once each, so the plane can
      flip /healthz degraded with shard attribution instead of hanging;
    * workers reap themselves if the owner dies (the getppid check in
      plane_worker.worker_main) — orphan processes never accumulate.
    """

    name = "process"

    def __init__(
        self,
        shards: int,
        *,
        ring_slots: int = 4096,
        ring_slot_bytes: int = 1024,
    ):
        if shards <= 0:
            raise ValueError("ProcessPlaneExecutor needs >= 1 shard")
        self.shards = shards
        self.ring_slots = ring_slots
        self.ring_slot_bytes = ring_slot_bytes
        self.actions: List[ShmRing] = []
        self.effects: List[ShmRing] = []
        self.obs: List[ShmRing] = []
        self._procs: list = []
        self._crashed: dict = {}  # sid -> exitcode, reported once
        self._closing = False
        self._started = False

    def start(self, make_spec: Callable[[int, str, str, str], Any]) -> None:
        """Create the rings, then spawn one worker per shard.
        ``make_spec(shard_id, actions_ring, effects_ring, obs_ring)``
        builds the picklable spec (broadcast/shards.py supplies it)."""
        if self._started:
            return
        self._started = True
        from .plane_worker import worker_main

        base = f"at2pl-{os.getpid()}-{os.urandom(3).hex()}"
        for sid in range(self.shards):
            self.actions.append(ShmRing(
                f"{base}-a{sid}", slots=self.ring_slots,
                slot_bytes=self.ring_slot_bytes, create=True,
            ))
            self.effects.append(ShmRing(
                f"{base}-e{sid}", slots=self.ring_slots,
                slot_bytes=self.ring_slot_bytes, create=True,
            ))
            # dedicated observability lane (worker -> owner): phase /
            # recorder / trace / folded-stack delta records must never
            # compete with protocol effects for ring capacity
            self.obs.append(ShmRing(
                f"{base}-o{sid}", slots=self.ring_slots,
                slot_bytes=self.ring_slot_bytes, create=True,
            ))
        ctx = multiprocessing.get_context("spawn")
        for sid in range(self.shards):
            proc = ctx.Process(
                target=worker_main,
                args=(make_spec(
                    sid, self.actions[sid].name, self.effects[sid].name,
                    self.obs[sid].name,
                ),),
                daemon=True,
                name=f"plane-shard-{sid}",
            )
            proc.start()
            self._procs.append(proc)

    def alive(self, shard_id: int) -> bool:
        return (
            shard_id < len(self._procs) and self._procs[shard_id].is_alive()
        )

    def poll_crashed(self) -> List[Tuple[int, int]]:
        """Newly-dead workers as ``(shard_id, exitcode)``, each reported
        exactly once. Empty during/after an intentional shutdown."""
        if self._closing or not self._started:
            return []
        out = []
        for sid, proc in enumerate(self._procs):
            if sid not in self._crashed and not proc.is_alive():
                code = proc.exitcode if proc.exitcode is not None else -1
                self._crashed[sid] = code
                out.append((sid, code))
        return out

    @property
    def crashed(self) -> dict:
        """All shard crashes seen so far: ``{shard_id: exitcode}``."""
        return dict(self._crashed)

    def submit(self, shard_id: int, fn, *args):
        raise RuntimeError(
            "process plane shards run in workers, not owner closures"
        )

    def stop_workers(self) -> None:
        """Send SHUTDOWN, join with a bounded timeout, terminate
        stragglers. Rings stay open so the caller can drain the final
        state flush the workers emit on the way out."""
        if self._closing:
            return
        self._closing = True
        from .plane_worker import C_SHUTDOWN

        for sid, proc in enumerate(self._procs):
            if proc.is_alive():
                self.actions[sid].put(C_SHUTDOWN, b"")
        for proc in self._procs:
            proc.join(timeout=2.0)
            if proc.is_alive():  # pragma: no cover - stuck worker
                proc.terminate()
                proc.join(timeout=1.0)

    def shutdown(self) -> None:
        self.stop_workers()
        for ring in (*self.actions, *self.effects, *self.obs):
            ring.close()
        self.actions = []
        self.effects = []
        self.obs = []


def make_plane_executor(
    kind: str,
    shards: int,
    *,
    ring_slots: int = 4096,
    ring_slot_bytes: int = 1024,
):
    """Factory behind the config seam: ``[plane] executor = ...``."""
    if kind == "inline":
        return InlinePlaneExecutor(shards)
    if kind == "thread":
        return ThreadPlaneExecutor(shards)
    if kind == "process":
        return ProcessPlaneExecutor(
            shards, ring_slots=ring_slots, ring_slot_bytes=ring_slot_bytes
        )
    raise ValueError(f"unknown plane executor {kind!r}")
