"""Fixed-slot shared-memory SPSC ring: the cross-process handoff lane.

The thread-mode sharded plane hands effects back to the owner loop
through an in-process ``SPSCQueue`` (parallel/plane.py) — Python object
references, no serialization, GIL-atomic deque ops. A process-mode
shard cannot share object references, but it doesn't need to: every
record that crosses the plane boundary is already flat bytes (wire
frames out, payload bodies in), so the handoff lane becomes a fixed-slot
ring over ``multiprocessing.shared_memory`` carrying ``(len, kind,
payload)`` records directly — no pickling per item, no per-record
allocation on the producer side beyond the payload copy into the
segment.

Layout (one segment per direction per shard)::

    header (64 bytes, 8-byte aligned fields):
      [ 0: 4)  magic   u32  0x52325441 ("AT2R")
      [ 4: 8)  slot    u32  slot size in bytes
      [ 8:16)  nslots  u64
      [16:24)  head    u64  producer-owned: total slots ever claimed
      [24:32)  tail    u64  consumer-owned: total slots ever consumed
      [32:40)  dropped u64  producer-owned: records refused at capacity
    data (nslots * slot bytes):
      records start on slot boundaries; each spans
      ceil((16 + len) / slot) CONTIGUOUS slots:
        [0: 1)  kind   u8   (application record type)
        [1: 2)  flag   u8   1 = wrap pad (no payload; consumer skips to
                            the ring start), 0 = data record
        [2: 4)  pad
        [4: 8)  len    u32  payload length in bytes
        [8:16)  t_ns   u64  producer CLOCK_MONOTONIC enqueue stamp
        [16:..) payload

Counters are MONOTONIC (they never wrap to zero; slot index = counter %
nslots), so fullness is ``head - tail`` with no ambiguous empty/full
state and no modular arithmetic races. The producer writes record bytes
first and publishes ``head`` last; the consumer reads records strictly
below ``head`` and publishes ``tail`` after copying them out. Each
counter has exactly ONE writer. On x86-64 (and AArch64 for an aligned
8-byte store) that single publish is not torn and stores are not
reordered past it under the TSO model CPython's memcpy-based
``pack_into`` compiles to; a port to a weaker memory model would need a
real fence here, which is called out rather than hidden.

``put`` never blocks and never overwrites: a record that does not fit —
including the wrap pad it may need to stay contiguous — increments
``dropped`` and returns False, preserving the producer-side drop
accounting contract of ``SPSCQueue.put``. The consumer's ``drain``
returns ``(records, max_handoff_ns)`` with the same shape the in-process
queue reports, so /metrics observes one handoff histogram regardless of
executor.

Stale segments: a node that died uncleanly leaves its rings in
``/dev/shm``. ``ShmRing(create=True)`` therefore unlinks any existing
segment of the same name before creating — an owner restart never
attaches to (or trips over) a predecessor's ring state. (Spawn workers
share the owner's resource-tracker process, so an owner crash also gets
the segments unlinked by the tracker once the tree is dead.)
"""

from __future__ import annotations

import struct
import time
from multiprocessing import shared_memory
from typing import List, Tuple

__all__ = ["ShmRing"]

_MAGIC = 0x52325441
_HDR = 64
_REC_HDR = 16
_HEAD_OFF = 16
_TAIL_OFF = 24
_DROP_OFF = 32

_u64 = struct.Struct("<Q")
_rec = struct.Struct("<BBxxIQ")


class ShmRing:
    """Bounded SPSC ring over one shared-memory segment.

    Exactly one producer process calls :meth:`put`; exactly one consumer
    process calls :meth:`drain`. The creating side owns the segment and
    unlinks it on :meth:`close`.
    """

    def __init__(
        self,
        name: str,
        *,
        slots: int = 4096,
        slot_bytes: int = 1024,
        create: bool = False,
    ) -> None:
        if create:
            if slots <= 0 or slot_bytes < _REC_HDR:
                raise ValueError("ShmRing needs slots > 0, slot >= 16")
            size = _HDR + slots * slot_bytes
            try:
                shm = shared_memory.SharedMemory(name, create=True, size=size)
            except FileExistsError:
                # stale segment from a dead predecessor: reclaim it
                stale = shared_memory.SharedMemory(name)
                stale.close()
                stale.unlink()
                shm = shared_memory.SharedMemory(name, create=True, size=size)
            buf = shm.buf
            struct.pack_into("<IIQ", buf, 0, _MAGIC, slot_bytes, slots)
            _u64.pack_into(buf, _HEAD_OFF, 0)
            _u64.pack_into(buf, _TAIL_OFF, 0)
            _u64.pack_into(buf, _DROP_OFF, 0)
        else:
            # NOTE on bpo-38119: attaching registers the segment with the
            # resource tracker a second time. That is harmless HERE —
            # spawn workers inherit the owner's tracker process (the
            # tracker fd rides in the spawn preparation data), and the
            # tracker's cache is a set, so attach-side registration is a
            # no-op add and the owner's unlink removes the one entry.
            # Unregistering on attach (the usual bpo-38119 workaround)
            # would be WRONG with a shared tracker: it strips the owner's
            # registration, making every clean unlink a tracker KeyError
            # and losing crash cleanup entirely.
            shm = shared_memory.SharedMemory(name)
            buf = shm.buf
            magic, slot_bytes, slots = struct.unpack_from("<IIQ", buf, 0)
            if magic != _MAGIC:
                shm.close()
                raise ValueError(f"segment {name!r} is not an AT2 ring")
        self._shm = shm
        self._buf = shm.buf
        self._slot = int(slot_bytes)
        self._nslots = int(slots)
        self._owner = create
        self._closed = False
        self.name = name

    # -- counters ---------------------------------------------------------

    @property
    def head(self) -> int:
        return _u64.unpack_from(self._buf, _HEAD_OFF)[0]

    @property
    def tail(self) -> int:
        return _u64.unpack_from(self._buf, _TAIL_OFF)[0]

    @property
    def dropped(self) -> int:
        """Records refused at capacity (producer-side accounting)."""
        return _u64.unpack_from(self._buf, _DROP_OFF)[0]

    def __len__(self) -> int:
        """Occupied SLOTS (allocation units, not records)."""
        return max(0, self.head - self.tail)

    # -- producer ---------------------------------------------------------

    def put(self, kind: int, payload) -> bool:
        """Append one record; False (and ``dropped`` += 1) when it does
        not fit. Producer-side only."""
        buf = self._buf
        ln = len(payload)
        need = (_REC_HDR + ln + self._slot - 1) // self._slot
        head = _u64.unpack_from(buf, _HEAD_OFF)[0]
        tail = _u64.unpack_from(buf, _TAIL_OFF)[0]
        free = self._nslots - (head - tail)
        idx = head % self._nslots
        till_end = self._nslots - idx
        pad = 0
        if need > till_end:
            # keep records contiguous: pad out the ring tail, restart at 0
            pad = till_end
            idx = 0
        if need + pad > free or need > self._nslots:
            drops = _u64.unpack_from(buf, _DROP_OFF)[0]
            _u64.pack_into(buf, _DROP_OFF, drops + 1)
            return False
        if pad:
            _rec.pack_into(buf, _HDR + (head % self._nslots) * self._slot,
                           0, 1, 0, 0)
        off = _HDR + idx * self._slot
        _rec.pack_into(buf, off, kind, 0, ln, time.monotonic_ns())
        if ln:
            buf[off + _REC_HDR : off + _REC_HDR + ln] = payload
        # publish LAST: one aligned 8-byte store makes the record(s)
        # visible; the consumer never reads past head
        _u64.pack_into(buf, _HEAD_OFF, head + pad + need)
        return True

    # -- consumer ---------------------------------------------------------

    def drain(
        self, max_records: int = 0
    ) -> Tuple[List[Tuple[int, bytes]], int]:
        """Pop up to ``max_records`` records (0 = all currently visible).

        Returns ``(records, max_handoff_ns)`` where records are
        ``(kind, payload)`` and the latency is the oldest
        enqueue-to-drain gap seen — the ``plane_shard_handoff_ns``
        number, same contract as ``SPSCQueue.drain``. Consumer-side
        only."""
        buf = self._buf
        out: List[Tuple[int, bytes]] = []
        worst = 0
        now = time.monotonic_ns()
        head = _u64.unpack_from(buf, _HEAD_OFF)[0]
        tail = _u64.unpack_from(buf, _TAIL_OFF)[0]
        while tail < head:
            if max_records and len(out) >= max_records:
                break
            idx = tail % self._nslots
            off = _HDR + idx * self._slot
            kind, flag, ln, t_ns = _rec.unpack_from(buf, off)
            if flag:  # wrap pad: nothing to read before the ring start
                tail += self._nslots - idx
                continue
            payload = bytes(buf[off + _REC_HDR : off + _REC_HDR + ln])
            dt = now - t_ns
            if dt > worst:
                worst = dt
            out.append((kind, payload))
            tail += (_REC_HDR + ln + self._slot - 1) // self._slot
        _u64.pack_into(buf, _TAIL_OFF, tail)
        return out, worst

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._buf = None
        try:
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except Exception:  # pragma: no cover - teardown best-effort
            pass

    def __del__(self):  # pragma: no cover - GC-order dependent
        try:
            self.close()
        except Exception:
            pass
