"""Process-mode plane shard: the spawn target and its wire records.

One worker process owns one shard's ENTIRE :class:`Broadcast` core —
slots, dedup sets, quorum bitmaps, entry registry, watermarks. The
memory model is confinement taken one level past the thread executor:
where a shard thread shares the owner's address space and merely
promises not to touch cross-shard state, a shard process CANNOT — the
only channel in or out is a pair of shared-memory rings
(parallel/ring.py):

* ``actions`` (owner -> worker): routed messages as flat
  ``peer_sign(32) + wire`` records plus control records (GC ticks,
  threshold updates, watermark restores, shutdown);
* ``effects`` (worker -> owner): outbound frames, delivered payload
  bodies, stall kicks, and periodic state diffs (stats counter deltas,
  attestation watermarks, gauge snapshots) the owner folds into its
  shared observability surfaces.

Everything that crosses is bytes that were already bytes on the wire —
no pickling. Verification happens IN the worker (native bulk ed25519
when the ingest library is available, per-item OpenSSL otherwise), so
shard processes genuinely overlap the dominant verify term on separate
cores with no GIL in common.

The worker is production-shaped about dying: it exits when told
(SHUTDOWN record), and it exits when ORPHANED — every loop iteration
checks ``os.getppid()`` against the owner pid captured at spawn, so an
owner that crashes without cleanup reaps its workers within one poll
interval instead of leaking them.

This module's import graph is deliberately light (stdlib only at module
level); the broadcast/crypto imports happen inside :func:`worker_main`
so the spawn child pays them, not every importer of the parallel
package.
"""

from __future__ import annotations

import json
import os
import struct
import time
from dataclasses import dataclass
from typing import Tuple

__all__ = ["WorkerSpec", "worker_main", "STAT_KEYS", "TRACE_STAGES"]

# owner -> worker control/message record kinds (ShmRing `kind` byte)
C_MSG = 1  # peer_sign(32) + one-message wire frame
C_GC = 2  # f64 monotonic now
C_SHUTDOWN = 3  # clean exit after flushing state
C_THRESH = 4  # u32 echo_threshold, u32 ready_threshold
C_WM_RESTORE = 5  # JSON watermark doc (floors fan-in)
C_RELEASE = 6  # sender(32) + u64 sequence (entry-registry release)
C_EXIT = 7  # u8 exit code: simulate a worker crash (tests only)
C_PROF = 8  # u8 start(1)/stop(0) + f64 duration (<=0 = until stopped)

# worker -> owner effect record kinds
E_SEND = 16  # peer_sign(32) + frame
E_BCAST = 17  # frame
E_DELIVER = 18  # payload body(140) + content hash(32)
E_STALL = 19  # empty
E_STATS = 20  # len(STAT_KEYS) * u64 counter deltas, STAT_KEYS order
E_WM = 21  # u8 plane (0=tx 1=batch) + key(32) + u64 sequence
E_INFO = 22  # u32 undelivered + u64 floor_refusals

# worker -> owner OBSERVABILITY record kinds. These ride a dedicated
# per-shard obs ring, never the effects ring: a firehose of phase deltas
# must not evict protocol frames, and an obs drop is a separate budget
# (`obs_records_dropped`) from `plane_shard_effects_dropped`.
O_PHASE = 32  # repeated per-changed-phase delta records (see _ophase)
O_REC = 33  # JSON [[t, code, [detail...]], ...] recorder event increments
O_TRACE = 34  # repeated sender(32) + u64 seq + u8 stage idx + f64 mono
O_FOLD = 35  # u64 sample-tick delta + folded-stack text increments

# The TxTrace stages a Broadcast core stamps, in wire order for O_TRACE
# records. Owner replays each through its real tracer; drift here would
# misattribute every worker-side lifecycle stamp.
TRACE_STAGES: Tuple[str, ...] = (
    "echoed",
    "ready_quorum",
    "delivered",
    "echo_quorum",
    "ready_sent",
)
_TRACE_IDX = {s: i for i, s in enumerate(TRACE_STAGES)}

# The shared plane counter names, in wire order for E_STATS records.
# MUST match the counter_group tuples in broadcast/stack.py and
# broadcast/shards.py (pinned by tests/test_plane_shards.py).
STAT_KEYS: Tuple[str, ...] = (
    "gossip_rx",
    "echo_rx",
    "ready_rx",
    "invalid_sig",
    "delivered",
    "slots_dropped",
    "content_req_tx",
    "content_req_rx",
    "content_served",
    "batch_rx",
    "batch_echo_rx",
    "batch_ready_rx",
    "batch_entries_delivered",
    "retransmits",
    "poison_resolved",
    "slots_retired",
    "stall_kicks_suppressed",
)

_LOCAL_SENTINEL = bytes(32)  # peer_sign of a locally-submitted message

_u64 = struct.Struct("<Q")
_info = struct.Struct("<IQ")
_prof = struct.Struct("<Bd")
# O_PHASE per-phase head: phase idx (PHASES order), ns delta, histogram
# count delta, histogram sum delta (seconds), ABSOLUTE histogram max
# (merged with max() on the owner). Bucket deltas follow as
# len(PHASE_BOUNDS)+1 little-endian u32s.
_ophase = struct.Struct("<BQQdd")
_otrace = struct.Struct("<32sQBd")


@dataclass(frozen=True)
class WorkerSpec:
    """Everything a spawn child needs; plain picklable data only."""

    shard_id: int
    shards: int
    sign_seed: bytes
    echo_threshold: int
    ready_threshold: int
    overlap_ready: bool
    # ((address, exchange_public, sign_public, region), ...)
    peers: Tuple[Tuple[str, bytes, bytes, str], ...]
    actions_ring: str
    effects_ring: str
    ring_slots: int
    ring_slot_bytes: int
    parent_pid: int
    # observability slice (all defaulted: pre-obs constructions and
    # pickles keep working). Empty obs_ring = no shipping lane at all.
    obs_ring: str = ""
    recorder_cap: int = 0
    trace_sample: int = 0
    phase_accounting: bool = False
    profiler_hz: float = 97.0
    profiler_max_nodes: int = 20000
    obs_flush_s: float = 0.05


class _ProcMesh:
    """Mesh facade inside the worker: reads serve the core's peer/quorum
    bookkeeping from the spec's peer table; sends become effect records
    (the real transports live in the owner process)."""

    __slots__ = ("peers", "by_sign", "_effects")

    def __init__(self, peers, effects) -> None:
        self.peers = peers
        self.by_sign = {p.sign_public: p for p in peers}
        self._effects = effects

    def send(self, peer, data: bytes) -> None:
        self._effects.put(E_SEND, peer.sign_public + bytes(data))

    def broadcast(self, data: bytes) -> None:
        self._effects.put(E_BCAST, bytes(data))


class _ProcDelivered:
    """Delivered-queue facade: payload body + content hash cross as one
    record; the owner rebuilds the Payload (hash pre-seeded, nothing
    re-hashes) and feeds the real asyncio queue the commit tail reads."""

    __slots__ = ("_effects",)

    def __init__(self, effects) -> None:
        self._effects = effects

    def put_nowait(self, payload) -> None:
        self._effects.put(
            E_DELIVER, payload.encode()[1:] + payload.content_hash()
        )


class _WorkerTrace:
    """TxTrace facade inside the worker: buffers ``(key, stage, t)``
    stamps for the obs lane instead of mutating a tracer — the real
    TxTrace lives in the owner, which replays these with the worker's
    CLOCK_MONOTONIC timestamp preserved (machine-wide, so spans stay
    aligned). Applies the same KEYED relay lottery obs/trace.py uses for
    relay-side opens, so a sampled fleet ships only stamps the owner
    could accept; at ``sample_every=1`` (the default) everything ships.
    Records that were origin-sampled by the owner's SEQUENTIAL lottery
    but lose the keyed one miss their worker-interior stamps — the
    documented cost of sampling under process mode."""

    __slots__ = ("_sample", "buf")

    _CAP = 8192  # stamps buffered between flushes; beyond this we shed

    def __init__(self, sample_every: int) -> None:
        self._sample = max(1, int(sample_every))
        self.buf: list = []

    def stamp(self, key, stage: str, now=None) -> None:
        idx = _TRACE_IDX.get(stage)
        if idx is None:
            return
        if self._sample > 1 and (key[0][0] + key[1]) % self._sample:
            return
        if len(self.buf) >= self._CAP:
            return
        self.buf.append(
            (key[0], key[1], idx, time.monotonic() if now is None else now)
        )


class _WorkerObs:
    """The worker process's private slice of the diagnosis tier, plus
    the shipping lane that folds it back into the owner's.

    Each shard process runs its OWN registry + PhaseAccounting (so every
    interior ``phases``/``recorder``/``trace`` mark site in
    broadcast/stack.py lights up unchanged inside the worker), its own
    FlightRecorder ring, and an opt-in StackSampler driven by C_PROF
    records from the owner. Every ``obs_flush_s`` (~50ms) the worker
    ships compact DELTA records over the dedicated obs ring:

    * O_PHASE — per-phase ns + histogram bucket/sum/count deltas (max is
      absolute, merged with max() on the owner), only for phases that
      changed;
    * O_REC — recorder events newer than the last ship, as the same
      formatted JSON the /debugz dump uses;
    * O_TRACE — buffered TxTrace stage stamps with their mono timestamp;
    * O_FOLD — folded-stack increments (the sampler tree is reset after
      each ship, so records are additive).

    ``put`` never blocks: a full obs ring sheds the record and the drop
    lands in the ring's producer-side counter, which the owner exports
    as ``obs_records_dropped``. Observability loss is survivable and
    accounted; it never backpressures the protocol.
    """

    def __init__(self, spec: "WorkerSpec", ring) -> None:
        from ..obs.profiler import (
            PHASE_BOUNDS,
            PHASES,
            PhaseAccounting,
            StackSampler,
        )
        from ..obs.recorder import FlightRecorder
        from ..obs.registry import Registry

        self._ring = ring
        self._phase_names = PHASES
        self._nb = len(PHASE_BOUNDS) + 1
        self._buckets = struct.Struct(f"<{self._nb}I")
        self.registry = Registry()
        self.phases = (
            PhaseAccounting(self.registry) if spec.phase_accounting else None
        )
        self.recorder = (
            FlightRecorder(cap=spec.recorder_cap)
            if spec.recorder_cap
            else None
        )
        self.trace = (
            _WorkerTrace(spec.trace_sample) if spec.trace_sample else None
        )
        self.sampler = StackSampler(
            hz=spec.profiler_hz, max_nodes=spec.profiler_max_nodes
        )
        self._last_phase: dict = {}
        self._rec_seen = 0
        self._flush_s = max(0.005, spec.obs_flush_s)
        self._next_flush = time.monotonic() + self._flush_s

    def handle_prof(self, payload: bytes) -> None:
        start, duration = _prof.unpack(payload)
        if start:
            self.sampler.reset()
            self.sampler.start(duration if duration > 0 else None)
        else:
            self.sampler.stop()
            self._ship_fold()

    def maybe_flush(self) -> None:
        now = time.monotonic()
        if now < self._next_flush:
            return
        self._next_flush = now + self._flush_s
        self.flush()

    def flush(self) -> None:
        self._ship_phases()
        self._ship_recorder()
        self._ship_trace()
        self._ship_fold()

    def _ship_phases(self) -> None:
        ph = self.phases
        if ph is None:
            return
        parts = []
        for idx, name in enumerate(self._phase_names):
            ns = ph._counters[name].value
            counts, total, count, mx = ph._hists[name].raw()
            last = self._last_phase.get(name)
            if last is None:
                last = (0, [0] * self._nb, 0.0, 0, 0.0)
            lns, lcounts, lsum, lcount, lmax = last
            if ns == lns and count == lcount and mx == lmax:
                continue
            deltas = [a - b for a, b in zip(counts, lcounts)]
            parts.append(
                _ophase.pack(idx, ns - lns, count - lcount, total - lsum, mx)
                + self._buckets.pack(*deltas)
            )
            self._last_phase[name] = (ns, counts, total, count, mx)
        if parts:
            self._ring.put(O_PHASE, b"".join(parts))

    def _ship_recorder(self) -> None:
        rec = self.recorder
        if rec is None:
            return
        events, self._rec_seen = rec.events_since(self._rec_seen)
        if events:
            self._ring.put(O_REC, json.dumps(events).encode())

    def _ship_trace(self) -> None:
        tr = self.trace
        if tr is None or not tr.buf:
            return
        buf, tr.buf = tr.buf, []
        # chunked so one full ring sheds hundreds of stamps, not all 8k
        for i in range(0, len(buf), 512):
            self._ring.put(
                O_TRACE,
                b"".join(_otrace.pack(*stamp) for stamp in buf[i : i + 512]),
            )

    def _ship_fold(self) -> None:
        samples = self.sampler.stats()["samples"]
        if not samples:
            return
        folded = self.sampler.folded()
        self.sampler.reset()
        self._ring.put(O_FOLD, _u64.pack(samples) + folded.encode())


def _flush_state(core, effects, last) -> None:
    """Ship observable-state DIFFS to the owner: counter deltas (the
    owner's group is the plane-wide aggregate), watermark bumps (merged
    with max on the owner; monotone either way), and the gauge pair."""
    vals = [int(core.stats[k]) for k in STAT_KEYS]
    if vals != last["stats"]:
        deltas = [v - o for v, o in zip(vals, last["stats"])]
        effects.put(E_STATS, b"".join(_u64.pack(max(0, d)) for d in deltas))
        last["stats"] = vals
    for tag, wm, seen in (
        (0, core._wm_tx, last["wm_tx"]),
        (1, core._wm_batch, last["wm_batch"]),
    ):
        for key, seq in wm.items():
            if seen.get(key) != seq:
                effects.put(E_WM, bytes([tag]) + key + _u64.pack(seq))
                seen[key] = seq
    info = (core._undelivered, core.floor_refusals)
    if info != last["info"]:
        effects.put(
            E_INFO, _info.pack(max(0, core._undelivered), core.floor_refusals)
        )
        last["info"] = info


def worker_main(spec: WorkerSpec) -> None:
    """Spawn entry point: build this shard's core, then drain the
    actions ring forever (parse -> admission pre-checks -> bulk verify
    -> state transitions -> effect records), exactly the three-stage
    pipeline the owner loop runs, minus everything cross-shard."""
    from ..broadcast.messages import WireError, parse_frame
    from ..broadcast.stack import Broadcast
    from ..crypto.keys import SignKeyPair, verify_one
    from ..native import ingest_available, verify_bulk_native
    from ..net.peers import Peer
    from .ring import ShmRing

    actions_ring = ShmRing(spec.actions_ring)
    effects = ShmRing(spec.effects_ring)
    obs = None
    obs_ring = None
    if spec.obs_ring:
        obs_ring = ShmRing(spec.obs_ring)
        obs = _WorkerObs(spec, obs_ring)
    peers = [
        Peer(address=a, exchange_public=x, sign_public=s, region=r)
        for a, x, s, r in spec.peers
    ]
    mesh = _ProcMesh(peers, effects)
    core = Broadcast(
        SignKeyPair(spec.sign_seed),
        mesh,
        None,  # verifier unused: this loop verifies, not _process_chunk
        echo_threshold=spec.echo_threshold,
        ready_threshold=spec.ready_threshold,
        workers=0,
        overlap_ready=spec.overlap_ready,
        registry=obs.registry if obs is not None else None,
        trace=obs.trace if obs is not None else None,
        recorder=obs.recorder if obs is not None else None,
        phases=obs.phases if obs is not None else None,
    )
    core.delivered = _ProcDelivered(effects)
    core.stall_handler = lambda: effects.put(E_STALL, b"")
    # .so already compiled by the owner's start(); this is a cached load
    native = ingest_available()

    last = {
        "stats": [0] * len(STAT_KEYS),
        "wm_tx": {},
        "wm_batch": {},
        "info": (0, 0),
    }
    idle = 0.0002
    stop = False
    ph = obs.phases if obs is not None else None
    while not stop:
        if os.getppid() != spec.parent_pid:
            break  # orphaned: the owner died without a clean shutdown
        recs, _ = actions_ring.drain()
        if not recs:
            if obs is not None:
                obs.maybe_flush()
            time.sleep(idle)
            idle = min(idle * 2.0, 0.002)
            continue
        idle = 0.0002
        # plane_total in a worker wraps the whole drain cycle (parse +
        # verify + apply + state flush) — the worker-side twin of the
        # owner-loop span, shipped as phase_plane_total_shardN_ns
        t_plane = ph.begin_plane() if ph is not None else -1
        t0 = ph.t() if ph is not None else 0
        to_verify: list = []
        acts: list = []
        for kind, payload in recs:
            if kind == C_MSG:
                peer = mesh.by_sign.get(payload[:32])
                try:
                    msgs = parse_frame(payload[32:])
                except WireError:
                    continue  # owner routed it, so it parsed there; defensive
                for msg in msgs:
                    core._pre_msg(peer, msg, to_verify, acts)
            elif kind == C_GC:
                core._gc_pass(struct.unpack("<d", payload)[0])
                if ph is not None:
                    t0 = ph.t()  # keep the GC sweep out of rx_decode
            elif kind == C_THRESH:
                core.echo_threshold, core.ready_threshold = struct.unpack(
                    "<II", payload
                )
            elif kind == C_WM_RESTORE:
                core.restore_watermarks(json.loads(payload.decode()))
            elif kind == C_RELEASE:
                core.release_entry(payload[:32], _u64.unpack(payload[32:])[0])
            elif kind == C_PROF:
                if obs is not None:
                    obs.handle_prof(payload)
            elif kind == C_EXIT:  # tests: simulate a crash mid-campaign
                os._exit(payload[0] if payload else 42)
            elif kind == C_SHUTDOWN:
                stop = True
        if ph is not None:
            t0 = ph.add("rx_decode", t0)
        if to_verify:
            if native:
                results = verify_bulk_native(to_verify, 1)
            else:
                results = [verify_one(pk, m, s) for pk, m, s in to_verify]
            if ph is not None:
                t0 = ph.add("verify_wait", t0)
            core._apply_actions(acts, results)
        _flush_state(core, effects, last)
        if ph is not None:
            ph.end_plane(t_plane)
        if obs is not None:
            obs.maybe_flush()
    _flush_state(core, effects, last)
    if obs is not None:
        obs.sampler.stop()
        obs.flush()
    actions_ring.close()
    effects.close()
    if obs_ring is not None:
        obs_ring.close()
