"""Process-mode plane shard: the spawn target and its wire records.

One worker process owns one shard's ENTIRE :class:`Broadcast` core —
slots, dedup sets, quorum bitmaps, entry registry, watermarks. The
memory model is confinement taken one level past the thread executor:
where a shard thread shares the owner's address space and merely
promises not to touch cross-shard state, a shard process CANNOT — the
only channel in or out is a pair of shared-memory rings
(parallel/ring.py):

* ``actions`` (owner -> worker): routed messages as flat
  ``peer_sign(32) + wire`` records plus control records (GC ticks,
  threshold updates, watermark restores, shutdown);
* ``effects`` (worker -> owner): outbound frames, delivered payload
  bodies, stall kicks, and periodic state diffs (stats counter deltas,
  attestation watermarks, gauge snapshots) the owner folds into its
  shared observability surfaces.

Everything that crosses is bytes that were already bytes on the wire —
no pickling. Verification happens IN the worker (native bulk ed25519
when the ingest library is available, per-item OpenSSL otherwise), so
shard processes genuinely overlap the dominant verify term on separate
cores with no GIL in common.

The worker is production-shaped about dying: it exits when told
(SHUTDOWN record), and it exits when ORPHANED — every loop iteration
checks ``os.getppid()`` against the owner pid captured at spawn, so an
owner that crashes without cleanup reaps its workers within one poll
interval instead of leaking them.

This module's import graph is deliberately light (stdlib only at module
level); the broadcast/crypto imports happen inside :func:`worker_main`
so the spawn child pays them, not every importer of the parallel
package.
"""

from __future__ import annotations

import json
import os
import struct
import time
from dataclasses import dataclass
from typing import Tuple

__all__ = ["WorkerSpec", "worker_main", "STAT_KEYS"]

# owner -> worker control/message record kinds (ShmRing `kind` byte)
C_MSG = 1  # peer_sign(32) + one-message wire frame
C_GC = 2  # f64 monotonic now
C_SHUTDOWN = 3  # clean exit after flushing state
C_THRESH = 4  # u32 echo_threshold, u32 ready_threshold
C_WM_RESTORE = 5  # JSON watermark doc (floors fan-in)
C_RELEASE = 6  # sender(32) + u64 sequence (entry-registry release)
C_EXIT = 7  # u8 exit code: simulate a worker crash (tests only)

# worker -> owner effect record kinds
E_SEND = 16  # peer_sign(32) + frame
E_BCAST = 17  # frame
E_DELIVER = 18  # payload body(140) + content hash(32)
E_STALL = 19  # empty
E_STATS = 20  # len(STAT_KEYS) * u64 counter deltas, STAT_KEYS order
E_WM = 21  # u8 plane (0=tx 1=batch) + key(32) + u64 sequence
E_INFO = 22  # u32 undelivered + u64 floor_refusals

# The shared plane counter names, in wire order for E_STATS records.
# MUST match the counter_group tuples in broadcast/stack.py and
# broadcast/shards.py (pinned by tests/test_plane_shards.py).
STAT_KEYS: Tuple[str, ...] = (
    "gossip_rx",
    "echo_rx",
    "ready_rx",
    "invalid_sig",
    "delivered",
    "slots_dropped",
    "content_req_tx",
    "content_req_rx",
    "content_served",
    "batch_rx",
    "batch_echo_rx",
    "batch_ready_rx",
    "batch_entries_delivered",
    "retransmits",
    "poison_resolved",
    "slots_retired",
    "stall_kicks_suppressed",
)

_LOCAL_SENTINEL = bytes(32)  # peer_sign of a locally-submitted message

_u64 = struct.Struct("<Q")
_info = struct.Struct("<IQ")


@dataclass(frozen=True)
class WorkerSpec:
    """Everything a spawn child needs; plain picklable data only."""

    shard_id: int
    shards: int
    sign_seed: bytes
    echo_threshold: int
    ready_threshold: int
    overlap_ready: bool
    # ((address, exchange_public, sign_public, region), ...)
    peers: Tuple[Tuple[str, bytes, bytes, str], ...]
    actions_ring: str
    effects_ring: str
    ring_slots: int
    ring_slot_bytes: int
    parent_pid: int


class _ProcMesh:
    """Mesh facade inside the worker: reads serve the core's peer/quorum
    bookkeeping from the spec's peer table; sends become effect records
    (the real transports live in the owner process)."""

    __slots__ = ("peers", "by_sign", "_effects")

    def __init__(self, peers, effects) -> None:
        self.peers = peers
        self.by_sign = {p.sign_public: p for p in peers}
        self._effects = effects

    def send(self, peer, data: bytes) -> None:
        self._effects.put(E_SEND, peer.sign_public + bytes(data))

    def broadcast(self, data: bytes) -> None:
        self._effects.put(E_BCAST, bytes(data))


class _ProcDelivered:
    """Delivered-queue facade: payload body + content hash cross as one
    record; the owner rebuilds the Payload (hash pre-seeded, nothing
    re-hashes) and feeds the real asyncio queue the commit tail reads."""

    __slots__ = ("_effects",)

    def __init__(self, effects) -> None:
        self._effects = effects

    def put_nowait(self, payload) -> None:
        self._effects.put(
            E_DELIVER, payload.encode()[1:] + payload.content_hash()
        )


def _flush_state(core, effects, last) -> None:
    """Ship observable-state DIFFS to the owner: counter deltas (the
    owner's group is the plane-wide aggregate), watermark bumps (merged
    with max on the owner; monotone either way), and the gauge pair."""
    vals = [int(core.stats[k]) for k in STAT_KEYS]
    if vals != last["stats"]:
        deltas = [v - o for v, o in zip(vals, last["stats"])]
        effects.put(E_STATS, b"".join(_u64.pack(max(0, d)) for d in deltas))
        last["stats"] = vals
    for tag, wm, seen in (
        (0, core._wm_tx, last["wm_tx"]),
        (1, core._wm_batch, last["wm_batch"]),
    ):
        for key, seq in wm.items():
            if seen.get(key) != seq:
                effects.put(E_WM, bytes([tag]) + key + _u64.pack(seq))
                seen[key] = seq
    info = (core._undelivered, core.floor_refusals)
    if info != last["info"]:
        effects.put(
            E_INFO, _info.pack(max(0, core._undelivered), core.floor_refusals)
        )
        last["info"] = info


def worker_main(spec: WorkerSpec) -> None:
    """Spawn entry point: build this shard's core, then drain the
    actions ring forever (parse -> admission pre-checks -> bulk verify
    -> state transitions -> effect records), exactly the three-stage
    pipeline the owner loop runs, minus everything cross-shard."""
    from ..broadcast.messages import WireError, parse_frame
    from ..broadcast.stack import Broadcast
    from ..crypto.keys import SignKeyPair, verify_one
    from ..native import ingest_available, verify_bulk_native
    from ..net.peers import Peer
    from .ring import ShmRing

    actions_ring = ShmRing(spec.actions_ring)
    effects = ShmRing(spec.effects_ring)
    peers = [
        Peer(address=a, exchange_public=x, sign_public=s, region=r)
        for a, x, s, r in spec.peers
    ]
    mesh = _ProcMesh(peers, effects)
    core = Broadcast(
        SignKeyPair(spec.sign_seed),
        mesh,
        None,  # verifier unused: this loop verifies, not _process_chunk
        echo_threshold=spec.echo_threshold,
        ready_threshold=spec.ready_threshold,
        workers=0,
        overlap_ready=spec.overlap_ready,
    )
    core.delivered = _ProcDelivered(effects)
    core.stall_handler = lambda: effects.put(E_STALL, b"")
    # .so already compiled by the owner's start(); this is a cached load
    native = ingest_available()

    last = {
        "stats": [0] * len(STAT_KEYS),
        "wm_tx": {},
        "wm_batch": {},
        "info": (0, 0),
    }
    idle = 0.0002
    stop = False
    while not stop:
        if os.getppid() != spec.parent_pid:
            break  # orphaned: the owner died without a clean shutdown
        recs, _ = actions_ring.drain()
        if not recs:
            time.sleep(idle)
            idle = min(idle * 2.0, 0.002)
            continue
        idle = 0.0002
        to_verify: list = []
        acts: list = []
        for kind, payload in recs:
            if kind == C_MSG:
                peer = mesh.by_sign.get(payload[:32])
                try:
                    msgs = parse_frame(payload[32:])
                except WireError:
                    continue  # owner routed it, so it parsed there; defensive
                for msg in msgs:
                    core._pre_msg(peer, msg, to_verify, acts)
            elif kind == C_GC:
                core._gc_pass(struct.unpack("<d", payload)[0])
            elif kind == C_THRESH:
                core.echo_threshold, core.ready_threshold = struct.unpack(
                    "<II", payload
                )
            elif kind == C_WM_RESTORE:
                core.restore_watermarks(json.loads(payload.decode()))
            elif kind == C_RELEASE:
                core.release_entry(payload[:32], _u64.unpack(payload[32:])[0])
            elif kind == C_EXIT:  # tests: simulate a crash mid-campaign
                os._exit(payload[0] if payload else 42)
            elif kind == C_SHUTDOWN:
                stop = True
        if to_verify:
            if native:
                results = verify_bulk_native(to_verify, 1)
            else:
                results = [verify_one(pk, m, s) for pk, m, s in to_verify]
            core._apply_actions(acts, results)
        _flush_state(core, effects, last)
    _flush_state(core, effects, last)
    actions_ring.close()
    effects.close()
