"""Multi-chip parallelism: the sharded verifier pool (see pool.py)."""

from .pool import PoolVerifier, make_mesh, pool_bucket_for, verify_batch_sharded

__all__ = ["PoolVerifier", "make_mesh", "pool_bucket_for", "verify_batch_sharded"]
