"""Multi-chip parallelism: the sharded verifier pool (see pool.py) and
the multi-host runtime seam (multihost.py).

Lazy exports (PEP 562): importing this package must NOT pull in jax —
CPU-verifier node processes never touch it, and a jax import costs tens
of seconds of startup across a small host's servers.
"""

__all__ = [
    "PoolVerifier",
    "make_mesh",
    "pool_bucket_for",
    "verify_batch_sharded",
    "InlinePlaneExecutor",
    "SPSCQueue",
    "ThreadPlaneExecutor",
    "make_plane_executor",
]

_PLANE = {
    "InlinePlaneExecutor",
    "SPSCQueue",
    "ThreadPlaneExecutor",
    "make_plane_executor",
}


def __getattr__(name):
    if name in _PLANE:
        from . import plane

        return getattr(plane, name)
    if name in __all__:
        from . import pool

        return getattr(pool, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
