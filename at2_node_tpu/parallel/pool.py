"""Multi-chip sharded verifier pool: one logical batch, N chips.

TPU-native scale-out for the verification hot path (SURVEY.md §2.3 P5 and
§7 step 8; BASELINE.json config 5 — "v5e-8 sharded verifier pool"). The
reference scales verification only by adding CPU worker threads
(`/root/reference/src/bin/server/rpc.rs:125`); here one large signature
batch is sharded over a `jax.sharding.Mesh` along the batch dimension and
verified by a single pjit-compiled program. XLA partitions the
embarrassingly-parallel curve math with zero communication, and inserts
the one genuine collective this workload has — an AllReduce over ICI when
the per-lane validity bitmap is summed into a replicated scalar.

There is deliberately no tensor/pipeline/sequence parallelism here: the
workload's only scaling axis IS the batch (SURVEY.md §5 "long-context"
note), so data-parallel sharding of the batch dim is the idiomatic — and
optimal — mesh mapping.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..crypto.verifier import TpuBatchVerifier
from ..ops import ed25519 as kernel

BATCH_AXIS = "batch"

# jit caches keyed by mesh (Mesh is hashable); one compiled program per
# (mesh, batch shape) pair.
_SHARDED_VERIFY: dict = {}
_SHARDED_PALLAS: dict = {}
_SHARDED_COUNT: dict = {}


def _pallas_on_mesh() -> bool:
    """On real TPU hardware the pool shards the Pallas kernel (the fast
    path); on the CPU virtual mesh it shards the XLA graph (Pallas has no
    compiled CPU lowering). Single source of truth: ed25519._use_pallas."""
    return kernel._use_pallas()


def make_mesh(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """1-D device mesh over the batch axis.

    The pool is data-parallel only, so the mesh is 1-D no matter how many
    chips participate; on a real v5e-8 slice the axis spans all 8 chips and
    the validity-sum AllReduce rides ICI.

    On a multi-host runtime (jax.distributed up, process_count > 1) the
    default is this process's LOCAL devices: a per-node verifier flushes
    its own traffic on its own schedule, so its compiled programs can
    never enter a cross-process SPMD collective in lockstep — a global
    mesh here would hang at the first flush (parallel/multihost.py
    explains the scaling model).
    """
    if devices is None:
        devices = (
            jax.local_devices() if jax.process_count() > 1 else jax.devices()
        )
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def _verify_fn(mesh: Mesh):
    fn = _SHARDED_VERIFY.get(mesh)
    if fn is None:
        shard = NamedSharding(mesh, PartitionSpec(BATCH_AXIS))
        fn = jax.jit(
            kernel.verify_kernel,
            in_shardings=(shard,) * 5,
            out_shardings=shard,
        )
        _SHARDED_VERIFY[mesh] = fn
    return fn


def _pallas_fn(mesh: Mesh):
    """shard_map of the Pallas verify graph: each chip runs the kernel on
    its batch shard; no cross-chip communication."""
    fn = _SHARDED_PALLAS.get(mesh)
    if fn is None:
        from jax.experimental.shard_map import shard_map

        from ..ops.pallas_verify import verify_graph

        spec = PartitionSpec(BATCH_AXIS)
        fn = jax.jit(
            shard_map(
                verify_graph,
                mesh=mesh,
                in_specs=(spec,) * 5,
                out_specs=spec,
                # pallas_call outputs carry no varying-mesh-axes metadata;
                # the graph is purely batch-elementwise, so this is safe
                check_rep=False,
            )
        )
        _SHARDED_PALLAS[mesh] = fn
    return fn


def _count_fn(mesh: Mesh):
    """verify + replicated valid-count: the scalar reduction is the one
    cross-chip collective (psum over ICI, inserted by XLA from the
    sharded->replicated transition)."""
    fn = _SHARDED_COUNT.get(mesh)
    if fn is None:
        shard = NamedSharding(mesh, PartitionSpec(BATCH_AXIS))
        replicated = NamedSharding(mesh, PartitionSpec())

        def verify_and_count(a, r, s_w, h_w, valid):
            ok = kernel.verify_kernel(a, r, s_w, h_w, valid)
            return ok, jnp.sum(ok.astype(jnp.int32))

        fn = jax.jit(
            verify_and_count,
            in_shardings=(shard,) * 5,
            out_shardings=(shard, replicated),
        )
        _SHARDED_COUNT[mesh] = fn
    return fn


def pool_bucket_for(n: int, n_devices: int, quantum: int | None = None) -> int:
    """Smallest bucket that fits n and splits evenly across the mesh.

    ``quantum`` is the required divisor of the bucket (defaults to the
    device count; the Pallas path needs device_count * TILE so each chip's
    shard fills whole kernel tiles). Buckets are rounded up to the next
    quantum multiple, so the set of compiled shapes stays fixed per mesh
    size (no recompiles on traffic jitter, same policy as the single-chip
    path).
    """
    q = quantum if quantum is not None else n_devices
    for b in kernel.BUCKETS:
        b = ((b + q - 1) // q) * q
        if n <= b:
            return b
    top = max(kernel.BUCKETS[-1], n)
    return ((top + q - 1) // q) * q


def _pool_quantum(n_devices: int) -> int:
    if _pallas_on_mesh():
        from ..ops.pallas_verify import TILE

        return n_devices * TILE
    return n_devices


def verify_batch_sharded(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
    mesh: Mesh | None = None,
    batch_size: int | None = None,
) -> np.ndarray:
    """Verify one batch across every chip in the mesh; (n,) bool."""
    if mesh is None:
        mesh = make_mesh()
    n_dev = mesh.devices.size
    quantum = _pool_quantum(n_dev)
    if batch_size is None:
        batch_size = pool_bucket_for(len(public_keys), n_dev, quantum)
    if batch_size % quantum != 0:
        raise ValueError(
            f"batch_size {batch_size} not divisible by pool quantum {quantum}"
            f" ({n_dev} devices)"
        )
    a, r, s_le, h_le, valid = kernel.prepare_batch(
        public_keys, messages, signatures, batch_size
    )
    fn = _pallas_fn(mesh) if _pallas_on_mesh() else _verify_fn(mesh)
    out = fn(
        jnp.asarray(a),
        jnp.asarray(r),
        jnp.asarray(s_le),
        jnp.asarray(h_le),
        jnp.asarray(valid),
    )
    return np.asarray(out)[: len(public_keys)]


class PoolVerifier(TpuBatchVerifier):
    """Async Verifier backed by the whole mesh (config: ``verifier = "pool"``).

    Same accumulate/pad/dispatch discipline as
    :class:`~at2_node_tpu.crypto.verifier.TpuBatchVerifier`, but each
    flushed batch is sharded over every chip. Useful behind many nodes
    (BASELINE.json config 5: 32 nodes sharing a v5e-8 pool).
    """

    def __init__(
        self,
        batch_size: int = 1024,
        max_delay: float = 0.002,
        mesh: Mesh | None = None,
    ) -> None:
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        # Every bucket (and the batch_size TpuBatchVerifier unions in) must
        # split evenly across the mesh — into whole Pallas tiles per chip
        # on hardware: round both up to quantum multiples.
        q = _pool_quantum(n_dev)
        batch_size = ((batch_size + q - 1) // q) * q
        # single bucket == single compiled program (see TpuBatchVerifier)
        super().__init__(batch_size=batch_size, max_delay=max_delay)

    # staged pipeline overrides (the base class overlaps these stages
    # across consecutive batches; see TpuBatchVerifier._dispatch)

    def _prep(self, pks, msgs, sigs, bucket):
        q = _pool_quantum(self.mesh.devices.size)
        if bucket % q != 0:
            raise ValueError(
                f"bucket {bucket} not divisible by pool quantum {q}"
            )
        return kernel.prepare_batch(pks, msgs, sigs, bucket)

    def _launch(self, prepared):
        fn = _pallas_fn(self.mesh) if _pallas_on_mesh() else _verify_fn(self.mesh)
        out = fn(*(jnp.asarray(x) for x in prepared))
        try:
            out.copy_to_host_async()
        except AttributeError:
            pass
        return out
