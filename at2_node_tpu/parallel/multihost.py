"""Multi-host runtime bring-up (jax.distributed over ICI/DCN).

SURVEY.md §7 step 8 ends at the single-host multi-chip pool and defers
multi-host; this module is the bring-up seam for that step — with the
scaling model stated honestly:

* **Verification pools stay host-local by design.** A node's
  `PoolVerifier` flushes ITS OWN traffic whenever its accumulator
  fills; two hosts' pools can never enter one SPMD program in lockstep,
  so a cross-process mesh under a per-node verifier would hang at its
  first collective. On a multi-host runtime, `pool.make_mesh()`
  therefore builds over this process's LOCAL devices only.
* **Cross-host scale-out is the replication dimension itself** (SURVEY
  §2.3 P1): more nodes, each owning its host's chips — exactly how the
  reference scales (one host's workers per node, rpc.rs:125), with the
  per-host verifier ceiling raised from CPU cores to a TPU slice.
* What the distributed runtime buys here: nodes on multi-host POD
  slices (where one process only addresses its local chips) still get
  their full local complement, plus single-controller SPMD jobs — the
  1M-replay benchmark, the multichip dryrun — can span hosts because a
  SINGLE driver feeds every process the same program in lockstep.

Configuration is by environment (the deployment shape k8s/GCE gives):

    AT2_COORDINATOR   host:port of process 0 (presence enables init)
    AT2_NUM_PROCESSES total process count
    AT2_PROCESS_ID    this process's index

`maybe_initialize()` is a no-op without AT2_COORDINATOR, so single-host
deployments never pay the coordinator round-trip; with it, call once
before any JAX use (the server CLI does this before Service.start when
the variables are present).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_initialized = False


def maybe_initialize() -> bool:
    """Initialize jax.distributed from AT2_* env vars; True if the
    multi-host runtime is (now or already) up, False when unconfigured.

    Idempotent; must run before the first JAX backend touch in the
    process (jax.distributed's own constraint)."""
    global _initialized
    if _initialized:
        return True
    coordinator = os.environ.get("AT2_COORDINATOR")
    if not coordinator:
        return False
    try:
        num_processes = int(os.environ["AT2_NUM_PROCESSES"])
        process_id = int(os.environ["AT2_PROCESS_ID"])
    except (KeyError, ValueError) as exc:
        raise ValueError(
            "AT2_COORDINATOR is set, so AT2_NUM_PROCESSES and "
            "AT2_PROCESS_ID must both be set to integers — the three "
            "variables configure the multi-host runtime together"
        ) from exc
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "multi-host runtime up: process %s/%s, %d local / %d global devices",
        os.environ["AT2_PROCESS_ID"],
        os.environ["AT2_NUM_PROCESSES"],
        jax.local_device_count(),
        jax.device_count(),
    )
    return True


def process_info() -> dict:
    """Operator-facing snapshot of the distributed topology."""
    import jax

    return {
        "initialized": _initialized,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
