"""Seeded schedule search: episode generation, campaign running, exact
replay, and greedy trace minimization.

An *episode* is an explicit timed event list — client transactions,
equivocating submissions, hostile frame salvos, partitions, and
kind-selective drop windows — applied to a fresh :class:`SimNet` and
run to quiescence, after which the AT2 invariants are checked. The
event list is plain JSON data: given the same ``(seed, config,
events)`` the episode replays bit-identically (same wire trace hash),
which is what makes a banked failing schedule a *reproducer*, not an
anecdote.

Minimization shrinks a failing schedule the way trace-based fuzzers
do: first the shortest failing prefix (bisection), then greedy
single-event removal to a fixpoint. The survivor is the minimal
schedule the invariant checker still rejects.
"""

from __future__ import annotations

import hashlib
import random
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from .fabric import LinkModel
from .hostile import HostileFrameGen
from .net import SimNet, sim_client

# An event is [t, kind, args-dict] — JSON-shaped on purpose (banked by
# tools/sim_run.py, replayed byte-identically from the file).
Event = list

# frame kinds a drop window can select on (messages.py)
_DROPPABLE_KINDS = (1, 2, 3, 9, 10, 11)


def _seed_int(*parts) -> int:
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big")


def generate_events(
    rng: random.Random,
    *,
    nodes: int = 4,
    n_clients: int = 4,
    n_events: int = 30,
    duration: float = 20.0,
    hostile: bool = True,
    faults: bool = True,
) -> List[Event]:
    """A random adversarial schedule: honest traffic interleaved with
    client equivocation, hostile frame salvos, partitions (healed
    within the episode), and kind-selective drop windows."""
    events: List[Event] = []
    next_seq = [1] * n_clients
    burned: set = set()  # equivocated clients: their gate may never advance
    for _ in range(n_events):
        t = round(rng.uniform(0.0, duration), 3)
        roll = rng.random()
        usable = [c for c in range(n_clients) if c not in burned]
        if (roll < 0.55 or not (hostile or faults)) and usable:
            c = rng.choice(usable)
            events.append(
                [
                    t,
                    "tx",
                    {
                        "node": rng.randrange(nodes),
                        "client": c,
                        "seq": next_seq[c],
                        "to": rng.randrange(n_clients),
                        "amount": rng.randint(1, 50),
                    },
                ]
            )
            next_seq[c] += 1
        elif roll < 0.62 and usable and nodes >= 2:
            c = rng.choice(usable)
            a, b = rng.sample(range(nodes), 2)
            amount = rng.randint(1, 50)
            events.append(
                [
                    t,
                    "equiv",
                    {
                        "node_a": a,
                        "node_b": b,
                        "client": c,
                        "seq": next_seq[c],
                        "to_a": rng.randrange(n_clients),
                        "to_b": rng.randrange(n_clients),
                        "amount_a": amount,
                        "amount_b": amount + 1,  # contents must differ
                    },
                ]
            )
            burned.add(c)
        elif roll < 0.80 and hostile:
            events.append(
                [
                    t,
                    "hostile",
                    {
                        "targets": sorted(
                            rng.sample(range(nodes), rng.randint(1, nodes))
                        ),
                        "count": rng.randint(1, 6),
                    },
                ]
            )
        elif roll < 0.90 and faults and nodes >= 2:
            a, b = rng.sample(range(nodes), 2)
            events.append(
                [
                    t,
                    "cut",
                    {"a": a, "b": b, "duration": round(rng.uniform(0.5, 6.0), 3)},
                ]
            )
        elif faults:
            events.append(
                [
                    t,
                    "drop",
                    {
                        "src": rng.choice([None] + list(range(nodes))),
                        "kinds": sorted(
                            rng.sample(_DROPPABLE_KINDS, rng.randint(1, 3))
                        ),
                        "duration": round(rng.uniform(0.2, 3.0), 3),
                    },
                ]
            )
    events.sort(key=lambda e: (e[0], e[1]))
    return events


@dataclass
class EpisodeResult:
    seed: int
    events: List[Event]
    violations: List[str]
    trace_hash: str
    committed: List[int]
    delivered: int
    dropped: int
    virtual_time: float
    wall_seconds: float
    minimized: Optional[List[Event]] = None

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "ok": self.ok,
            "n_events": len(self.events),
            "violations": self.violations,
            "trace_hash": self.trace_hash,
            "committed": self.committed,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "virtual_time": self.virtual_time,
            "wall_seconds": round(self.wall_seconds, 3),
            "events": self.events,
            "minimized": self.minimized,
        }


def _install_interposer(net: SimNet, rules: List[list]) -> None:
    """Drop-window interposer: rules are [until_t, src_sign|None, kinds]."""

    def interpose(src: bytes, dst: bytes, frame: bytes):
        if not rules:
            return None
        now = net.loop.time()
        live = [r for r in rules if r[0] >= now]
        if len(live) != len(rules):
            rules[:] = live
        for _until, src_sign, kinds in rules:
            if frame and frame[0] in kinds and (
                src_sign is None or src_sign == src
            ):
                return []
        return None

    net.fabric.interposer = interpose


def apply_events(
    net: SimNet,
    events: List[Event],
    clients: List,
    hostile_gen: Optional[HostileFrameGen],
) -> None:
    """Schedule every event onto the net's virtual timeline (relative to
    now). Submissions go through the real SendAsset handler; rejections
    (SimRpcError) are normal traffic in adversarial schedules."""
    loop = net.loop
    rules: List[list] = []
    _install_interposer(net, rules)

    def node_sign(i: int) -> bytes:
        return net.configs[i].sign_key.public

    def submit(node, client_i, seq, to_i, amount):
        client = clients[client_i]
        task = loop.create_task(
            net.asubmit(node, client, seq, clients[to_i].public, amount)
        )
        net.fabric._tasks.add(task)
        task.add_done_callback(net.fabric._tasks.discard)

    for t, kind, args in events:
        if kind == "tx":
            loop.call_later(
                t,
                submit,
                args["node"],
                args["client"],
                args["seq"],
                args["to"],
                args["amount"],
            )
        elif kind == "equiv":

            def equiv(args=args):
                c = clients[args["client"]]
                for node, to_i, amount in (
                    (args["node_a"], args["to_a"], args["amount_a"]),
                    (args["node_b"], args["to_b"], args["amount_b"]),
                ):
                    task = loop.create_task(
                        net.asubmit(
                            node, c, args["seq"], clients[to_i].public, amount
                        )
                    )
                    net.fabric._tasks.add(task)
                    task.add_done_callback(net.fabric._tasks.discard)

            loop.call_later(t, equiv)
        elif kind == "hostile":
            if hostile_gen is None:
                continue

            def salvo(args=args):
                for _ in range(args["count"]):
                    frame = hostile_gen.next_frame()
                    for target in args["targets"]:
                        net.fabric.inject(
                            hostile_gen.sign.public, node_sign(target), frame
                        )

            loop.call_later(t, salvo)
        elif kind == "cut":

            def cut(args=args):
                a, b = node_sign(args["a"]), node_sign(args["b"])
                net.fabric.partition(a, b)
                loop.call_later(args["duration"], net.fabric.heal, a, b)

            loop.call_later(t, cut)
        elif kind == "drop":

            def drop(args=args):
                src = (
                    None if args["src"] is None else node_sign(args["src"])
                )
                rules.append(
                    [loop.time() + args["duration"], src, set(args["kinds"])]
                )

            loop.call_later(t, drop)
        elif kind == "inject":
            # raw frame injection (hex), for hand-built scenarios
            def inject(args=args):
                frame = bytes.fromhex(args["frame"])
                src = node_sign(args.get("src", 0))
                if "src_hostile" in args and hostile_gen is not None:
                    src = hostile_gen.sign.public
                net.fabric.inject(src, node_sign(args["target"]), frame)

            loop.call_later(t, inject)
        else:
            raise ValueError(f"unknown event kind: {kind}")


def run_episode(
    seed: int,
    *,
    nodes: int = 4,
    f: int = 1,
    hostile: int = 1,
    events: Optional[List[Event]] = None,
    n_events: int = 30,
    duration: float = 20.0,
    n_clients: int = 4,
    link: Optional[LinkModel] = None,
    settle_horizon: float = 150.0,
    echo_threshold: Optional[int] = None,
    ready_threshold: Optional[int] = None,
    config_overrides: Optional[dict] = None,
) -> EpisodeResult:
    """One self-contained episode: fresh SimNet, (generated or given)
    events, run + settle, invariant check, teardown. Pure in
    ``(seed, parameters, events)``."""
    wall0 = time.monotonic()
    rng = random.Random(_seed_int("episode", seed))
    net = SimNet(
        nodes,
        f,
        seed,
        hostile=hostile,
        link=link,
        echo_threshold=echo_threshold,
        ready_threshold=ready_threshold,
        **(config_overrides or {}),
    ).start()
    try:
        clients = [sim_client(seed, i) for i in range(n_clients)]
        if events is None:
            events = generate_events(
                rng,
                nodes=nodes,
                n_clients=n_clients,
                n_events=n_events,
                duration=duration,
                hostile=hostile > 0,
            )
        hostile_gen = (
            HostileFrameGen(
                net.hostile_configs[0].sign_key,
                random.Random(_seed_int("hostile", seed)),
            )
            if hostile > 0
            else None
        )
        apply_events(net, events, clients, hostile_gen)
        last_t = max((e[0] for e in events), default=0.0)
        net.run_for(last_t + 1.0)
        net.fabric.heal_all()
        virtual = last_t + 1.0 + net.settle(horizon=settle_horizon)
        violations = net.check_invariants()
        return EpisodeResult(
            seed=seed,
            events=events,
            violations=violations,
            trace_hash=net.fabric.trace_hash(),
            committed=[s.committed for s in net.services],
            delivered=net.fabric.delivered,
            dropped=net.fabric.dropped,
            virtual_time=virtual,
            wall_seconds=time.monotonic() - wall0,
        )
    finally:
        net.close()


def minimize_events(
    events: List[Event],
    failing: Callable[[List[Event]], bool],
    *,
    max_passes: int = 3,
) -> List[Event]:
    """Shrink a failing schedule: shortest failing prefix by bisection,
    then greedy single-event removal to a fixpoint. ``failing`` must be
    deterministic (replay the same seed/config with the candidate
    list)."""
    if not failing(events):
        raise ValueError("schedule does not fail: nothing to minimize")
    # 1. shortest failing prefix
    lo, hi = 1, len(events)
    while lo < hi:
        mid = (lo + hi) // 2
        if failing(events[:mid]):
            hi = mid
        else:
            lo = mid + 1
    current = list(events[:hi])
    # 2. greedy removal to fixpoint
    for _ in range(max_passes):
        removed_any = False
        i = len(current) - 1
        while i >= 0 and len(current) > 1:
            candidate = current[:i] + current[i + 1 :]
            if failing(candidate):
                current = candidate
                removed_any = True
            i -= 1
        if not removed_any:
            break
    return current


def run_campaign(
    seed: int,
    episodes: int,
    *,
    nodes: int = 4,
    f: int = 1,
    hostile: int = 1,
    n_events: int = 30,
    duration: float = 20.0,
    minimize: bool = False,
    link: Optional[LinkModel] = None,
    progress: Optional[Callable[[int, "EpisodeResult"], None]] = None,
) -> dict:
    """``episodes`` independent seeded episodes; per-episode seeds derive
    from the campaign seed, failures carry their exact replay recipe
    (seed + event list), and the campaign hash — sha256 over the
    episode trace hashes — is the determinism fingerprint CI compares
    across two same-seed runs."""
    camp_rng = random.Random(_seed_int("campaign", seed))
    results: List[EpisodeResult] = []
    for ep in range(episodes):
        ep_seed = camp_rng.getrandbits(32)
        result = run_episode(
            ep_seed,
            nodes=nodes,
            f=f,
            hostile=hostile,
            n_events=n_events,
            duration=duration,
            link=link,
        )
        if result.violations and minimize:
            result.minimized = minimize_events(
                result.events,
                lambda evs: bool(
                    run_episode(
                        ep_seed,
                        nodes=nodes,
                        f=f,
                        hostile=hostile,
                        events=evs,
                        link=link,
                    ).violations
                ),
            )
        results.append(result)
        if progress is not None:
            progress(ep, result)
    h = hashlib.sha256()
    for r in results:
        h.update(r.trace_hash.encode())
    return {
        "campaign_seed": seed,
        "episodes": episodes,
        "nodes": nodes,
        "f": f,
        "hostile": hostile,
        "campaign_hash": h.hexdigest(),
        "failures": sum(1 for r in results if not r.ok),
        "results": [r.to_dict() for r in results],
    }
