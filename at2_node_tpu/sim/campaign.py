"""Seeded schedule search: episode generation, campaign running, exact
replay, and greedy trace minimization.

An *episode* is an explicit timed event list — client transactions,
equivocating submissions, hostile frame salvos, partitions, and
kind-selective drop windows — applied to a fresh :class:`SimNet` and
run to quiescence, after which the AT2 invariants are checked. The
event list is plain JSON data: given the same ``(seed, config,
events)`` the episode replays bit-identically (same wire trace hash),
which is what makes a banked failing schedule a *reproducer*, not an
anecdote.

Minimization shrinks a failing schedule the way trace-based fuzzers
do: first the shortest failing prefix (bisection), then greedy
single-event removal to a fixpoint. The survivor is the minimal
schedule the invariant checker still rejects.
"""

from __future__ import annotations

import hashlib
import random
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..crypto.keys import verify_one
from ..proto import distill
from ..types import transfer_signing_bytes
from .fabric import LinkModel
from .hostile import (
    CertAdversary,
    HostileFrameGen,
    SaltingClientGen,
    mutate_distilled_frame,
)
from .net import SimNet, sim_client

# An event is [t, kind, args-dict] — JSON-shaped on purpose (banked by
# tools/sim_run.py, replayed byte-identically from the file).
Event = list

# frame kinds a drop window can select on (messages.py)
_DROPPABLE_KINDS = (1, 2, 3, 9, 10, 11)


def _seed_int(*parts) -> int:
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big")


def generate_events(
    rng: random.Random,
    *,
    nodes: int = 4,
    n_clients: int = 4,
    n_events: int = 30,
    duration: float = 20.0,
    hostile: bool = True,
    faults: bool = True,
) -> List[Event]:
    """A random adversarial schedule: honest traffic interleaved with
    client equivocation, hostile frame salvos, partitions (healed
    within the episode), and kind-selective drop windows."""
    events: List[Event] = []
    next_seq = [1] * n_clients
    burned: set = set()  # equivocated clients: their gate may never advance
    for _ in range(n_events):
        t = round(rng.uniform(0.0, duration), 3)
        roll = rng.random()
        usable = [c for c in range(n_clients) if c not in burned]
        if (roll < 0.55 or not (hostile or faults)) and usable:
            c = rng.choice(usable)
            events.append(
                [
                    t,
                    "tx",
                    {
                        "node": rng.randrange(nodes),
                        "client": c,
                        "seq": next_seq[c],
                        "to": rng.randrange(n_clients),
                        "amount": rng.randint(1, 50),
                    },
                ]
            )
            next_seq[c] += 1
        elif roll < 0.62 and usable and nodes >= 2:
            c = rng.choice(usable)
            a, b = rng.sample(range(nodes), 2)
            amount = rng.randint(1, 50)
            events.append(
                [
                    t,
                    "equiv",
                    {
                        "node_a": a,
                        "node_b": b,
                        "client": c,
                        "seq": next_seq[c],
                        "to_a": rng.randrange(n_clients),
                        "to_b": rng.randrange(n_clients),
                        "amount_a": amount,
                        "amount_b": amount + 1,  # contents must differ
                    },
                ]
            )
            burned.add(c)
        elif roll < 0.80 and hostile:
            events.append(
                [
                    t,
                    "hostile",
                    {
                        "targets": sorted(
                            rng.sample(range(nodes), rng.randint(1, nodes))
                        ),
                        "count": rng.randint(1, 6),
                    },
                ]
            )
        elif roll < 0.90 and faults and nodes >= 2:
            a, b = rng.sample(range(nodes), 2)
            events.append(
                [
                    t,
                    "cut",
                    {"a": a, "b": b, "duration": round(rng.uniform(0.5, 6.0), 3)},
                ]
            )
        elif faults:
            events.append(
                [
                    t,
                    "drop",
                    {
                        "src": rng.choice([None] + list(range(nodes))),
                        "kinds": sorted(
                            rng.sample(_DROPPABLE_KINDS, rng.randint(1, 3))
                        ),
                        "duration": round(rng.uniform(0.2, 3.0), 3),
                    },
                ]
            )
    events.sort(key=lambda e: (e[0], e[1]))
    return events


def generate_durability_events(
    rng: random.Random,
    *,
    nodes: int = 4,
    n_clients: int = 4,
    n_events: int = 30,
    duration: float = 24.0,
    hostile: bool = True,
    faults: bool = True,
) -> List[Event]:
    """A durability schedule: honest traffic with crash/restart cycles
    woven through it — a victim node is killed mid-load (optionally
    after a store flush, so restart covers both the segments+WAL-tail
    and the pure-WAL recovery paths), rebooted later, and sometimes
    partitioned from a peer right as its catchup starts. With
    ``hostile`` a membership reconfiguration races the in-flight slots:
    the fleet admin evicts the byzantine identity and re-weights the
    quorum thresholds, and every node flushes right after so the new
    epoch is durable across any later crash."""
    events: List[Event] = []
    next_seq = [1] * n_clients
    for _ in range(n_events):
        t = round(rng.uniform(0.0, duration), 3)
        c = rng.randrange(n_clients)
        events.append(
            [
                t,
                "tx",
                {
                    "node": rng.randrange(nodes),
                    "client": c,
                    "seq": next_seq[c],
                    "to": rng.randrange(n_clients),
                    "amount": rng.randint(1, 50),
                },
            ]
        )
        next_seq[c] += 1
    # crash/restart cycles on distinct victims, in DISJOINT downtime
    # windows: the schedule must respect the f-budget. Two nodes down at
    # once (f=1) leaves slots committed during the overlap with fewer
    # live copies than the catchup vote quorum (ready_threshold), which
    # correctly stalls recovery forever — a schedule bug, not a finding.
    n_cycles = rng.randint(1, 2) if nodes > 2 else 1
    victims = rng.sample(range(nodes), n_cycles)
    span = (duration * 0.7) / n_cycles
    for k, v in enumerate(victims):
        w0 = duration * 0.2 + k * span
        t_kill = round(w0 + rng.uniform(0.0, span * 0.25), 3)
        t_boot = round(t_kill + rng.uniform(1.5, max(1.6, span * 0.45)), 3)
        if rng.random() < 0.7:
            # flush first: restart sees segments + a WAL tail, not just
            # a WAL (the "restart from stale checkpoint" case when more
            # traffic lands between flush and kill)
            events.append(
                [
                    round(max(0.0, t_kill - rng.uniform(0.5, 3.0)), 3),
                    "flush",
                    {"node": v},
                ]
            )
        events.append([t_kill, "kill", {"node": v}])
        events.append([t_boot, "boot", {"node": v}])
        if faults and rng.random() < 0.5 and nodes >= 2:
            # partition the rebooting node from one peer while its
            # catchup runs (it must still confirm via the others)
            other = rng.choice([x for x in range(nodes) if x != v])
            events.append(
                [
                    round(t_boot + 0.1, 3),
                    "cut",
                    {
                        "a": v,
                        "b": other,
                        "duration": round(rng.uniform(1.0, 5.0), 3),
                    },
                ]
            )
    if hostile and rng.random() < 0.6:
        # reconfiguration racing in-flight slots: evict the hostile
        # identity, tighten nothing (thresholds re-derived for the
        # smaller peer set), then persist the epoch everywhere
        t = round(rng.uniform(duration * 0.1, duration * 0.8), 3)
        events.append(
            [
                t,
                "reconfig",
                {"node": rng.randrange(nodes), "change": {"remove_hostile": True}},
            ]
        )
        for i in range(nodes):
            events.append([round(t + 1.0, 3), "flush", {"node": i}])
    events.sort(key=lambda e: (e[0], e[1]))
    return events


BROKER_MUTATIONS = ("none", "dup", "reorder", "garbage", "withhold", "reseq")


def generate_broker_events(
    rng: random.Random,
    *,
    nodes: int = 4,
    n_clients: int = 4,
    n_events: int = 30,
    duration: float = 20.0,
    hostile: bool = True,
    faults: bool = True,
) -> List[Event]:
    """A byzantine-broker schedule: every client registers into the
    directory early, then distilled-batch submissions arrive with the
    broker misbehaving per frame — duplicating, reordering, corrupting
    ("garbage"), withholding entries, or replaying a captured signature
    at a shifted sequence ("reseq"). None of these may cost safety:
    entries stay client-signed over sequence-binding preimages, so a
    bad broker is a lossy wire, not a forger. Partitions and hostile salvos (which now include
    DirectoryAnnounce poisoning) interleave as in ``generate_events``."""
    events: List[Event] = []
    # registration window [0, 0.5): ids exist before the first frame
    for c in range(n_clients):
        events.append(
            [
                round(rng.uniform(0.0, 0.5), 3),
                "breg",
                {"node": rng.randrange(nodes), "client": c},
            ]
        )
    next_seq = [1] * n_clients
    for _ in range(n_events):
        t = round(rng.uniform(1.0, duration), 3)
        roll = rng.random()
        if roll < 0.70 or not (hostile or faults):
            rows = []
            for _ in range(rng.randint(1, 8)):
                c = rng.randrange(n_clients)
                rows.append(
                    [
                        c,
                        next_seq[c],
                        rng.randrange(n_clients),
                        rng.randint(1, 50),
                    ]
                )
                next_seq[c] += 1
            mutation = (
                "none"
                if rng.random() < 0.5
                else rng.choice(BROKER_MUTATIONS[1:])
            )
            events.append(
                [
                    t,
                    "bsub",
                    {
                        "node": rng.randrange(nodes),
                        "mutation": mutation,
                        "salt": rng.getrandbits(32),
                        "entries": rows,
                    },
                ]
            )
        elif roll < 0.85 and hostile:
            events.append(
                [
                    t,
                    "hostile",
                    {
                        "targets": sorted(
                            rng.sample(range(nodes), rng.randint(1, nodes))
                        ),
                        "count": rng.randint(1, 6),
                    },
                ]
            )
        elif faults and nodes >= 2:
            a, b = rng.sample(range(nodes), 2)
            events.append(
                [
                    t,
                    "cut",
                    {"a": a, "b": b, "duration": round(rng.uniform(0.5, 6.0), 3)},
                ]
            )
    events.sort(key=lambda e: (e[0], e[1]))
    return events


def generate_salting_events(
    rng: random.Random,
    *,
    nodes: int = 4,
    n_clients: int = 4,
    n_events: int = 30,
    duration: float = 20.0,
    hostile: bool = True,
    faults: bool = True,
) -> List[Event]:
    """A batch-poisoning schedule (ISSUE 10): honest traffic — including
    bulk flushes big enough for the auto router to amortize — interleaved
    with salted flushes from ONE byzantine client (``salt`` events; the
    salter identity itself lives in the episode's seeded
    :class:`SaltingClientGen`). Honest sequences are allocated in TIME
    order, so with no partitions in the schedule every honest entry is
    committable the moment it arrives — which is what lets the salting
    sweep count them as a hard bounded-loss invariant.

    Two anchors are always present regardless of the rolls: an early
    honest bulk flush (the RLC path must engage at all) and at least two
    salted flushes (the router must both fall back and converge)."""
    events: List[Event] = []
    next_seq = [1] * n_clients

    def bulk_event(t: float) -> Event:
        c = rng.randrange(n_clients)
        # above the engine's bisection leaf (16), so the flush exercises
        # the actual one-check amortized path, not the exact-leaf floor
        count = rng.randint(18, 32)
        ev = [
            t,
            "bulk",
            {
                "node": rng.randrange(nodes),
                "client": c,
                "seq0": next_seq[c],
                "count": count,
                "to": rng.randrange(n_clients),
                "amount": rng.randint(1, 20),
            },
        ]
        next_seq[c] += count
        return ev

    def salt_event(t: float) -> Event:
        return [
            t,
            "salt",
            {"node": rng.randrange(nodes), "size": rng.choice((24, 32, 40))},
        ]

    events.append(bulk_event(0.4))
    events.append(salt_event(1.0))
    events.append(salt_event(round(duration / 2, 3)))
    times = sorted(
        round(rng.uniform(1.5, duration), 3) for _ in range(n_events)
    )
    for t in times:
        roll = rng.random()
        if roll < 0.25:
            events.append(salt_event(t))
        elif roll < 0.40 and hostile:
            events.append(
                [
                    t,
                    "hostile",
                    {
                        "targets": sorted(
                            rng.sample(range(nodes), rng.randint(1, nodes))
                        ),
                        "count": rng.randint(1, 4),
                    },
                ]
            )
        elif roll < 0.65:
            events.append(bulk_event(t))
        else:
            c = rng.randrange(n_clients)
            events.append(
                [
                    t,
                    "tx",
                    {
                        "node": rng.randrange(nodes),
                        "client": c,
                        "seq": next_seq[c],
                        "to": rng.randrange(n_clients),
                        "amount": rng.randint(1, 50),
                    },
                ]
            )
            next_seq[c] += 1
    events.sort(key=lambda e: (e[0], e[1]))
    return events


def generate_cert_events(
    rng: random.Random,
    *,
    nodes: int = 4,
    n_clients: int = 4,
    n_events: int = 40,
    duration: float = 20.0,
    hostile: bool = True,
) -> List[Event]:
    """A finality-campaign schedule: serialized honest transfers (so
    the commit frontier crosses several ``audit_every`` strides and
    certificates actually assemble) with a byzantine member attacking
    the certificate lane — equivocating co-signature pairs, off-epoch
    co-signatures, forged signatures, and mutated kind-16 frames."""
    stride = max(0.2, duration / max(1, n_events))
    events: List[Event] = []
    next_seq = [1] * n_clients
    for k in range(n_events):
        c = k % n_clients
        events.append(
            [
                round(0.4 + stride * k, 3),
                "tx",
                {
                    "node": rng.randrange(nodes),
                    "client": c,
                    "seq": next_seq[c],
                    "to": (c + 1) % n_clients,
                    "amount": 1 + rng.randint(0, 9),
                },
            ]
        )
        next_seq[c] += 1
    if hostile:
        targets = list(range(nodes))
        for _ in range(3):
            events.append(
                [
                    round(rng.uniform(1.0, duration), 3),
                    "cert_equiv",
                    {"targets": targets},
                ]
            )
        for _ in range(2):
            events.append(
                [
                    round(rng.uniform(1.0, duration), 3),
                    "cert_stale",
                    {"targets": targets, "epoch": 7},
                ]
            )
        for _ in range(2):
            events.append(
                [
                    round(rng.uniform(1.0, duration), 3),
                    "cert_forge",
                    {"targets": targets, "count": 4},
                ]
            )
    events.sort(key=lambda e: (e[0], e[1]))
    return events


@dataclass
class EpisodeResult:
    seed: int
    events: List[Event]
    violations: List[str]
    trace_hash: str
    committed: List[int]
    delivered: int
    dropped: int
    virtual_time: float
    wall_seconds: float
    minimized: Optional[List[Event]] = None
    # failing episodes carry the fleet's observability state next to the
    # reproducer: per-node flight-recorder dumps + the cross-node
    # stitched timeline of every traced tx (tools/trace_collect.stitch).
    # Deterministic under sim virtual time — same seed, same artifact.
    obs: Optional[dict] = None
    # per-node fleet-audit state at quiescence (obs/audit.py): latched
    # divergence record, beacon counters, and the order-independent
    # digest coordinates — what the CI audit gate and the shard/wan
    # digest-equality tests assert on.
    audit: Optional[List[dict]] = None

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "ok": self.ok,
            "n_events": len(self.events),
            "violations": self.violations,
            "trace_hash": self.trace_hash,
            "committed": self.committed,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "virtual_time": self.virtual_time,
            "wall_seconds": round(self.wall_seconds, 3),
            "events": self.events,
            "minimized": self.minimized,
            "obs": self.obs,
            "audit": self.audit,
        }


def _capture_obs(net: SimNet) -> dict:
    """Freeze the fleet's observability state while the net is still
    open: one flight-recorder dump per node plus the stitched cross-node
    timeline. Reads net.services directly — sim nodes don't serve the
    HTTP mux, and the capture must happen before net.close() tears the
    services down."""
    from ..tools.trace_collect import stitch  # tools -> sim is the
    # import direction elsewhere; keep this one lazy to avoid a cycle

    for svc in net.services:
        svc.recorder.snapshot("episode_capture")
    return {
        "recorders": [svc.debugz() for svc in net.services],
        "stitched": stitch([svc.tracez() for svc in net.services]),
    }


def _install_interposer(net: SimNet, rules: List[list]) -> None:
    """Drop-window interposer: rules are [until_t, src_sign|None, kinds]."""

    def interpose(src: bytes, dst: bytes, frame: bytes):
        if not rules:
            return None
        now = net.loop.time()
        live = [r for r in rules if r[0] >= now]
        if len(live) != len(rules):
            rules[:] = live
        for _until, src_sign, kinds in rules:
            if frame and frame[0] in kinds and (
                src_sign is None or src_sign == src
            ):
                return []
        return None

    net.fabric.interposer = interpose


def apply_events(
    net: SimNet,
    events: List[Event],
    clients: List,
    hostile_gen: Optional[HostileFrameGen],
    salting_gen: Optional[SaltingClientGen] = None,
    cert_adv: Optional[CertAdversary] = None,
) -> None:
    """Schedule every event onto the net's virtual timeline (relative to
    now). Submissions go through the real SendAsset handler; rejections
    (SimRpcError) are normal traffic in adversarial schedules."""
    loop = net.loop
    rules: List[list] = []
    _install_interposer(net, rules)

    def node_sign(i: int) -> bytes:
        return net.configs[i].sign_key.public

    def _track(task) -> None:
        net.fabric._tasks.add(task)
        task.add_done_callback(net.fabric._tasks.discard)

    def _live(node: int) -> Optional[int]:
        """The node itself, or deterministically the next live one when
        it is crashed (durability schedules keep traffic flowing)."""
        total = len(net.services)
        for k in range(total):
            cand = (node + k) % total
            if cand not in net.down:
                return cand
        return None

    def submit(node, client_i, seq, to_i, amount):
        node = _live(node)
        if node is None:
            return
        client = clients[client_i]
        task = loop.create_task(
            net.asubmit(node, client, seq, clients[to_i].public, amount)
        )
        _track(task)

    def bulk(args):
        """One honest bulk flush through SendAssetBatch: count entries
        from one client at consecutive sequences — the traffic shape the
        auto router amortizes through the RLC path."""
        node = _live(args["node"])
        if node is None:
            return
        client = clients[args["client"]]
        to = clients[args["to"]].public
        rows = [
            (args["seq0"] + j, to, args["amount"], True)
            for j in range(args["count"])
        ]
        _track(loop.create_task(net.asubmit_batch(node, client, rows)))

    def salt(args):
        """One salted flush from the byzantine client: honest-looking
        except k bad-signature entries at adversarial positions
        (SaltingClientGen). All-or-nothing admission rejects the whole
        flush; the sweep asserts the router then prices this source out
        of the RLC route."""
        if salting_gen is None:
            return
        node = _live(args["node"])
        if node is None:
            return
        rows = salting_gen.next_flush(args["size"])
        _track(
            loop.create_task(
                net.asubmit_batch(
                    node, salting_gen.key, rows, source="sim-salter"
                )
            )
        )

    # client index -> directory id, filled by "breg" events (first
    # successful registration wins; later "bsub" events read it)
    directory_ids: Dict[int, int] = {}

    def breg(args):
        async def _reg():
            cid = await net.aregister(
                args["node"], clients[args["client"]].public
            )
            if cid is not None:
                directory_ids.setdefault(args["client"], cid)

        task = loop.create_task(_reg())
        net.fabric._tasks.add(task)
        task.add_done_callback(net.fabric._tasks.discard)

    def bsub(args):
        """One broker flush, possibly byzantine. The mutation happens
        AFTER the clients signed their entries — exactly a corrupting
        collector's position: it can drop, repeat, split, or mangle
        frames, but every entry it forwards is client-signed."""
        rng = random.Random(args["salt"])
        entries = []
        for c_i, seq, to_i, amount in args["entries"]:
            cid = directory_ids.get(c_i)
            if cid is None:
                continue  # registration never landed: liveness-only loss
            to = clients[to_i].public
            entries.append(
                distill.DistilledEntry(
                    sender_id=cid,
                    sequence=seq,
                    recipient=to,
                    amount=amount,
                    signature=clients[c_i].sign(
                        transfer_signing_bytes(
                            clients[c_i].public, seq, to, amount
                        )
                    ),
                )
            )
            net.touched.add(clients[c_i].public)
            net.touched.add(to)
        mutation = args["mutation"]
        if mutation == "withhold" and len(entries) > 1:
            # censor a random proper subset: gaps park at the sequence
            # gate and time out, they never commit out of order
            keep = sorted(
                rng.sample(range(len(entries)), rng.randint(1, len(entries) - 1))
            )
            entries = [entries[i] for i in keep]
        if not entries:
            return
        if mutation == "reseq":
            # The replay forgery: re-encode a captured client signature
            # at the sender's next unused sequence. Under the v2 tagged
            # preimage (types.transfer_signing_bytes binds sender AND
            # sequence) the shifted entry's signature no longer
            # verifies, so ingress drops it; were it ever to commit,
            # _forged_commit_sweep would flag the episode.
            target = rng.choice(entries)
            victim = max(
                (e for e in entries if e.sender_id == target.sender_id),
                key=lambda e: e.sequence,
            )
            entries.append(
                distill.DistilledEntry(
                    sender_id=victim.sender_id,
                    sequence=victim.sequence + 1,
                    recipient=victim.recipient,
                    amount=victim.amount,
                    signature=victim.signature,
                )
            )
        if mutation == "dup":
            frame, _ = distill.distill(entries)
            frames = [frame, frame]
        elif mutation == "reorder" and len(entries) > 1:
            cut = rng.randint(1, len(entries) - 1)
            # later sequences ship first: the gap-fill fixpoint must
            # hold them until the earlier half lands
            frames = [
                distill.distill(half)[0]
                for half in (entries[cut:], entries[:cut])
            ]
        else:
            frame, _ = distill.distill(entries)
            if mutation == "garbage":
                frame = mutate_distilled_frame(frame, rng)
            frames = [frame]
        for frame in frames:
            task = loop.create_task(net.asubmit_distilled(args["node"], frame))
            net.fabric._tasks.add(task)
            task.add_done_callback(net.fabric._tasks.discard)

    for t, kind, args in events:
        if kind == "tx":
            loop.call_later(
                t,
                submit,
                args["node"],
                args["client"],
                args["seq"],
                args["to"],
                args["amount"],
            )
        elif kind == "equiv":

            def equiv(args=args):
                c = clients[args["client"]]
                for node, to_i, amount in (
                    (args["node_a"], args["to_a"], args["amount_a"]),
                    (args["node_b"], args["to_b"], args["amount_b"]),
                ):
                    task = loop.create_task(
                        net.asubmit(
                            node, c, args["seq"], clients[to_i].public, amount
                        )
                    )
                    net.fabric._tasks.add(task)
                    task.add_done_callback(net.fabric._tasks.discard)

            loop.call_later(t, equiv)
        elif kind == "hostile":
            if hostile_gen is None:
                continue

            def salvo(args=args):
                for _ in range(args["count"]):
                    frame = hostile_gen.next_frame()
                    for target in args["targets"]:
                        net.fabric.inject(
                            hostile_gen.sign.public, node_sign(target), frame
                        )

            loop.call_later(t, salvo)
        elif kind == "cut":

            def cut(args=args):
                a, b = node_sign(args["a"]), node_sign(args["b"])
                net.fabric.partition(a, b)
                loop.call_later(args["duration"], net.fabric.heal, a, b)

            loop.call_later(t, cut)
        elif kind == "breg":
            loop.call_later(t, breg, args)
        elif kind == "bsub":
            loop.call_later(t, bsub, args)
        elif kind == "bulk":
            loop.call_later(t, bulk, args)
        elif kind == "salt":
            loop.call_later(t, salt, args)
        elif kind == "kill":

            def kill(args=args):
                if args["node"] not in net.down:
                    _track(loop.create_task(net._acrash(args["node"])))

            loop.call_later(t, kill)
        elif kind == "boot":

            def boot(args=args):
                if args["node"] in net.down:
                    _track(loop.create_task(net.arestart(args["node"])))

            loop.call_later(t, boot)
        elif kind == "flush":

            def flush(args=args):
                if args["node"] in net.down:
                    return
                svc = net.services[args["node"]]
                if svc.store is not None:
                    _track(loop.create_task(svc._store_flush()))

            loop.call_later(t, flush)
        elif kind == "reconfig":

            def reconfig(args=args):
                node = _live(args["node"])
                if node is None:
                    return
                change = dict(args["change"])
                if change.pop("remove_hostile", None):
                    removes = [
                        c.sign_key.public.hex() for c in net.hostile_configs
                    ]
                    change["remove"] = list(change.get("remove", [])) + removes
                    # re-derive the crash-fault quorum for the smaller
                    # peer set (the byzantine margin is no longer needed)
                    n_peers = len(net.peers) - 1 - len(removes)
                    thr = max(1, n_peers - net.f)
                    change.setdefault("echo_threshold", thr)
                    change.setdefault("ready_threshold", thr)
                _track(
                    loop.create_task(
                        net.areconfig(node, change, epoch=args.get("epoch"))
                    )
                )

            loop.call_later(t, reconfig)
        elif kind == "drop":

            def drop(args=args):
                src = (
                    None if args["src"] is None else node_sign(args["src"])
                )
                rules.append(
                    [loop.time() + args["duration"], src, set(args["kinds"])]
                )

            loop.call_later(t, drop)
        elif kind == "inject":
            # raw frame injection (hex), for hand-built scenarios
            def inject(args=args):
                frame = bytes.fromhex(args["frame"])
                src = node_sign(args.get("src", 0))
                if "src_hostile" in args and hostile_gen is not None:
                    src = hostile_gen.sign.public
                net.fabric.inject(src, node_sign(args["target"]), frame)

            loop.call_later(t, inject)
        elif kind == "cert_equiv":
            # byzantine member co-signs two conflicting ledger states at
            # one (epoch, watermark): every receiver must latch the
            # culprit and neither state may reach a certificate
            if cert_adv is None:
                continue

            def cert_equiv(args=args):
                fa, fb = cert_adv.equivocating_pair(args.get("epoch", 0))
                for target in args["targets"]:
                    dst = node_sign(target)
                    net.fabric.inject(cert_adv.sign.public, dst, fa)
                    net.fabric.inject(cert_adv.sign.public, dst, fb)

            loop.call_later(t, cert_equiv)
        elif kind == "cert_stale":
            if cert_adv is None:
                continue

            def cert_stale(args=args):
                frame = cert_adv.off_epoch(args.get("epoch", 7))
                for target in args["targets"]:
                    net.fabric.inject(
                        cert_adv.sign.public, node_sign(target), frame
                    )

            loop.call_later(t, cert_stale)
        elif kind == "cert_forge":
            if cert_adv is None:
                continue

            def cert_forge(args=args):
                for _ in range(args.get("count", 1)):
                    frame = (
                        cert_adv.forged()
                        if cert_adv.rng.random() < 0.5
                        else cert_adv.mutant()
                    )
                    for target in args["targets"]:
                        net.fabric.inject(
                            cert_adv.sign.public, node_sign(target), frame
                        )

            loop.call_later(t, cert_forge)
        elif kind == "misapply":
            # arm one node's ledger failpoint (node/service.py
            # _apply_pass): the next `count` successful transfers it
            # commits misapply `delta` to the recipient's balance —
            # a silent local corruption only the fleet auditor's
            # cross-node beacon compare can catch.
            def misapply(args=args):
                svc = net.services[args["node"]]
                remaining = [int(args.get("count", 1))]
                delta = int(args["delta"])

                def failpoint(_payload, _r=remaining):
                    if _r[0] <= 0:
                        return 0
                    _r[0] -= 1
                    return delta

                svc.ledger_failpoint = failpoint

            loop.call_later(t, misapply)
        else:
            raise ValueError(f"unknown event kind: {kind}")


def _forged_commit_sweep(net: SimNet) -> List[str]:
    """Broker-campaign extra invariant: every payload any node committed
    carries a valid client signature over its own signing bytes. A
    byzantine broker (or any distilled-path bug) that smuggled an
    unsigned or altered transfer past ingress shows up here — this is
    the 'broker can censor but never forge' claim, checked at the
    ledger, not at the door."""
    violations: List[str] = []
    for si, s in enumerate(net.services):
        for sender, last_seq in sorted(s.accounts.frontier_nowait().items()):
            for p in s.history.get_range(sender, 1, last_seq + 1):
                if not verify_one(p.sender, p.to_sign(), p.signature):
                    violations.append(
                        f"forged commit on node {si}: slot "
                        f"({sender.hex()[:16]}, {p.sequence}) committed "
                        "with an invalid client signature"
                    )
    return violations


def _salting_sweep(
    net: SimNet, events: List[Event], salter_pk: bytes
) -> List[str]:
    """Batch-poisoning campaign invariants (ISSUE 10), checked against
    the shared verifier after quiescence:

    * the RLC path engaged at all (an episode that silently ran per-sig
      everywhere proves nothing),
    * amortization loss is BOUNDED: at most one RLC fallback per salted
      flush — a salter can burn the batches it is in, never more,
    * the router CONVERGED: the salter's failure EWMA prices any
      min_batch-size flush of its traffic out of the RLC route,
    * honest throughput survived: every honest scheduled entry committed
      on every live node, and no salted entry ever did."""
    violations: List[str] = []
    n_salt = sum(1 for _t, kind, _a in events if kind == "salt")
    vs = net.verifier.stats()
    if not vs.get("rlc_batches", 0):
        violations.append("salting: RLC path never engaged (rlc_batches == 0)")
    fallbacks = vs.get("rlc_fallbacks", 0)
    if n_salt and not fallbacks:
        violations.append(
            "salting: no salted flush ever reached the RLC path "
            "(rlc_fallbacks == 0)"
        )
    if fallbacks > n_salt:
        violations.append(
            f"salting: unbounded amortization loss — {fallbacks} RLC "
            f"fallbacks for {n_salt} salted flushes"
        )
    router = net.verifier.router
    if n_salt and router.expected_bad(
        [salter_pk] * router.min_batch
    ) <= router.expected_bad_budget:
        violations.append(
            "salting: router never converged — a full flush of salter "
            "traffic would still route to RLC"
        )
    expected = sum(1 for _t, k, _a in events if k == "tx") + sum(
        a["count"] for _t, k, a in events if k == "bulk"
    )
    for si, s in enumerate(net.services):
        if si in net.down:
            continue
        if s.committed < expected:
            violations.append(
                f"salting: node {si} committed {s.committed}/{expected} "
                "honest entries (unbounded throughput loss)"
            )
        if s.accounts.frontier_nowait().get(salter_pk, 0):
            violations.append(
                f"salting: node {si} committed an entry from a salted "
                "flush (all-or-nothing admission breached)"
            )
    return violations


def _cert_sweep(
    net: SimNet, events: List[Event], adversary_pk: Optional[bytes]
) -> List[str]:
    """Finality-campaign invariants (checked at quiescence):

    * certificate production is LIVE: every live node assembled at
      least one certificate over the episode's commit frontier,
    * every retained certificate passes FULL light verification
      (finality/light.py members mode — bitmap, per-rank signatures,
      quorum) and the chain never rolls progress back,
    * the planted equivocation LATCHED on every live node with culprit
      attribution (the adversary's key, both signed statements),
    * no equivocating/forged/stale co-signature ever reached a
      certificate: no two nodes hold certificates naming different
      ledger states at the same (epoch, watermark), and the adversary
      attacks show up in the defense counters, not the chain."""
    from ..finality import LightVerifier, verify_chain

    violations: List[str] = []
    n_equiv = sum(1 for _t, k, _a in events if k == "cert_equiv")
    n_stale = sum(1 for _t, k, _a in events if k == "cert_stale")
    n_forge = sum(1 for _t, k, _a in events if k == "cert_forge")
    adversary_hex = adversary_pk.hex() if adversary_pk else None
    for si, svc in enumerate(net.services):
        if si in net.down:
            continue
        certs = svc.certs
        if certs is None:
            violations.append(
                f"finality: node {si} runs without an assembler despite "
                "[finality] enabled"
            )
            continue
        if certs.latest is None:
            violations.append(
                f"finality: node {si} assembled no certificate "
                f"(commits={svc.auditor.commits}, "
                f"counters={certs.counters})"
            )
        else:
            lv = LightVerifier(
                [], members=certs.members, quorum=certs.quorum
            )
            verdict = verify_chain(certs.chain, lv)
            if not verdict["ok"]:
                violations.append(
                    f"finality: node {si} serves an unverifiable chain: "
                    f"{verdict}"
                )
        if n_equiv:
            eq = certs.equivocation
            if eq is None:
                violations.append(
                    f"finality: node {si} never latched the planted "
                    "certificate equivocation"
                )
            elif adversary_hex and eq.get("origin") != adversary_hex:
                violations.append(
                    f"finality: node {si} latched equivocation but "
                    f"attributed {eq.get('origin', '')[:16]}… instead of "
                    f"the adversary {adversary_hex[:16]}…"
                )
        if n_stale and not certs.counters.get("epoch_skew"):
            violations.append(
                f"finality: node {si} accepted or lost the off-epoch "
                "co-signatures (epoch_skew == 0)"
            )
        if n_forge and not certs.counters.get("bad_sig"):
            violations.append(
                f"finality: node {si} accepted or lost the forged "
                "co-signatures (bad_sig == 0)"
            )
    # cross-node: equal watermark digest ⇔ equal committed set, so two
    # certificates naming different (ranges, dir) at one (epoch, wm)
    # would mean an equivocating state was actually certified somewhere
    seen: Dict[tuple, tuple] = {}
    for si, svc in enumerate(net.services):
        if si in net.down or svc.certs is None:
            continue
        for cert in svc.certs.chain:
            key = (cert.epoch, cert.wm_digest)
            state = (cert.ranges, cert.dir_digest)
            prior = seen.setdefault(key, (si, state))
            if prior[1] != state:
                violations.append(
                    "finality: conflicting certificates at epoch "
                    f"{cert.epoch} wm {cert.wm_digest.hex()[:16]}… "
                    f"(nodes {prior[0]} and {si})"
                )
    return violations


def run_episode(
    seed: int,
    *,
    nodes: int = 4,
    f: int = 1,
    hostile: int = 1,
    events: Optional[List[Event]] = None,
    n_events: int = 30,
    duration: float = 20.0,
    n_clients: int = 4,
    link: Optional[LinkModel] = None,
    settle_horizon: float = 150.0,
    echo_threshold: Optional[int] = None,
    ready_threshold: Optional[int] = None,
    config_overrides: Optional[dict] = None,
    capture_obs: Optional[bool] = None,
    broker: bool = False,
    durability: bool = False,
    salting: bool = False,
    finality: bool = False,
) -> EpisodeResult:
    """One self-contained episode: fresh SimNet, (generated or given)
    events, run + settle, invariant check, teardown. Pure in
    ``(seed, parameters, events)``.

    ``capture_obs``: None (default) attaches recorder dumps + the
    stitched timeline exactly when the episode fails invariants; True
    always captures; False never does (minimization re-runs use this —
    they only need the boolean verdict).

    ``broker``: generate a byzantine-broker schedule (ingress via
    distilled frames with broker mutations) instead of the per-tx one,
    and additionally sweep every committed payload for a valid client
    signature (:func:`_forged_commit_sweep`).

    ``durability``: run every node on a durable sharded store with
    membership armed, and generate a crash/restart/reconfig schedule
    (:func:`generate_durability_events`). The invariant sweep then also
    covers no-post-restart-equivocation (recorded live by the net).

    ``salting``: run the batch-poisoning flavor — the shared verifier in
    auto mode with a sim-sized RLC threshold, a schedule from
    :func:`generate_salting_events`, and the amortized-verification
    invariant sweep (:func:`_salting_sweep`).

    ``finality``: run the certificate-lane flavor — every node with a
    ``[finality]`` table and a sim-sized ``audit_every``, a schedule
    from :func:`generate_cert_events` (honest load + a byzantine member
    attacking the certificate lane), and the certificate invariant
    sweep (:func:`_cert_sweep`)."""
    wall0 = time.monotonic()
    rng = random.Random(_seed_int("episode", seed))
    sim_kwargs = dict(config_overrides or {})
    if durability:
        sim_kwargs.setdefault("durable", True)
        sim_kwargs.setdefault("membership_grace", 1.0)
    if salting:
        sim_kwargs.setdefault("verifier_mode", "auto")
        sim_kwargs.setdefault("rlc_min_batch", 8)
    if finality:
        from ..node.config import FinalityConfig, ObservabilityConfig

        sim_kwargs.setdefault("finality", FinalityConfig(enabled=True))
        sim_kwargs.setdefault(
            "observability", ObservabilityConfig(audit_every=8)
        )
    net = SimNet(
        nodes,
        f,
        seed,
        hostile=hostile,
        link=link,
        echo_threshold=echo_threshold,
        ready_threshold=ready_threshold,
        **sim_kwargs,
    ).start()
    try:
        clients = [sim_client(seed, i) for i in range(n_clients)]
        if events is None:
            if durability:
                generate = generate_durability_events
            elif broker:
                generate = generate_broker_events
            elif salting:
                generate = generate_salting_events
            elif finality:
                generate = generate_cert_events
            else:
                generate = generate_events
            events = generate(
                rng,
                nodes=nodes,
                n_clients=n_clients,
                n_events=n_events,
                duration=duration,
                hostile=hostile > 0,
            )
        hostile_gen = (
            HostileFrameGen(
                net.hostile_configs[0].sign_key,
                random.Random(_seed_int("hostile", seed)),
            )
            if hostile > 0
            else None
        )
        salting_gen = (
            SaltingClientGen(random.Random(_seed_int("salter", seed)))
            if salting
            else None
        )
        cert_adv = (
            CertAdversary(
                net.hostile_configs[0].sign_key,
                random.Random(_seed_int("certadv", seed)),
            )
            if finality and hostile > 0
            else None
        )
        apply_events(net, events, clients, hostile_gen, salting_gen, cert_adv)
        last_t = max((e[0] for e in events), default=0.0)
        net.run_for(last_t + 1.0)
        net.fabric.heal_all()
        virtual = last_t + 1.0 + net.settle(horizon=settle_horizon)
        # fleet-audit sweep at quiescence: every live node beacons its
        # FINAL frontier (production's wall timer does this on served
        # nodes; sim schedules are timer-free), so matched-watermark
        # comparisons always happen at least once per episode no matter
        # how the mid-run commit-stride beacons interleaved.
        for i, svc in enumerate(net.services):
            if i not in net.down:
                svc._emit_beacon()
        net.settle(horizon=10.0)
        audit = [
            {
                "divergence": svc.auditor.divergence,
                "counters": svc.auditor.stats(),
                "commits": svc.auditor.commits,
                "wm": svc.accounts.digest.wm,
                "ranges": list(svc.accounts.digest.ranges),
                "dir": svc.directory.digest,
                "finality": (
                    svc.certs.status() if svc.certs is not None else None
                ),
            }
            for svc in net.services
        ]
        violations = net.check_invariants()
        if broker:
            violations += _forged_commit_sweep(net)
        if salting:
            violations += _salting_sweep(
                net, events, salting_gen.key.public
            )
        if finality:
            violations += _cert_sweep(
                net, events,
                cert_adv.sign.public if cert_adv is not None else None,
            )
        if durability and net.down:
            # a schedule must always reboot what it kills; a node still
            # down at quiescence is a schedule bug, not a safety pass
            violations.append(
                f"durability schedule left nodes down: {sorted(net.down)}"
            )
        obs = None
        if capture_obs or (capture_obs is None and violations):
            obs = _capture_obs(net)
        return EpisodeResult(
            seed=seed,
            events=events,
            violations=violations,
            trace_hash=net.fabric.trace_hash(),
            committed=[s.committed for s in net.services],
            delivered=net.fabric.delivered,
            dropped=net.fabric.dropped,
            virtual_time=virtual,
            wall_seconds=time.monotonic() - wall0,
            obs=obs,
            audit=audit,
        )
    finally:
        net.close()


def planted_breach_episode(
    seed: int = 20260805, *, capture_obs: Optional[bool] = None
) -> EpisodeResult:
    """The canonical planted safety bug, as a one-call reproducer: echo
    and ready thresholds forced to 1 (below the quorum-intersection
    bound), honest attestations suppressed net-wide, and a hostile peer
    hand-delivering a split vote for an equivocating client — nodes 0
    and 1 commit divergent contents and the invariant checker flags a
    sieve violation.

    scripts/ci.sh runs this to assert the failure artifact carries
    per-node flight-recorder dumps and the stitched cross-node timeline
    of the offending tx; tests/test_sim.py asserts the same shape."""
    from ..broadcast.messages import ECHO, READY, Attestation, Payload
    from ..node.config import BatchingConfig
    from ..types import ThinTransaction
    from .net import sim_keypairs

    clients = [sim_client(seed, i) for i in range(4)]
    hostile_sign, _ = sim_keypairs(seed, 4)  # identity 4: hostile peer

    def payload(to_i, amount):
        tx = ThinTransaction(clients[to_i].public, amount)
        return Payload.create(clients[0], 1, tx)

    def att_frames(chash):
        out = []
        for phase in (ECHO, READY):
            sig = hostile_sign.sign(
                Attestation.signing_bytes(phase, clients[0].public, 1, chash)
            )
            out.append(
                Attestation(
                    phase, hostile_sign.public, clients[0].public, 1,
                    chash, sig,
                ).encode().hex()
            )
        return out

    echo_a, ready_a = att_frames(payload(1, 5).content_hash())
    echo_b, ready_b = att_frames(payload(2, 6).content_hash())
    events = [
        [0.0, "drop", {"src": s, "kinds": [2, 3], "duration": 60.0}]
        for s in range(4)
    ] + [
        [
            0.2,
            "equiv",
            {
                "node_a": 0,
                "node_b": 1,
                "client": 0,
                "seq": 1,
                "to_a": 1,
                "to_b": 2,
                "amount_a": 5,
                "amount_b": 6,
            },
        ],
        [0.6, "inject", {"src_hostile": 1, "target": 0, "frame": echo_a}],
        [0.6, "inject", {"src_hostile": 1, "target": 0, "frame": ready_a}],
        [0.6, "inject", {"src_hostile": 1, "target": 1, "frame": echo_b}],
        [0.6, "inject", {"src_hostile": 1, "target": 1, "frame": ready_b}],
    ]
    return run_episode(
        seed,
        events=events,
        echo_threshold=1,
        ready_threshold=1,
        config_overrides={"batching": BatchingConfig(enabled=False)},
        settle_horizon=40.0,
        capture_obs=capture_obs,
    )


def planted_divergence_episode(
    seed: int = 20260805, *, capture_obs: Optional[bool] = None
) -> EpisodeResult:
    """The canonical planted STATE divergence, as a one-call reproducer:
    a clean 3-node fleet runs serialized honest traffic (client 0 pays
    client 1, one transfer settling fully before the next), and at
    t=2.6 node 0's ledger failpoint is armed to misapply a +7 balance
    delta to the recipient of its next committed transfer — a silent
    local corruption that is consistent across node 0's own WAL, ring,
    and digest, so only the fleet auditor's cross-node beacon compare
    (obs/audit.py, ``audit_every=8``) can catch it.

    The episode FAILS the invariant sweep by design (the fork is real:
    balance agreement breaks at quiescence); the point of the episode
    is what the ``audit`` block shows — both honest nodes latch a
    divergence attributing node 0, the recipient's account-range lane,
    and the first divergent watermark, within two beacon intervals of
    the corruption. scripts/ci.sh's fleet-audit gate and
    tests/test_sim.py assert exactly that."""
    from ..node.config import ObservabilityConfig

    events: List[Event] = [
        [0.5 + 0.5 * k, "tx",
         {"node": k % 3, "client": 0, "seq": k + 1, "to": 1, "amount": 1}]
        for k in range(40)
    ]
    events.append([2.6, "misapply", {"node": 0, "delta": 7, "count": 1}])
    events.sort(key=lambda e: (e[0], e[1]))
    return run_episode(
        seed,
        nodes=3,
        f=0,
        hostile=0,
        events=events,
        config_overrides={"observability": ObservabilityConfig(audit_every=8)},
        settle_horizon=60.0,
        capture_obs=capture_obs,
    )


def planted_cert_equivocation_episode(
    seed: int = 20260807, *, capture_obs: Optional[bool] = None
) -> EpisodeResult:
    """The canonical certificate-lane attack, as a one-call reproducer:
    a 4-node fleet with finality enabled runs serialized honest
    transfers, and a byzantine fleet MEMBER (its key in the epoch
    member set, so its co-signatures verify) emits equivocating
    co-signature pairs, off-epoch co-signatures, and forged frames at
    every node.

    The episode PASSES iff the defense held: honest certificates
    assembled and fully verify, every live node latched the
    equivocation with the adversary's key and both signed statements as
    evidence, the off-epoch/forged attacks landed in the
    ``epoch_skew``/``bad_sig`` counters, and no conflicting state was
    ever certified anywhere. scripts/ci.sh runs this twice and compares
    trace hashes (the determinism gate) and asserts the latch +
    attribution on every node's ``audit[i]["finality"]`` block."""
    rng = random.Random(_seed_int("cert-planted", seed))
    events = generate_cert_events(
        rng, nodes=4, n_clients=4, n_events=40, duration=16.0, hostile=True
    )
    return run_episode(
        seed,
        nodes=4,
        f=1,
        hostile=1,
        events=events,
        finality=True,
        settle_horizon=60.0,
        capture_obs=capture_obs,
    )


def minimize_events(
    events: List[Event],
    failing: Callable[[List[Event]], bool],
    *,
    max_passes: int = 3,
) -> List[Event]:
    """Shrink a failing schedule: shortest failing prefix by bisection,
    then greedy single-event removal to a fixpoint. ``failing`` must be
    deterministic (replay the same seed/config with the candidate
    list)."""
    if not failing(events):
        raise ValueError("schedule does not fail: nothing to minimize")
    # 1. shortest failing prefix
    lo, hi = 1, len(events)
    while lo < hi:
        mid = (lo + hi) // 2
        if failing(events[:mid]):
            hi = mid
        else:
            lo = mid + 1
    current = list(events[:hi])
    # 2. greedy removal to fixpoint
    for _ in range(max_passes):
        removed_any = False
        i = len(current) - 1
        while i >= 0 and len(current) > 1:
            candidate = current[:i] + current[i + 1 :]
            if failing(candidate):
                current = candidate
                removed_any = True
            i -= 1
        if not removed_any:
            break
    return current


def run_campaign(
    seed: int,
    episodes: int,
    *,
    nodes: int = 4,
    f: int = 1,
    hostile: int = 1,
    n_events: int = 30,
    duration: float = 20.0,
    minimize: bool = False,
    link: Optional[LinkModel] = None,
    progress: Optional[Callable[[int, "EpisodeResult"], None]] = None,
    broker: bool = False,
    durability: bool = False,
    salting: bool = False,
    finality: bool = False,
    config_overrides: Optional[dict] = None,
) -> dict:
    """``episodes`` independent seeded episodes; per-episode seeds derive
    from the campaign seed, failures carry their exact replay recipe
    (seed + event list), and the campaign hash — sha256 over the
    episode trace hashes — is the determinism fingerprint CI compares
    across two same-seed runs. ``broker=True`` runs the byzantine-broker
    flavor of every episode (distilled ingress + forged-commit sweep);
    ``durability=True`` the crash/restart/reconfig flavor (durable
    stores + membership + no-post-restart-equivocation);
    ``salting=True`` the batch-poisoning flavor (amortized verification
    under a salting client + bounded-loss/router-convergence sweep);
    ``finality=True`` the certificate-lane flavor (finality enabled
    fleet-wide + a byzantine member attacking the lane + the
    certificate invariant sweep)."""
    camp_rng = random.Random(_seed_int("campaign", seed))
    results: List[EpisodeResult] = []
    for ep in range(episodes):
        ep_seed = camp_rng.getrandbits(32)
        result = run_episode(
            ep_seed,
            nodes=nodes,
            f=f,
            hostile=hostile,
            n_events=n_events,
            duration=duration,
            link=link,
            broker=broker,
            durability=durability,
            salting=salting,
            finality=finality,
            config_overrides=config_overrides,
        )
        if result.violations and minimize:
            result.minimized = minimize_events(
                result.events,
                lambda evs: bool(
                    run_episode(
                        ep_seed,
                        nodes=nodes,
                        f=f,
                        hostile=hostile,
                        events=evs,
                        link=link,
                        capture_obs=False,
                        broker=broker,
                        durability=durability,
                        salting=salting,
                        finality=finality,
                        config_overrides=config_overrides,
                    ).violations
                ),
            )
        results.append(result)
        if progress is not None:
            progress(ep, result)
    h = hashlib.sha256()
    for r in results:
        h.update(r.trace_hash.encode())
    return {
        "campaign_seed": seed,
        "episodes": episodes,
        "nodes": nodes,
        "f": f,
        "hostile": hostile,
        "broker": broker,
        "durability": durability,
        "salting": salting,
        "finality": finality,
        "campaign_hash": h.hexdigest(),
        "failures": sum(1 for r in results if not r.ok),
        "results": [r.to_dict() for r in results],
    }
