"""SimNet: an n-node f-tolerant network of REAL ``Service`` cores on a
virtual clock and simulated fabric, plus the AT2 invariant checker.

The services here are not mocks: the full bring-up path runs (broadcast
planes, delivery→commit loop, catchup runner, admission), with exactly
three substitutions via ``Service.start``'s simulator seams — the
virtual clock, a ``SimMesh`` in place of the socket mesh, and
``serve_rpc=False`` (client traffic enters through the real
``SendAsset`` handler called with a simulated gRPC context, so
validation and admission still run).

Keys, catchup nonces, and all fabric randomness derive from the net's
seed; under ``SimScheduler`` the entire run is a pure function of
``(seed, config, events)``.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random
import shutil
import tempfile
from typing import Dict, List, Optional

from ..broadcast.messages import ConfigTx
from ..crypto.keys import ExchangeKeyPair, SignKeyPair
from ..crypto.verifier import CpuVerifier
from ..net.peers import Peer
from ..node.config import Config, MembershipConfig, StoreConfig
from ..node.service import Service
from ..proto import at2_pb2 as pb
from ..types import transfer_signing_bytes
from .fabric import LinkModel, SimFabric, SimMesh
from .scheduler import SimClock, SimScheduler


class InvariantViolation(AssertionError):
    """An AT2 safety property failed; carries all violation strings."""

    def __init__(self, violations: List[str]):
        super().__init__("; ".join(violations))
        self.violations = violations


class SimRpcError(Exception):
    """What ``context.abort`` raises in the sim (mirrors grpc's
    AbortError: the handler never resumes past an abort)."""

    def __init__(self, code, details: str = ""):
        super().__init__(f"{code}: {details}")
        self.code = code
        self.details = details


class _SimContext:
    """The slice of the grpc.aio servicer context the handlers use."""

    def __init__(self, source: str):
        self._source = source

    def peer(self) -> str:
        return self._source

    async def abort(self, code, details: str = "") -> None:
        raise SimRpcError(code, details)


def sim_keypairs(seed: int, i: int):
    """Deterministic node identity i for a given net seed."""
    import hashlib

    sk = hashlib.sha256(f"at2-sim-sign-{seed}-{i}".encode()).digest()
    xk = hashlib.sha256(f"at2-sim-xchg-{seed}-{i}".encode()).digest()
    return SignKeyPair(sk), ExchangeKeyPair(xk)


def sim_client(seed: int, i: int) -> SignKeyPair:
    """Deterministic client identity i (disjoint from node identities)."""
    import hashlib

    return SignKeyPair(
        hashlib.sha256(f"at2-sim-client-{seed}-{i}".encode()).digest()
    )


def sim_admin(seed: int) -> SignKeyPair:
    """Deterministic fleet-admin identity (signs ConfigTx transitions)."""
    return SignKeyPair(hashlib.sha256(f"at2-sim-admin-{seed}".encode()).digest())


class SimNet:
    """``n`` correct nodes (+ ``hostile`` configured-but-unstarted
    byzantine identities) on one fabric. Construct, ``start()``, drive
    with ``submit``/``run_for``/``settle``, then ``check_invariants``
    and ``close``."""

    def __init__(
        self,
        n: int = 4,
        f: int = 1,
        seed: int = 0,
        *,
        hostile: int = 0,
        link: Optional[LinkModel] = None,
        echo_threshold: Optional[int] = None,
        ready_threshold: Optional[int] = None,
        durable: bool = False,
        store_root: Optional[str] = None,
        membership_grace: Optional[float] = None,
        verifier_mode: str = "auto",
        rlc_min_batch: int = 128,
        plane_shards: int = 1,
        plane_executor: str = "inline",
        **config_overrides,
    ) -> None:
        # convenience for the shard-determinism campaigns: shards > 1
        # becomes a [plane] table on every node. ``plane_executor`` is
        # recorded as configured ("inline"/"thread"/"process") while
        # Service forces inline under the sim clock regardless — which
        # is precisely what the executor hash sweep pins: the wire
        # schedule must not depend on the configured executor.
        if plane_shards > 1 and "plane" not in config_overrides:
            from ..node.config import PlaneConfig

            config_overrides["plane"] = PlaneConfig(
                shards=plane_shards, executor=plane_executor
            )
        self.n = n
        self.f = f
        self.seed = seed
        self.loop = SimScheduler()
        asyncio.set_event_loop(self.loop)
        self.clock = SimClock(self.loop)
        self.fabric = SimFabric(self.loop, seed=seed, default_link=link)
        total = n + hostile
        n_peers = total - 1  # thresholds count peers, self excluded
        if echo_threshold is None:
            # With live byzantine identities the echo/ready quorum must
            # satisfy 2q - n_peers > h (two quorums intersect in a
            # correct node); with only crash/link faults, n_peers - f
            # keeps liveness through f unreachable peers while two
            # quorums still intersect in >= 1 (correct) node.
            if hostile:
                echo_threshold = (n_peers + hostile) // 2 + 1
            else:
                echo_threshold = max(1, n_peers - f)
        if ready_threshold is None:
            ready_threshold = echo_threshold
        self.echo_threshold = echo_threshold
        self.ready_threshold = ready_threshold

        # durability: per-node sharded store dirs under one root. The sim
        # always runs the store with sync="always" so an abrupt crash()
        # loses nothing the WAL claims durable — the torn-write cases are
        # exercised separately through the store's failpoint seam.
        self.durable = durable or store_root is not None
        self._own_store_root = False
        self.store_root = store_root
        if self.durable and self.store_root is None:
            self.store_root = tempfile.mkdtemp(prefix="at2-sim-store-")
            self._own_store_root = True

        # membership: a deterministic fleet admin; membership_grace not
        # None arms every node's MembershipManager with that grace window
        self.admin_key = sim_admin(seed)
        self.membership_grace = membership_grace

        keys = [sim_keypairs(seed, i) for i in range(total)]
        peers = [
            Peer(f"sim-{i}:0", keys[i][1].public, keys[i][0].public)
            for i in range(total)
        ]
        self.peers = peers
        self.configs: List[Config] = []
        for i in range(total):
            cfg = Config(
                node_address=f"sim-{i}:0",
                rpc_address=f"sim-rpc-{i}:0",
                sign_key=keys[i][0],
                network_key=keys[i][1],
                echo_threshold=echo_threshold,
                ready_threshold=ready_threshold,
                **config_overrides,
            )
            cfg.nodes = [p for j, p in enumerate(peers) if j != i]
            if self.durable and "store" not in config_overrides:
                cfg.store = StoreConfig(
                    dir=os.path.join(self.store_root, f"node-{i}"),
                    sync="always",
                    shards=8,
                )
            if membership_grace is not None and "membership" not in config_overrides:
                cfg.membership = MembershipConfig(
                    admin_public=self.admin_key.public.hex(),
                    grace=membership_grace,
                )
            self.configs.append(cfg)

        self.services: List[Service] = []
        self.hostile_configs = self.configs[n:]
        self.touched: set = set()  # account keys episodes interacted with
        self.down: set = set()  # node indexes crashed and not yet restarted
        self._incarnation: Dict[int, int] = {}
        # no-post-restart-equivocation invariant: every attestation a
        # node SIGNS (via Broadcast.on_attest), keyed by
        # (node, phase, origin, seq), across ALL incarnations. A second
        # signing of the same slot with a different content hash is a
        # broadcast-safety violation — exactly what the persisted
        # watermark floors exist to prevent.
        self._attest: Dict[tuple, bytes] = {}
        self.attest_violations: List[str] = []
        self._started = False
        # shared across nodes like production; verifier_mode/rlc_min_batch
        # select the amortized (RLC) path — the salting campaign drops
        # min_batch so sim-sized admission flushes actually route there
        self.verifier = CpuVerifier(
            mode=verifier_mode, rlc_min_batch=rlc_min_batch
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SimNet":
        # the net owns the shared verifier, so it warms it (Service.start
        # only warms verifiers it creates); under the sim scheduler the
        # executor runs inline, so this is synchronous and deterministic
        self.loop.run_until_complete(self.verifier.warmup())
        for i in range(self.n):
            self.services.append(self._start_node(i))
        self._started = True
        return self

    def _start_node(self, i: int) -> Service:
        return self.loop.run_until_complete(self._astart_node(i))

    async def _astart_node(self, i: int) -> Service:
        """Bring up node ``i`` from its config (first boot or restart):
        fresh SimMesh (``fabric.register`` overwrites, so a restarted
        node simply replaces its dead mesh), shared verifier, seeded
        catchup nonces salted with the node's incarnation count."""
        cfg = self.configs[i]
        mesh_factory = lambda c, on_frame: SimMesh(  # noqa: E731
            self.fabric, c.sign_key.public, c.nodes, on_frame,
            region_fanout=c.wan.region_fanout,
        )
        service = await Service.start(
            cfg,
            verifier=self.verifier,
            clock=self.clock,
            mesh_factory=mesh_factory,
            serve_rpc=False,
        )
        # catchup session nonces from the net seed, not secrets
        incarnation = self._incarnation.get(i, 0)
        service._nonce_bits = random.Random(
            ((self.seed << 8) | i) ^ (incarnation * 0x9E3779B9)
        ).getrandbits
        if service.broadcast is not None:
            service.broadcast.on_attest = self._attest_hook(i)
        return service

    def _attest_hook(self, i: int):
        def hook(phase, origin, sequence, chash) -> None:
            key = (i, phase, bytes(origin), int(sequence))
            prev = self._attest.get(key)
            if prev is None:
                self._attest[key] = bytes(chash)
            elif prev != bytes(chash):
                self.attest_violations.append(
                    f"equivocation: node {i} signed phase {phase} slot "
                    f"({bytes(origin).hex()[:16]}, {sequence}) with two contents"
                )

        return hook

    def close(self) -> None:
        for s in self.services:
            try:
                self.loop.run_until_complete(s.close())
            except Exception:
                pass
        self.services.clear()
        try:
            self.loop.run_until_complete(self.verifier.close())
        except Exception:
            pass
        self.loop.close()
        asyncio.set_event_loop(None)
        if self._own_store_root and self.store_root:
            shutil.rmtree(self.store_root, ignore_errors=True)

    # -- node lifecycle (crash / restart) ----------------------------------

    def crash(self, i: int) -> None:
        """Abrupt death of node ``i``: tasks cancelled, mesh closed, NO
        final store flush and no graceful shutdown drain — whatever the
        WAL holds is all a restart gets (sync="always" in the sim, so
        that is every committed slot)."""
        self.loop.run_until_complete(self._acrash(i))

    async def _acrash(self, i: int) -> None:
        if i in self.down:
            return
        s = self.services[i]
        self.down.add(i)
        s._closing = True
        for task in (
            getattr(s, "_catchup_task", None),
            getattr(s, "_stats_task", None),
            getattr(s, "_slo_task", None),
            getattr(s, "_checkpoint_task", None),
            getattr(s, "_store_task", None),
            getattr(s, "_membership_task", None),
            getattr(s, "_batch_flush_task", None),
            getattr(s, "_delivery_task", None),
        ):
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        if s.broadcast is not None:
            await s.broadcast.close()
        if s.mesh is not None:
            await s.mesh.close()
        if s.store is not None:
            # close the WAL fd only — deliberately no flush/set_meta: the
            # on-disk state is whatever the last flush + WAL tail say
            s.store.close()
        self.fabric._record("crash", s.config.sign_key.public, b"", b"")

    def restart(self, i: int) -> Service:
        return self.loop.run_until_complete(self.arestart(i))

    async def arestart(self, i: int) -> Service:
        """Restart a crashed node from its durable store: same identity
        and config, fresh mesh registered over the dead one, recovery
        path (segments -> WAL replay -> catchup) runs inside
        ``Service.start``."""
        if i not in self.down:
            raise RuntimeError(f"node {i} is not down")
        self._incarnation[i] = self._incarnation.get(i, 0) + 1
        service = await self._astart_node(i)
        self.services[i] = service
        self.down.discard(i)
        self.fabric._record("boot", service.config.sign_key.public, b"", b"")
        return service

    def flush_store(self, i: int) -> None:
        """Force node ``i``'s store flush (segment fold + manifest
        commit). The sim drives flushes explicitly — no periodic tasks —
        so episodes control exactly which state a crash preserves."""
        svc = self.services[i]
        if svc.store is not None:
            self.loop.run_until_complete(svc._store_flush())

    # -- membership driving ------------------------------------------------

    async def areconfig(
        self, node: int, change: dict, *, epoch: Optional[int] = None
    ) -> ConfigTx:
        """Build an admin-signed ConfigTx for the NEXT epoch and inject
        it at ``node`` through the service's config handler — the node
        applies it locally and re-gossips it to the fleet, exactly the
        production admin path."""
        svc = self.services[node]
        if epoch is None:
            epoch = (svc.membership.epoch if svc.membership else 0) + 1
        tx = ConfigTx.create(self.admin_key, epoch, change)
        svc._on_config_tx(None, tx)
        return tx

    def reconfig(self, node: int, change: dict, **kw) -> ConfigTx:
        return self.loop.run_until_complete(self.areconfig(node, change, **kw))

    def sweep_membership(self) -> None:
        """Finalize expired evictions on every live node (the sim has no
        periodic membership loop; settle() calls this each window)."""
        for i, s in enumerate(self.services):
            if i not in self.down and s.membership is not None:
                s.membership.sweep()

    def __enter__(self) -> "SimNet":
        return self.start() if not self._started else self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- driving -----------------------------------------------------------

    def run_for(self, duration: float) -> None:
        self.loop.run_for(duration)

    async def asubmit(
        self,
        node: int,
        client: SignKeyPair,
        sequence: int,
        recipient: bytes,
        amount: int,
        *,
        good_sig: bool = True,
        source: Optional[str] = None,
    ) -> Optional[SimRpcError]:
        """One client transaction through the real SendAsset handler
        (validation + admission + ingress batcher). Returns the
        handler's outcome: ``None`` on accept, the ``SimRpcError`` on
        rejection (rejections are normal traffic in hostile episodes)."""
        sig = (
            client.sign(
                transfer_signing_bytes(
                    client.public, sequence, recipient, amount
                )
            )
            if good_sig
            else b"\x5a" * 64
        )
        request = pb.SendAssetRequest(
            sender=client.public,
            sequence=sequence,
            recipient=recipient,
            amount=amount,
            signature=sig,
        )
        ctx = _SimContext(source or f"sim-client-{client.public[:4].hex()}")
        self.touched.add(client.public)
        self.touched.add(recipient)
        try:
            await self.services[node].SendAsset(request, ctx)
            return None
        except SimRpcError as exc:
            return exc

    def submit(self, node: int, client: SignKeyPair, sequence: int,
               recipient: bytes, amount: int, **kw):
        """Synchronous wrapper over :meth:`asubmit` for direct driving."""
        return self.loop.run_until_complete(
            self.asubmit(node, client, sequence, recipient, amount, **kw)
        )

    async def asubmit_batch(
        self,
        node: int,
        client: SignKeyPair,
        rows,
        *,
        source: Optional[str] = None,
    ) -> Optional[SimRpcError]:
        """One bulk flush through the real ``SendAssetBatch`` handler —
        the batch-poisoning campaign's ingress. ``rows`` is a list of
        ``(sequence, recipient, amount, good_sig)``; a bad row carries a
        REAL signature with one bit of ``s`` flipped (still decodable
        and torsion-free, so only the verification equation catches it).
        Returns None on accept or the ``SimRpcError`` (a salted flush
        rejecting wholesale is the expected outcome)."""
        txs = []
        for sequence, recipient, amount, good_sig in rows:
            sig = client.sign(
                transfer_signing_bytes(
                    client.public, sequence, recipient, amount
                )
            )
            if not good_sig:
                sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
            txs.append(
                pb.SendAssetRequest(
                    sender=client.public,
                    sequence=sequence,
                    recipient=recipient,
                    amount=amount,
                    signature=sig,
                )
            )
            self.touched.add(recipient)
        self.touched.add(client.public)
        ctx = _SimContext(source or f"sim-client-{client.public[:4].hex()}")
        try:
            await self.services[node].SendAssetBatch(
                pb.SendAssetBatchRequest(transactions=txs), ctx
            )
            return None
        except SimRpcError as exc:
            return exc

    async def aregister(self, node: int, pubkey: bytes) -> Optional[int]:
        """Register a client pubkey through the real ``Register`` handler
        (directory assign + DirectoryAnnounce gossip over the fabric).
        Returns the assigned client-id, or None on rejection."""
        ctx = _SimContext("sim-register")
        try:
            reply = await self.services[node].Register(
                pb.RegisterRequest(public_key=pubkey), ctx
            )
            return int(reply.client_id)
        except SimRpcError:
            return None

    async def asubmit_distilled(
        self, node: int, frame: bytes, *, source: str = "sim-broker"
    ):
        """One distilled-batch frame through the real
        ``SendDistilledBatch`` handler — the byzantine-broker campaign's
        ingress (a simulated broker is just whoever built ``frame``).
        Returns None on accept or the ``SimRpcError`` (malformed frames
        are normal traffic in hostile episodes)."""
        ctx = _SimContext(source)
        try:
            await self.services[node].SendDistilledBatch(
                pb.SendDistilledBatchRequest(frame=frame), ctx
            )
            return None
        except SimRpcError as exc:
            return exc

    def settle(
        self, horizon: float = 120.0, window: float = 5.0, stable: int = 4
    ) -> float:
        """Advance virtual time until the net is quiescent — ledger
        progress (commits, retained history) stable for ``stable``
        consecutive windows — or the horizon is reached. Wire chatter is
        deliberately NOT part of the signal: catchup polling and
        retransmission of permanently-poisoned slots keep the fabric
        busy forever; what matters is that they stopped changing
        committed state. The default window (stable * window = 20s
        virtual) exceeds the retransmission and catchup periods, so a
        heal in flight always gets a chance to land before we stop.
        Returns virtual seconds consumed."""
        last = None
        streak = 0
        t = 0.0
        while t < horizon:
            self.loop.run_for(window)
            t += window
            self.sweep_membership()
            snap = (
                tuple(s.committed for s in self.services),
                tuple(len(s.history) for s in self.services),
            )
            if snap == last:
                streak += 1
                if streak >= stable:
                    return t
            else:
                streak = 0
            last = snap
        return t

    # -- invariants --------------------------------------------------------

    def check_invariants(self) -> List[str]:
        """The AT2 safety properties, checked across all correct nodes.
        Returns violation strings (empty = all green)."""
        return self.loop.run_until_complete(self._check())

    def assert_invariants(self) -> None:
        violations = self.check_invariants()
        if violations:
            raise InvariantViolation(violations)

    async def _check(self) -> List[str]:
        violations: List[str] = []
        # crashed-and-not-restarted nodes are excluded: they are allowed
        # to be behind (that is what restart + catchup repairs)
        services = [
            s for i, s in enumerate(self.services) if i not in self.down
        ]

        # 0. no-post-restart-equivocation: recorded live by the
        # Broadcast.on_attest hook across every incarnation of each node
        violations.extend(self.attest_violations)

        # every account any node knows about, plus everything submitted
        keys: set = set(self.touched)
        for s in services:
            keys.update(s.accounts.frontier_nowait().keys())

        # 1. agreement: identical balance and frontier everywhere
        for key in sorted(keys):
            seqs = {await s.accounts.get_last_sequence(key) for s in services}
            if len(seqs) != 1:
                violations.append(
                    f"frontier divergence for {key.hex()[:16]}: {sorted(seqs)}"
                )
            bals = {await s.accounts.get_balance(key) for s in services}
            if len(bals) != 1:
                violations.append(
                    f"balance divergence for {key.hex()[:16]}: {sorted(bals)}"
                )

        # 2. sieve consistency + no double-spend past the sequence gate:
        # a (sender, seq) slot commits at most ONE content network-wide,
        # and each node's history for a sender is gap-free up to its
        # frontier (the gate admits seq k only after k-1).
        slot_content: Dict[tuple, bytes] = {}
        for si, s in enumerate(services):
            frontier = s.accounts.frontier_nowait()
            for sender, last_seq in frontier.items():
                payloads = s.history.get_range(sender, 1, last_seq + 1)
                got = {p.sequence for p in payloads}
                # history is capacity-bounded; only flag gaps the ring
                # still covers
                expected = set(range(1, last_seq + 1))
                missing = expected - got
                if missing and len(s.history) < s.config.catchup.history_cap:
                    violations.append(
                        f"node {si}: history gap for {sender.hex()[:16]}: "
                        f"missing seqs {sorted(missing)[:8]}"
                    )
                for p in payloads:
                    slot = (sender, p.sequence)
                    chash = p.content_hash()
                    seen = slot_content.get(slot)
                    if seen is None:
                        slot_content[slot] = chash
                    elif seen != chash:
                        violations.append(
                            "sieve violation: slot "
                            f"({sender.hex()[:16]}, {p.sequence}) committed "
                            "two contents"
                        )

        # 3. totality: a slot committed anywhere is committed everywhere
        # (after quiescence + catchup, all correct nodes hold the union)
        for sender, seq in sorted(slot_content):
            for si, s in enumerate(services):
                if s.accounts.frontier_nowait().get(sender, 0) < seq:
                    violations.append(
                        f"totality violation: node {si} missing slot "
                        f"({sender.hex()[:16]}, {seq})"
                    )

        # 4. conservation: replaying each node's committed history from
        # fresh-account state reproduces its reported balances exactly
        for si, s in enumerate(services):
            expect: Dict[bytes, int] = {}
            frontier = s.accounts.frontier_nowait()
            ok_replay = True
            for sender, last_seq in sorted(frontier.items()):
                payloads = s.history.get_range(sender, 1, last_seq + 1)
                if len(payloads) < last_seq:
                    ok_replay = False  # ring evicted history: cannot replay
                    continue
                for p in payloads:
                    expect[p.sender] = (
                        expect.get(p.sender, 100_000) - p.transaction.amount
                    )
                    expect[p.transaction.recipient] = (
                        expect.get(p.transaction.recipient, 100_000)
                        + p.transaction.amount
                    )
            if ok_replay:
                for key, want in sorted(expect.items()):
                    got = await s.accounts.get_balance(key)
                    if got != want:
                        violations.append(
                            f"conservation violation on node {si}: "
                            f"{key.hex()[:16]} balance {got} != replayed {want}"
                        )
        return violations


__all__ = [
    "InvariantViolation",
    "SimNet",
    "SimRpcError",
    "sim_admin",
    "sim_client",
    "sim_keypairs",
]
