"""Simulated network fabric: the mesh surface without sockets.

``SimFabric`` is the single authority for everything that happens on
the wire in a simulated episode: per-link latency (+ seeded jitter, the
source of reordering), probabilistic loss and duplication, partitions,
and a byzantine *interposer* hook that can drop / replace / multiply
any frame in flight. Every wire event is appended to a trace whose
hash is the episode's determinism fingerprint.

``SimMesh`` implements the duck-type surface ``Broadcast`` and
``Service`` consume from the real ``net.peers.Mesh`` (``peers``,
``by_sign`` / ``by_exchange``, ``send`` / ``broadcast``, ``stats``,
``start`` / ``close``). One ``send`` is one frame is one delivery — no
coalescing — so an interposer can dispatch on the frame's leading kind
byte (GOSSIP=1, ECHO=2, READY=3, BATCH=9, ...).

``SimChannel`` separately implements the low-level transport
``Channel`` surface (``send`` / ``recv`` / ``close`` /
``peer_public``) for tests that exercise channel consumers directly.
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..net.peers import Peer
from ..net.transport import ChannelClosed


def _seed_int(*parts) -> int:
    """A stable 64-bit seed derived from arbitrary labeled parts."""
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big")


@dataclass
class LinkModel:
    """Behavior of one directed link. Jitter is drawn per frame from the
    fabric rng, so equal-latency links still interleave — and reorder —
    deterministically under a fixed seed."""

    latency: float = 0.01
    jitter: float = 0.005
    loss: float = 0.0
    dup: float = 0.0


# interposer(src_sign, dst_sign, frame) -> None to pass through,
# [] to drop, or replacement frames (each delivered independently).
Interposer = Callable[[bytes, bytes, bytes], Optional[List[bytes]]]


class SimFabric:
    """All links between all simulated nodes, plus the wire trace."""

    def __init__(self, loop, seed: int = 0, default_link: Optional[LinkModel] = None) -> None:
        import random

        self.loop = loop
        self.rng = random.Random(_seed_int("fabric", seed))
        self.default_link = default_link or LinkModel()
        self.links: Dict[Tuple[bytes, bytes], LinkModel] = {}
        self.meshes: Dict[bytes, "SimMesh"] = {}
        self._blocked: set = set()  # frozenset({a_sign, b_sign})
        self.interposer: Optional[Interposer] = None
        self.trace: List[tuple] = []
        self.in_flight = 0
        self.delivered = 0
        self.dropped = 0
        self._tasks: set = set()

    # -- topology ----------------------------------------------------------

    def register(self, sign_public: bytes, mesh: "SimMesh") -> None:
        self.meshes[sign_public] = mesh

    def set_link(self, src_sign: bytes, dst_sign: bytes, model: LinkModel) -> None:
        self.links[(src_sign, dst_sign)] = model

    def link(self, src_sign: bytes, dst_sign: bytes) -> LinkModel:
        return self.links.get((src_sign, dst_sign), self.default_link)

    def partition(self, a_sign: bytes, b_sign: bytes) -> None:
        """Block both directions between two nodes."""
        self._blocked.add(frozenset((a_sign, b_sign)))
        self._record("part", a_sign, b_sign, b"")

    def heal(self, a_sign: bytes, b_sign: bytes) -> None:
        self._blocked.discard(frozenset((a_sign, b_sign)))
        self._record("heal", a_sign, b_sign, b"")

    def heal_all(self) -> None:
        for pair in list(self._blocked):
            a, b = tuple(pair)
            self.heal(a, b)

    def is_partitioned(self, a_sign: bytes, b_sign: bytes) -> bool:
        return frozenset((a_sign, b_sign)) in self._blocked

    # -- the wire ----------------------------------------------------------

    def send(self, src_sign: bytes, dst_sign: bytes, frame: bytes) -> None:
        """One frame from src to dst, through partition check, the
        interposer, then loss/dup/latency of the directed link."""
        if self.is_partitioned(src_sign, dst_sign):
            self.dropped += 1
            self._record("cut", src_sign, dst_sign, frame)
            return
        frames: List[bytes] = [frame]
        if self.interposer is not None:
            out = self.interposer(src_sign, dst_sign, frame)
            if out is not None:
                self.dropped += 1 if not out else 0
                self._record("ipose", src_sign, dst_sign, frame)
                frames = out
        model = self.link(src_sign, dst_sign)
        for f in frames:
            if model.loss and self.rng.random() < model.loss:
                self.dropped += 1
                self._record("loss", src_sign, dst_sign, f)
                continue
            copies = 2 if (model.dup and self.rng.random() < model.dup) else 1
            for c in range(copies):
                if c:
                    self._record("dup", src_sign, dst_sign, f)
                delay = model.latency + (
                    self.rng.uniform(0.0, model.jitter) if model.jitter else 0.0
                )
                self.in_flight += 1
                self._record("send", src_sign, dst_sign, f)
                self.loop.call_later(delay, self._deliver, src_sign, dst_sign, f)

    def inject(self, src_sign: bytes, dst_sign: bytes, frame: bytes) -> None:
        """A frame from a hostile identity: same link pipeline, traced as
        an injection. ``src_sign`` must be a configured identity of the
        destination (the real mesh only accepts authenticated peers)."""
        self._record("inj", src_sign, dst_sign, frame)
        self.send(src_sign, dst_sign, frame)

    def _deliver(self, src_sign: bytes, dst_sign: bytes, frame: bytes) -> None:
        self.in_flight -= 1
        mesh = self.meshes.get(dst_sign)
        if mesh is None or mesh.closed:
            self.dropped += 1
            self._record("dead", src_sign, dst_sign, frame)
            return
        peer = mesh.by_sign.get(src_sign)
        if peer is None:  # unauthenticated identity: real mesh refuses too
            self.dropped += 1
            self._record("unauth", src_sign, dst_sign, frame)
            return
        self.delivered += 1
        self._record("dlv", src_sign, dst_sign, frame)
        task = self.loop.create_task(mesh.on_frame(peer, frame))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    # -- trace -------------------------------------------------------------

    def _record(self, kind: str, src: bytes, dst: bytes, frame: bytes) -> None:
        digest = hashlib.sha256(frame).hexdigest()[:12] if frame else "-"
        self.trace.append(
            (
                round(self.loop.time(), 9),
                kind,
                src[:4].hex(),
                dst[:4].hex(),
                frame[0] if frame else -1,
                digest,
            )
        )

    def trace_hash(self) -> str:
        h = hashlib.sha256()
        for ev in self.trace:
            h.update(repr(ev).encode())
        return h.hexdigest()


class SimMesh:
    """The ``net.peers.Mesh`` surface, backed by a :class:`SimFabric`."""

    def __init__(
        self,
        fabric: SimFabric,
        own_sign: bytes,
        peers: Iterable[Peer],
        on_frame,
        region_fanout: bool = False,
    ) -> None:
        self.fabric = fabric
        self.own_sign = own_sign
        # [wan] region-aware fanout: broadcast walks peers nearest-first
        # by configured link latency. The sim twin of the real mesh's
        # RTT-EWMA ordering — here latency is declared, so the order is
        # a pure function of topology (deterministic, but it DOES change
        # the fabric-rng draw order vs the off path, hence knob-gated).
        self.region_fanout = region_fanout
        self.peers: List[Peer] = list(peers)
        self.by_exchange: Dict[bytes, Peer] = {
            p.exchange_public: p for p in self.peers
        }
        self.by_sign: Dict[bytes, Peer] = {p.sign_public: p for p in self.peers}
        self.on_frame = on_frame
        self.closed = False
        self.send_overflows = 0
        fabric.register(own_sign, self)

    def stats(self) -> dict:
        # same keys as the real Mesh: health_verdict and the stats loop
        # read these. Every configured peer counts as connected — link
        # faults are the fabric's business, not the channel layer's.
        return {
            "channels": 0 if self.closed else len(self.peers),
            "send_queue_depth": self.fabric.in_flight,
            "redials": 0,
            "dial_failures": 0,
            "peer_reconnects": 0,
            "send_overflows": self.send_overflows,
            "native_readers": 0,
            "reader_drops": 0,
        }

    async def start(self) -> None:
        pass

    async def close(self) -> None:
        self.closed = True

    def send(self, peer: Peer, frame: bytes) -> None:
        if not self.closed:
            self.fabric.send(self.own_sign, peer.sign_public, frame)

    def broadcast(self, frame: bytes, exclude: Iterable[bytes] = ()) -> None:
        skip = set(exclude)
        peers = self.peers
        if self.region_fanout:
            # stable sort: equal-latency (same-region) peers keep their
            # configured order, so the schedule stays deterministic
            peers = sorted(
                peers,
                key=lambda p: self.fabric.link(
                    self.own_sign, p.sign_public
                ).latency,
            )
        for peer in peers:
            if peer.exchange_public not in skip:
                self.send(peer, frame)

    # -- membership (net.peers.Mesh parity) -------------------------------
    # Removal doubles as the post-grace attestation filter exactly like
    # the real mesh: _deliver drops frames whose source is no longer in
    # by_sign ("unauth"), and the broadcast stack rejects origins missing
    # from by_sign.

    def add_peer(self, peer: Peer) -> bool:
        if (
            peer.sign_public == self.own_sign
            or peer.exchange_public in self.by_exchange
        ):
            return False
        self.peers.append(peer)
        self.by_exchange[peer.exchange_public] = peer
        self.by_sign[peer.sign_public] = peer
        return True

    def remove_peer(self, sign_public: bytes) -> bool:
        peer = self.by_sign.pop(sign_public, None)
        if peer is None:
            return False
        self.by_exchange.pop(peer.exchange_public, None)
        self.peers = [
            p for p in self.peers if p.exchange_public != peer.exchange_public
        ]
        return True


class SimChannel:
    """The transport ``Channel`` duck type (send/recv/close/peer_public)
    over an in-memory pipe with optional virtual latency. Built in
    connected pairs — handshake identity is simply asserted."""

    def __init__(self, loop, peer_public: bytes, latency: float = 0.0) -> None:
        self._loop = loop
        self.peer_public = peer_public
        self.latency = latency
        self._queue: asyncio.Queue = asyncio.Queue()
        self._other: Optional["SimChannel"] = None
        self._closed = False

    @classmethod
    def pair(
        cls, loop, a_public: bytes, b_public: bytes, latency: float = 0.0
    ) -> Tuple["SimChannel", "SimChannel"]:
        """(a_end, b_end): a_end talks TO b (sees b's key), and vice versa."""
        a_end = cls(loop, b_public, latency)
        b_end = cls(loop, a_public, latency)
        a_end._other = b_end
        b_end._other = a_end
        return a_end, b_end

    async def send(self, payload: bytes) -> None:
        if self._closed or self._other is None or self._other._closed:
            raise ChannelClosed("simulated channel closed")
        other = self._other
        if self.latency:
            self._loop.call_later(self.latency, other._queue.put_nowait, payload)
        else:
            other._queue.put_nowait(payload)

    async def recv(self) -> bytes:
        if self._closed:
            raise ChannelClosed("simulated channel closed")
        item = await self._queue.get()
        if item is None:
            raise ChannelClosed("peer closed")
        return item

    def close(self) -> None:
        self._closed = True
        if self._other is not None and not self._other._closed:
            self._other._queue.put_nowait(None)
