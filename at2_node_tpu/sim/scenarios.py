"""WAN scenario grid: geo topologies × workload shapes × fault mixes,
each cell measured against its service-level objectives.

Where campaign.py searches for *safety* violations under adversarial
schedules, this module measures *service quality* under realistic
conditions: regional WAN latency matrices on the simulated fabric,
flash-crowd and hot-account traffic shapes, and mid-run partitions.
Every cell runs the REAL node stack on the deterministic simulator —
``(seed, cell parameters)`` fully determine the wire trace, so a banked
cell's ``trace_hash`` is an exact replay receipt, not a ballpark.

A cell's measures come from the same observability surfaces operators
use live: per-tx commit latency from the stitched ``/tracez`` timelines
(tools/trace_collect.stitch), commit counts from the ledger, rejection
counts from admission stats. The SLO verdict reuses the burn-rate
engine's offline entry point (obs/slo.evaluate_point), so a cell
breaching in the grid means exactly what ``/sloz`` breaching means on a
live node.

Driven by tools/scenario_grid.py; scripts/ci.sh runs the 2×2 smoke
slice and replays one cell to assert the hash reproduces.
"""

from __future__ import annotations

import hashlib
import random
import time
from typing import Dict, List, Optional

from ..obs.slo import default_objectives, evaluate_point
from .campaign import Event, apply_events
from .fabric import LinkModel
from .net import SimNet, sim_client

# -- grid axes -------------------------------------------------------------

TOPOLOGIES = ("lan", "wan3")
WORKLOADS = ("steady", "flash_crowd", "hot_account")
FAULT_MIXES = ("none", "cut")

#: the full (topology × workload × faults) matrix
GRID = [
    (t, w, fx) for t in TOPOLOGIES for w in WORKLOADS for fx in FAULT_MIXES
]
#: every wan3 cell re-run with the [wan] finality knobs on (overlapped
#: quorum phases + region-aware fanout + verify-ahead). A fourth "wan"
#: coordinate keeps the default cells' derived seeds untouched and
#: shows up as a "+wan" suffix in cell names; the knobs reorder fabric
#: sends, so these cells hash differently from their defaults by design.
WAN_GRID = [
    ("wan3", w, fx, "wan") for w in WORKLOADS for fx in FAULT_MIXES
]
GRID = GRID + WAN_GRID
#: the CI smoke slice: LAN/WAN × steady/flash-crowd, no faults
SMOKE = [
    (t, w, "none") for t in TOPOLOGIES for w in ("steady", "flash_crowd")
]

# one-way inter-region latencies (seconds) for the 3-region WAN profile:
# a near pair (same continent), a transatlantic pair, and a long-haul
# pair — the 80–250 ms band real geo-replicated deployments live in
_INTER_REGION = {
    frozenset((0, 1)): 0.080,
    frozenset((0, 2)): 0.140,
    frozenset((1, 2)): 0.250,
}
_INTRA = LinkModel(latency=0.002, jitter=0.001)


def _seed_int(*parts) -> int:
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big")


def apply_topology(net: SimNet, topology: str) -> None:
    """Install the geo profile's per-link models on the net's fabric.
    ``lan`` keeps the uniform default; ``wan3`` pins node i to region
    i % 3 and gives every directed inter-region link its pair's one-way
    latency with 10% jitter (jitter is what makes equal-latency links
    reorder, so it stays proportional to the haul)."""
    if topology == "lan":
        return
    if topology != "wan3":
        raise ValueError(f"unknown topology {topology!r}")
    signs = [cfg.sign_key.public for cfg in net.configs]
    for i, a in enumerate(signs):
        for j, b in enumerate(signs):
            if i == j:
                continue
            ra, rb = i % 3, j % 3
            if ra == rb:
                model = _INTRA
            else:
                lat = _INTER_REGION[frozenset((ra, rb))]
                model = LinkModel(latency=lat, jitter=lat * 0.1)
            net.fabric.set_link(a, b, model)


# -- workload generators ---------------------------------------------------


def _finish_txs(
    rng: random.Random, raw: List[tuple], n_clients: int
) -> List[Event]:
    """Turn (t, node, client) triples into ``tx`` events: sort by time,
    then assign each sender's sequences in arrival order — a sender's
    seqs are time-ordered, so nothing parks at the sequence gate longer
    than its own pipeline depth."""
    raw = sorted(
        (round(t, 3), node, client) for t, node, client in raw
    )
    next_seq = [1] * n_clients
    events: List[Event] = []
    for t, node, client in raw:
        to = rng.randrange(n_clients)
        events.append(
            [
                t,
                "tx",
                {
                    "node": node,
                    "client": client,
                    "seq": next_seq[client],
                    "to": to,
                    "amount": rng.randint(1, 20),
                },
            ]
        )
        next_seq[client] += 1
    return events


def steady_workload(
    rng: random.Random, *, nodes: int, n_clients: int, n_tx: int,
    duration: float,
) -> List[Event]:
    """Evenly paced traffic: senders round-robin, arrival times jittered
    around a uniform schedule — the baseline every other shape is
    measured against."""
    step = duration / max(1, n_tx)
    raw = [
        (
            min(duration, max(0.0, i * step + rng.uniform(0, step * 0.5))),
            rng.randrange(nodes),
            i % n_clients,
        )
        for i in range(n_tx)
    ]
    return _finish_txs(rng, raw, n_clients)


def flash_crowd_workload(
    rng: random.Random, *, nodes: int, n_clients: int, n_tx: int,
    duration: float,
) -> List[Event]:
    """A burst riding on baseline traffic: half the volume arrives in a
    window one-tenth of the run (a ~10× instantaneous rate spike) —
    the viral-moment shape that exposes queueing and quorum-stall
    behavior a steady offered rate never does."""
    n_burst = n_tx // 2
    n_base = n_tx - n_burst
    burst_at = duration * 0.45
    burst_len = duration * 0.10
    raw = [
        (rng.uniform(0.0, duration), rng.randrange(nodes), i % n_clients)
        for i in range(n_base)
    ]
    raw += [
        (
            burst_at + rng.uniform(0.0, burst_len),
            rng.randrange(nodes),
            i % n_clients,
        )
        for i in range(n_burst)
    ]
    return _finish_txs(rng, raw, n_clients)


def hot_account_workload(
    rng: random.Random, *, nodes: int, n_clients: int, n_tx: int,
    duration: float,
) -> List[Event]:
    """Skewed senders: client 0 originates ~40% of all traffic. Because
    a sender's transfers serialize through its sequence gate, the hot
    account's tail latency grows with its pipeline depth while everyone
    else stays cheap — the fairness index and the p99/p50 gap are the
    signals this shape exists to produce."""
    raw = []
    for i in range(n_tx):
        client = 0 if rng.random() < 0.4 else 1 + rng.randrange(n_clients - 1)
        raw.append((rng.uniform(0.0, duration), rng.randrange(nodes), client))
    return _finish_txs(rng, raw, n_clients)


_WORKLOAD_FNS = {
    "steady": steady_workload,
    "flash_crowd": flash_crowd_workload,
    "hot_account": hot_account_workload,
}


def fault_events(
    faults: str, *, duration: float
) -> List[Event]:
    """The cell's fault mix. ``cut`` partitions nodes 0↔1 for 3 virtual
    seconds mid-run — f=1 keeps commits flowing through the remaining
    quorum, and totality after heal is part of what the invariant check
    asserts."""
    if faults == "none":
        return []
    if faults == "cut":
        return [
            [round(duration * 0.35, 3), "cut",
             {"a": 0, "b": 1, "duration": 3.0}]
        ]
    raise ValueError(f"unknown fault mix {faults!r}")


# -- SLO targets per cell --------------------------------------------------

# ingress→fleet-commit p99 ceilings (ms). WAN rounds cost 2–3 long-haul
# RTTs; hot-account tails additionally stack the hot sender's pipeline
# depth on top of the per-commit round trip. wan3/steady is the
# sub-second WAN-finality bar: with phase overlap the worst commit
# chain is gossip + one long-haul attestation round (~2×250 ms + tail),
# and the measured default-path p99 already clears it with margin.
_LATENCY_P99_MS = {
    ("lan", "steady"): 250.0,
    ("lan", "flash_crowd"): 500.0,
    ("lan", "hot_account"): 1000.0,
    ("wan3", "steady"): 1000.0,
    ("wan3", "flash_crowd"): 2500.0,
    ("wan3", "hot_account"): 5000.0,
}


def cell_objectives(topology: str, workload: str):
    """The cell's declarative objectives — same Objective/evaluate_point
    machinery a live node serves on /sloz, targets scaled to the cell's
    physics (a WAN hot-account cell is *supposed* to be slow; it is not
    supposed to reject or stall)."""
    return default_objectives(
        latency_p99_ms=_LATENCY_P99_MS[(topology, workload)],
        throughput_floor_tps=0.2,
        rejection_ratio_max=0.02,
        stall_budget=0.25,
    )


def jain_index(xs: List[float]) -> float:
    """Jain's fairness index over per-sender commit counts: 1.0 = all
    senders progressed equally, 1/n = one sender got everything."""
    total = sum(xs)
    if not xs or total <= 0:
        return 1.0
    return (total * total) / (len(xs) * sum(x * x for x in xs))


# -- the cell runner -------------------------------------------------------


def run_cell(
    seed: int,
    topology: str = "lan",
    workload: str = "steady",
    faults: str = "none",
    *,
    nodes: int = 4,
    f: int = 1,
    n_clients: int = 6,
    n_tx: int = 48,
    duration: float = 12.0,
    settle_horizon: float = 150.0,
    capture_trace: bool = False,
    wan: bool = False,
    plane_shards: int = 1,
) -> dict:
    """One grid cell: fresh SimNet with the topology's link matrix, the
    workload's schedule plus the fault mix, run + settle, then measure
    throughput / latency / fairness from the fleet's own observability
    surfaces and evaluate the cell's SLOs. Pure in ``(seed, params)``.

    ``wan`` turns on the [wan] finality knobs on every node (overlapped
    quorum phases, region-aware fanout, verify-ahead) — the overlap
    levers the WAN_GRID cells exist to measure. ``capture_trace``
    attaches the full stitched timeline (big; the grid driver keeps it
    off for banked cells and on for --inspect)."""
    from ..tools.trace_collect import _pctl, stitch  # lazy: tools→sim
    # is the import direction elsewhere; avoid the cycle

    wall0 = time.monotonic()
    rng = random.Random(_seed_int("cell", seed, topology, workload, faults))
    overrides: dict = {"plane_shards": plane_shards}
    if wan:
        from ..node.config import WanConfig

        overrides["wan"] = WanConfig(
            overlap_ready=True, region_fanout=True, verify_ahead=True
        )
    net = SimNet(nodes, f, seed, hostile=0, link=_INTRA, **overrides)
    apply_topology(net, topology)
    net.start()
    try:
        clients = [sim_client(seed, i) for i in range(n_clients)]
        events = _WORKLOAD_FNS[workload](
            rng, nodes=nodes, n_clients=n_clients, n_tx=n_tx,
            duration=duration,
        )
        offered_by_client = [0] * n_clients
        for _t, _k, args in events:
            offered_by_client[args["client"]] += 1
        events = events + fault_events(faults, duration=duration)
        events.sort(key=lambda e: (e[0], e[1]))
        apply_events(net, events, clients, None)
        last_t = max((e[0] for e in events), default=0.0)
        net.run_for(last_t + 1.0)
        net.fabric.heal_all()
        settle_t = net.settle(horizon=settle_horizon)
        violations = net.check_invariants()

        offered = sum(offered_by_client)
        committed = min(s.committed for s in net.services)
        rejected = sum(
            s.admission_stats["rejected_at_ingress"] for s in net.services
        )
        # throughput over the ACTIVE window: injection plus settle time
        # minus the trailing stability windows settle() spends proving
        # quiescence (stable=4 × window=5.0 defaults) — idle tail is
        # proof work, not service time
        active_s = last_t + 1.0 + max(0.0, settle_t - 20.0)
        throughput = committed / active_s if active_s > 0 else 0.0

        stitched = stitch([s.tracez() for s in net.services])
        lats = []
        for tx in stitched["txs"]:
            if tx["terminal"] != "committed":
                continue
            commit_rels = [
                rel
                for span in tx["spans"]
                for s, rel in span["stages"]
                if s == "committed"
            ]
            if commit_rels:
                lats.append(max(commit_rels))
        lats.sort()
        lat_p50 = round(1e3 * _pctl(lats, 0.50), 3)
        lat_p90 = round(1e3 * _pctl(lats, 0.90), 3)
        lat_p99 = round(1e3 * _pctl(lats, 0.99), 3)

        frontier = net.services[0].accounts.frontier_nowait()
        commit_counts = [
            float(frontier.get(clients[c].public, 0))
            for c in range(n_clients)
            if offered_by_client[c] > 0
        ]
        fairness = round(jain_index(commit_counts), 6)
        rejection_ratio = round(rejected / offered, 6) if offered else 0.0
        stall_fraction = (
            1.0 if (settle_t >= settle_horizon or committed < offered)
            else 0.0
        )

        slo = evaluate_point(
            cell_objectives(topology, workload),
            {
                "throughput_tps": throughput,
                "latency_p99_ms": lat_p99,
                "rejection_ratio": rejection_ratio,
                "stall_fraction": stall_fraction,
            },
        )
        cell = {
            "topology": topology,
            "workload": workload,
            "faults": faults,
            "wan": bool(wan),
            "seed": seed,
            "nodes": nodes,
            "f": f,
            "offered": offered,
            "committed": committed,
            "rejected": rejected,
            "throughput_tps": round(throughput, 3),
            "latency_p50_ms": lat_p50,
            "latency_p90_ms": lat_p90,
            "latency_p99_ms": lat_p99,
            "fairness": fairness,
            "rejection_ratio": rejection_ratio,
            "stall_fraction": stall_fraction,
            "virtual_time": round(last_t + 1.0 + settle_t, 3),
            "wall_seconds": round(time.monotonic() - wall0, 3),
            "trace_hash": net.fabric.trace_hash(),
            "violations": violations,
            "slo": slo,
            "ok": bool(not violations and slo["ok"]),
        }
        if capture_trace:
            cell["stitched"] = stitched
        return cell
    finally:
        net.close()


def run_grid(
    seed: int,
    cells: Optional[List[tuple]] = None,
    *,
    nodes: int = 4,
    f: int = 1,
    n_clients: int = 6,
    n_tx: int = 48,
    duration: float = 12.0,
    progress=None,
) -> dict:
    """Run every (topology, workload, faults) cell — the full GRID by
    default — and fold the per-cell trace hashes into one grid hash,
    the determinism fingerprint CI compares across same-seed runs. The
    per-cell seed derives from the grid seed + the cell's coordinates,
    so any single cell replays standalone via :func:`run_cell`."""
    cells = list(GRID if cells is None else cells)
    results: List[dict] = []
    for coords in cells:
        # 3-tuples are default-path cells; a 4th "wan" coordinate turns
        # the [wan] knobs on AND feeds the seed derivation, so adding
        # WAN cells leaves every default cell's seed (and hash) intact
        topology, workload, faults = coords[:3]
        wan = len(coords) > 3 and coords[3] == "wan"
        seed_parts = ("grid", seed, topology, workload, faults) + (
            ("wan",) if wan else ()
        )
        cell_seed = _seed_int(*seed_parts) % (1 << 32)
        cell = run_cell(
            cell_seed, topology, workload, faults,
            nodes=nodes, f=f, n_clients=n_clients, n_tx=n_tx,
            duration=duration, wan=wan,
        )
        results.append(cell)
        if progress is not None:
            progress(cell)
    h = hashlib.sha256()
    for cell in results:
        h.update(cell["trace_hash"].encode())
    return {
        "grid_seed": seed,
        "nodes": nodes,
        "f": f,
        "n_clients": n_clients,
        "n_tx": n_tx,
        "duration": duration,
        "cells": results,
        "grid_hash": h.hexdigest(),
        "breaching": [
            f"{c['topology']}/{c['workload']}/{c['faults']}"
            + ("+wan" if c.get("wan") else "")
            for c in results
            if not c["ok"]
        ],
    }


__all__ = [
    "FAULT_MIXES",
    "GRID",
    "SMOKE",
    "TOPOLOGIES",
    "WAN_GRID",
    "WORKLOADS",
    "apply_topology",
    "cell_objectives",
    "fault_events",
    "jain_index",
    "run_cell",
    "run_grid",
]
