"""WAN scenario grid: geo topologies × workload shapes × fault mixes,
each cell measured against its service-level objectives.

Where campaign.py searches for *safety* violations under adversarial
schedules, this module measures *service quality* under realistic
conditions: regional WAN latency matrices on the simulated fabric,
flash-crowd and hot-account traffic shapes, and mid-run partitions.
Every cell runs the REAL node stack on the deterministic simulator —
``(seed, cell parameters)`` fully determine the wire trace, so a banked
cell's ``trace_hash`` is an exact replay receipt, not a ballpark.

A cell's measures come from the same observability surfaces operators
use live: per-tx commit latency from the stitched ``/tracez`` timelines
(tools/trace_collect.stitch), commit counts from the ledger, rejection
counts from admission stats. The SLO verdict reuses the burn-rate
engine's offline entry point (obs/slo.evaluate_point), so a cell
breaching in the grid means exactly what ``/sloz`` breaching means on a
live node.

Driven by tools/scenario_grid.py; scripts/ci.sh runs the 2×2 smoke
slice and replays one cell to assert the hash reproduces.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
import time
from typing import Dict, List, Optional

from ..obs.slo import default_objectives, evaluate_point
from .campaign import Event, apply_events
from .fabric import LinkModel
from .net import SimNet, sim_client

# -- grid axes -------------------------------------------------------------

TOPOLOGIES = ("lan", "wan3")
WORKLOADS = ("steady", "flash_crowd", "hot_account")
FAULT_MIXES = ("none", "cut")

#: the full (topology × workload × faults) matrix
GRID = [
    (t, w, fx) for t in TOPOLOGIES for w in WORKLOADS for fx in FAULT_MIXES
]
#: every wan3 cell re-run with the [wan] finality knobs on (overlapped
#: quorum phases + region-aware fanout + verify-ahead). A fourth "wan"
#: coordinate keeps the default cells' derived seeds untouched and
#: shows up as a "+wan" suffix in cell names; the knobs reorder fabric
#: sends, so these cells hash differently from their defaults by design.
WAN_GRID = [
    ("wan3", w, fx, "wan") for w in WORKLOADS for fx in FAULT_MIXES
]
GRID = GRID + WAN_GRID
#: the CI smoke slice: LAN/WAN × steady/flash-crowd, no faults
SMOKE = [
    (t, w, "none") for t in TOPOLOGIES for w in ("steady", "flash_crowd")
]

# one-way inter-region latencies (seconds) for the 3-region WAN profile:
# a near pair (same continent), a transatlantic pair, and a long-haul
# pair — the 80–250 ms band real geo-replicated deployments live in
_INTER_REGION = {
    frozenset((0, 1)): 0.080,
    frozenset((0, 2)): 0.140,
    frozenset((1, 2)): 0.250,
}
_INTRA = LinkModel(latency=0.002, jitter=0.001)


def _seed_int(*parts) -> int:
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big")


def apply_topology(net: SimNet, topology: str) -> None:
    """Install the geo profile's per-link models on the net's fabric.
    ``lan`` keeps the uniform default; ``wan3`` pins node i to region
    i % 3 and gives every directed inter-region link its pair's one-way
    latency with 10% jitter (jitter is what makes equal-latency links
    reorder, so it stays proportional to the haul)."""
    if topology == "lan":
        return
    if topology != "wan3":
        raise ValueError(f"unknown topology {topology!r}")
    signs = [cfg.sign_key.public for cfg in net.configs]
    for i, a in enumerate(signs):
        for j, b in enumerate(signs):
            if i == j:
                continue
            ra, rb = i % 3, j % 3
            if ra == rb:
                model = _INTRA
            else:
                lat = _INTER_REGION[frozenset((ra, rb))]
                model = LinkModel(latency=lat, jitter=lat * 0.1)
            net.fabric.set_link(a, b, model)


# -- workload generators ---------------------------------------------------


def _finish_txs(
    rng: random.Random, raw: List[tuple], n_clients: int
) -> List[Event]:
    """Turn (t, node, client) triples into ``tx`` events: sort by time,
    then assign each sender's sequences in arrival order — a sender's
    seqs are time-ordered, so nothing parks at the sequence gate longer
    than its own pipeline depth."""
    raw = sorted(
        (round(t, 3), node, client) for t, node, client in raw
    )
    next_seq = [1] * n_clients
    events: List[Event] = []
    for t, node, client in raw:
        to = rng.randrange(n_clients)
        events.append(
            [
                t,
                "tx",
                {
                    "node": node,
                    "client": client,
                    "seq": next_seq[client],
                    "to": to,
                    "amount": rng.randint(1, 20),
                },
            ]
        )
        next_seq[client] += 1
    return events


def steady_workload(
    rng: random.Random, *, nodes: int, n_clients: int, n_tx: int,
    duration: float,
) -> List[Event]:
    """Evenly paced traffic: senders round-robin, arrival times jittered
    around a uniform schedule — the baseline every other shape is
    measured against."""
    step = duration / max(1, n_tx)
    raw = [
        (
            min(duration, max(0.0, i * step + rng.uniform(0, step * 0.5))),
            rng.randrange(nodes),
            i % n_clients,
        )
        for i in range(n_tx)
    ]
    return _finish_txs(rng, raw, n_clients)


def flash_crowd_workload(
    rng: random.Random, *, nodes: int, n_clients: int, n_tx: int,
    duration: float, crowd: Optional[int] = None,
) -> List[Event]:
    """A burst riding on baseline traffic: half the volume arrives in a
    window one-tenth of the run (a ~10× instantaneous rate spike) —
    the viral-moment shape that exposes queueing and quorum-stall
    behavior a steady offered rate never does.

    ``crowd`` splits the sender population the way real flash crowds
    look: the LAST ``crowd`` client indices originate only the burst
    (newcomers, ~1 tx each at crowd ≈ n_tx//2) while the first
    ``n_clients - crowd`` carry the baseline — the shape the overload
    cells shed against. The split changes only which client index each
    triple carries, never the rng draw sequence, so ``crowd=None``
    (the grid default) is byte-identical to the historical generator at
    any client count (the scaled 10k–100k populations included)."""
    n_burst = n_tx // 2
    n_base = n_tx - n_burst
    burst_at = duration * 0.45
    burst_len = duration * 0.10
    base_pool = n_clients if crowd is None else max(1, n_clients - crowd)
    raw = [
        (rng.uniform(0.0, duration), rng.randrange(nodes), i % base_pool)
        for i in range(n_base)
    ]
    raw += [
        (
            burst_at + rng.uniform(0.0, burst_len),
            rng.randrange(nodes),
            i % n_clients if crowd is None else base_pool + (i % crowd),
        )
        for i in range(n_burst)
    ]
    return _finish_txs(rng, raw, n_clients)


def hot_account_workload(
    rng: random.Random, *, nodes: int, n_clients: int, n_tx: int,
    duration: float,
) -> List[Event]:
    """Skewed senders: client 0 originates ~40% of all traffic. Because
    a sender's transfers serialize through its sequence gate, the hot
    account's tail latency grows with its pipeline depth while everyone
    else stays cheap — the fairness index and the p99/p50 gap are the
    signals this shape exists to produce. Scales to any population
    (the overload cells run it at thousands of clients): the hot share
    stays ~40% regardless of ``n_clients``, so skew does not dilute as
    the population grows."""
    raw = []
    for i in range(n_tx):
        client = 0 if rng.random() < 0.4 else 1 + rng.randrange(n_clients - 1)
        raw.append((rng.uniform(0.0, duration), rng.randrange(nodes), client))
    return _finish_txs(rng, raw, n_clients)


_WORKLOAD_FNS = {
    "steady": steady_workload,
    "flash_crowd": flash_crowd_workload,
    "hot_account": hot_account_workload,
}


def fault_events(
    faults: str, *, duration: float
) -> List[Event]:
    """The cell's fault mix. ``cut`` partitions nodes 0↔1 for 3 virtual
    seconds mid-run — f=1 keeps commits flowing through the remaining
    quorum, and totality after heal is part of what the invariant check
    asserts."""
    if faults == "none":
        return []
    if faults == "cut":
        return [
            [round(duration * 0.35, 3), "cut",
             {"a": 0, "b": 1, "duration": 3.0}]
        ]
    raise ValueError(f"unknown fault mix {faults!r}")


# -- SLO targets per cell --------------------------------------------------

# ingress→fleet-commit p99 ceilings (ms). WAN rounds cost 2–3 long-haul
# RTTs; hot-account tails additionally stack the hot sender's pipeline
# depth on top of the per-commit round trip. wan3/steady is the
# sub-second WAN-finality bar: with phase overlap the worst commit
# chain is gossip + one long-haul attestation round (~2×250 ms + tail),
# and the measured default-path p99 already clears it with margin.
_LATENCY_P99_MS = {
    ("lan", "steady"): 250.0,
    ("lan", "flash_crowd"): 500.0,
    ("lan", "hot_account"): 1000.0,
    ("wan3", "steady"): 1000.0,
    ("wan3", "flash_crowd"): 2500.0,
    ("wan3", "hot_account"): 5000.0,
}


def cell_objectives(topology: str, workload: str):
    """The cell's declarative objectives — same Objective/evaluate_point
    machinery a live node serves on /sloz, targets scaled to the cell's
    physics (a WAN hot-account cell is *supposed* to be slow; it is not
    supposed to reject or stall)."""
    return default_objectives(
        latency_p99_ms=_LATENCY_P99_MS[(topology, workload)],
        throughput_floor_tps=0.2,
        rejection_ratio_max=0.02,
        stall_budget=0.25,
    )


def jain_index(xs: List[float]) -> float:
    """Jain's fairness index over per-sender commit counts: 1.0 = all
    senders progressed equally, 1/n = one sender got everything."""
    total = sum(xs)
    if not xs or total <= 0:
        return 1.0
    return (total * total) / (len(xs) * sum(x * x for x in xs))


# -- the cell runner -------------------------------------------------------


def run_cell(
    seed: int,
    topology: str = "lan",
    workload: str = "steady",
    faults: str = "none",
    *,
    nodes: int = 4,
    f: int = 1,
    n_clients: int = 6,
    n_tx: int = 48,
    duration: float = 12.0,
    settle_horizon: float = 150.0,
    capture_trace: bool = False,
    wan: bool = False,
    plane_shards: int = 1,
    overload=None,
) -> dict:
    """One grid cell: fresh SimNet with the topology's link matrix, the
    workload's schedule plus the fault mix, run + settle, then measure
    throughput / latency / fairness from the fleet's own observability
    surfaces and evaluate the cell's SLOs. Pure in ``(seed, params)``.

    ``wan`` turns on the [wan] finality knobs on every node (overlapped
    quorum phases, region-aware fanout, verify-ahead) — the overlap
    levers the WAN_GRID cells exist to measure. ``capture_trace``
    attaches the full stitched timeline (big; the grid driver keeps it
    off for banked cells and on for --inspect). ``overload`` installs
    an [overload] table (node/config.OverloadConfig) on every node; a
    default (disabled) instance leaves the wire trace byte-identical to
    ``overload=None`` — the off-identity the overload CI gate asserts."""
    from ..tools.trace_collect import _pctl, stitch  # lazy: tools→sim
    # is the import direction elsewhere; avoid the cycle

    wall0 = time.monotonic()
    rng = random.Random(_seed_int("cell", seed, topology, workload, faults))
    overrides: dict = {"plane_shards": plane_shards}
    if wan:
        from ..node.config import WanConfig

        overrides["wan"] = WanConfig(
            overlap_ready=True, region_fanout=True, verify_ahead=True
        )
    if overload is not None:
        overrides["overload"] = overload
    net = SimNet(nodes, f, seed, hostile=0, link=_INTRA, **overrides)
    apply_topology(net, topology)
    net.start()
    try:
        clients = [sim_client(seed, i) for i in range(n_clients)]
        events = _WORKLOAD_FNS[workload](
            rng, nodes=nodes, n_clients=n_clients, n_tx=n_tx,
            duration=duration,
        )
        offered_by_client = [0] * n_clients
        for _t, _k, args in events:
            offered_by_client[args["client"]] += 1
        events = events + fault_events(faults, duration=duration)
        events.sort(key=lambda e: (e[0], e[1]))
        apply_events(net, events, clients, None)
        last_t = max((e[0] for e in events), default=0.0)
        net.run_for(last_t + 1.0)
        net.fabric.heal_all()
        settle_t = net.settle(horizon=settle_horizon)
        violations = net.check_invariants()

        offered = sum(offered_by_client)
        committed = min(s.committed for s in net.services)
        rejected = sum(
            s.admission_stats["rejected_at_ingress"] for s in net.services
        )
        # throughput over the ACTIVE window: injection plus settle time
        # minus the trailing stability windows settle() spends proving
        # quiescence (stable=4 × window=5.0 defaults) — idle tail is
        # proof work, not service time
        active_s = last_t + 1.0 + max(0.0, settle_t - 20.0)
        throughput = committed / active_s if active_s > 0 else 0.0

        stitched = stitch([s.tracez() for s in net.services])
        lats = []
        for tx in stitched["txs"]:
            if tx["terminal"] != "committed":
                continue
            commit_rels = [
                rel
                for span in tx["spans"]
                for s, rel in span["stages"]
                if s == "committed"
            ]
            if commit_rels:
                lats.append(max(commit_rels))
        lats.sort()
        lat_p50 = round(1e3 * _pctl(lats, 0.50), 3)
        lat_p90 = round(1e3 * _pctl(lats, 0.90), 3)
        lat_p99 = round(1e3 * _pctl(lats, 0.99), 3)

        frontier = net.services[0].accounts.frontier_nowait()
        commit_counts = [
            float(frontier.get(clients[c].public, 0))
            for c in range(n_clients)
            if offered_by_client[c] > 0
        ]
        fairness = round(jain_index(commit_counts), 6)
        rejection_ratio = round(rejected / offered, 6) if offered else 0.0
        stall_fraction = (
            1.0 if (settle_t >= settle_horizon or committed < offered)
            else 0.0
        )

        slo = evaluate_point(
            cell_objectives(topology, workload),
            {
                "throughput_tps": throughput,
                "latency_p99_ms": lat_p99,
                "rejection_ratio": rejection_ratio,
                "stall_fraction": stall_fraction,
            },
        )
        cell = {
            "topology": topology,
            "workload": workload,
            "faults": faults,
            "wan": bool(wan),
            "seed": seed,
            "nodes": nodes,
            "f": f,
            "offered": offered,
            "committed": committed,
            "rejected": rejected,
            "throughput_tps": round(throughput, 3),
            "latency_p50_ms": lat_p50,
            "latency_p90_ms": lat_p90,
            "latency_p99_ms": lat_p99,
            "fairness": fairness,
            "rejection_ratio": rejection_ratio,
            "stall_fraction": stall_fraction,
            "virtual_time": round(last_t + 1.0 + settle_t, 3),
            "wall_seconds": round(time.monotonic() - wall0, 3),
            "trace_hash": net.fabric.trace_hash(),
            "violations": violations,
            "slo": slo,
            "ok": bool(not violations and slo["ok"]),
        }
        if capture_trace:
            cell["stitched"] = stitched
        return cell
    finally:
        net.close()


def run_grid(
    seed: int,
    cells: Optional[List[tuple]] = None,
    *,
    nodes: int = 4,
    f: int = 1,
    n_clients: int = 6,
    n_tx: int = 48,
    duration: float = 12.0,
    progress=None,
) -> dict:
    """Run every (topology, workload, faults) cell — the full GRID by
    default — and fold the per-cell trace hashes into one grid hash,
    the determinism fingerprint CI compares across same-seed runs. The
    per-cell seed derives from the grid seed + the cell's coordinates,
    so any single cell replays standalone via :func:`run_cell`."""
    cells = list(GRID if cells is None else cells)
    results: List[dict] = []
    for coords in cells:
        # 3-tuples are default-path cells; a 4th "wan" coordinate turns
        # the [wan] knobs on AND feeds the seed derivation, so adding
        # WAN cells leaves every default cell's seed (and hash) intact
        topology, workload, faults = coords[:3]
        wan = len(coords) > 3 and coords[3] == "wan"
        seed_parts = ("grid", seed, topology, workload, faults) + (
            ("wan",) if wan else ()
        )
        cell_seed = _seed_int(*seed_parts) % (1 << 32)
        cell = run_cell(
            cell_seed, topology, workload, faults,
            nodes=nodes, f=f, n_clients=n_clients, n_tx=n_tx,
            duration=duration, wan=wan,
        )
        results.append(cell)
        if progress is not None:
            progress(cell)
    h = hashlib.sha256()
    for cell in results:
        h.update(cell["trace_hash"].encode())
    return {
        "grid_seed": seed,
        "nodes": nodes,
        "f": f,
        "n_clients": n_clients,
        "n_tx": n_tx,
        "duration": duration,
        "cells": results,
        "grid_hash": h.hexdigest(),
        "breaching": [
            f"{c['topology']}/{c['workload']}/{c['faults']}"
            + ("+wan" if c.get("wan") else "")
            for c in results
            if not c["ok"]
        ],
    }


# -- overload A/B cells ----------------------------------------------------
#
# The default grid has no load→latency coupling: the sim charges virtual
# time for link latency and batching windows but verification is
# instantaneous, so a 10× flash crowd cannot build the queue the
# [overload] controller exists to sense. The overload cells close that
# gap with a capacity model on the fleet's SHARED verifier (the TPU-pool
# semantics the real deployment has): every verify_many call FIFO-queues
# behind one modeled device and charges n/sigs_per_sec of virtual time.
# Admission sheds happen before preverify, so shed work consumes zero
# modeled capacity — exactly the feedback loop being measured.


class ModeledVerifier:
    """Sim-only finite-capacity wrapper around the net's shared verifier.

    FIFO service through one asyncio.Lock (lock wakeups are FIFO and the
    sim scheduler is deterministic, so arrival order fully determines
    service order); each ``verify_many`` charges ``n / sigs_per_sec``
    virtual seconds. Exposes the surfaces the OverloadController samples:
    ``stats()["queue_depth"]`` (signatures waiting or in service) and
    ``stage_histograms()["queue_wait"]`` (cumulative per-call wait, the
    count/sum_ms pair the sojourn signal differences). Everything else
    delegates to the wrapped verifier — verdicts stay real."""

    def __init__(self, inner, clock, sigs_per_sec: float) -> None:
        self._inner = inner
        self._clock = clock
        self._rate = float(sigs_per_sec)
        self._lock = asyncio.Lock()
        self._depth = 0
        self._qw_count = 0
        self._qw_sum_ms = 0.0
        self.total_sigs = 0

    async def verify_many(self, items):
        n = len(items)
        self._depth += n
        self.total_sigs += n
        t0 = self._clock.monotonic()
        async with self._lock:
            self._qw_count += 1
            self._qw_sum_ms += (self._clock.monotonic() - t0) * 1e3
            await self._clock.sleep(n / self._rate)
            self._depth -= n
        return await self._inner.verify_many(items)

    def stats(self) -> dict:
        fn = getattr(self._inner, "stats", None)
        base = dict(fn()) if callable(fn) else {}
        base["queue_depth"] = self._depth
        base["modeled_sigs_per_sec"] = self._rate
        base["modeled_total_sigs"] = self.total_sigs
        return base

    def stage_histograms(self) -> dict:
        return {
            "queue_wait": {
                "count": self._qw_count,
                "sum_ms": round(self._qw_sum_ms, 3),
            }
        }

    def __getattr__(self, name):
        return getattr(self._inner, name)


def overload_objectives(capacity_sigs_per_sec: float):
    """Tuned [overload] knobs for the A/B cells' controlled arm: the
    queue target is half a second of modeled capacity (queueing beyond
    that is latency the SLO can see), sampling is tightened so the
    controller reacts within a burst's first tenth, and the smoothing
    is raised to track a spike that lasts ~1 virtual second."""
    from ..node.config import OverloadConfig

    return OverloadConfig(
        enabled=True,
        sample_interval=0.1,
        smoothing=0.5,
        queue_target=max(8, int(capacity_sigs_per_sec * 0.1)),
        sojourn_target_ms=250.0,
        sojourn_arm_s=0.3,
        shed_start=0.5,
        shed_full=0.9,
        registered_grace=0.3,
        # crowd hold-offs long enough to smear a burst's retry waves
        # over the drain's headroom; registered sheds ignore the max
        # and come back at the base (see retry_after_ms in overload.py)
        retry_after_ms=250,
        retry_after_max_ms=3000,
    )


#: measured fleet-wide verification cost of one committed transfer on a
#: 4-node net (admission preverify + every node's echo/ready attestation
#: checks) — used only to size the modeled pool relative to offered load
_OVERLOAD_SIGS_PER_TX = 33.0

#: per-workload A/B tuning: the modeled pool as a fraction of the cell's
#: average offered signature rate (<1 ⇒ the cell is overcommitted and
#: the uncontrolled arm MUST queue), and the steady-tier latency SLO.
#: hot_account runs its steady tier near saturation by design, so its
#: SLO is laxer — same reasoning as the grid's per-workload ceilings.
_OVERLOAD_WORKLOADS = {
    "flash_crowd": {"capacity_frac": 0.90, "latency_slo_ms": 2500.0},
    "hot_account": {"capacity_frac": 0.68, "latency_slo_ms": 4500.0},
}


def run_overload_cell(
    seed: int,
    workload: str = "flash_crowd",
    *,
    controlled: bool,
    nodes: int = 4,
    f: int = 1,
    n_clients: int = 60,
    crowd: int = 40,
    n_tx: int = 80,
    duration: float = 12.0,
    capacity_sigs_per_sec: float = 200.0,
    settle_horizon: float = 300.0,
    latency_slo_ms: float = 2500.0,
    fairness_floor: float = 0.8,
    retry_budget: int = 4,
    overload=None,
) -> dict:
    """One overload A/B arm: scaled workload against a finite modeled
    verifier, measured on the STEADY tier (the clients the fleet knew
    before the event — registered into the directory pre-burst). Both
    arms run the identical offered schedule (same derived rng, same sim
    seed); only the [overload] table differs, so any delta is the
    controller's doing. ``controlled=False`` runs with the table off —
    the collapse baseline the bench banks alongside the controlled arm.

    Every client retries RESOURCE_EXHAUSTED sheds up to
    ``retry_budget`` times with deterministic jittered exponential
    backoff honoring the server's ``retry_after_ms`` hint — the sim
    analog of client.py's RetryPolicy, so a shed is pacing, not loss.
    Latency is CLIENT-perceived: from the tx's originally offered time
    to its last node's commit, retry hold-offs included.

    For ``flash_crowd`` the crowd is the last ``crowd`` client indices
    (never registered, ~1 tx each); for ``hot_account`` the hot sender
    (client 0) plays the newcomer and everyone else is steady."""
    import grpc

    from ..node.config import ObservabilityConfig
    from ..node.overload import parse_retry_after_ms
    from ..tools.trace_collect import _pctl

    wall0 = time.monotonic()
    # one schedule for BOTH arms: the arm must not feed the derivation
    rng = random.Random(
        _seed_int("overload", seed, workload, n_clients, crowd, n_tx)
    )
    if workload == "flash_crowd":
        steady_ids = list(range(max(1, n_clients - crowd)))
    elif workload == "hot_account":
        steady_ids = list(range(1, n_clients))
    else:
        raise ValueError(f"no overload variant for workload {workload!r}")

    cap = max(4096, 4 * n_tx)
    overrides: dict = {
        "observability": ObservabilityConfig(
            trace_cap=cap, trace_done_cap=cap, recorder_cap=cap
        )
    }
    if controlled:
        ov = overload or overload_objectives(capacity_sigs_per_sec)
        overrides["overload"] = ov
    net = SimNet(nodes, f, seed, hostile=0, link=_INTRA, **overrides)
    net.verifier = ModeledVerifier(
        net.verifier, net.clock, capacity_sigs_per_sec
    )
    net.start()
    try:
        clients = [sim_client(seed, i) for i in range(n_clients)]

        async def _register_steady():
            for i in steady_ids:
                await net.aregister(i % nodes, clients[i].public)

        net.loop.run_until_complete(_register_steady())
        net.run_for(2.0)  # let DirectoryAnnounce gossip reach every node

        if workload == "flash_crowd":
            events = flash_crowd_workload(
                rng, nodes=nodes, n_clients=n_clients, n_tx=n_tx,
                duration=duration, crowd=crowd,
            )
        else:
            events = hot_account_workload(
                rng, nodes=nodes, n_clients=n_clients, n_tx=n_tx,
                duration=duration,
            )
        offered_by_client = [0] * n_clients
        for _t, _k, args in events:
            offered_by_client[args["client"]] += 1

        # submission driver with the client-side retry budget: shed
        # responses are retried after the server's hint, scaled by a
        # hash-derived deterministic jitter (no rng draws — draw order
        # under concurrent tasks would couple the schedule to scheduler
        # internals) and an exponential per-attempt factor. Anything
        # other than RESOURCE_EXHAUSTED is terminal.
        t_base = net.clock.monotonic()
        offered_mono: Dict[tuple, float] = {}

        async def _one(ev) -> None:
            t, _kind, a = ev
            ci, seq = a["client"], a["seq"]
            offered_mono[(clients[ci].public.hex(), seq)] = t_base + t
            await net.clock.sleep(
                max(0.0, t_base + t - net.clock.monotonic())
            )
            to = clients[a["to"]].public
            for attempt in range(retry_budget + 1):
                err = await net.asubmit(
                    a["node"], clients[ci], seq, to, a["amount"]
                )
                if err is None:
                    return
                if err.code != grpc.StatusCode.RESOURCE_EXHAUSTED:
                    return
                if attempt >= retry_budget:
                    return
                hint = parse_retry_after_ms(err.details)
                base_s = (hint if hint is not None else 250) / 1e3
                jitter = (
                    (ci * 2654435761 + seq * 40503 + attempt * 97) % 1024
                ) / 1024.0
                await net.clock.sleep(
                    min(8.0, base_s * (2.0 ** attempt) * (0.5 + jitter))
                )

        async def _drive() -> None:
            await asyncio.gather(*(
                asyncio.ensure_future(_one(ev)) for ev in events
            ))

        net.loop.run_until_complete(_drive())
        last_t = max((e[0] for e in events), default=0.0)
        settle_t = net.settle(horizon=settle_horizon)
        violations = net.check_invariants()

        # client-perceived commit latency: offered time -> the LAST
        # node's committed stamp (fleet commit), straight from the
        # per-node trace rings — retry hold-offs included, which the
        # stitched per-attempt view would hide
        commit_mono: Dict[tuple, float] = {}
        for s in net.services:
            dump = s.tracez()
            for rec in list(dump.get("completed", ())) + list(
                dump.get("live", ())
            ):
                for st, m, _w in rec["stages"]:
                    if st == "committed":
                        k = (rec["sender"], rec["seq"])
                        commit_mono[k] = max(commit_mono.get(k, 0.0), m)
        steady_pubs = {clients[i].public.hex() for i in steady_ids}
        steady_lats: List[float] = []
        all_lats: List[float] = []
        for k, m in commit_mono.items():
            t0 = offered_mono.get(k)
            if t0 is None:
                continue
            lat = m - t0
            all_lats.append(lat)
            if k[0] in steady_pubs:
                steady_lats.append(lat)
        steady_lats.sort()
        all_lats.sort()

        frontier = net.services[0].accounts.frontier_nowait()
        ratios = [
            float(frontier.get(clients[i].public, 0)) / offered_by_client[i]
            for i in steady_ids
            if offered_by_client[i] > 0
        ]
        fairness = round(jain_index(ratios), 6)
        shed = sum(
            s.overload_stats["overload_shed_entries"]
            + s.overload_stats["overload_shed_distilled"]
            for s in net.services
        )
        shed_events = sum(
            1
            for s in net.services
            for ev in s.recorder.dump()["events"]
            if ev[1] in ("overload_shed", "overload_shed_distilled")
        )
        steady_p99 = round(1e3 * _pctl(steady_lats, 0.99), 3)
        slo_ok = bool(steady_lats) and steady_p99 <= latency_slo_ms
        fairness_ok = fairness >= fairness_floor
        return {
            "workload": workload,
            "arm": "controlled" if controlled else "uncontrolled",
            "seed": seed,
            "nodes": nodes,
            "f": f,
            "n_clients": n_clients,
            "crowd": crowd if workload == "flash_crowd" else 1,
            "capacity_sigs_per_sec": capacity_sigs_per_sec,
            "modeled_sigs": net.verifier.total_sigs,
            "offered": sum(offered_by_client),
            "offered_steady": sum(offered_by_client[i] for i in steady_ids),
            "committed": min(s.committed for s in net.services),
            "committed_steady": len(steady_lats),
            "shed": shed,
            "shed_events": shed_events,
            "steady_p50_ms": round(1e3 * _pctl(steady_lats, 0.50), 3),
            "steady_p99_ms": steady_p99,
            "all_p99_ms": round(1e3 * _pctl(all_lats, 0.99), 3),
            "fairness": fairness,
            "latency_slo_ms": latency_slo_ms,
            "fairness_floor": fairness_floor,
            "slo_ok": slo_ok,
            "fairness_ok": fairness_ok,
            "virtual_time": round(last_t + 1.0 + 2.0 + settle_t, 3),
            "wall_seconds": round(time.monotonic() - wall0, 3),
            "trace_hash": net.fabric.trace_hash(),
            "violations": violations,
        }
    finally:
        net.close()


def run_overload_ab(
    seed: int,
    *,
    workloads=("flash_crowd", "hot_account"),
    n_clients: int = 120,
    crowd: int = 80,
    n_tx: int = 160,
    duration: float = 12.0,
    progress=None,
    **cell_kw,
) -> dict:
    """The BENCH_OVERLOAD.json document: each workload run uncontrolled
    then controlled against the same schedule, folded into one A/B hash
    (the determinism fingerprint the overload CI gate compares across
    same-seed runs). The bench's claim is the pair: the uncontrolled
    arm must breach the steady-tier latency SLO and the controlled arm
    must hold it while keeping fairness above the floor. The modeled
    pool is sized per workload relative to the cell's offered load
    (_OVERLOAD_WORKLOADS), so the A/B dynamics are scale-invariant —
    growing ``n_clients``/``n_tx`` grows the capacity with them."""
    offered_sig_rate = _OVERLOAD_SIGS_PER_TX * n_tx / duration
    cells: List[dict] = []
    for w in workloads:
        tune = _OVERLOAD_WORKLOADS[w]
        for controlled in (False, True):
            cell = run_overload_cell(
                seed, w, controlled=controlled,
                n_clients=n_clients, crowd=crowd, n_tx=n_tx,
                duration=duration,
                capacity_sigs_per_sec=round(
                    tune["capacity_frac"] * offered_sig_rate, 3
                ),
                latency_slo_ms=tune["latency_slo_ms"],
                **cell_kw,
            )
            cells.append(cell)
            if progress is not None:
                progress(cell)
    h = hashlib.sha256()
    for cell in cells:
        h.update(cell["trace_hash"].encode())
    ok = all(
        (c["slo_ok"] and c["fairness_ok"])
        if c["arm"] == "controlled"
        else not c["slo_ok"]
        for c in cells
    ) and not any(c["violations"] for c in cells)
    return {
        "bench": "overload_ab",
        "seed": seed,
        "cells": cells,
        "ab_hash": h.hexdigest(),
        "ok": bool(ok),
    }


__all__ = [
    "FAULT_MIXES",
    "GRID",
    "SMOKE",
    "TOPOLOGIES",
    "WAN_GRID",
    "WORKLOADS",
    "ModeledVerifier",
    "apply_topology",
    "cell_objectives",
    "fault_events",
    "jain_index",
    "overload_objectives",
    "run_cell",
    "run_grid",
    "run_overload_ab",
    "run_overload_cell",
]
