"""Seeded hostile-frame generation, shared by the live-socket byzantine
fuzz campaign (tests/test_byzantine_fuzz.py) and the simulated fabric
campaigns (`at2_node_tpu.sim.campaign`).

``HostileFrameGen`` is the pure part of the fuzzer: an authenticated
byzantine peer's frame builders — valid-but-conflicting attestations,
batch equivocation, poison batches, oversized bitmaps, catchup-plane
junk, truncations, verbatim replays — driven entirely by an injected
``random.Random``. It never touches a socket; the live test wraps it
with transport channels, the simulator feeds its frames through
``SimFabric.inject``.

Client/recipient identities are derived from the rng (not
``SignKeyPair.random()``), so a `(seed, config)` pair fixes the entire
hostile byte stream — the property exact replay rests on.
"""

from __future__ import annotations

import random
import struct

from ..broadcast.messages import (
    BATCH_ECHO,
    BATCH_READY,
    ECHO,
    READY,
    Attestation,
    BatchAttestation,
    CertSig,
    ContentRequest,
    DirectoryAnnounce,
    HistoryBatch,
    HistoryIndexRequest,
    HistoryRequest,
    Payload,
    TxBatch,
)
from ..crypto.keys import SignKeyPair
from ..types import ThinTransaction


def mutate_distilled_frame(frame: bytes, rng: random.Random) -> bytes:
    """One hostile mutation of a well-formed distilled-batch frame
    (proto/distill.py). Used by the codec fuzz tests (differential: the
    Python and native parsers must agree on every mutant) and by the
    byzantine-broker campaign's "garbage" mutation. Mutants are not
    guaranteed malformed — a flip inside the signature block decodes
    fine and must then fail per-entry verification instead — which is
    exactly the coverage a corrupting broker needs."""
    choice = rng.randrange(6)
    b = bytearray(frame)
    if choice == 0 and b:  # magic / version stomp
        b[rng.randrange(min(2, len(b)))] ^= 0xFF
    elif choice == 1 and len(b) > 1:  # truncation
        del b[rng.randint(1, len(b) - 1):]
    elif choice == 2:  # trailing junk (length checks must catch it)
        b.extend(rng.getrandbits(8) for _ in range(rng.randint(1, 64)))
    elif choice == 3 and len(b) > 3:  # single bit flip anywhere past magic
        b[rng.randrange(2, len(b))] ^= 1 << rng.randrange(8)
    elif choice == 4 and len(b) > 4:  # stomp the count varints
        b[2] = rng.choice((0x00, 0x7F, 0x80, 0xFF))
        b[3] = rng.choice((0x00, 0x7F, 0x80, 0xFF))
    else:  # pure garbage
        return bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 200)))
    return bytes(b)


def mutate_cert_frame(frame: bytes, rng: random.Random) -> bytes:
    """One hostile mutation of a well-formed kind-16 certificate
    co-signature frame (broadcast/messages.py CertSig). Same contract
    as :func:`mutate_distilled_frame`: mutants are not guaranteed
    malformed — a flip inside the 64-byte signature tail parses fine
    and must then fail the assembler's per-cosig verification
    (``bad_sig``), while kind stomps and truncations must die in the
    frame parser without desyncing the frames behind them."""
    choice = rng.randrange(6)
    b = bytearray(frame)
    if choice == 0 and b:  # kind stomp: reroute to another parser
        b[0] ^= rng.choice((0x01, 0x10, 0xFF))
    elif choice == 1 and len(b) > 1:  # truncation
        del b[rng.randint(1, len(b) - 1):]
    elif choice == 2:  # trailing junk (wire-size discipline must catch)
        b.extend(rng.getrandbits(8) for _ in range(rng.randint(1, 64)))
    elif choice == 3 and len(b) > 65:  # body flip: epoch/wm/ranges/dir
        b[rng.randrange(1, len(b) - 64)] ^= 1 << rng.randrange(8)
    elif choice == 4 and len(b) >= 64:  # signature flip: parses, bad sig
        b[rng.randrange(len(b) - 64, len(b))] ^= 1 << rng.randrange(8)
    else:  # pure garbage
        return bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 300)))
    return bytes(b)


def _rng_keypair(rng: random.Random) -> SignKeyPair:
    return SignKeyPair(bytes(rng.getrandbits(8) for _ in range(32)))


class SaltingClientGen:
    """Batch-poisoning byzantine CLIENT: emits bulk-ingress flushes that
    look honest except ``k_bad`` bad-signature entries at adversarial
    positions — spread so every bisection level has to split a bad pair,
    the worst case for an amortized (RLC) batch verifier. The bad entries
    are REAL signatures with one flipped bit in ``s``: R still decodes
    and is torsion-free, so they survive every cheap classification and
    force the batch equation itself to fail.

    Pure like :class:`HostileFrameGen`: seeded rng in, deterministic
    flush specs out; the sim feeds them through the real
    ``SendAssetBatch`` handler (`SimNet.asubmit_batch`)."""

    def __init__(self, rng: random.Random, k_bad: int = 3):
        self.rng = rng
        self.k_bad = k_bad
        self.key = _rng_keypair(rng)
        self.recipient = _rng_keypair(rng).public
        self._seq = 0

    def positions(self, size: int) -> list:
        """Adversarial placement: endpoints plus an even spread, so the
        bad lanes land in different bisection halves at every depth."""
        k = min(self.k_bad, size)
        if k <= 0:
            return []
        if k == 1:
            return [0]
        return sorted({round(i * (size - 1) / (k - 1)) for i in range(k)})

    def next_flush(self, size: int) -> list:
        """``(sequence, recipient, amount, good_sig)`` rows for one
        salted flush. Sequences advance monotonically — the honest-
        looking entries are individually committable, which is exactly
        what makes the salting adversarial (all-or-nothing admission
        burns them alongside the poison)."""
        bad = set(self.positions(size))
        rows = []
        for j in range(size):
            self._seq += 1
            rows.append(
                (
                    self._seq,
                    self.recipient,
                    1 + self.rng.randint(0, 9),
                    j not in bad,
                )
            )
        return rows


class HostileFrameGen:
    """Authenticated byzantine peer emitting seeded random frame salvos."""

    def __init__(self, sign_key: SignKeyPair, rng: random.Random):
        self.sign = sign_key
        self.rng = rng
        self.sent_log = []  # replay source
        # identities this fuzzer signs client payloads with
        self.clients = [_rng_keypair(rng) for _ in range(3)]
        self.recipients = [_rng_keypair(rng).public for _ in range(3)]
        self.batches = []  # real TxBatches sent: targets for oversized bitmaps

    # -- frame builders ---------------------------------------------------

    def _payload(self, client, seq, recipient, amount, good_sig=True):
        tx = ThinTransaction(recipient, amount)
        if good_sig:
            return Payload.create(client, seq, tx)
        sig = bytes(self.rng.getrandbits(8) for _ in range(64))
        return Payload(client.public, seq, tx, sig)

    def _rand_payload(self):
        rng = self.rng
        return self._payload(
            rng.choice(self.clients),
            rng.randint(1, 4),
            rng.choice(self.recipients),
            rng.randint(1, 50),
            good_sig=rng.random() > 0.25,
        )

    def _rand_batch(self):
        rng = self.rng
        entries = b"".join(
            self._rand_payload().encode()[1:]
            for _ in range(rng.randint(1, 6))
        )
        batch = TxBatch.create(self.sign, rng.randint(1, 5), entries)
        self.batches.append(batch)
        return batch

    def _poison_batch(self):
        """A batch GUARANTEED to carry at least one never-verifiable
        entry among honest-looking ones — the poison-slot resolution
        path's bread and butter (slot must retire, never stall)."""
        rng = self.rng
        payloads = [self._rand_payload() for _ in range(rng.randint(1, 4))]
        payloads.insert(
            rng.randrange(len(payloads) + 1),
            self._payload(
                rng.choice(self.clients),
                rng.randint(1, 4),
                rng.choice(self.recipients),
                rng.randint(1, 50),
                good_sig=False,
            ),
        )
        entries = b"".join(p.encode()[1:] for p in payloads)
        batch = TxBatch.create(self.sign, rng.randint(1, 5), entries)
        self.batches.append(batch)
        return batch

    def _oversized_batch_attestation(self):
        """A correctly signed attestation for a REAL previously-sent
        batch whose bitmap claims far more entries than the batch has:
        exercises the width clamp (phantom bits must not grow nbits or
        spuriously quorate). Falls back to a random one before any batch
        exists."""
        rng = self.rng
        if not self.batches:
            return self._rand_batch_attestation()
        batch = rng.choice(self.batches)
        phase = rng.choice((BATCH_ECHO, BATCH_READY))
        bitmap = bytes(
            rng.getrandbits(8) | 1 for _ in range(rng.choice((16, 64, 128)))
        )
        sig = self.sign.sign(
            BatchAttestation.signing_bytes(
                phase, batch.origin, batch.batch_seq, batch.content_hash(), bitmap
            )
        )
        return BatchAttestation(
            phase,
            self.sign.public,
            batch.origin,
            batch.batch_seq,
            batch.content_hash(),
            bitmap,
            sig,
        )

    def _rand_attestation(self):
        rng = self.rng
        phase = rng.choice((ECHO, READY))
        sender = rng.choice(self.clients).public
        seq = rng.randint(1, 4)
        chash = (
            self._rand_payload().content_hash()
            if rng.random() < 0.6
            else bytes(rng.getrandbits(8) for _ in range(32))
        )
        sig = self.sign.sign(
            Attestation.signing_bytes(phase, sender, seq, chash)
        )
        return Attestation(phase, self.sign.public, sender, seq, chash, sig)

    def targeted_attestation(self, phase, sender, seq, chash):
        """A correctly signed attestation for an EXACT (sender, seq,
        content) — the building block of split-vote schedules, where the
        hostile peer vouches for different contents to different nodes."""
        sig = self.sign.sign(
            Attestation.signing_bytes(phase, sender, seq, chash)
        )
        return Attestation(phase, self.sign.public, sender, seq, chash, sig)

    def _rand_batch_attestation(self):
        rng = self.rng
        phase = rng.choice((BATCH_ECHO, BATCH_READY))
        b_origin = self.sign.public
        b_seq = rng.randint(1, 5)
        b_hash = bytes(rng.getrandbits(8) for _ in range(32))
        bitmap = bytes(
            rng.getrandbits(8) for _ in range(rng.choice((1, 2, 16, 128)))
        )
        sig = self.sign.sign(
            BatchAttestation.signing_bytes(phase, b_origin, b_seq, b_hash, bitmap)
        )
        return BatchAttestation(
            phase, self.sign.public, b_origin, b_seq, b_hash, bitmap, sig
        )

    def _rand_catchup_junk(self):
        rng = self.rng
        kind = rng.randrange(4)
        if kind == 0:
            return HistoryIndexRequest(rng.getrandbits(64))
        if kind == 1:
            return HistoryRequest(
                rng.getrandbits(64),
                rng.choice(self.clients).public,
                1,
                rng.randint(1, 1 << 20),  # absurd range: server must clamp
            )
        if kind == 2:
            return HistoryBatch(
                rng.getrandbits(64),
                tuple(self._rand_payload() for _ in range(rng.randint(1, 4))),
            )
        return ContentRequest(
            rng.choice(self.clients).public,
            rng.randint(1, 4),
            bytes(rng.getrandbits(8) for _ in range(32)),
        )

    def _rand_dir_announce(self):
        """Directory-poisoning attempts: out-of-stride ids, zero keys,
        rebinding collisions. All liveness-only by the trust argument
        (node/directory.py) — the receiver's stride check and
        first-binding-wins rule drop or defang every one of these."""
        rng = self.rng
        entries = tuple(
            (
                rng.getrandbits(rng.choice((4, 16, 62))),
                (
                    b"\x00" * 32
                    if rng.random() < 0.2
                    else bytes(rng.getrandbits(8) for _ in range(32))
                ),
            )
            for _ in range(rng.randint(0, 5))
        )
        return DirectoryAnnounce(self.sign.public, entries)

    def _malformed(self) -> bytes:
        rng = self.rng
        choice = rng.randrange(4)
        if choice == 0:  # unknown kind
            return bytes([rng.randint(14, 255)]) + bytes(
                rng.getrandbits(8) for _ in range(rng.randint(0, 64))
            )
        if choice == 1:  # truncated known message
            full = self._rand_payload().encode()
            return full[: rng.randint(1, len(full) - 1)]
        if choice == 2:  # batch header with an absurd count field
            b = bytearray(self._rand_batch().encode())
            b[41:45] = struct.pack("<I", rng.randint(1025, 1 << 30))
            return bytes(b)
        # random garbage
        return bytes(rng.getrandbits(8) for _ in range(rng.randint(1, 200)))

    def next_frame(self) -> bytes:
        rng = self.rng
        roll = rng.random()
        if roll < 0.22:
            msgs = [self._rand_payload() for _ in range(rng.randint(1, 3))]
            frame = b"".join(m.encode() for m in msgs)
        elif roll < 0.34:
            frame = self._rand_batch().encode()
        elif roll < 0.42:
            frame = self._poison_batch().encode()
        elif roll < 0.58:
            frame = self._rand_attestation().encode()
        elif roll < 0.68:
            frame = self._rand_batch_attestation().encode()
        elif roll < 0.75:
            frame = self._oversized_batch_attestation().encode()
        elif roll < 0.84:
            frame = self._rand_catchup_junk().encode()
        elif roll < 0.89:
            frame = self._rand_dir_announce().encode()
        elif roll < 0.95 and self.sent_log:
            frame = rng.choice(self.sent_log)  # verbatim replay
        else:
            frame = self._malformed()
        self.sent_log.append(frame)
        return frame


class CertAdversary:
    """Byzantine fleet MEMBER attacking the finality-certificate lane
    (finality/certs.py): its sign key is in the epoch member set, so
    its kind-16 co-signatures verify — the attacks below are exactly
    the ones a single compromised member can mount, and the assembler
    must defang every one without help from the honest majority.

    Pure like the other generators: seeded rng in, deterministic frames
    out; the sim injects them through ``SimFabric.inject``."""

    def __init__(self, sign_key: SignKeyPair, rng: random.Random):
        self.sign = sign_key
        self.rng = rng

    def _digests(self):
        rng = self.rng
        wm = bytes(rng.getrandbits(8) for _ in range(16))
        ranges = bytes(rng.getrandbits(8) for _ in range(128))
        dird = bytes(rng.getrandbits(8) for _ in range(8))
        return wm, ranges, dird

    def equivocating_pair(self, epoch: int = 0) -> tuple:
        """Two VALIDLY SIGNED co-signatures for the same (epoch,
        watermark) naming different ledger states — cryptographic
        equivocation. The receiving assembler must latch the culprit
        with both signed statements, and neither statement may ever
        reach a certificate (an honest quorum never co-signs either
        fabricated state)."""
        wm, ranges, dird = self._digests()
        commits = self.rng.getrandbits(16)
        a = CertSig.create(self.sign, epoch, commits, wm, ranges, dird)
        ranges2 = bytes(x ^ 0xFF for x in ranges)
        b = CertSig.create(self.sign, epoch, commits, wm, ranges2, dird)
        return a.encode(), b.encode()

    def off_epoch(self, epoch: int) -> bytes:
        """A validly signed co-signature at a stale (or future) epoch:
        counted as ``epoch_skew`` and never bucketed — a pre-reconfig
        member cannot vote under the new epoch's quorum rule."""
        wm, ranges, dird = self._digests()
        return CertSig.create(
            self.sign, epoch, self.rng.getrandbits(16), wm, ranges, dird
        ).encode()

    def forged(self, epoch: int = 0) -> bytes:
        """A well-formed frame whose signature is garbage: survives the
        wire parser, must die at the assembler's scheme verification
        (``bad_sig``)."""
        wm, ranges, dird = self._digests()
        sig = bytes(self.rng.getrandbits(8) for _ in range(64))
        return CertSig(
            self.sign.public, epoch, self.rng.getrandbits(16),
            wm, ranges, dird, sig,
        ).encode()

    def mutant(self, epoch: int = 0) -> bytes:
        """A mutated kind-16 frame (wire fuzz: parser robustness)."""
        base = self.off_epoch(epoch) if self.rng.random() < 0.5 else (
            self.forged(epoch)
        )
        return mutate_cert_frame(base, self.rng)
