"""Virtual-time event loop: the heart of the deterministic simulator.

``SimScheduler`` subclasses ``asyncio.SelectorEventLoop`` with a
selector that never touches the OS: ``select(timeout)`` *is* the
passage of time. When asyncio computes "nothing runnable for the next
``timeout`` seconds" the virtual selector advances ``loop.time()`` by
exactly that much and returns no I/O events — so an episode's worth of
GC ticks, retransmission timers, catchup windows, and flush delays
execute back-to-back in microseconds of real time, in a total order
fixed entirely by the schedule.

Determinism notes:

* asyncio's ready queue is FIFO and its timer heap tie-breaks equal
  deadlines with a monotonic insertion counter, so callback order is a
  pure function of the schedule — no randomness to pin down here. Seeded
  tie-breaking for *network* events lives in the fabric (per-delivery
  jitter drawn from the episode rng).
* ``run_in_executor`` executes the function INLINE and returns an
  already-completed future: the CPU verifier's thread pool, checkpoint
  ``asyncio.to_thread`` saves, and jax warmup all become synchronous
  and ordered. Nothing in the sim ever runs off-loop.
* ``time()`` starts at :data:`SIM_START` (not 0.0): several components
  use ``0.0`` as a "never happened" sentinel (e.g. a slot's
  ``content_requested_at``), and a virtual epoch of zero would alias
  those.
* A ``select(None)`` — no runnable callbacks AND no timers — can never
  make progress in virtual time; it raises :class:`SimDeadlockError`
  instead of hanging, turning a lost-wakeup bug into a test failure.
"""

from __future__ import annotations

import asyncio
import selectors

# Virtual monotonic epoch. Nonzero so "stamp == 0.0 means unset"
# sentinels in the production code never collide with a real sim stamp.
SIM_START = 1000.0

# Virtual wall-clock epoch (2026-01-01T00:00:00Z). Only uniqueness
# matters to the code under test (batch_seq derivation).
SIM_WALL_EPOCH = 1_767_225_600.0


class SimDeadlockError(RuntimeError):
    """The loop would wait forever: no ready callbacks and no timers."""


class _VirtualSelector(selectors.BaseSelector):
    """A selector whose ``select`` advances virtual time.

    File registrations (the event loop's internal self-pipe, mostly)
    are recorded but never polled: no simulated component owns a real
    socket, and the inline executor means no thread ever needs the
    self-pipe wakeup.
    """

    def __init__(self, advance) -> None:
        self._advance = advance
        self._fd_to_key: dict = {}

    def register(self, fileobj, events, data=None):
        key = selectors.SelectorKey(
            fileobj, self._fileobj_fd(fileobj), events, data
        )
        self._fd_to_key[key.fd] = key
        return key

    def unregister(self, fileobj):
        return self._fd_to_key.pop(self._fileobj_fd(fileobj))

    def modify(self, fileobj, events, data=None):
        self.unregister(fileobj)
        return self.register(fileobj, events, data)

    def select(self, timeout=None):
        if timeout is None:
            raise SimDeadlockError(
                "simulation deadlock: no runnable callbacks and no timers"
                " — every task is awaiting an event nothing will fire"
            )
        if timeout > 0:
            self._advance(timeout)
        return []

    def close(self) -> None:
        self._fd_to_key.clear()

    def get_map(self):
        return {key.fileobj: key for key in self._fd_to_key.values()}

    @staticmethod
    def _fileobj_fd(fileobj) -> int:
        return fileobj if isinstance(fileobj, int) else fileobj.fileno()


class SimScheduler(asyncio.SelectorEventLoop):
    """Deterministic virtual-time asyncio loop.

    Drive it like any loop: ``loop.run_until_complete(coro)``. A
    convenience ``run_for(duration)`` advances virtual time by exactly
    ``duration``, executing everything scheduled inside the window.
    """

    def __init__(self, start: float = SIM_START) -> None:
        self._sim_now = start
        super().__init__(_VirtualSelector(self._advance_time))

    # -- virtual time ------------------------------------------------------

    def time(self) -> float:
        return self._sim_now

    def _advance_time(self, delta: float) -> None:
        self._sim_now += delta

    def run_for(self, duration: float) -> None:
        """Advance virtual time by ``duration``, running all work due."""
        self.run_until_complete(asyncio.sleep(duration))

    # -- no real threads ---------------------------------------------------

    def run_in_executor(self, executor, func, *args):
        fut = self.create_future()
        try:
            fut.set_result(func(*args))
        except BaseException as exc:  # delivered through the future
            fut.set_exception(exc)
        return fut


class SimClock:
    """The injectable clock (see ``at2_node_tpu.clock``) bound to a
    :class:`SimScheduler`: ``monotonic()`` is the loop's virtual time,
    ``wall()`` offsets it to a fixed virtual epoch, and ``sleep``
    suspends in virtual time via the loop's timer heap."""

    def __init__(self, loop: SimScheduler) -> None:
        self._loop = loop
        self._wall_offset = SIM_WALL_EPOCH - loop.time()

    def monotonic(self) -> float:
        return self._loop.time()

    def wall(self) -> float:
        return self._wall_offset + self._loop.time()

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)
