"""Deterministic simulation harness for the AT2 stack.

FoundationDB-style discrete-event simulation: the REAL node logic
(broadcast planes, service commit tail, catchup, admission) runs
unmodified under a virtual clock and a simulated network fabric — no
real sockets, no real sleeps, no wall-clock time. A whole multi-node
adversarial episode (partitions, loss, byzantine frames, equivocating
clients) executes in milliseconds and, given the same ``(seed,
config)``, replays bit-identically.

Layout:

* :mod:`.scheduler` — ``SimScheduler`` (a virtual-time asyncio event
  loop) and ``SimClock`` (the injectable clock bound to it);
* :mod:`.fabric`    — ``SimFabric`` / ``SimMesh`` / ``SimChannel``:
  the simulated network with per-link latency/loss/duplication,
  partitions, a byzantine interposer hook, and full event tracing;
* :mod:`.hostile`   — ``HostileFrameGen``: seeded hostile-frame
  generators (shared with the live-socket byzantine fuzz tests);
* :mod:`.net`       — ``SimNet``: an n-node f-tolerant network of real
  ``Service`` cores plus the AT2 invariant checker;
* :mod:`.campaign`  — seeded episode generation, campaign runner,
  exact replay, and greedy trace minimization.

Entry point: ``python -m at2_node_tpu.tools.sim_run`` (see README).
"""

from .campaign import (  # noqa: F401
    EpisodeResult,
    generate_events,
    minimize_events,
    run_campaign,
    run_episode,
)
from .fabric import LinkModel, SimChannel, SimFabric, SimMesh  # noqa: F401
from .hostile import HostileFrameGen  # noqa: F401
from .net import InvariantViolation, SimNet  # noqa: F401
from .scheduler import SimClock, SimDeadlockError, SimScheduler  # noqa: F401
