"""Untrusted broker ingress tier (Chop Chop-style batch distillation).

The broker sits between clients and a node and converts many small
per-transfer submissions into few distilled `SendDistilledBatch` frames
(proto/distill.py): sorted delta-coded client-ids, deduped senders,
columnar signatures. It serves the same `at2.AT2` gRPC surface a node
does, so existing clients point at a broker unmodified — submissions are
collected, everything else proxies through to the node.

Trust argument (TECHNICAL.md "Directory & broker ingress"): the broker
is OUTSIDE the trust boundary. Every entry it forwards is signed by its
client over the v2 tagged transfer form (types.py
``transfer_signing_bytes``) which binds sender AND sequence — so a
captured signature is valid for exactly one ledger slot, and the broker
cannot re-encode it at another sequence to spend again (nor, of course,
alter recipient or amount). The node verifies per entry against the
gossiped directory; what remains to a byzantine broker is liveness-only:
withhold, reorder, or duplicate-within-one-slot (bounded by the node's
dedup memory and the ledger's per-account sequence gate). It also cannot
shift blame for bad signatures onto other clients: admission buckets at
the node are keyed by CLIENT id, not broker identity.

The broker auto-registers unknown sender keys via the node's `Register`
RPC and compresses recipient keys to directory ids when it knows them,
so a warmed-up broker emits near-minimal frames.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, List, Optional

import grpc

from .client import _target
from .crypto.keys import SignKeyPair  # noqa: F401  (re-export for runners)
from .net.webmux import PortMux
from .node.config import OverloadConfig
from .node.overload import broker_retry_after_ms, format_shed_details
from .obs.recorder import FlightRecorder
from .obs.registry import Registry
from .obs.trace import TxTrace
from .proto import at2_pb2 as pb
from .proto import distill
from .proto.rpc import At2Servicer, At2Stub, add_to_server

logger = logging.getLogger(__name__)

# Entries buffered while the node is unreachable or the builder lags.
# Beyond the cap new submissions are refused (RESOURCE_EXHAUSTED) — an
# unbounded buffer would turn a dead node into broker OOM.
PENDING_CAP = 1 << 16

# /healthz flips to "degraded" when the pending buffer crosses this
# fraction of PENDING_CAP: overflow refusals are imminent, so fleet
# tooling (top.py --once) should gate BEFORE clients start seeing
# RESOURCE_EXHAUSTED, not after.
BACKPRESSURE_FRAC = 0.75

# [wan] eager flush never shrinks the deadline below this fraction of
# the configured window: a lone straggler still gets a quarter window
# of batching opportunity instead of flushing as a singleton frame.
EAGER_MIN_FRAC = 0.25


class Broker(At2Servicer):
    """One broker. `await Broker.start(...)`, then `serve_forever`."""

    def __init__(
        self,
        node_uri: str,
        *,
        max_entries: int = distill.DISTILL_MAX_ENTRIES,
        window: float = 0.005,
        eager: bool = False,
        clock=None,
        trace_sample: int = 1,
        recorder_cap: int = 2048,
        overload: Optional[OverloadConfig] = None,
    ) -> None:
        from .clock import SYSTEM_CLOCK

        if not (1 <= max_entries <= distill.DISTILL_MAX_ENTRIES):
            raise ValueError(
                f"max_entries must be in [1, {distill.DISTILL_MAX_ENTRIES}]"
            )
        self.node_uri = node_uri
        self.max_entries = max_entries
        self.window = window
        # graduated brownout ([overload], node/overload.py): above
        # brownout_frac of PENDING_CAP flush deadlines shrink (the eager
        # machinery below), above refuse_frac new submissions are
        # refused with a retry-after hint — the drop-at-cap cliff
        # becomes a ladder. None/disabled keeps the historical behavior
        # (hard cap only), though refusals are typed either way.
        self.overload = overload if overload is not None and overload.enabled \
            else None
        self._retry_cfg = overload if overload is not None else OverloadConfig()
        # [wan] eager flush: anchor the flush deadline to the FIRST entry
        # of the pending batch instead of restarting a full window on
        # every delayed-flush cycle, and shrink it as the buffer fills —
        # a near-full buffer has little batching left to gain from
        # waiting, so it ships early. Off (default) keeps the fixed
        # window verbatim.
        self.eager = eager
        self._first_at = 0.0
        self.clock = SYSTEM_CLOCK if clock is None else clock
        self._channel = grpc.aio.insecure_channel(_target(node_uri))
        self._stub = At2Stub(self._channel)
        self._ids: Dict[bytes, int] = {}  # pubkey -> directory client-id
        self._keys: Dict[int, bytes] = {}  # directory client-id -> pubkey
        self._buf: List[distill.DistilledEntry] = []
        self._flush_task: Optional[asyncio.Task] = None
        self._closing = False
        self._grpc_server: Optional[grpc.aio.Server] = None
        self._mux: Optional[PortMux] = None
        self._started_at = self.clock.monotonic()
        self._health_was_ok = True

        self.registry = Registry()
        self.stats = self.registry.counter_group(
            (
                "broker_entries_rx",  # transfers accepted into the buffer
                "broker_entries_tx",  # transfers forwarded inside frames
                "broker_batches_tx",  # distilled frames forwarded
                "broker_dedup_drops",  # (id, seq) dups dropped at build
                "broker_overflow_drops",  # hard-shed: buffer hit PENDING_CAP
                "broker_refusals",  # refused BEFORE buffering (retryable)
                "broker_forward_errors",  # SendDistilledBatch RPC failures
                "broker_registrations",  # Register round-trips to the node
                "broker_eager_flushes",  # flushes taken on the eager path
                "broker_brownout_flushes",  # deadline-shrunk brownout flushes
            )
        )
        # seconds from flush trigger to frame handed to the RPC stack:
        # the distillation cost a broker adds over direct submission
        self.h_build = self.registry.histogram(
            "broker_build_latency", "distilled frame build seconds"
        )
        self.registry.gauge(
            "broker_pending", "entries buffered awaiting a flush",
            fn=lambda: len(self._buf),
        )
        self.registry.gauge(
            "broker_directory_known", "client ids cached from Register",
            fn=lambda: len(self._ids),
        )
        self.registry.register_provider(
            "rpc_",
            lambda: self._mux.stats() if self._mux is not None else {},
        )
        # Relay-only lifecycle tracer: the broker never calls begin() —
        # broker_rx/broker_flush stamps open relay spans via the SAME
        # keyed lottery the nodes use, so trace_collect joins the
        # client→broker→node→commit timeline fleet-wide. Custody ends at
        # flush, so records retire there and populate GET /tracez.
        self.tx_trace = TxTrace(
            self.registry,
            sample_every=trace_sample,
            clock=self.clock,
            retire_at="broker_flush",
        )
        # Black box for the broker's only two interesting decisions:
        # when it flushed (and how big) and when it pushed back.
        self.recorder = FlightRecorder(cap=recorder_cap, clock=self.clock)
        self.registry.gauge(
            "recorder_events", "flight-recorder events currently in the ring",
            fn=lambda: self.recorder.recorded,
        )
        self.registry.gauge(
            "recorder_snapshots", "flight-recorder snapshots frozen",
            fn=lambda: self.recorder.snapshots_taken,
        )

    # -- lifecycle --------------------------------------------------------

    @staticmethod
    async def start(
        node_uri: str,
        listen: str,
        *,
        max_entries: int = distill.DISTILL_MAX_ENTRIES,
        window: float = 0.005,
        eager: bool = False,
        clock=None,
        overload: Optional[OverloadConfig] = None,
    ) -> "Broker":
        """Bring up a broker serving `at2.AT2` on ``listen`` (same
        PortMux surface as a node: native gRPC + grpc-web + GET
        /metrics), collecting for the node at ``node_uri``."""
        broker = Broker(
            node_uri, max_entries=max_entries, window=window, eager=eager,
            clock=clock, overload=overload,
        )
        try:
            server = grpc.aio.server()
            add_to_server(broker, server)
            broker._grpc_server = server
            internal_port = server.add_insecure_port("127.0.0.1:0")
            if internal_port == 0:
                raise OSError("cannot bind internal grpc port")
            await server.start()
            broker._mux = PortMux(listen, internal_port, broker)
            try:
                await broker._mux.start()
            except OSError as exc:
                raise OSError(f"cannot bind broker address {listen}") from exc
        except BaseException:
            await broker.close()
            raise
        logger.info("broker up: rpc on %s -> node %s", listen, node_uri)
        return broker

    async def serve_forever(self) -> None:
        await self._grpc_server.wait_for_termination()

    async def close(self) -> None:
        self._closing = True
        if self._mux is not None:
            await self._mux.close()
        if self._grpc_server is not None:
            try:
                await self._grpc_server.stop(grace=0.5)
            except Exception:
                logger.exception("broker grpc server stop failed")
        if self._flush_task is not None:
            self._flush_task.cancel()
            try:
                await self._flush_task
            except asyncio.CancelledError:
                pass
        # best-effort final flush: like a node's ingress buffer, ACKed
        # submissions are not commit receipts and may drop on shutdown,
        # but draining what we can costs one RPC
        if self._buf:
            try:
                await self._flush()
            except Exception:
                logger.exception("broker final flush failed")
        await self._channel.close()

    # -- observability (PortMux GET surface, duck-typed) ------------------

    _OBS_JSON = "application/json; charset=utf-8"
    _OBS_PROM = "text/plain; version=0.0.4; charset=utf-8"

    def health_verdict(self) -> dict:
        """Liveness + backpressure verdict. A broker has no quorum to
        watch; what can go wrong is exactly one thing — the pending
        buffer filling because the node is unreachable or lagging — so
        "degraded" means overflow refusals are imminent
        (pending >= BACKPRESSURE_FRAC * PENDING_CAP). Transitions out of
        "ok" freeze a flight-recorder snapshot, same edge-trigger
        contract as the node."""
        pending = len(self._buf)
        ratio = pending / PENDING_CAP
        backpressure = pending >= int(PENDING_CAP * BACKPRESSURE_FRAC)
        brownout = (
            self.overload is not None and ratio >= self.overload.brownout_frac
        )
        if self._closing:
            status = "closing"
        elif backpressure:
            status = "degraded"
        elif brownout:
            # deadline-shrinking/refusing but still serving: the
            # "overloaded" grade is NOT a 503 — pulling a browning-out
            # broker from rotation only concentrates the crowd
            status = "overloaded"
        else:
            status = "ok"
        ok = status in ("ok", "overloaded")
        if self._health_was_ok and not ok:
            self.recorder.snapshot(f"broker_degraded:{status}")
        self._health_was_ok = ok
        return {
            "status": status,
            "role": "broker",
            "node": self.node_uri,
            "pending": pending,
            "pending_cap": PENDING_CAP,
            "backpressure": backpressure,
            "pressure": round(ratio, 4),
            "brownout": brownout,
            "flush_p99_ms": self.h_build.snapshot()["p99_ms"],
            "uptime_s": round(self.clock.monotonic() - self._started_at, 3),
        }

    def pressure_block(self) -> dict:
        """The /statusz ``pressure`` block, broker flavor: the broker's
        only pressure signal is its buffer-fill ratio, so the block is
        the ladder position derived from it."""
        ratio = len(self._buf) / PENDING_CAP
        ov = self.overload
        if self._closing:
            level = "closing"
        elif ratio >= 1.0:
            level = "saturated"
        elif ov is not None and ratio >= ov.refuse_frac:
            level = "refusing"
        elif ov is not None and ratio >= ov.brownout_frac:
            level = "brownout"
        else:
            level = "normal"
        return {
            "enabled": ov is not None,
            "pressure": round(ratio, 4),
            "level": level,
            "retry_after_ms": broker_retry_after_ms(self._retry_cfg, ratio),
            "brownout_frac": self._retry_cfg.brownout_frac,
            "refuse_frac": self._retry_cfg.refuse_frac,
        }

    def tracez(self, limit: int | None = None) -> dict:
        """Broker-side trace dump in the shape trace_collect expects
        (one dump per party, keyed by a fleet-unique "node" label)."""
        out = self.tx_trace.tracez(limit)
        out["node"] = f"broker:{self.node_uri}"
        out["clock"] = {
            "monotonic": round(self.clock.monotonic(), 9),
            "wall": round(self.clock.wall(), 9),
        }
        return out

    def obs_http(self, path: str):
        route, _, query = path.partition("?")
        if route == "/metrics":
            return 200, self._OBS_PROM, self.registry.render_prometheus().encode()
        if route == "/healthz":
            verdict = self.health_verdict()
            status = 200 if verdict["status"] in ("ok", "overloaded") else 503
            return status, self._OBS_JSON, json.dumps(verdict, sort_keys=True).encode()
        if route == "/statusz":
            body = json.dumps(
                {
                    "role": "broker",
                    "health": self.health_verdict(),
                    "pressure": self.pressure_block(),
                    "flush": self.h_build.snapshot(),
                    "stats": self.registry.snapshot(),
                },
                sort_keys=True,
                default=float,
            ).encode()
            return 200, self._OBS_JSON, body
        if route == "/tracez":
            limit = None
            if query.startswith("limit="):
                try:
                    limit = int(query[6:])
                except ValueError:
                    limit = None
            body = json.dumps(
                self.tracez(limit), sort_keys=True, default=float
            ).encode()
            return 200, self._OBS_JSON, body
        if route == "/debugz":
            body = json.dumps(
                {
                    "node": f"broker:{self.node_uri}",
                    "recorder": self.recorder.dump(),
                },
                sort_keys=True,
                default=float,
            ).encode()
            return 200, self._OBS_JSON, body
        return None

    # -- collection -------------------------------------------------------

    async def _client_id(self, pubkey: bytes) -> int:
        """The directory id for ``pubkey``, registering it with the node
        on first sight. Concurrent first-sights race benignly: Register
        is idempotent on the node, last writer caches the same id."""
        cid = self._ids.get(pubkey)
        if cid is None:
            reply = await self._stub.Register(
                pb.RegisterRequest(public_key=pubkey)
            )
            cid = int(reply.client_id)
            self._ids[pubkey] = cid
            self._keys[cid] = pubkey
            self.stats["broker_registrations"] += 1
        return cid

    def _refuse_retry_ms(self) -> int:
        return broker_retry_after_ms(
            self._retry_cfg, len(self._buf) / PENDING_CAP
        )

    async def _collect(self, requests, context) -> None:
        if self._closing:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, "broker shutting down"
            )
        # graduated refusal ([overload]): above refuse_frac the broker
        # turns submissions away with a typed retry-after BEFORE riding
        # into the hard cap — refusals are retryable and cheap, cap hits
        # mean work already interleaved past the ladder
        if (
            self.overload is not None
            and len(self._buf) >= int(PENDING_CAP * self.overload.refuse_frac)
        ):
            self.stats["broker_refusals"] += len(requests)
            retry_ms = self._refuse_retry_ms()
            self.recorder.record(
                "brownout_refuse", (len(requests), len(self._buf), retry_ms)
            )
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                format_shed_details(
                    "broker refusing under brownout", retry_ms
                ),
            )
        if len(self._buf) + len(requests) > PENDING_CAP:
            # refused before any buffering or register round-trips:
            # retryable, counted apart from hard sheds
            self.stats["broker_refusals"] += len(requests)
            retry_ms = self._refuse_retry_ms()
            self.recorder.record(
                "backpressure", (len(requests), len(self._buf))
            )
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                format_shed_details(
                    "broker buffer full; node unreachable or lagging",
                    retry_ms,
                ),
            )
        entries = []
        for i, req in enumerate(requests):
            where = f" (entry {i})" if len(requests) > 1 else ""
            if len(req.sender) != 32 or len(req.recipient) != 32:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"keys must be 32 bytes{where}",
                )
            if len(req.signature) != 64:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"signature must be 64 bytes{where}",
                )
            cid = await self._client_id(bytes(req.sender))
            # recipient compression is opportunistic: ids we happen to
            # know shrink the frame; unknown recipients ride raw (the
            # node never needs the recipient in its directory)
            recipient = self._ids.get(bytes(req.recipient), bytes(req.recipient))
            entries.append(
                distill.DistilledEntry(
                    sender_id=cid,
                    sequence=req.sequence,
                    recipient=recipient,
                    amount=req.amount,
                    signature=bytes(req.signature),
                )
            )
        # re-check occupancy AFTER the awaits above: concurrent _collect
        # calls can each pass the entry check and then interleave at the
        # Register round-trips, so only a check with no await point
        # between it and the extend actually enforces PENDING_CAP. This
        # is the hard-shed path — work was already performed for these
        # entries — counted apart from the pre-buffer refusals above.
        if len(self._buf) + len(entries) > PENDING_CAP:
            self.stats["broker_overflow_drops"] += len(entries)
            retry_ms = self._refuse_retry_ms()
            self.recorder.record(
                "backpressure", (len(entries), len(self._buf))
            )
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                format_shed_details(
                    "broker buffer full; node unreachable or lagging",
                    retry_ms,
                ),
            )
        if not self._buf:
            # empty -> non-empty transition: this batch's age clock
            # starts now (the eager deadline is measured from here)
            self._first_at = self.clock.monotonic()
        self._buf.extend(entries)
        self.stats["broker_entries_rx"] += len(entries)
        # the raw request still has the sender pubkey in hand here, so
        # this is the cheapest place to open the broker-hop relay span
        for req in requests:
            self.tx_trace.stamp(
                (bytes(req.sender), int(req.sequence)), "broker_rx"
            )
        if len(self._buf) >= self.max_entries:
            await self._flush()
        elif self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.create_task(self._delayed_flush())

    async def _delayed_flush(self) -> None:
        while True:
            depth = len(self._buf)
            # brownout ([overload]): a buffer past brownout_frac of the
            # cap has nothing left to gain from batching patience —
            # shrink the effective window toward zero as fill deepens,
            # riding the same anchored-deadline machinery as eager
            brownout = (
                self.overload is not None
                and depth >= int(PENDING_CAP * self.overload.brownout_frac)
            )
            shrink = (
                max(0.05, 1.0 - depth / PENDING_CAP) if brownout else 1.0
            )
            if self.eager or brownout:
                # queue-depth-adaptive deadline anchored to the batch's
                # first entry: deep buffers flush sooner (less batching
                # upside left), and time already spent buffered counts
                # against the deadline instead of restarting it
                frac = max(
                    EAGER_MIN_FRAC, 1.0 - depth / self.max_entries
                )
                elapsed = self.clock.monotonic() - self._first_at
                delay = frac * self.window * shrink - elapsed
                if delay > 0.0:
                    await self.clock.sleep(delay)
                if self.eager:
                    self.stats["broker_eager_flushes"] += 1
                if brownout:
                    self.stats["broker_brownout_flushes"] += 1
            else:
                await self.clock.sleep(self.window)
            await self._flush()
            if not self._buf:
                return

    async def _flush(self) -> None:
        """Distill and forward the buffered entries, one frame per
        max_entries chunk. Snapshot-at-entry like the node's batcher:
        entries arriving while a forward is awaited wait for their own
        trigger instead of leaking into this flush."""
        buf, self._buf = self._buf, []
        if buf:
            self.recorder.record("flush", (len(buf),))
        for lo in range(0, len(buf), self.max_entries):
            chunk = buf[lo : lo + self.max_entries]
            t0 = self.clock.monotonic()
            frame, dropped = distill.distill(chunk)
            self.h_build.observe(self.clock.monotonic() - t0)
            # DistilledEntry only carries the directory id; the reverse
            # map recovers the (pubkey, seq) trace key so the flush
            # stamp joins the span opened at broker_rx
            for e in chunk:
                pub = self._keys.get(e.sender_id)
                if pub is not None:
                    self.tx_trace.stamp((pub, e.sequence), "broker_flush")
            if dropped:
                self.stats["broker_dedup_drops"] += dropped
                self.recorder.record("dedup_drop", (dropped,))
            try:
                await self._stub.SendDistilledBatch(
                    pb.SendDistilledBatchRequest(frame=frame)
                )
            except grpc.aio.AioRpcError as exc:
                # fire-and-forget past this point, like a node dropping
                # its ingress buffer on shutdown: ACK was never a commit
                # receipt. The counter (and /metrics) carries the loss.
                self.stats["broker_forward_errors"] += 1
                self.recorder.record(
                    "forward_error", (str(exc.code()), len(chunk))
                )
                logger.warning(
                    "distilled forward failed (%s): %s",
                    exc.code(),
                    exc.details(),
                )
                continue
            self.stats["broker_batches_tx"] += 1
            self.stats["broker_entries_tx"] += len(chunk) - dropped

    # -- gRPC surface -----------------------------------------------------

    async def SendAsset(self, request, context):
        await self._collect([request], context)
        return pb.SendAssetReply()

    async def SendAssetBatch(self, request, context):
        if not request.transactions:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "empty batch"
            )
        await self._collect(list(request.transactions), context)
        return pb.SendAssetReply()

    async def Register(self, request, context):
        """Proxy: clients may pre-register through the broker (warms the
        broker's id cache as a side effect)."""
        reply = await self._stub.Register(request)
        if len(request.public_key) == 32:
            self._ids[bytes(request.public_key)] = int(reply.client_id)
            self._keys[int(reply.client_id)] = bytes(request.public_key)
        return reply

    async def SendDistilledBatch(self, request, context):
        """Pass-through: a pre-distilled frame needs no collection. A
        node-side refusal (overload shed, RESOURCE_EXHAUSTED) re-aborts
        with the SAME code and detail string, so the typed
        ``retry_after_ms`` hint survives the hop instead of collapsing
        into a generic INTERNAL error."""
        try:
            return await self._stub.SendDistilledBatch(request)
        except grpc.aio.AioRpcError as exc:
            await context.abort(exc.code(), exc.details() or "")

    async def GetBalance(self, request, context):
        return await self._stub.GetBalance(request)

    async def GetLastSequence(self, request, context):
        return await self._stub.GetLastSequence(request)

    async def GetLatestTransactions(self, request, context):
        return await self._stub.GetLatestTransactions(request)
