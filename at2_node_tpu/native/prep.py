"""ctypes bindings for the native batch-prep library (at2_prep.cpp).

Build-on-first-use: the .so is compiled with g++ into this package's
``build/`` directory and cached by source mtime. Loading or building can
fail (no compiler, read-only tree); callers must check
:func:`native_available` and fall back to the Python path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "at2_prep.cpp")
_BUILD_DIR = os.path.join(_HERE, "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libat2prep.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_U8P = ctypes.POINTER(ctypes.c_uint8)
_U64P = ctypes.POINTER(ctypes.c_uint64)


def _build() -> Optional[str]:
    # per-process temp name: concurrent first-use builds in separate
    # processes must not promote each other's half-written output
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC):
            return _LIB_PATH
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except Exception as exc:  # missing g++, read-only tree, missing source
        logger.warning("native prep build failed (%s); using python path", exc)
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as exc:
            logger.warning("native prep load failed (%s)", exc)
            return None
        lib.at2_prep_batch.argtypes = [
            _U8P, _U64P, _U8P, _U64P, _U8P, _U64P,
            ctypes.c_int64, ctypes.c_int64,
            _U8P, _U8P, _U8P, _U8P, _U8P,
        ]
        lib.at2_prep_batch.restype = None
        lib.at2_sha512.argtypes = [_U8P, ctypes.c_int64, _U8P]
        lib.at2_sha512.restype = None
        lib.at2_mod_l.argtypes = [_U8P, _U8P]
        lib.at2_mod_l.restype = None
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _pack(chunks: Sequence[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    offsets = np.zeros(len(chunks) + 1, dtype=np.uint64)
    np.cumsum([len(c) for c in chunks], out=offsets[1:])
    flat = np.frombuffer(b"".join(chunks), dtype=np.uint8) if chunks else np.zeros(0, np.uint8)
    return flat, offsets


def _ptr8(a: np.ndarray):
    return a.ctypes.data_as(_U8P)


def prep_batch_native(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
    batch_size: int,
    n_threads: int = 0,
):
    """Native equivalent of ops.ed25519.prepare_batch (same contract)."""
    lib = _load()
    assert lib is not None, "call native_available() first"
    n = len(public_keys)
    if n > batch_size:
        raise ValueError(f"batch of {n} exceeds bucket size {batch_size}")
    pk_flat, pk_off = _pack(public_keys)
    msg_flat, msg_off = _pack(messages)
    sig_flat, sig_off = _pack(signatures)

    a = np.zeros((batch_size, 32), dtype=np.uint8)
    r = np.zeros((batch_size, 32), dtype=np.uint8)
    s = np.zeros((batch_size, 32), dtype=np.uint8)
    h = np.zeros((batch_size, 32), dtype=np.uint8)
    valid8 = np.zeros(batch_size, dtype=np.uint8)
    if n_threads <= 0:
        n_threads = os.cpu_count() or 1
    lib.at2_prep_batch(
        _ptr8(pk_flat), pk_off.ctypes.data_as(_U64P),
        _ptr8(msg_flat), msg_off.ctypes.data_as(_U64P),
        _ptr8(sig_flat), sig_off.ctypes.data_as(_U64P),
        n, n_threads,
        _ptr8(a), _ptr8(r), _ptr8(s), _ptr8(h), _ptr8(valid8),
    )
    return a, r, s, h, valid8.astype(bool)


def sha512_native(data: bytes) -> bytes:
    lib = _load()
    assert lib is not None
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(0, np.uint8)
    out = np.zeros(64, dtype=np.uint8)
    lib.at2_sha512(_ptr8(buf), len(data), _ptr8(out))
    return out.tobytes()


def mod_l_native(digest64: bytes) -> int:
    lib = _load()
    assert lib is not None
    buf = np.frombuffer(digest64, dtype=np.uint8)
    out = np.zeros(32, dtype=np.uint8)
    lib.at2_mod_l(_ptr8(buf), _ptr8(out))
    return int.from_bytes(out.tobytes(), "little")
