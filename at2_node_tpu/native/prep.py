"""ctypes bindings for the native batch-prep library (at2_prep.cpp).

Build-on-first-use: the .so is compiled with g++ into this package's
``build/`` directory and cached by source mtime. Loading or building can
fail (no compiler, read-only tree); callers must check
:func:`native_available` and fall back to the Python path.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Sequence

import numpy as np

from ._build import U8P, U64P, load_lib
from ._build import pack_ragged as _pack
from ._build import ptr8 as _ptr8

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        lib = load_lib("at2_prep.cpp", "libat2prep.so")
        if lib is None:
            return None
        lib.at2_prep_batch.argtypes = [
            U8P, U64P, U8P, U64P, U8P, U64P,
            ctypes.c_int64, ctypes.c_int64,
            U8P, U8P, U8P, U8P, U8P,
        ]
        lib.at2_prep_batch.restype = None
        lib.at2_sha512.argtypes = [U8P, ctypes.c_int64, U8P]
        lib.at2_sha512.restype = None
        lib.at2_mod_l.argtypes = [U8P, U8P]
        lib.at2_mod_l.restype = None
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def prep_batch_native(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
    batch_size: int,
    n_threads: int = 0,
):
    """Native equivalent of ops.ed25519.prepare_batch (same contract)."""
    lib = _load()
    assert lib is not None, "call native_available() first"
    n = len(public_keys)
    if n > batch_size:
        raise ValueError(f"batch of {n} exceeds bucket size {batch_size}")
    pk_flat, pk_off = _pack(public_keys)
    msg_flat, msg_off = _pack(messages)
    sig_flat, sig_off = _pack(signatures)

    a = np.zeros((batch_size, 32), dtype=np.uint8)
    r = np.zeros((batch_size, 32), dtype=np.uint8)
    s = np.zeros((batch_size, 32), dtype=np.uint8)
    h = np.zeros((batch_size, 32), dtype=np.uint8)
    valid8 = np.zeros(batch_size, dtype=np.uint8)
    if n_threads <= 0:
        n_threads = os.cpu_count() or 1
    lib.at2_prep_batch(
        _ptr8(pk_flat), pk_off.ctypes.data_as(U64P),
        _ptr8(msg_flat), msg_off.ctypes.data_as(U64P),
        _ptr8(sig_flat), sig_off.ctypes.data_as(U64P),
        n, n_threads,
        _ptr8(a), _ptr8(r), _ptr8(s), _ptr8(h), _ptr8(valid8),
    )
    return a, r, s, h, valid8.astype(bool)


def sha512_native(data: bytes) -> bytes:
    lib = _load()
    assert lib is not None
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(0, np.uint8)
    out = np.zeros(64, dtype=np.uint8)
    lib.at2_sha512(_ptr8(buf), len(data), _ptr8(out))
    return out.tobytes()


def mod_l_native(digest64: bytes) -> int:
    lib = _load()
    assert lib is not None
    buf = np.frombuffer(digest64, dtype=np.uint8)
    out = np.zeros(32, dtype=np.uint8)
    lib.at2_mod_l(_ptr8(buf), _ptr8(out))
    return int.from_bytes(out.tobytes(), "little")
