"""Shared build/load/packing helpers for the native libraries.

Both ctypes bindings (`prep.py`, `ingest.py`) compile their translation
unit with the system g++ on first use into ``build/`` (cached by source
mtime, per-process temp names so concurrent first-use builds in separate
processes never promote each other's half-written output) and pack
ragged byte sequences into (flat, offsets) ndarray pairs for the C ABI.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

U8P = ctypes.POINTER(ctypes.c_uint8)
U32P = ctypes.POINTER(ctypes.c_uint32)
U64P = ctypes.POINTER(ctypes.c_uint64)

_HERE = os.path.dirname(os.path.abspath(__file__))
BUILD_DIR = os.path.join(_HERE, "build")


def build_lib(
    src_name: str, lib_name: str, extra_args: Sequence[str] = ()
) -> Optional[str]:
    """Compile ``src_name`` into ``build/lib_name`` unless cached-fresh.
    Returns the library path or None when the toolchain/link deps are
    missing (callers fall back to their Python paths)."""
    src = os.path.join(_HERE, src_name)
    lib_path = os.path.join(BUILD_DIR, lib_name)
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        src, *extra_args, "-o", tmp,
    ]
    try:
        os.makedirs(BUILD_DIR, exist_ok=True)
        if os.path.exists(lib_path) and os.path.getmtime(
            lib_path
        ) >= os.path.getmtime(src):
            return lib_path
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return lib_path
    except Exception as exc:  # missing g++/libs, read-only tree
        logger.warning("native build of %s failed (%s)", src_name, exc)
        return None


def load_lib(
    src_name: str, lib_name: str, extra_args: Sequence[str] = ()
) -> Optional[ctypes.CDLL]:
    path = build_lib(src_name, lib_name, extra_args)
    if path is None:
        return None
    try:
        return ctypes.CDLL(path)
    except OSError as exc:
        logger.warning("native load of %s failed (%s)", lib_name, exc)
        return None


def pack_ragged(chunks: Sequence[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten byte chunks into (flat u8 array, u64 offsets) for the C ABI."""
    offsets = np.zeros(len(chunks) + 1, dtype=np.uint64)
    np.cumsum([len(c) for c in chunks], out=offsets[1:])
    flat = (
        np.frombuffer(b"".join(chunks), dtype=np.uint8)
        if chunks
        else np.zeros(0, np.uint8)
    )
    return flat, offsets


def ptr8(a: np.ndarray):
    return a.ctypes.data_as(U8P)
