"""ctypes bindings for the native message-plane ingest (at2_ingest.cpp).

Same build-on-first-use pattern as `prep.py` (shared helpers in
`_build.py`); additionally links the system libcrypto (OpenSSL 3) for
the bulk ed25519 verify, so on images without it the build fails cleanly
and callers fall back to Python.

Exports:
* :func:`parse_frames_native` — one C call parses a whole chunk of wire
  frames (kind dispatch + record extraction + payload SHA-256 content
  hashes) and returns the same message objects `parse_frame` would, with
  the content hash pre-seeded so the state machine never re-hashes.
* :func:`verify_bulk_native` — one C call verifies a whole list of
  (pk, msg, sig) items on native threads; verdicts bit-identical with
  `crypto.keys.verify_one` (same libcrypto under both).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..broadcast.messages import (
    BATCH,
    BATCH_ECHO,
    BATCH_READY,
    BATCH_REQ,
    BEACON,
    CERT_SIG,
    CONFIG_TX,
    DIR_ANNOUNCE,
    ECHO,
    GOSSIP,
    HIST_BATCH,
    HIST_IDX,
    HIST_IDX_REQ,
    HIST_REQ,
    MAX_MSGS_PER_FRAME,
    READY,
    REQUEST,
    _DIR_HDR,
    _HIST_HDR,
    Attestation,
    BatchAttestation,
    BatchContentRequest,
    CertSig,
    ConfigTx,
    ContentRequest,
    DirectoryAnnounce,
    HistoryBatch,
    HistoryIndex,
    HistoryIndexRequest,
    HistoryRequest,
    Payload,
    StateBeacon,
    TxBatch,
)
from ._build import U8P, U32P, U64P, load_lib, pack_ragged, ptr8

_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

# Preferred OpenSSL soname first, but hosts differ (build VMs still ship
# 1.1): probe each candidate until one links. The C source only uses the
# stable EVP verify API, which is identical across both majors.
_LINK_CANDIDATES = (
    ("-l:libcrypto.so.3",),
    ("-l:libcrypto.so.1.1",),
    ("-lcrypto",),
)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        lib = None
        for link_args in _LINK_CANDIDATES:
            lib = load_lib("at2_ingest.cpp", "libat2ingest.so", link_args)
            if lib is not None:
                break
        if lib is None:
            return None
        lib.at2_parse_frames.argtypes = [
            U8P, U64P, ctypes.c_int64, U8P, ctypes.c_int64, U32P, U8P,
        ]
        lib.at2_parse_frames.restype = ctypes.c_int64
        lib.at2_plane_drain.argtypes = [
            U8P, U64P, ctypes.c_int64, ctypes.c_int64, U8P, ctypes.c_int64,
            U32P, U8P, U32P, _I64P,
        ]
        lib.at2_plane_drain.restype = ctypes.c_int64
        lib.at2_verify_bulk.argtypes = [
            U8P, U64P, U8P, U64P, U8P, U64P,
            ctypes.c_int64, ctypes.c_int64, U8P,
        ]
        lib.at2_verify_bulk.restype = None
        lib.at2_ingest_row_stride.argtypes = []
        lib.at2_ingest_row_stride.restype = ctypes.c_int64
        lib.at2_ingest_min_wire.argtypes = []
        lib.at2_ingest_min_wire.restype = ctypes.c_int64
        lib.at2_distill_parse.argtypes = [
            U8P, ctypes.c_int64, U8P, ctypes.c_int64,
            U8P, U64P, U8P, ctypes.c_int64,
        ]
        lib.at2_distill_parse.restype = ctypes.c_int64
        lib.at2_counts_add.argtypes = [
            U8P, ctypes.c_int64, _I32P, ctypes.c_int64,
        ]
        lib.at2_counts_add.restype = ctypes.c_int64
        lib.at2_quorum_mask.argtypes = [
            _I32P, ctypes.c_int64, ctypes.c_int32, U8P, ctypes.c_int64,
        ]
        lib.at2_quorum_mask.restype = ctypes.c_int64
        _lib = lib
        return _lib


def ingest_available() -> bool:
    if os.environ.get("AT2_NO_NATIVE_INGEST"):
        return False  # explicit kill-switch (benchmarking / incident triage)
    return _load() is not None


def ingest_ready() -> bool:
    """Non-BUILDING probe for hot paths: True only when the library load
    already completed. `ingest_available` can run the first-use g++
    compile (seconds, synchronous) — that must never happen on an event
    loop inside a live worker chunk; Broadcast.start/warmup pre-build
    off-loop, and anything used without warmup consults this instead and
    kicks the build to a background thread via :func:`kick_ingest_build`."""
    if os.environ.get("AT2_NO_NATIVE_INGEST"):
        return False
    return _lib is not None


_build_kicked = False


def kick_ingest_build() -> None:
    """Start the build/load on a daemon thread if no one has yet, so a
    verifier used without warmup converges to the native path after the
    first few chunks instead of freezing the loop on chunk one."""
    global _build_kicked
    if _build_kicked or _tried:
        return
    _build_kicked = True
    threading.Thread(
        target=ingest_available, daemon=True, name="at2-ingest-build"
    ).start()


def ingest_ready_or_kick() -> bool:
    """THE hot-path probe: True when the native path is usable right now;
    otherwise kicks the background build (once) and returns False so the
    caller takes the Python path this time. Keeps the
    never-build-on-the-event-loop policy in one place."""
    if ingest_ready():
        return True
    kick_ingest_build()
    return False


def parse_frames_native(frames: Sequence[bytes]):
    """Parse many frames in one native call.

    Returns ``(messages, frame_ok)`` where messages is a list of
    ``(frame_index, message_object)`` and ``frame_ok[i]`` says whether
    frame i parsed cleanly (malformed frames are dropped whole, matching
    ``parse_frame``'s WireError behavior)."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    flat, offsets = pack_ragged(frames)
    stride = int(lib.at2_ingest_row_stride())
    # Row capacity: size the buffer for the hot-path mix first (nothing on
    # the wire smaller than a ContentRequest, 69 bytes); if a frame turns
    # out to be dense with tiny catchup control messages (min_wire bytes
    # each) the parser returns -1 and we retry once with the true bound —
    # which the per-frame message cap (MAX_MSGS_PER_FRAME, pinned against
    # kMaxMsgsPerFrame by test_native_ingest; frames beyond it are
    # malformed and drop whole) keeps proportional to the frame count,
    # not the byte count.
    per_frame_bound = len(frames) * MAX_MSGS_PER_FRAME
    for min_wire in (69, int(lib.at2_ingest_min_wire())):
        cap = min(int(flat.size // min_wire), per_frame_bound) + len(frames) + 1
        rows = np.zeros((cap, stride), dtype=np.uint8)
        msg_frame = np.zeros(cap, dtype=np.uint32)
        frame_ok = np.zeros(len(frames), dtype=np.uint8)
        n = int(
            lib.at2_parse_frames(
                ptr8(flat),
                offsets.ctypes.data_as(U64P),
                len(frames),
                ptr8(rows),
                cap,
                msg_frame.ctypes.data_as(U32P),
                ptr8(frame_ok),
            )
        )
        if n >= 0:
            break
    if n < 0:  # cannot happen given the final bound; survive `python -O`
        raise RuntimeError("native parse overflowed its row capacity")

    out = [
        (frame_idx, msg)
        for _, frame_idx, msg in _build_rows(rows, msg_frame, flat, n, stride)
    ]
    return out, frame_ok.astype(bool)


def _build_rows(rows, msg_frame, flat, n: int, stride: int):
    """Yield ``(row_index, frame_index, message_object)`` for every
    parsed row. Object building reuses the same Struct-based decode_body
    paths the Python parser uses (one C-level unpack per message); the
    native side's contribution is the GIL-released validation pass and
    the payload content hashes (seeded here so nothing re-hashes)."""
    row_bytes = rows[:n].tobytes()
    frame_idx = msg_frame[:n].tolist()
    setattr_ = object.__setattr__
    for i in range(n):
        base = i * stride
        kind = row_bytes[base]
        if kind == GOSSIP:
            msg = Payload.decode_body(row_bytes[base + 1 : base + 141])
            setattr_(msg, "_chash", row_bytes[base + 141 : base + 173])
        elif kind in (ECHO, READY):
            msg = Attestation.decode_body(
                kind, row_bytes[base + 1 : base + 165]
            )
        elif kind == REQUEST:
            msg = ContentRequest.decode_body(row_bytes[base + 1 : base + 69])
        elif kind == HIST_IDX_REQ:
            msg = HistoryIndexRequest.decode_body(row_bytes[base + 1 : base + 9])
        elif kind == HIST_REQ:
            msg = HistoryRequest.decode_body(row_bytes[base + 1 : base + 49])
        elif kind == BATCH_REQ:
            msg = BatchContentRequest.decode_body(row_bytes[base + 1 : base + 73])
        elif kind in (
            HIST_IDX, HIST_BATCH, BATCH, BATCH_ECHO, BATCH_READY,
            DIR_ANNOUNCE, CONFIG_TX, BEACON, CERT_SIG,
        ):
            # variable-length rows carry (offset, length) into `flat`
            # (BEACON/CERT_SIG are fixed-size but wider than the row stride)
            off = int.from_bytes(row_bytes[base + 1 : base + 9], "little")
            ln = int.from_bytes(row_bytes[base + 9 : base + 17], "little")
            body = flat[off : off + ln].tobytes()
            if kind == BATCH:
                msg = TxBatch.decode_body(body)
            elif kind in (BATCH_ECHO, BATCH_READY):
                msg = BatchAttestation.decode_body(kind, body)
            elif kind == CONFIG_TX:
                msg = ConfigTx.decode_body(body)
            elif kind == BEACON:
                msg = StateBeacon.decode_body(body)
            elif kind == CERT_SIG:
                msg = CertSig.decode_body(body)
            elif kind == DIR_ANNOUNCE:
                origin, _count = _DIR_HDR.unpack_from(body)
                msg = DirectoryAnnounce.decode_body(origin, body[_DIR_HDR.size :])
            else:
                nonce, _count = _HIST_HDR.unpack_from(body)
                if kind == HIST_IDX:
                    msg = HistoryIndex.decode_body(nonce, body[_HIST_HDR.size :])
                else:
                    msg = HistoryBatch.decode_body(nonce, body[_HIST_HDR.size :])
        else:  # pragma: no cover - the C side never emits other kinds
            continue
        yield i, frame_idx[i], msg


# fixed-wire kinds whose full body lives in the parse row (everything
# else stores (offset, length) into the flat frame buffer)
_FIXED_BODY_LEN = {
    GOSSIP: 140,
    ECHO: 164,
    READY: 164,
    REQUEST: 68,
    HIST_IDX_REQ: 8,
    HIST_REQ: 48,
    BATCH_REQ: 72,
}


def plane_drain_ready() -> bool:
    """Hot-path probe for the fused owner drain (parse + shard routing
    in one GIL-released call). Separate kill-switch from the rest of the
    native ingest so the phase-accounting A/B (tools/plane_bench.py
    --compare-drain) can isolate exactly this fusion."""
    if os.environ.get("AT2_NO_PLANE_DRAIN"):
        return False
    return ingest_ready_or_kick()


def plane_drain_native(frames: Sequence[bytes], shards: int,
                       want_objects: bool = True):
    """Parse a whole drain chunk AND route every message to its owning
    shard in ONE native call (at2_plane_drain).

    Returns ``(items, frame_ok, shard_counts)``:

    * ``want_objects=True`` (thread/inline planes): items are
      ``(frame_index, shard_id, message_object)`` — what
      ``parse_frames_native`` returns plus the routing the owner loop
      would otherwise derive per message with an isinstance chain.
    * ``want_objects=False`` (process plane): items are
      ``(frame_index, shard_id, kind, wire_bytes)`` where wire_bytes is
      the single-message frame to forward into the shard's actions
      ring — NO Python message objects are built for slot-bound kinds;
      the owning worker parses its own copy.

    ``shard_counts`` is the per-shard routed-row tally (int64 ndarray),
    rollback-corrected for malformed frames."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    flat, offsets = pack_ragged(frames)
    stride = int(lib.at2_ingest_row_stride())
    per_frame_bound = len(frames) * MAX_MSGS_PER_FRAME
    for min_wire in (69, int(lib.at2_ingest_min_wire())):
        cap = min(int(flat.size // min_wire), per_frame_bound) + len(frames) + 1
        rows = np.zeros((cap, stride), dtype=np.uint8)
        msg_frame = np.zeros(cap, dtype=np.uint32)
        frame_ok = np.zeros(len(frames), dtype=np.uint8)
        shard_ids = np.zeros(cap, dtype=np.uint32)
        shard_counts = np.zeros(shards, dtype=np.int64)
        n = int(
            lib.at2_plane_drain(
                ptr8(flat),
                offsets.ctypes.data_as(U64P),
                len(frames),
                shards,
                ptr8(rows),
                cap,
                msg_frame.ctypes.data_as(U32P),
                ptr8(frame_ok),
                shard_ids.ctypes.data_as(U32P),
                shard_counts.ctypes.data_as(_I64P),
            )
        )
        if n >= 0:
            break
    if n < 0:  # cannot happen given the final bound; survive `python -O`
        raise RuntimeError("native plane drain overflowed its row capacity")

    sids = shard_ids[:n].tolist()
    if want_objects:
        items = [
            (fidx, sids[i], msg)
            for i, fidx, msg in _build_rows(rows, msg_frame, flat, n, stride)
        ]
        return items, frame_ok.astype(bool), shard_counts

    row_bytes = rows[:n].tobytes()
    frame_idx = msg_frame[:n].tolist()
    items = []
    for i in range(n):
        base = i * stride
        kind = row_bytes[base]
        blen = _FIXED_BODY_LEN.get(kind)
        if blen is not None:
            wire = row_bytes[base : base + 1 + blen]
        else:
            off = int.from_bytes(row_bytes[base + 1 : base + 9], "little")
            ln = int.from_bytes(row_bytes[base + 9 : base + 17], "little")
            wire = bytes([kind]) + flat[off : off + ln].tobytes()
        items.append((frame_idx[i], sids[i], kind, wire))
    return items, frame_ok.astype(bool), shard_counts


def distill_parse_native(
    frame: bytes, dir_keys: np.ndarray, dir_count: int
) -> Optional[Tuple[bytes, np.ndarray, np.ndarray]]:
    """Parse + expand one distilled frame (proto/distill.py format) in a
    single GIL-released native call, resolving client-ids against the
    directory's ``(cap, 32)`` uint8 key table.

    Returns ``(bodies, sender_ids, ok)`` — ``bodies`` is ``n * 140``
    canonical entry bytes (TxBatch ``entries_raw`` layout), ``ok[i]``
    False marks a directory miss — or ``None`` when the frame is
    malformed (same acceptance set as ``distill.decode``; differential-
    tested in tests/test_distill.py)."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    from ..proto.distill import DISTILL_MAX_ENTRIES, ENTRY_WIRE

    buf = np.frombuffer(frame, dtype=np.uint8)
    cap = DISTILL_MAX_ENTRIES
    bodies = np.zeros(cap * ENTRY_WIRE, dtype=np.uint8)
    ids = np.zeros(cap, dtype=np.uint64)
    ok = np.zeros(cap, dtype=np.uint8)
    assert dir_keys.dtype == np.uint8 and dir_keys.flags["C_CONTIGUOUS"]
    n = int(
        lib.at2_distill_parse(
            ptr8(buf), len(frame), ptr8(dir_keys), int(dir_count),
            ptr8(bodies), ids.ctypes.data_as(U64P), ptr8(ok), cap,
        )
    )
    if n < 0:
        return None
    return bodies[: n * ENTRY_WIRE].tobytes(), ids[:n], ok[:n].astype(bool)


def verify_bulk_native(
    items: Sequence[Tuple[bytes, bytes, bytes]], n_threads: int = 1
) -> np.ndarray:
    """Verify (public_key, message, signature) items in one native call.
    The GIL is released for the whole call (ctypes), so the event loop
    breathes while OpenSSL grinds; n_threads > 1 fans out on real cores."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    n = len(items)
    out = np.zeros(n, dtype=np.uint8)
    if n == 0:
        return out.astype(bool)
    pk_flat, pk_off = pack_ragged([it[0] for it in items])
    msg_flat, msg_off = pack_ragged([it[1] for it in items])
    sig_flat, sig_off = pack_ragged([it[2] for it in items])
    lib.at2_verify_bulk(
        ptr8(pk_flat), pk_off.ctypes.data_as(U64P),
        ptr8(msg_flat), msg_off.ctypes.data_as(U64P),
        ptr8(sig_flat), sig_off.ctypes.data_as(U64P),
        n, n_threads, ptr8(out),
    )
    return out.astype(bool)


def counts_add_native(bitmap: bytes, counts: np.ndarray) -> int:
    """Fold a little-endian endorsement bitmap into an int32 tally array
    (counts[i] += 1 for every set bit i). GIL released for the scan, so
    shard threads applying attestations genuinely overlap. Returns the
    number of bits folded. ``counts`` must be C-contiguous int32 and is
    mutated in place."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    assert counts.dtype == np.int32 and counts.flags["C_CONTIGUOUS"]
    buf = np.frombuffer(bitmap, dtype=np.uint8)
    return int(
        lib.at2_counts_add(
            ptr8(buf), len(bitmap),
            counts.ctypes.data_as(_I32P), len(counts),
        )
    )


def quorum_mask_native(counts: np.ndarray, threshold: int, nbits: int) -> int:
    """Little-endian packed quorum bitmap (as a Python int) of tally
    indices with counts[i] >= threshold, over the first ``nbits``
    entries. The GIL-released native twin of broadcast._quorate_mask."""
    lib = _load()
    assert lib is not None, "call ingest_available() first"
    assert counts.dtype == np.int32 and counts.flags["C_CONTIGUOUS"]
    n = min(nbits, len(counts))
    if n <= 0:
        return 0
    out = np.zeros((n + 7) // 8, dtype=np.uint8)
    lib.at2_quorum_mask(
        counts.ctypes.data_as(_I32P), n, threshold, ptr8(out), len(out)
    )
    return int.from_bytes(out.tobytes(), "little")
