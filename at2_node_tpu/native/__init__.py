"""Native (C++) host-side batch preparation for the TPU verifier.

The runtime around the TPU compute path is native where the reference's
is (its whole broadcast/crypto stack is Rust): `at2_prep.cpp` implements
SHA-512, the mod-L scalar reduction, the S < L check, and batch packing,
compiled on first use with the system g++ into a shared library loaded
via ctypes (no pybind11 in this image). Falls back to the pure-Python
path transparently if compilation fails.
"""

from .ingest import (
    counts_add_native,
    ingest_available,
    ingest_ready,
    ingest_ready_or_kick,
    kick_ingest_build,
    parse_frames_native,
    plane_drain_native,
    plane_drain_ready,
    quorum_mask_native,
    verify_bulk_native,
)
from .prep import native_available, prep_batch_native
from .reader import NativeChannelReader, reader_available

__all__ = [
    "NativeChannelReader",
    "reader_available",
    "counts_add_native",
    "ingest_available",
    "ingest_ready",
    "ingest_ready_or_kick",
    "kick_ingest_build",
    "native_available",
    "parse_frames_native",
    "plane_drain_native",
    "plane_drain_ready",
    "prep_batch_native",
    "quorum_mask_native",
    "verify_bulk_native",
]
