// at2_rlc.cpp — GIL-released random-linear-combination (RLC) batch
// verification engine for ed25519 (ISSUE 10).
//
// One RLC check over B signatures replaces B double-scalar-mults:
//
//     [ sum z_i s_i mod L ] B  ==  sum [z_i] R_i  +  sum [z_i h_i] A_i
//
// with per-batch random 128-bit z_i. The two right-hand sums are
// Pippenger multi-scalar-mults (signed 8-bit windows), so the marginal
// cost per signature drops from one 512-bit Straus double-mult (~1500
// point ops) to ~55 point ops at B=1024 — that is the whole trick.
//
// Soundness on the full curve (cofactor 8) needs more than the equation:
// a signer-malleated R' = R + T (T small-order) passes the cofactorless
// per-signature check with probability 0 but would pass a naive RLC with
// probability 1/8 per torsion component (z_i mod ord(T) cancels). Two
// complementary defences, mirroring the exact [L]P precheck in
// ops/aggregate.py:
//
//   * A-side: `at2_rlc_certify` does the exact [L]A == identity test per
//     public key. The verifier caches the verdict per key (keys repeat
//     across batches; the ~80us exact test amortizes to ~0), and any key
//     whose A carries torsion is routed to the exact per-signature path
//     forever — certification REROUTES, it never rejects, so verdicts
//     still agree with per-sig on tainted-A inputs.
//   * R-side: R points are fresh per signature, so per-point exact tests
//     cannot amortize. Instead we run `k` randomized subset rounds: each
//     round folds S_r = sum c_{r,i} R_i with independent uniform 3-bit
//     coefficients c and requires [L] S_r == identity. A lane whose R
//     carries a torsion component of order m in {2,4,8} survives one
//     round with probability 1/m <= 1/2, so k rounds bound the miss
//     probability by 2^-k (k=64 from the Python side: 2^-64, far below
//     the 2^-124 prime-order soundness of the 128-bit z themselves).
//
// Layout mirrors at2_ingest.cpp: plain extern "C" entry points over
// packed numpy buffers, built by native/_build.py with g++ -O3, loaded
// via ctypes (which releases the GIL for the whole call).
//
// Field/point code: 5x51-bit limb arithmetic (unsigned __int128
// products) and extended twisted-Edwards coordinates with the complete
// a=-1 addition law (Hisil-Wong-Carter-Dawson), the same formulas as
// ops/edwards.py — completeness means bucket accumulation never needs
// case analysis. Decompression implements RFC 8032 §5.1.3 with the
// exact edge-case semantics of crypto/_fallback.py and ops/edwards.py:
// reject y >= p, reject non-square x^2, reject x=0 with sign bit set.

#include <cstdint>
#include <cstring>
#include <vector>

typedef uint64_t u64;
typedef unsigned __int128 u128;

static const u64 MASK51 = ((u64)1 << 51) - 1;

// ---------------------------------------------------------------- field

struct fe {
    u64 v[5];
};

static const fe FE_ZERO = {{0, 0, 0, 0, 0}};
static const fe FE_ONE = {{1, 0, 0, 0, 0}};
// d = -121665/121666 mod p
static const fe FE_D = {{0x34dca135978a3ULL, 0x1a8283b156ebdULL,
                         0x5e7a26001c029ULL, 0x739c663a03cbbULL,
                         0x52036cee2b6ffULL}};
// 2d mod p
static const fe FE_D2 = {{0x69b9426b2f159ULL, 0x35050762add7aULL,
                          0x3cf44c0038052ULL, 0x6738cc7407977ULL,
                          0x2406d9dc56dffULL}};
// sqrt(-1) mod p
static const fe FE_SQRTM1 = {{0x61b274a0ea0b0ULL, 0xd5a5fc8f189dULL,
                              0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL,
                              0x2b8324804fc1dULL}};

static inline void fe_reduce(fe &r) {
    u64 c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
    c = r.v[1] >> 51; r.v[1] &= MASK51; r.v[2] += c;
    c = r.v[2] >> 51; r.v[2] &= MASK51; r.v[3] += c;
    c = r.v[3] >> 51; r.v[3] &= MASK51; r.v[4] += c;
    c = r.v[4] >> 51; r.v[4] &= MASK51; r.v[0] += 19 * c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
}

static inline void fe_add(fe &r, const fe &a, const fe &b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
    fe_reduce(r);
}

static inline void fe_sub(fe &r, const fe &a, const fe &b) {
    // add 2p so every limb stays non-negative before subtracting
    r.v[0] = a.v[0] + 0xFFFFFFFFFFFDAULL - b.v[0];
    r.v[1] = a.v[1] + 0xFFFFFFFFFFFFEULL - b.v[1];
    r.v[2] = a.v[2] + 0xFFFFFFFFFFFFEULL - b.v[2];
    r.v[3] = a.v[3] + 0xFFFFFFFFFFFFEULL - b.v[3];
    r.v[4] = a.v[4] + 0xFFFFFFFFFFFFEULL - b.v[4];
    fe_reduce(r);
}

static inline void fe_neg(fe &r, const fe &a) { fe_sub(r, FE_ZERO, a); }

static void fe_mul(fe &r, const fe &a, const fe &b) {
    const u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
    const u64 b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3], b4 = b.v[4];
    const u64 b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19, b4_19 = b4 * 19;

    u128 t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
              (u128)a3 * b2_19 + (u128)a4 * b1_19;
    u128 t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
              (u128)a3 * b3_19 + (u128)a4 * b2_19;
    u128 t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
              (u128)a3 * b4_19 + (u128)a4 * b3_19;
    u128 t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
              (u128)a3 * b0 + (u128)a4 * b4_19;
    u128 t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
              (u128)a3 * b1 + (u128)a4 * b0;

    u64 c;
    r.v[0] = (u64)t0 & MASK51; c = (u64)(t0 >> 51);
    t1 += c; r.v[1] = (u64)t1 & MASK51; c = (u64)(t1 >> 51);
    t2 += c; r.v[2] = (u64)t2 & MASK51; c = (u64)(t2 >> 51);
    t3 += c; r.v[3] = (u64)t3 & MASK51; c = (u64)(t3 >> 51);
    t4 += c; r.v[4] = (u64)t4 & MASK51; c = (u64)(t4 >> 51);
    r.v[0] += 19 * c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
}

static inline void fe_sq(fe &r, const fe &a) { fe_mul(r, a, a); }

static void fe_sqn(fe &r, const fe &a, int n) {
    fe_sq(r, a);
    for (int i = 1; i < n; i++) fe_sq(r, r);
}

// z^(2^250 - 1) — the shared tail of the inversion and sqrt chains
static void fe_pow_2_250_1(fe &out, fe &t0_out, const fe &z) {
    fe t0, t1, t2, t3;
    fe_sq(t0, z);                  // z^2
    fe_sqn(t1, t0, 2);             // z^8
    fe_mul(t1, z, t1);             // z^9
    fe_mul(t0, t0, t1);            // z^11
    fe_sq(t2, t0);                 // z^22
    fe_mul(t1, t1, t2);            // z^31 = z^(2^5-1)
    fe_sqn(t2, t1, 5);
    fe_mul(t1, t2, t1);            // z^(2^10-1)
    fe_sqn(t2, t1, 10);
    fe_mul(t2, t2, t1);            // z^(2^20-1)
    fe_sqn(t3, t2, 20);
    fe_mul(t2, t3, t2);            // z^(2^40-1)
    fe_sqn(t2, t2, 10);
    fe_mul(t1, t2, t1);            // z^(2^50-1)
    fe_sqn(t2, t1, 50);
    fe_mul(t2, t2, t1);            // z^(2^100-1)
    fe_sqn(t3, t2, 100);
    fe_mul(t2, t3, t2);            // z^(2^200-1)
    fe_sqn(t2, t2, 50);
    fe_mul(out, t2, t1);           // z^(2^250-1)
    t0_out = t0;                   // z^11, reused by fe_invert
}

static void fe_invert(fe &r, const fe &z) {
    fe t, z11;
    fe_pow_2_250_1(t, z11, z);
    fe_sqn(t, t, 5);               // z^(2^255 - 32)
    fe_mul(r, t, z11);             // z^(2^255 - 21) = z^(p-2)
}

// z^((p-5)/8) = z^(2^252 - 3)
static void fe_pow22523(fe &r, const fe &z) {
    fe t, z11;
    fe_pow_2_250_1(t, z11, z);
    fe_sqn(t, t, 2);               // z^(2^252 - 4)
    fe_mul(r, t, z);               // z^(2^252 - 3)
}

// canonical little-endian bytes (freeze mod p)
static void fe_tobytes(uint8_t out[32], const fe &a) {
    fe t = a;
    fe_reduce(t);
    fe_reduce(t);
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;
    u64 w0 = t.v[0] | (t.v[1] << 51);
    u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(out, &w0, 8);
    memcpy(out + 8, &w1, 8);
    memcpy(out + 16, &w2, 8);
    memcpy(out + 24, &w3, 8);
}

static void fe_frombytes(fe &r, const uint8_t in[32]) {
    u64 w0, w1, w2, w3;
    memcpy(&w0, in, 8);
    memcpy(&w1, in + 8, 8);
    memcpy(&w2, in + 16, 8);
    memcpy(&w3, in + 24, 8);
    r.v[0] = w0 & MASK51;
    r.v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
    r.v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
    r.v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
    r.v[4] = (w3 >> 12) & MASK51;  // drops bit 255 (the sign bit)
}

static bool fe_is_zero(const fe &a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    uint8_t acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

static bool fe_eq(const fe &a, const fe &b) {
    fe t;
    fe_sub(t, a, b);
    return fe_is_zero(t);
}

// ---------------------------------------------------------------- group

struct ge {
    fe X, Y, Z, T;  // extended homogeneous, T = XY/Z
};

static const ge GE_IDENTITY = {FE_ZERO, FE_ONE, FE_ONE, FE_ZERO};

// complete a=-1 addition (add-2008-hwcd-3 with precomputed 2d)
static void ge_add(ge &r, const ge &p, const ge &q) {
    fe a, b, c, d, e, f, g, h, t;
    fe_sub(a, p.Y, p.X);
    fe_sub(t, q.Y, q.X);
    fe_mul(a, a, t);
    fe_add(b, p.Y, p.X);
    fe_add(t, q.Y, q.X);
    fe_mul(b, b, t);
    fe_mul(c, p.T, FE_D2);
    fe_mul(c, c, q.T);
    fe_add(d, p.Z, p.Z);
    fe_mul(d, d, q.Z);
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.Z, f, g);
    fe_mul(r.T, e, h);
}

// dbl-2008-hwcd (a=-1): A=X^2 B=Y^2 C=2Z^2 E=(X+Y)^2-A-B G=B-A F=G-C H=-(A+B)
static void ge_dbl(ge &r, const ge &p) {
    fe a, b, c, e, f, g, h, t;
    fe_sq(a, p.X);
    fe_sq(b, p.Y);
    fe_sq(c, p.Z);
    fe_add(c, c, c);
    fe_add(t, p.X, p.Y);
    fe_sq(t, t);
    fe_add(e, a, b);
    fe_sub(e, t, e);
    fe_sub(g, b, a);
    fe_sub(f, g, c);
    fe_add(h, a, b);
    fe_neg(h, h);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.Z, f, g);
    fe_mul(r.T, e, h);
}

static void ge_neg(ge &r, const ge &p) {
    fe_neg(r.X, p.X);
    r.Y = p.Y;
    r.Z = p.Z;
    fe_neg(r.T, p.T);
}

static bool ge_is_identity(const ge &p) {
    // (X:Y:Z) == (0:1:1) projectively: X == 0 and Y == Z
    return fe_is_zero(p.X) && fe_eq(p.Y, p.Z);
}

static bool ge_eq(const ge &p, const ge &q) {
    fe a, b;
    fe_mul(a, p.X, q.Z);
    fe_mul(b, q.X, p.Z);
    if (!fe_eq(a, b)) return false;
    fe_mul(a, p.Y, q.Z);
    fe_mul(b, q.Y, p.Z);
    return fe_eq(a, b);
}

// RFC 8032 §5.1.3 decompression; returns false on invalid encodings with
// the same edge semantics as crypto/_fallback.py::_recover_x.
static bool ge_decompress(ge &r, const uint8_t enc[32]) {
    int sign = enc[31] >> 7;
    fe y;
    fe_frombytes(y, enc);

    // canonical check: the masked 255-bit value must be < p
    {
        uint8_t canon[32];
        fe_tobytes(canon, y);
        uint8_t masked[32];
        memcpy(masked, enc, 32);
        masked[31] &= 0x7F;
        if (memcmp(canon, masked, 32) != 0) return false;  // y >= p
    }

    fe yy, u, v;
    fe_sq(yy, y);
    fe_sub(u, yy, FE_ONE);            // y^2 - 1
    fe_mul(v, yy, FE_D);
    fe_add(v, v, FE_ONE);             // d y^2 + 1

    // x = u v^3 (u v^7)^((p-5)/8)
    fe v3, v7, x, t;
    fe_sq(v3, v);
    fe_mul(v3, v3, v);
    fe_sq(v7, v3);
    fe_mul(v7, v7, v);
    fe_mul(t, u, v7);
    fe_pow22523(t, t);
    fe_mul(x, u, v3);
    fe_mul(x, x, t);

    fe vxx, neg_u;
    fe_sq(vxx, x);
    fe_mul(vxx, v, vxx);
    fe_neg(neg_u, u);
    if (!fe_eq(vxx, u)) {
        if (!fe_eq(vxx, neg_u)) return false;  // x^2 not a square
        fe_mul(x, x, FE_SQRTM1);
    }

    uint8_t xb[32];
    fe_tobytes(xb, x);
    bool x_zero = true;
    for (int i = 0; i < 32; i++)
        if (xb[i]) { x_zero = false; break; }
    if (x_zero && sign) return false;  // -0 encoding (RFC 8032 step 4)
    if ((xb[0] & 1) != sign) fe_neg(x, x);

    r.X = x;
    r.Y = y;
    r.Z = FE_ONE;
    fe_mul(r.T, x, y);
    return true;
}

static void ge_compress(uint8_t out[32], const ge &p) {
    fe zinv, x, y;
    fe_invert(zinv, p.Z);
    fe_mul(x, p.X, zinv);
    fe_mul(y, p.Y, zinv);
    fe_tobytes(out, y);
    uint8_t xb[32];
    fe_tobytes(xb, x);
    out[31] |= (xb[0] & 1) << 7;
}

// ------------------------------------------------- scalar multiplication

// group order L = 2^252 + 27742317777372353535851937790883648493, LE bytes
static const uint8_t L_BYTES[32] = {
    0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58,
    0xd6, 0x9c, 0xf7, 0xa2, 0xde, 0xf9, 0xde, 0x14,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};

// r = (a + b) mod L for 32-byte LE scalars a, b < L
static void sc_add_mod_l(uint8_t r[32], const uint8_t a[32],
                         const uint8_t b[32]) {
    u64 aw[4], bw[4], lw[4], s[4];
    memcpy(aw, a, 32);
    memcpy(bw, b, 32);
    memcpy(lw, L_BYTES, 32);
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)aw[i] + bw[i];
        s[i] = (u64)c;
        c >>= 64;
    }
    // sum < 2L < 2^254: at most one subtraction of L needed
    bool ge = true;
    for (int i = 3; i >= 0; i--) {
        if (s[i] > lw[i]) break;
        if (s[i] < lw[i]) { ge = false; break; }
    }
    if (ge) {
        u128 borrow = 0;
        for (int i = 0; i < 4; i++) {
            u128 d = (u128)s[i] - lw[i] - borrow;
            s[i] = (u64)d;
            borrow = (d >> 64) ? 1 : 0;
        }
    }
    memcpy(r, s, 32);
}

// [k]P by plain double-and-add over big-endian bits of a 32-byte LE scalar.
// Verification-side: scalars are public, no constant-time requirement.
static void ge_scalarmul(ge &r, const ge &p, const uint8_t sc[32]) {
    ge acc = GE_IDENTITY;
    bool started = false;
    for (int byte = 31; byte >= 0; byte--) {
        for (int bit = 7; bit >= 0; bit--) {
            if (started) ge_dbl(acc, acc);
            if ((sc[byte] >> bit) & 1) {
                if (started) ge_add(acc, acc, p);
                else { acc = p; started = true; }
            }
        }
    }
    r = started ? acc : GE_IDENTITY;
}

static bool ge_mul_l_is_identity(const ge &p) {
    ge t;
    ge_scalarmul(t, p, L_BYTES);
    return ge_is_identity(t);
}

// ------------------------------------------------------- Pippenger MSM

// signed base-256 recoding of a 32-byte LE scalar: digits in [-128, 128),
// at most 33 digits (carry out of byte 31)
static void recode_signed(int16_t out[33], const uint8_t sc[32]) {
    int carry = 0;
    for (int i = 0; i < 32; i++) {
        int t = sc[i] + carry;
        if (t >= 128) {
            out[i] = (int16_t)(t - 256);
            carry = 1;
        } else {
            out[i] = (int16_t)t;
            carry = 0;
        }
    }
    out[32] = (int16_t)carry;
}

// acc += sum_i [scalars_i] pts_i over lanes with active[i] != 0.
// n_digits: 17 covers 128-bit scalars (+ carry), 33 covers 256-bit.
static void msm_accumulate(ge &acc, const ge *pts, const uint8_t *scalars,
                           const uint8_t *active, u64 n, int n_digits) {
    std::vector<int16_t> digits(n * 33);
    for (u64 i = 0; i < n; i++) {
        if (active && !active[i]) {
            memset(&digits[i * 33], 0, 33 * sizeof(int16_t));
            continue;
        }
        recode_signed(&digits[i * 33], scalars + i * 32);
    }

    ge buckets[128];
    bool used[128];
    ge local = GE_IDENTITY;
    bool acc_started = false;

    for (int w = n_digits - 1; w >= 0; w--) {
        if (acc_started)
            for (int k = 0; k < 8; k++) ge_dbl(local, local);
        memset(used, 0, sizeof(used));
        int max_b = -1;
        for (u64 i = 0; i < n; i++) {
            int d = digits[i * 33 + w];
            if (d == 0) continue;
            int b;
            ge p;
            if (d > 0) {
                b = d - 1;
                p = pts[i];
            } else {
                b = -d - 1;
                ge_neg(p, pts[i]);
            }
            if (used[b]) ge_add(buckets[b], buckets[b], p);
            else { buckets[b] = p; used[b] = true; }
            if (b > max_b) max_b = b;
        }
        if (max_b < 0) continue;
        // window sum = sum_b (b+1) * buckets[b] via running suffix sums
        ge run, wsum;
        bool run_started = false, wsum_started = false;
        for (int b = max_b; b >= 0; b--) {
            if (used[b]) {
                if (run_started) ge_add(run, run, buckets[b]);
                else { run = buckets[b]; run_started = true; }
            }
            if (run_started) {
                if (wsum_started) ge_add(wsum, wsum, run);
                else { wsum = run; wsum_started = true; }
            }
        }
        if (wsum_started) {
            if (acc_started) ge_add(local, local, wsum);
            else { local = wsum; acc_started = true; }
        }
    }
    if (acc_started) ge_add(acc, acc, local);
}

// ------------------------------------------------------------ base point

// B: y = 4/5, x even (RFC 8032), compressed encoding
static const uint8_t B_ENC[32] = {
    0x58, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66};

static ge BASE_POINT;
// eager init at dlopen time: no lazy races between verifier threads
static const bool BASE_READY = ge_decompress(BASE_POINT, B_ENC);

static const ge &base_point() { return BASE_POINT; }

// ------------------------------------------------------------- exports

extern "C" {

// out[i]: 0 = bad encoding, 1 = decompresses but carries torsion,
//         2 = certified torsion-free (exact [L]P == identity)
void at2_rlc_certify(const uint8_t *enc, u64 n, uint8_t *out) {
    for (u64 i = 0; i < n; i++) {
        ge p;
        if (!ge_decompress(p, enc + i * 32)) {
            out[i] = 0;
            continue;
        }
        out[i] = ge_mul_l_is_identity(p) ? 2 : 1;
    }
}

// One RLC check over n lanes.
//   r_enc, a_enc : n*32 compressed R_i / A_i
//   z_sc         : n*32 LE scalars z_i (128-bit, high half zero)
//   zh_sc        : n*32 LE scalars z_i*h_i mod L
//   zs_sc        : n*32 LE scalars z_i*s_i mod L (summed here over the
//                  lanes that actually decompress, so a bad encoding
//                  never unbalances the equation for the others)
//   valid        : n lane mask (0 lanes are excluded entirely)
//   tors         : k_rounds*n coefficients in [0,8) for the R-side
//                  randomized torsion rounds (row-major by round)
//   decomp_ok    : out, n — 1 when both R_i and A_i decompressed
// Returns 1 when the equation holds AND every torsion round folds to a
// point killed by [L], over lanes with valid && decomp_ok; 0 otherwise.
// Callers must treat decomp_ok[i]==0 lanes as individually invalid.
int at2_rlc_verify(const uint8_t *r_enc, const uint8_t *a_enc,
                   const uint8_t *z_sc, const uint8_t *zh_sc,
                   const uint8_t *zs_sc, const uint8_t *valid,
                   const uint8_t *tors, u64 k_rounds, u64 n,
                   uint8_t *decomp_ok) {
    if (n == 0) return 1;
    std::vector<ge> R(n), A(n);
    std::vector<uint8_t> active(n);
    u64 n_active = 0;
    for (u64 i = 0; i < n; i++) {
        if (!valid[i]) {
            decomp_ok[i] = 1;  // excluded lane: nothing to report
            active[i] = 0;
            continue;
        }
        bool ok = ge_decompress(R[i], r_enc + i * 32) &&
                  ge_decompress(A[i], a_enc + i * 32);
        decomp_ok[i] = ok ? 1 : 0;
        active[i] = ok ? 1 : 0;
        if (ok) n_active++;
    }
    if (n_active == 0) return 1;  // empty equation holds

    // RHS = sum [z_i] R_i + sum [z_i h_i] A_i
    ge rhs = GE_IDENTITY;
    msm_accumulate(rhs, R.data(), z_sc, active.data(), n, 17);
    msm_accumulate(rhs, A.data(), zh_sc, active.data(), n, 33);

    // LHS = [sum z_i s_i] B over the active lanes
    uint8_t zs[32] = {0};
    for (u64 i = 0; i < n; i++)
        if (active[i]) sc_add_mod_l(zs, zs, zs_sc + i * 32);
    ge lhs;
    ge_scalarmul(lhs, base_point(), zs);
    if (!ge_eq(lhs, rhs)) return 0;

    // R-side randomized torsion rounds: per-lane table of 1..7 multiples,
    // then k folds each killed by [L]
    std::vector<ge> tab(n * 7);
    for (u64 i = 0; i < n; i++) {
        if (!active[i]) continue;
        ge *t = &tab[i * 7];
        t[0] = R[i];
        ge_dbl(t[1], t[0]);          // 2R
        ge_add(t[2], t[1], t[0]);    // 3R
        ge_dbl(t[3], t[1]);          // 4R
        ge_add(t[4], t[3], t[0]);    // 5R
        ge_dbl(t[5], t[2]);          // 6R
        ge_add(t[6], t[5], t[0]);    // 7R
    }
    for (u64 r = 0; r < k_rounds; r++) {
        const uint8_t *c = tors + r * n;
        ge s = GE_IDENTITY;
        bool started = false;
        for (u64 i = 0; i < n; i++) {
            if (!active[i]) continue;
            int ci = c[i] & 7;
            if (ci == 0) continue;
            const ge &m = tab[i * 7 + (ci - 1)];
            if (started) ge_add(s, s, m);
            else { s = m; started = true; }
        }
        if (started && !ge_mul_l_is_identity(s)) return 0;
    }
    return 1;
}

// [k]P on a compressed point; returns 0 on bad encoding. Test hook for
// differential validation against the pure-python group law.
int at2_rlc_scalarmul(const uint8_t *enc, const uint8_t *sc, uint8_t *out) {
    ge p, r;
    if (!ge_decompress(p, enc)) return 0;
    ge_scalarmul(r, p, sc);
    ge_compress(out, r);
    return 1;
}

// decompression verdict alone (test hook)
int at2_rlc_decompress_check(const uint8_t *enc) {
    ge p;
    return ge_decompress(p, enc) ? 1 : 0;
}

// built-in sanity: field, decompression, group law, MSM, order
int at2_rlc_selftest() {
    // (p-1) + 2 == 1
    fe pm1 = {{MASK51 - 19, MASK51, MASK51, MASK51, MASK51}};
    fe two = FE_ONE, r;
    fe_add(two, FE_ONE, FE_ONE);
    fe_add(r, pm1, two);
    if (!fe_eq(r, FE_ONE)) return 1;
    // sqrt(-1)^2 == -1
    fe m1;
    fe_neg(m1, FE_ONE);
    fe_sq(r, FE_SQRTM1);
    if (!fe_eq(r, m1)) return 2;
    // base decompresses and [L]B == identity
    const ge &B = base_point();
    uint8_t benc[32];
    ge_compress(benc, B);
    if (memcmp(benc, B_ENC, 32) != 0) return 3;
    if (!ge_mul_l_is_identity(B)) return 4;
    // [2]B + [3]B == [5]B, dbl vs add agreement
    ge b2a, b2d, b3, b5a, b5b;
    ge_add(b2a, B, B);
    ge_dbl(b2d, B);
    if (!ge_eq(b2a, b2d)) return 5;
    ge_add(b3, b2d, B);
    ge_add(b5a, b2d, b3);
    uint8_t five[32] = {5};
    ge_scalarmul(b5b, B, five);
    if (!ge_eq(b5a, b5b)) return 6;
    // MSM: [2]B + [3]B via msm == [5]B
    ge pts[2] = {B, B};
    uint8_t scs[64] = {0};
    scs[0] = 2;
    scs[32] = 3;
    ge acc = GE_IDENTITY;
    msm_accumulate(acc, pts, scs, nullptr, 2, 33);
    if (!ge_eq(acc, b5b)) return 7;
    return 0;
}

}  // extern "C"
