// Native message-plane ingest for the broadcast stack.
//
// The reference runs its message plane on native worker threads
// (/root/reference/src/bin/server/rpc.rs:125 — num_cpus broadcast tasks
// in a compiled runtime); this build keeps the state machine in Python
// (single-writer asyncio, SURVEY.md §5) and moves the per-message grind
// here, called ONCE per worker chunk with the GIL released (ctypes):
//
//  * at2_parse_frames — wire-frame parsing for a whole chunk of frames:
//    kind dispatch, fixed-record extraction, and the SHA-256 payload
//    content hash (sieve's equivocation unit, broadcast/messages.py
//    Payload.content_hash) computed inline while the bytes are hot.
//  * at2_verify_bulk — ed25519 verification for every signature the
//    chunk needs, one call, fanned out over std::thread workers, each
//    thread reusing an EVP context and a per-call pubkey-object cache
//    (origins repeat heavily inside a chunk: echo/ready votes come from
//    the same small peer set). Backed by the system libcrypto
//    (OpenSSL 3), the same engine the Python `cryptography` path uses,
//    so verdicts are bit-identical with keys.verify_one.
//
// Wire layout parity (broadcast/messages.py, all integers LE):
//   GOSSIP       = 0x01 | sender(32) seq(u32) recipient(32) amount(u64) sig(64)
//   ECHO         = 0x02 | origin(32) sender(32) seq(u32) chash(32) sig(64)
//   READY        = 0x03 | (same body as ECHO)
//   REQUEST      = 0x04 | sender(32) seq(u32) chash(32)
//   HIST_IDX_REQ = 0x05 | nonce(u64)
//   HIST_IDX     = 0x06 | nonce(u64) count(u32) count*(sender(32) seq(u32))
//   HIST_REQ     = 0x07 | nonce(u64) sender(32) from(u32) to(u32)
//   HIST_BATCH   = 0x08 | nonce(u64) count(u32) count*(140-byte GOSSIP body)
// content_hash = SHA-256 over the 140-byte GOSSIP body (kind excluded).
// Variable-length kinds (6, 8) don't fit a fixed row: their row stores the
// body's (offset, length) into the caller's flat buffer and Python decodes
// the slice — they are rare control traffic, not the hot path.

#include <atomic>
#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include <sys/socket.h>
#include <unistd.h>

// ---------------- OpenSSL 3 EVP surface (no headers in the image; the
// declarations below are the stable libcrypto ABI) ----------------

extern "C" {
typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_md_ctx_st EVP_MD_CTX;
typedef struct engine_st ENGINE;
typedef struct evp_md_st EVP_MD;
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
EVP_PKEY* EVP_PKEY_new_raw_public_key(int type, ENGINE* e,
                                      const unsigned char* pub, size_t len);
void EVP_PKEY_free(EVP_PKEY* k);
EVP_MD_CTX* EVP_MD_CTX_new(void);
void EVP_MD_CTX_free(EVP_MD_CTX* ctx);
int EVP_MD_CTX_reset(EVP_MD_CTX* ctx);
int EVP_DigestVerifyInit(EVP_MD_CTX* ctx, void** pctx, const EVP_MD* type,
                         ENGINE* e, EVP_PKEY* pkey);
int EVP_DigestVerify(EVP_MD_CTX* ctx, const unsigned char* sig, size_t siglen,
                     const unsigned char* data, size_t datalen);
const EVP_CIPHER* EVP_chacha20_poly1305(void);
EVP_CIPHER_CTX* EVP_CIPHER_CTX_new(void);
void EVP_CIPHER_CTX_free(EVP_CIPHER_CTX* ctx);
int EVP_DecryptInit_ex(EVP_CIPHER_CTX* ctx, const EVP_CIPHER* cipher,
                       ENGINE* impl, const unsigned char* key,
                       const unsigned char* iv);
int EVP_CIPHER_CTX_ctrl(EVP_CIPHER_CTX* ctx, int type, int arg, void* ptr);
int EVP_DecryptUpdate(EVP_CIPHER_CTX* ctx, unsigned char* out, int* outl,
                      const unsigned char* in, int inl);
int EVP_DecryptFinal_ex(EVP_CIPHER_CTX* ctx, unsigned char* outm, int* outl);
}

static constexpr int kEvpPkeyEd25519 = 1087;  // NID_ED25519
static constexpr int kEvpCtrlAeadSetIvlen = 0x9;
static constexpr int kEvpCtrlAeadSetTag = 0x11;

namespace {

// ---------------- SHA-256 (FIPS 180-4) ----------------

constexpr uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr32(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }
inline uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// One-shot SHA-256 for short inputs (the 140-byte payload body spans
// exactly two blocks with padding; generic loop kept for clarity).
void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  auto block = [&](const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++) w[i] = be32(p + 4 * i);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
      uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  };
  size_t off = 0;
  for (; off + 64 <= len; off += 64) block(data + off);
  uint8_t tail[128];
  size_t rem = len - off;
  std::memcpy(tail, data + off, rem);
  tail[rem] = 0x80;
  size_t padded = (rem + 9 <= 64) ? 64 : 128;
  std::memset(tail + rem + 1, 0, padded - rem - 9);
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; i++) tail[padded - 1 - i] = uint8_t(bits >> (8 * i));
  block(tail);
  if (padded == 128) block(tail + 64);
  for (int i = 0; i < 8; i++) {
    out[4 * i + 0] = uint8_t(h[i] >> 24);
    out[4 * i + 1] = uint8_t(h[i] >> 16);
    out[4 * i + 2] = uint8_t(h[i] >> 8);
    out[4 * i + 3] = uint8_t(h[i]);
  }
}

// ---------------- wire constants (must match broadcast/messages.py) ----

constexpr uint8_t kGossip = 1, kEcho = 2, kReady = 3, kRequest = 4;
constexpr uint8_t kHistIdxReq = 5, kHistIdx = 6, kHistReq = 7, kHistBatch = 8;
constexpr uint8_t kBatch = 9, kBatchEcho = 10, kBatchReady = 11, kBatchReq = 12;
constexpr uint8_t kDirAnnounce = 13, kConfigTx = 14, kBeacon = 15;
constexpr uint8_t kCertSig = 16;
constexpr size_t kPayloadWire = 1 + 140;
constexpr size_t kAttestWire = 1 + 164;
constexpr size_t kRequestWire = 1 + 68;
constexpr size_t kHistIdxReqWire = 1 + 8;
constexpr size_t kHistReqWire = 1 + 48;
constexpr size_t kHistHdrWire = 1 + 12;  // nonce(u64) + count(u32)
constexpr size_t kHistIdxEntry = 36;
constexpr size_t kHistBatchEntry = 140;
// Batched broadcast plane (messages.py BATCH/BATCH_ECHO/BATCH_READY/
// BATCH_REQ):
//   BATCH      = 0x09 | origin(32) batch_seq(u64) count(u32) sig(64)
//                       count*(140-byte GOSSIP body)
//   BATCH_ECHO = 0x0a | origin(32) b_origin(32) b_seq(u64) b_hash(32)
//                       bm_len(u32) bitmap(bm_len) sig(64)
//   BATCH_READY= 0x0b | (same body as BATCH_ECHO)
//   BATCH_REQ  = 0x0c | b_origin(32) b_seq(u64) b_hash(32)
constexpr size_t kBatchHdrWire = 1 + 108;  // header before entries
constexpr size_t kBatchAttWire = 1 + 108 + 64;  // + bitmap between hdr/sig
constexpr size_t kBatchReqWire = 1 + 72;
constexpr uint64_t kMaxBatchEntries = 1024;  // messages.MAX_BATCH_ENTRIES
constexpr uint64_t kMaxBitmapBytes = kMaxBatchEntries / 8;
// DIR_ANNOUNCE = 0x0d | origin(32) count(u32) count*(id(u64) pubkey(32))
constexpr size_t kDirHdrWire = 1 + 36;
constexpr size_t kDirEntry = 40;
constexpr uint64_t kMaxDirEntries = 4096;  // messages.MAX_DIR_ENTRIES
// CONFIG_TX = 0x0e | epoch(u64) len(u32) sig(64) len*JSON bytes
constexpr size_t kConfigHdrWire = 1 + 76;
constexpr uint64_t kMaxConfigBytes = 4096;  // messages.MAX_CONFIG_BYTES
// BEACON = 0x0f | origin(32) epoch(u64) commits(u64) wm(16) ranges(128)
//                 dir(8) chain(32) sig(64) — fixed, messages.BEACON_WIRE
constexpr size_t kBeaconWire = 1 + 232 + 64;
// CERT_SIG = 0x10 | origin(32) epoch(u64) commits(u64) wm(16) ranges(128)
//                   dir(8) sig(64) — fixed, messages.CERT_SIG_WIRE
constexpr size_t kCertSigWire = 1 + 200 + 64;
constexpr size_t kMinWire = kHistIdxReqWire;  // smallest message on the wire
// A legitimate frame coalesces at most MAX_BATCH_MSGS = 1024 messages
// (net/peers.py); 4x that is the malformed-frame bound. Without it a
// frame dense with 9-byte messages forces a row allocation ~8x the frame
// size and millions of Python objects downstream.
constexpr int64_t kMaxMsgsPerFrame = 4096;

inline uint32_t le32(const uint8_t* p) {
  return uint32_t(p[0]) | (uint32_t(p[1]) << 8) | (uint32_t(p[2]) << 16) |
         (uint32_t(p[3]) << 24);
}

inline uint64_t le64(const uint8_t* p) {
  return uint64_t(le32(p)) | (uint64_t(le32(p + 4)) << 32);
}

// Output record: one fixed-stride row per message.
//   byte 0            : kind (0 = row unused)
//   GOSSIP  row [1..141): the 140-byte wire body, [141..173): content hash
//   ECHO/READY [1..165): the 164-byte wire body
//   REQUEST row [1..69) : the 68-byte wire body
//   HIST_IDX_REQ [1..9) : the 8-byte wire body
//   HIST_REQ  row [1..49): the 48-byte wire body
//   HIST_IDX / HIST_BATCH [1..9): u64 LE body offset into `flat`,
//                         [9..17): u64 LE body length (incl. the header)
constexpr size_t kRowStride = 176;  // 173 rounded up for alignment

inline void put_le64(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = uint8_t(v >> (8 * i));
}

inline void put_le32(uint8_t* p, uint32_t v) {
  for (int i = 0; i < 4; i++) p[i] = uint8_t(v >> (8 * i));
}

// ---------------- distilled frames (proto/distill.py reference) --------

constexpr uint8_t kDistillMagic = 0xD5, kDistillVersion = 0x01;
constexpr uint64_t kDistillMaxEntries = 4096;  // distill.DISTILL_MAX_ENTRIES
constexpr size_t kEntryWire = 140;
constexpr size_t kSigWire = 64;

// LEB128 u64 with exactly distill._read_varint's acceptance set: up to
// 10 bytes, values <= 2^64-1, non-minimal encodings allowed (the Python
// and native decoders must accept/reject identical byte strings — they
// are differential-tested in tests/test_distill.py).
inline bool read_varint(const uint8_t* buf, size_t len, size_t& off,
                        uint64_t& out) {
  uint64_t result = 0;
  int shift = 0;
  for (int i = 0; i < 10; i++) {
    if (off >= len) return false;
    uint8_t b = buf[off++];
    uint64_t bits = uint64_t(b & 0x7F);
    if (shift == 63 && bits > 1) return false;  // > 2^64-1
    result |= bits << shift;
    if (!(b & 0x80)) {
      out = result;
      return true;
    }
    shift += 7;
  }
  return false;  // longer than 10 bytes
}

}  // namespace

extern "C" {

// Shared parse loop behind at2_parse_frames and at2_plane_drain: when
// `shard_ids` is non-null, every row additionally gets its owning
// shard — computed from the SLOT origin key exactly like
// broadcast/shards.shard_of (first 8 key bytes, little-endian, modulo):
//   GOSSIP/REQUEST            -> sender      (body offset 0)
//   ECHO/READY                -> sender      (body offset 32; byte 0..32
//                                             is the attesting origin)
//   BATCH/BATCH_REQ           -> batch origin (body offset 0)
//   BATCH_ECHO/BATCH_READY    -> batch origin (body offset 32)
//   control kinds             -> shard 0 (stateless wrt shard slots)
static int64_t parse_frames_impl(const uint8_t* flat, const uint64_t* offsets,
                                 int64_t n_frames, uint8_t* rows, int64_t cap,
                                 uint32_t* msg_frame, uint8_t* frame_ok,
                                 int64_t shards, uint32_t* shard_ids) {
  int64_t n_out = 0;
  for (int64_t f = 0; f < n_frames; f++) {
    const uint8_t* p = flat + offsets[f];
    const uint8_t* end = flat + offsets[f + 1];
    int64_t start = n_out;
    bool ok = true;
    while (p < end) {
      size_t left = size_t(end - p);
      uint8_t kind = p[0];
      size_t wire;
      if (kind == kGossip) wire = kPayloadWire;
      else if (kind == kEcho || kind == kReady) wire = kAttestWire;
      else if (kind == kRequest) wire = kRequestWire;
      else if (kind == kHistIdxReq) wire = kHistIdxReqWire;
      else if (kind == kHistReq) wire = kHistReqWire;
      else if (kind == kHistIdx || kind == kHistBatch) {
        if (left < kHistHdrWire) { ok = false; break; }
        uint64_t count = le32(p + 9);
        size_t entry = (kind == kHistIdx) ? kHistIdxEntry : kHistBatchEntry;
        wire = kHistHdrWire + size_t(count) * entry;  // < 2^40, no overflow
      } else if (kind == kBatch) {
        if (left < kBatchHdrWire) { ok = false; break; }
        uint64_t count = le32(p + 1 + 40);  // after origin(32) + seq(8)
        if (count < 1 || count > kMaxBatchEntries) { ok = false; break; }
        wire = kBatchHdrWire + size_t(count) * kHistBatchEntry;
      } else if (kind == kBatchEcho || kind == kBatchReady) {
        if (left < kBatchAttWire) { ok = false; break; }
        uint64_t bm_len = le32(p + 1 + 104);  // last header field
        if (bm_len > kMaxBitmapBytes) { ok = false; break; }
        wire = kBatchAttWire + size_t(bm_len);
      } else if (kind == kBatchReq) {
        wire = kBatchReqWire;
      } else if (kind == kDirAnnounce) {
        if (left < kDirHdrWire) { ok = false; break; }
        uint64_t count = le32(p + 1 + 32);
        if (count > kMaxDirEntries) { ok = false; break; }
        wire = kDirHdrWire + size_t(count) * kDirEntry;
      } else if (kind == kConfigTx) {
        if (left < kConfigHdrWire) { ok = false; break; }
        uint64_t body_len = le32(p + 1 + 8);  // after epoch(u64)
        if (body_len > kMaxConfigBytes) { ok = false; break; }
        wire = kConfigHdrWire + size_t(body_len);
      } else if (kind == kBeacon) {
        wire = kBeaconWire;  // fixed but wider than kRowStride
      } else if (kind == kCertSig) {
        wire = kCertSigWire;  // fixed but wider than kRowStride
      } else { ok = false; break; }
      if (left < wire) { ok = false; break; }
      if (n_out - start >= kMaxMsgsPerFrame) { ok = false; break; }
      if (n_out >= cap) return -1;
      uint8_t* row = rows + n_out * kRowStride;
      row[0] = kind;
      if (kind == kHistIdx || kind == kHistBatch || kind == kBatch ||
          kind == kBatchEcho || kind == kBatchReady || kind == kDirAnnounce ||
          kind == kConfigTx || kind == kBeacon || kind == kCertSig) {
        // variable-length kinds (and the beacon/cert co-sig, whose fixed
        // bodies are wider than kRowStride): row carries (offset, length)
        // into `flat`
        put_le64(row + 1, uint64_t(p + 1 - flat));
        put_le64(row + 9, uint64_t(wire - 1));
      } else {
        std::memcpy(row + 1, p + 1, wire - 1);
        if (kind == kGossip) sha256(p + 1, 140, row + 141);
      }
      if (shard_ids != nullptr) {
        const uint8_t* rkey = nullptr;
        if (kind == kGossip || kind == kRequest || kind == kBatch ||
            kind == kBatchReq) {
          rkey = p + 1;  // sender / batch origin leads the body
        } else if (kind == kEcho || kind == kReady || kind == kBatchEcho ||
                   kind == kBatchReady) {
          rkey = p + 33;  // slot key follows the attesting origin
        }
        shard_ids[n_out] =
            rkey ? uint32_t(le64(rkey) % uint64_t(shards)) : 0;
      }
      msg_frame[n_out] = uint32_t(f);
      n_out++;
      p += wire;
    }
    frame_ok[f] = ok ? 1 : 0;
    if (!ok) n_out = start;  // drop the whole frame, like parse_frame
  }
  return n_out;
}

// Parse n_frames concatenated-message frames (flat + offsets, like the
// prep library's ragged layout) into fixed rows. Returns the number of
// messages written, or -1 if `cap` rows were not enough (caller resizes
// and retries). A malformed frame sets frame_ok[f]=0 and contributes no
// rows (mirrors on_frame's per-frame drop); well-formed frames set 1.
// msg_frame[i] = source frame index of row i (the peer association).
int64_t at2_parse_frames(const uint8_t* flat, const uint64_t* offsets,
                         int64_t n_frames, uint8_t* rows, int64_t cap,
                         uint32_t* msg_frame, uint8_t* frame_ok) {
  return parse_frames_impl(flat, offsets, n_frames, rows, cap, msg_frame,
                           frame_ok, 1, nullptr);
}

// The owner drain loop's ONE GIL-released call (ISSUE 17): parse a whole
// chunk of frames AND route every row to its owning shard in the same
// pass, so the Python side goes straight from raw frames to per-shard
// record batches with no per-message isinstance dispatch. Outputs are
// at2_parse_frames' plus shard_ids[i] (owning shard of row i) and
// shard_counts[s] (rows routed to shard s, rollback-corrected for
// malformed frames). Quorum folding stays in at2_counts_add /
// at2_quorum_mask, which the shard cores call per transition — this
// kernel's job is everything BEFORE the cores: validate, extract, hash,
// route, tally.
int64_t at2_plane_drain(const uint8_t* flat, const uint64_t* offsets,
                        int64_t n_frames, int64_t shards, uint8_t* rows,
                        int64_t cap, uint32_t* msg_frame, uint8_t* frame_ok,
                        uint32_t* shard_ids, int64_t* shard_counts) {
  if (shards <= 0) return -2;
  int64_t n = parse_frames_impl(flat, offsets, n_frames, rows, cap,
                                msg_frame, frame_ok, shards, shard_ids);
  if (n < 0) return n;
  for (int64_t s = 0; s < shards; s++) shard_counts[s] = 0;
  for (int64_t i = 0; i < n; i++) shard_counts[shard_ids[i]]++;
  return n;
}

// Bulk ed25519 verify: out[i] = 1 iff signature i verifies under OpenSSL
// (bit-identical verdicts with crypto/keys.verify_one — same libcrypto).
// Ragged inputs like at2_prep_batch; fans out over n_threads.
void at2_verify_bulk(const uint8_t* pk_flat, const uint64_t* pk_off,
                     const uint8_t* msg_flat, const uint64_t* msg_off,
                     const uint8_t* sig_flat, const uint64_t* sig_off,
                     int64_t n, int64_t n_threads, uint8_t* out) {
  if (n <= 0) return;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n) n_threads = n;

  auto worker = [&](int64_t lo, int64_t hi) {
    // per-thread pubkey-object cache: echo/ready origins inside one
    // chunk come from the same handful of peers
    struct KeyHash {
      size_t operator()(const std::vector<uint8_t>& k) const {
        uint64_t h = 1469598103934665603ULL;
        for (uint8_t b : k) { h ^= b; h *= 1099511628211ULL; }
        return size_t(h);
      }
    };
    std::unordered_map<std::vector<uint8_t>, EVP_PKEY*, KeyHash> cache;
    EVP_MD_CTX* ctx = EVP_MD_CTX_new();
    for (int64_t i = lo; i < hi; i++) {
      out[i] = 0;
      size_t pk_len = size_t(pk_off[i + 1] - pk_off[i]);
      size_t sig_len = size_t(sig_off[i + 1] - sig_off[i]);
      if (pk_len != 32 || sig_len != 64 || ctx == nullptr) continue;
      std::vector<uint8_t> key(pk_flat + pk_off[i], pk_flat + pk_off[i + 1]);
      EVP_PKEY* pkey;
      auto it = cache.find(key);
      if (it != cache.end()) {
        pkey = it->second;
      } else {
        pkey = EVP_PKEY_new_raw_public_key(kEvpPkeyEd25519, nullptr,
                                           key.data(), 32);
        cache.emplace(std::move(key), pkey);  // cache NULL too (bad key)
      }
      if (pkey == nullptr) continue;
      // one-shot EdDSA contexts don't re-init cleanly: reset between items
      EVP_MD_CTX_reset(ctx);
      if (EVP_DigestVerifyInit(ctx, nullptr, nullptr, nullptr, pkey) != 1)
        continue;
      int rc = EVP_DigestVerify(ctx, sig_flat + sig_off[i], 64,
                                msg_flat + msg_off[i],
                                size_t(msg_off[i + 1] - msg_off[i]));
      out[i] = (rc == 1) ? 1 : 0;
    }
    EVP_MD_CTX_free(ctx);
    for (auto& kv : cache)
      if (kv.second != nullptr) EVP_PKEY_free(kv.second);
  };

  if (n_threads == 1) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t step = (n + n_threads - 1) / n_threads;
  for (int64_t t = 0; t < n_threads; t++) {
    int64_t lo = t * step;
    int64_t hi = lo + step < n ? lo + step : n;
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// Distilled-frame bulk parse + expansion (the broker ingress fast path;
// proto/distill.py documents the wire format and is the reference
// decoder). One GIL-released pass: decode the varint/delta head, resolve
// sender/recipient client-ids against the directory table (`dir_keys` =
// dir_count x 32 contiguous rows, an all-zero row means unassigned), and
// expand every entry to its 140-byte canonical GOSSIP body — exactly the
// `entries_raw` bytes TxBatch carries — with the columnar signature
// copied in. No per-entry Python objects are ever built on this path.
//
// Returns the entry count, or -1 on any malformation (same acceptance
// set as distill.decode). Per entry i: out_ids[i] = sender client-id,
// out_ok[i] = 1 iff both sender and recipient ids resolved (misses zero
// the unresolved field; the caller counts them as directory_misses and
// drops the entry before verification).
int64_t at2_distill_parse(const uint8_t* frame, int64_t frame_len,
                          const uint8_t* dir_keys, int64_t dir_count,
                          uint8_t* out_bodies, uint64_t* out_ids,
                          uint8_t* out_ok, int64_t cap) {
  static const uint8_t kZero32[32] = {0};
  if (frame_len < 4) return -1;
  size_t len = size_t(frame_len);
  if (frame[0] != kDistillMagic || frame[1] != kDistillVersion) return -1;
  size_t off = 2;
  uint64_t n_groups, n_entries;
  if (!read_varint(frame, len, off, n_groups)) return -1;
  if (!read_varint(frame, len, off, n_entries)) return -1;
  if (n_groups == 0 || n_entries == 0) return -1;
  if (n_entries > kDistillMaxEntries || n_groups > n_entries) return -1;
  if (int64_t(n_entries) > cap) return -1;
  uint64_t sig_len = n_entries * kSigWire;
  if (len < off + sig_len) return -1;
  size_t sig_start = len - size_t(sig_len);

  auto resolve = [&](uint64_t id) -> const uint8_t* {
    if (id >= uint64_t(dir_count)) return nullptr;
    const uint8_t* row = dir_keys + size_t(id) * 32;
    if (std::memcmp(row, kZero32, 32) == 0) return nullptr;
    return row;
  };

  int64_t n_out = 0;
  uint64_t prev_id = 0;
  bool first_group = true;
  for (uint64_t g = 0; g < n_groups; g++) {
    uint64_t delta, gid;
    if (!read_varint(frame, len, off, delta)) return -1;
    if (first_group) {
      gid = delta;
      first_group = false;
    } else {
      if (delta == 0) return -1;  // ids not strictly increasing
      if (delta > UINT64_MAX - prev_id) return -1;  // id exceeds u64
      gid = prev_id + delta;
    }
    prev_id = gid;
    uint64_t n;
    if (!read_varint(frame, len, off, n)) return -1;
    if (n == 0 || uint64_t(n_out) + n > n_entries) return -1;
    const uint8_t* sender = resolve(gid);
    uint64_t prev_seq = 0;
    for (uint64_t e = 0; e < n; e++) {
      uint64_t sd;
      if (!read_varint(frame, len, off, sd)) return -1;
      if (sd == 0) return -1;  // seqs not strictly increasing
      uint64_t seq = prev_seq + sd;
      if (seq > 0xFFFFFFFFULL) return -1;  // sequence exceeds u32
      prev_seq = seq;
      uint64_t rtag;
      if (!read_varint(frame, len, off, rtag)) return -1;
      const uint8_t* recipient;
      bool recipient_ok;
      if (rtag == 0) {
        if (off + 32 > sig_start) return -1;  // truncated raw recipient
        recipient = frame + off;
        recipient_ok = true;
        off += 32;
      } else {
        recipient = resolve(rtag - 1);
        recipient_ok = recipient != nullptr;
      }
      uint64_t amount;
      if (!read_varint(frame, len, off, amount)) return -1;
      if (off > sig_start) return -1;  // head overruns signature block
      uint8_t* body = out_bodies + size_t(n_out) * kEntryWire;
      std::memcpy(body, sender != nullptr ? sender : kZero32, 32);
      put_le32(body + 32, uint32_t(seq));
      std::memcpy(body + 36, recipient != nullptr ? recipient : kZero32, 32);
      put_le64(body + 68, amount);
      std::memcpy(body + 76, frame + sig_start + size_t(n_out) * kSigWire,
                  kSigWire);
      out_ids[n_out] = gid;
      out_ok[n_out] = (sender != nullptr && recipient_ok) ? 1 : 0;
      n_out++;
    }
  }
  if (uint64_t(n_out) != n_entries) return -1;
  if (off != sig_start) return -1;  // trailing bytes before signatures
  return n_out;
}

}  // extern "C"

// ---------------- native channel reader ----------------
//
// One thread per INBOUND mesh connection (the responder side only ever
// reads — net/peers.py's one-connection-per-ordered-pair design). The
// thread owns the socket reads, the per-frame ChaCha20-Poly1305
// decryption (transport.py wire format: u32-LE ciphertext length ||
// ciphertext, nonce = LE frame counter || 4 zero bytes, 16-byte tag
// appended), and frame assembly; decrypted frames accumulate in a
// byte-bounded queue and Python is woken via ONE pipe byte per
// empty->nonempty transition — collapsing the per-frame event-loop
// wakeups that profiling showed were the plane's asyncio floor
// (BENCH_E2E.json analysis). Parsing stays in the existing per-chunk
// native call, so the inbox byte budget and catchup plane are
// untouched.

namespace {

constexpr size_t kReaderMaxFrame = 16 * 1024 * 1024;  // transport.MAX_FRAME
constexpr size_t kReaderQueueBytes = 32 * 1024 * 1024;

struct At2Reader {
  int fd = -1;
  int wake_fd = -1;
  uint8_t key[32];
  uint64_t ctr = 0;
  std::thread thread;
  std::mutex mu;
  std::deque<std::vector<uint8_t>> pending;
  size_t pending_bytes = 0;
  int32_t status = 0;  // 0 open, 1 clean eof, 2 protocol/decrypt error
  uint64_t drops = 0;
  std::atomic<bool> stopping{false};

  bool read_exact(uint8_t* buf, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t r = ::read(fd, buf + off, n - off);
      if (r > 0) {
        off += size_t(r);
      } else if (r == 0) {
        return false;  // eof
      } else if (errno == EINTR) {
        continue;
      } else {
        return false;
      }
    }
    return true;
  }

  void wake() {
    uint8_t b = 1;
    // best-effort: a full pipe already guarantees a pending wakeup
    (void)!::write(wake_fd, &b, 1);
  }

  void finish(int32_t st) {
    {
      std::lock_guard<std::mutex> lock(mu);
      status = st;
    }
    wake();
  }

  void run() {
    EVP_CIPHER_CTX* ctx = EVP_CIPHER_CTX_new();
    if (ctx == nullptr) { finish(2); return; }
    std::vector<uint8_t> ct, pt;
    while (!stopping.load(std::memory_order_relaxed)) {
      uint8_t hdr[4];
      if (!read_exact(hdr, 4)) { finish(1); break; }
      uint32_t len = le32(hdr);
      if (len < 16 || len > kReaderMaxFrame) { finish(2); break; }
      ct.resize(len);
      if (!read_exact(ct.data(), len)) { finish(1); break; }
      uint8_t iv[12] = {0};
      uint64_t c = ctr++;
      for (int i = 0; i < 8; i++) iv[i] = uint8_t(c >> (8 * i));
      pt.resize(len - 16);
      int outl = 0, finl = 0;
      bool ok = EVP_DecryptInit_ex(ctx, EVP_chacha20_poly1305(), nullptr,
                                   nullptr, nullptr) == 1 &&
                EVP_CIPHER_CTX_ctrl(ctx, kEvpCtrlAeadSetIvlen, 12,
                                    nullptr) == 1 &&
                EVP_DecryptInit_ex(ctx, nullptr, nullptr, key, iv) == 1 &&
                EVP_DecryptUpdate(ctx, pt.data(), &outl, ct.data(),
                                  int(len - 16)) == 1 &&
                EVP_CIPHER_CTX_ctrl(ctx, kEvpCtrlAeadSetTag, 16,
                                    ct.data() + (len - 16)) == 1 &&
                EVP_DecryptFinal_ex(ctx, pt.data() + outl, &finl) == 1;
      if (!ok || size_t(outl + finl) != pt.size()) {
        finish(2);  // bad tag == wire corruption/attacker: channel-fatal
        break;
      }
      bool was_empty;
      {
        std::lock_guard<std::mutex> lock(mu);
        if (pending_bytes + pt.size() > kReaderQueueBytes) {
          drops++;  // best-effort plane: saturated queue drops new frames
          continue;
        }
        was_empty = pending.empty();
        pending_bytes += pt.size();
        pending.emplace_back(std::move(pt));
        pt = std::vector<uint8_t>();
      }
      if (was_empty) wake();
    }
    EVP_CIPHER_CTX_free(ctx);
  }
};

}  // namespace

extern "C" {

void* at2_reader_start(int fd, const uint8_t* key, int wake_fd) {
  auto* r = new At2Reader();
  r->fd = fd;
  r->wake_fd = wake_fd;
  std::memcpy(r->key, key, 32);
  r->thread = std::thread([r] { r->run(); });
  return r;
}

// Copy out queued frames: up to max_frames frames whose total size fits
// buf_cap. offsets[0..n] are frame boundaries in buf. Returns the frame
// count (0 = nothing pending), or -(size) when the next frame alone
// exceeds buf_cap (the caller grows its buffer and retries — a frame can
// legitimately be up to transport.MAX_FRAME). *status_out reports the
// channel state and *drops_out the saturated-queue drop counter.
int64_t at2_reader_take(void* handle, uint8_t* buf, int64_t buf_cap,
                        uint64_t* offsets, int64_t max_frames,
                        int32_t* status_out, uint64_t* drops_out) {
  auto* r = static_cast<At2Reader*>(handle);
  std::lock_guard<std::mutex> lock(r->mu);
  int64_t n = 0;
  uint64_t off = 0;
  offsets[0] = 0;
  while (n < max_frames && !r->pending.empty()) {
    auto& f = r->pending.front();
    if (off + f.size() > uint64_t(buf_cap)) {
      if (n == 0) {
        *status_out = r->status;
        *drops_out = r->drops;
        return -int64_t(f.size());
      }
      break;
    }
    std::memcpy(buf + off, f.data(), f.size());
    off += f.size();
    offsets[++n] = off;
    r->pending_bytes -= f.size();
    r->pending.pop_front();
  }
  *status_out = r->status;
  *drops_out = r->drops;
  return n;
}

// Stop the thread (shutdown unblocks the read), join, free. The caller
// still owns fd and wake_fd and closes them afterwards.
void at2_reader_stop(void* handle) {
  auto* r = static_cast<At2Reader*>(handle);
  r->stopping.store(true, std::memory_order_relaxed);
  ::shutdown(r->fd, SHUT_RD);
  if (r->thread.joinable()) r->thread.join();
  delete r;
}

// Layout exports so the Python binding never hardcodes them.
int64_t at2_ingest_row_stride(void) { return int64_t(kRowStride); }
int64_t at2_ingest_min_wire(void) { return int64_t(kMinWire); }

// ---------------------------------------------------------------------------
// Shard-local quorum counting. The sharded broadcast plane keeps its per-slot
// endorsement bitmaps as little-endian byte strings (Python ints on the wire
// side) and its vote tallies as int32 arrays. The two hot loops — "fold a
// newly-seen bitmap into the tally" and "which entries cleared threshold" —
// used to bounce through numpy per attestation; here they run GIL-released
// per ctypes call so shard threads genuinely overlap.

// counts[i] += 1 for every set bit i in bm[0..nbytes). ncounts caps the
// writable tally range; bits at or past it are ignored (callers clamp nbits
// before ever reaching here, this is belt-and-braces against overrun).
// Returns the number of bits folded in.
int64_t at2_counts_add(const uint8_t* bm, int64_t nbytes,
                       int32_t* counts, int64_t ncounts) {
  int64_t folded = 0;
  for (int64_t byte = 0; byte < nbytes; ++byte) {
    uint8_t b = bm[byte];
    while (b) {
      int bit = __builtin_ctz(b);
      b &= uint8_t(b - 1);
      int64_t idx = byte * 8 + bit;
      if (idx < ncounts) {
        counts[idx] += 1;
        ++folded;
      }
    }
  }
  return folded;
}

// out[0..out_len) becomes the little-endian packed bitmap of indices with
// counts[i] >= threshold, for i < n. Returns the popcount of the mask.
int64_t at2_quorum_mask(const int32_t* counts, int64_t n, int32_t threshold,
                        uint8_t* out, int64_t out_len) {
  std::memset(out, 0, size_t(out_len));
  int64_t set = 0;
  int64_t lim = n < out_len * 8 ? n : out_len * 8;
  for (int64_t i = 0; i < lim; ++i) {
    if (counts[i] >= threshold) {
      out[i >> 3] |= uint8_t(1u << (i & 7));
      ++set;
    }
  }
  return set;
}

}  // extern "C"
