// Native host-side batch preparation for the TPU ed25519 verifier.
//
// The TPU kernel (ops/pallas_verify.py) consumes per-signature arrays
// (A, R, S, h = SHA-512(R||A||M) mod L, valid). Producing them in Python
// costs ~6-10us/signature (hashlib + int conversions in a loop), which
// caps the pipeline well below the device rate. This translation unit
// does the same work at ~0.5us/signature/core: SHA-512 (FIPS 180-4,
// implemented here because no system OpenSSL headers exist in the image),
// the 512-bit -> mod-L reduction, the S < L malleability check, and
// batch packing — optionally fanned out over std::thread workers.
//
// Mirrors the reference's native execution model (its Rust broadcast
// stack verifies and hashes on native threads,
// /root/reference/src/bin/server/rpc.rs:125); here the native side feeds
// the TPU instead of doing the curve math itself.
//
// Exact-parity contract with ops.ed25519.prepare_batch: invalid items
// (bad lengths, S >= L) leave their rows zeroed and valid=0.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---------------- SHA-512 (FIPS 180-4) ----------------

constexpr uint64_t K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

inline uint64_t rotr(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }
inline uint64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

struct Sha512 {
  uint64_t h[8] = {0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
                   0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
                   0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
                   0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  uint8_t buf[128];
  size_t buflen = 0;
  uint64_t total = 0;

  void block(const uint8_t* p) {
    uint64_t w[80];
    for (int i = 0; i < 16; i++) w[i] = be64(p + 8 * i);
    for (int i = 16; i < 80; i++) {
      uint64_t s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
      uint64_t s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
      uint64_t S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
      uint64_t ch = (e & f) ^ (~e & g);
      uint64_t t1 = hh + S1 + ch + K[i] + w[i];
      uint64_t S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
      uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint64_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    total += n;
    if (buflen) {
      size_t take = n < 128 - buflen ? n : 128 - buflen;
      std::memcpy(buf + buflen, p, take);
      buflen += take; p += take; n -= take;
      if (buflen == 128) { block(buf); buflen = 0; }
    }
    while (n >= 128) { block(p); p += 128; n -= 128; }
    if (n) { std::memcpy(buf, p, n); buflen = n; }
  }

  void final(uint8_t out[64]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (buflen != 112) update(&z, 1);
    uint8_t len[16] = {0};
    for (int i = 0; i < 8; i++) len[15 - i] = (uint8_t)(bits >> (8 * i));
    update(len, 16);
    for (int i = 0; i < 8; i++)
      for (int j = 0; j < 8; j++) out[8 * i + j] = (uint8_t)(h[i] >> (56 - 8 * j));
  }
};

// ---------------- mod-L scalar arithmetic ----------------
// L = 2^252 + C, C = 27742317777372353535851937790883648493

constexpr uint64_t C0 = 0x5812631a5cf5d3edULL;  // C low word
constexpr uint64_t C1 = 0x14def9dea2f79cd6ULL;  // C high word (C = C1<<64 | C0)
constexpr uint64_t L0 = C0, L1 = C1, L2 = 0, L3 = 1ULL << 60;  // L words

inline bool geq256(const uint64_t a[4], const uint64_t b[4]) {
  for (int i = 3; i >= 0; i--) {
    if (a[i] != b[i]) return a[i] > b[i];
  }
  return true;
}

// ---- sign/magnitude bignum helpers over fixed 7-word (448-bit) values --

constexpr int NW = 7;

struct Big {
  uint64_t w[NW] = {0};  // little-endian magnitude
  bool neg = false;
};

inline bool big_is_zero(const Big& a) {
  for (int i = 0; i < NW; i++)
    if (a.w[i]) return false;
  return true;
}

// magnitude >> 252 (252 = 3*64 + 60)
inline void shr252(const uint64_t in[NW], uint64_t out[NW]) {
  for (int i = 0; i < NW; i++) {
    uint64_t lo = (i + 3 < NW) ? in[i + 3] >> 60 : 0;
    uint64_t hi = (i + 4 < NW) ? in[i + 4] << 4 : 0;
    out[i] = lo | hi;
  }
}

// magnitude & (2^252 - 1)
inline void low252(const uint64_t in[NW], uint64_t out[NW]) {
  out[0] = in[0]; out[1] = in[1]; out[2] = in[2];
  out[3] = in[3] & 0x0FFFFFFFFFFFFFFFULL;
  for (int i = 4; i < NW; i++) out[i] = 0;
}

// out = a * C (C is 2 words); a limited so the product fits NW words
inline void mul_c(const uint64_t a[NW], uint64_t out[NW]) {
  uint64_t c[2] = {C0, C1};
  uint64_t t[NW + 2] = {0};
  for (int i = 0; i < NW; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 2; j++) {
      if (i + j >= NW + 2) break;
      unsigned __int128 cur =
          (unsigned __int128)a[i] * c[j] + t[i + j] + (uint64_t)carry;
      t[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    for (int k = i + 2; carry && k < NW + 2; k++) {
      unsigned __int128 cur = (unsigned __int128)t[k] + (uint64_t)carry;
      t[k] = (uint64_t)cur;
      carry = cur >> 64;
    }
  }
  for (int i = 0; i < NW; i++) out[i] = t[i];
}

// out = |a - b|, returns true iff (a - b) is negative
inline bool sub_mag(const uint64_t a[NW], const uint64_t b[NW],
                    uint64_t out[NW]) {
  unsigned __int128 borrow = 0;
  uint64_t d[NW];
  for (int i = 0; i < NW; i++) {
    unsigned __int128 cur =
        (unsigned __int128)a[i] - b[i] - (uint64_t)borrow;
    d[i] = (uint64_t)cur;
    borrow = (cur >> 64) ? 1 : 0;
  }
  if (!borrow) {
    std::memcpy(out, d, sizeof(d));
    return false;
  }
  // negate (two's complement) to get |a - b|
  unsigned __int128 carry = 1;
  for (int i = 0; i < NW; i++) {
    unsigned __int128 cur = (unsigned __int128)(~d[i]) + (uint64_t)carry;
    out[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  return true;
}

// Reduce a 512-bit little-endian value mod L into out[32] (little-endian).
//
// Fold identity: x = h*2^252 + l  ==>  x === l - h*C (mod L), since
// 2^252 === -C (mod L). Each fold shrinks the magnitude by ~127 bits
// (C ~ 2^125), so three folds bring 512 bits under 2^253; sign is
// tracked explicitly and resolved against L at the end.
void mod_l(const uint8_t in[64], uint8_t out[32]) {
  Big x;
  for (int i = 0; i < 8; i++) {
    uint64_t v = 0;
    for (int j = 7; j >= 0; j--) v = (v << 8) | in[8 * i + j];
    if (i < NW) x.w[i] = v;
    else {
      // word 7 (bits 448..511): fold immediately via 2^448 = 2^196 * 2^252
      // by placing it in a high Big and running the generic folds below —
      // NW=7 can't hold it, so pre-fold: x = h448*2^448 + rest;
      // 2^448 === -C * 2^196 (mod L). h448 * C < 2^189, shifted by 196
      // stays < 2^385: subtract (h448*C) << 196 from the magnitude.
      uint64_t hc[NW] = {0};
      uint64_t h1[NW] = {v, 0, 0, 0, 0, 0, 0};
      mul_c(h1, hc);
      // shift hc left by 196 = 3*64 + 4
      uint64_t shifted[NW] = {0};
      for (int k = NW - 1; k >= 3; k--) {
        uint64_t lo = hc[k - 3] << 4;
        uint64_t hi = (k - 4 >= 0) ? hc[k - 4] >> 60 : 0;
        shifted[k] = lo | hi;
      }
      bool n = sub_mag(x.w, shifted, x.w);
      x.neg = n ? !x.neg : x.neg;
    }
  }
  for (int round = 0; round < 4; round++) {
    uint64_t h[NW], l[NW], hc[NW];
    shr252(x.w, h);
    bool h_zero = true;
    for (int i = 0; i < NW; i++) h_zero = h_zero && !h[i];
    if (h_zero) break;
    low252(x.w, l);
    mul_c(h, hc);
    bool n = sub_mag(l, hc, x.w);
    x.neg = n ? !x.neg : x.neg;  // l - h*C with x's sign preserved
  }
  // |x| < 2^253 < 2L; resolve into [0, L):
  //   1. if |x| >= L subtract L once (now |x| in [0, L))
  //   2. if the sign is negative and |x| != 0, result = L - |x|
  uint64_t Lw[NW] = {L0, L1, L2, L3, 0, 0, 0};
  uint64_t tmp[NW];
  if (!sub_mag(x.w, Lw, tmp)) {  // x.w >= L
    std::memcpy(x.w, tmp, sizeof(tmp));
  }
  if (x.neg && !big_is_zero(x)) {
    sub_mag(Lw, x.w, x.w);  // L - |x|, always non-negative here
  }
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) out[8 * i + j] = (uint8_t)(x.w[i] >> (8 * j));
}

// S < L check on 32 little-endian bytes
bool scalar_in_range(const uint8_t s[32]) {
  uint64_t w[4];
  for (int i = 0; i < 4; i++) {
    uint64_t v = 0;
    for (int j = 7; j >= 0; j--) v = (v << 8) | s[8 * i + j];
    w[i] = v;
  }
  uint64_t Lw[4] = {L0, L1, L2, L3};
  return !geq256(w, Lw);
}

void prep_range(const uint8_t* pks, const uint64_t* pk_off,
                const uint8_t* msgs, const uint64_t* msg_off,
                const uint8_t* sigs, const uint64_t* sig_off,
                int64_t start, int64_t end,
                uint8_t* a_out, uint8_t* r_out, uint8_t* s_out,
                uint8_t* h_out, uint8_t* valid_out) {
  for (int64_t i = start; i < end; i++) {
    const uint64_t pk_len = pk_off[i + 1] - pk_off[i];
    const uint64_t sig_len = sig_off[i + 1] - sig_off[i];
    if (pk_len != 32 || sig_len != 64) continue;
    const uint8_t* pk = pks + pk_off[i];
    const uint8_t* sig = sigs + sig_off[i];
    const uint8_t* r = sig;
    const uint8_t* s = sig + 32;
    if (!scalar_in_range(s)) continue;
    Sha512 ctx;
    ctx.update(r, 32);
    ctx.update(pk, 32);
    ctx.update(msgs + msg_off[i], msg_off[i + 1] - msg_off[i]);
    uint8_t digest[64];
    ctx.final(digest);
    mod_l(digest, h_out + 32 * i);
    std::memcpy(a_out + 32 * i, pk, 32);
    std::memcpy(r_out + 32 * i, r, 32);
    std::memcpy(s_out + 32 * i, s, 32);
    valid_out[i] = 1;
  }
}

}  // namespace

extern "C" {

// Batch prep; all output buffers are caller-allocated and zeroed.
void at2_prep_batch(const uint8_t* pks, const uint64_t* pk_off,
                    const uint8_t* msgs, const uint64_t* msg_off,
                    const uint8_t* sigs, const uint64_t* sig_off,
                    int64_t n, int64_t n_threads,
                    uint8_t* a_out, uint8_t* r_out, uint8_t* s_out,
                    uint8_t* h_out, uint8_t* valid_out) {
  if (n_threads <= 1 || n < 256) {
    prep_range(pks, pk_off, msgs, msg_off, sigs, sig_off, 0, n, a_out, r_out,
               s_out, h_out, valid_out);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int64_t t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back(prep_range, pks, pk_off, msgs, msg_off, sigs,
                         sig_off, lo, hi, a_out, r_out, s_out, h_out,
                         valid_out);
  }
  for (auto& w : workers) w.join();
}

// Single SHA-512, for tests.
void at2_sha512(const uint8_t* data, int64_t len, uint8_t* out64) {
  Sha512 ctx;
  ctx.update(data, (size_t)len);
  ctx.final(out64);
}

// 512-bit -> mod L, for tests.
void at2_mod_l(const uint8_t* in64, uint8_t* out32) { mod_l(in64, out32); }
}
