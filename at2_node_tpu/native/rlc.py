"""ctypes binding for the native RLC batch-verification engine.

`at2_rlc.cpp` does the curve arithmetic (decompress, Pippenger MSMs,
randomized torsion rounds, exact [L]P certification); this module owns
the scalar side — per-batch random 128-bit ``z_i`` and the mod-L
products ``z_i*h_i`` / ``z_i*s_i`` as python bigints — and the
build/kick lifecycle, mirroring `ingest.py`: never compile on the event
loop, kick a daemon-thread build on first probe and fall back to the
per-signature path until the library is ready.

The verification-relevant outputs:

* :func:`rlc_check` — one RLC equation + k torsion rounds over prepared
  lanes; returns the batch verdict plus a per-lane decompress mask
  (undecompressable lanes are individually invalid, never batch-fatal).
* :func:`certify_keys` — exact [L]A verdict per public key, cached by
  the verifier so the per-key cost amortizes to ~0 across flushes.

Soundness parameters: ``Z_BITS = 128`` random linear coefficients bound
the prime-subgroup forgery probability by 2^-124 (matching
ops/aggregate.py); ``TORSION_ROUNDS = 64`` randomized subset rounds
bound the small-order miss probability by 2^-64 (each round halves the
survival odds of any lane whose R carries a torsion component; see the
soundness argument in TECHNICAL.md).
"""

from __future__ import annotations

import ctypes
import secrets
import threading
from typing import Optional, Sequence

import numpy as np

from ._build import U8P, load_lib

Z_BITS = 128
TORSION_ROUNDS = 64

L = 2**252 + 27742317777372353535851937790883648493

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        lib = load_lib("at2_rlc.cpp", "libat2rlc.so")
        if lib is None:
            return None
        lib.at2_rlc_selftest.restype = ctypes.c_int
        lib.at2_rlc_certify.restype = None
        lib.at2_rlc_certify.argtypes = [U8P, ctypes.c_uint64, U8P]
        lib.at2_rlc_verify.restype = ctypes.c_int
        lib.at2_rlc_verify.argtypes = [
            U8P, U8P, U8P, U8P, U8P, U8P, U8P,
            ctypes.c_uint64, ctypes.c_uint64, U8P,
        ]
        lib.at2_rlc_scalarmul.restype = ctypes.c_int
        lib.at2_rlc_scalarmul.argtypes = [U8P, U8P, U8P]
        lib.at2_rlc_decompress_check.restype = ctypes.c_int
        lib.at2_rlc_decompress_check.argtypes = [U8P]
        if lib.at2_rlc_selftest() != 0:
            return None
        _lib = lib
        return _lib


def rlc_available() -> bool:
    """Build (if needed), load, and selftest the engine. Blocking."""
    return _load() is not None


def rlc_ready() -> bool:
    """True only when the library is already loaded — never builds."""
    return _lib is not None


_build_kicked = False


def kick_rlc_build() -> None:
    """Start build/load on a daemon thread (once), same contract as
    `ingest.kick_ingest_build`: the caller takes the per-sig path now and
    converges to RLC once the build lands."""
    global _build_kicked
    if _build_kicked or _tried:
        return
    _build_kicked = True
    threading.Thread(
        target=rlc_available, daemon=True, name="at2-rlc-build"
    ).start()


def rlc_ready_or_kick() -> bool:
    if rlc_ready():
        return True
    kick_rlc_build()
    return False


def _as_rows(buf: np.ndarray | Sequence[bytes], n: int) -> np.ndarray:
    a = np.ascontiguousarray(np.asarray(buf, dtype=np.uint8)).reshape(n, 32)
    return a


def certify_keys(pks: Sequence[bytes] | np.ndarray) -> np.ndarray:
    """Exact subgroup certification per public key.

    Returns uint8 verdicts: 0 = bad encoding, 1 = decompresses but
    carries torsion, 2 = certified torsion-free. Lanes with verdict < 2
    must be verified on the exact per-signature path (certification
    reroutes; it never flips a verdict).
    """
    lib = _load()
    assert lib is not None, "call rlc_available() first"
    if isinstance(pks, np.ndarray):
        n = pks.shape[0]
        flat = np.ascontiguousarray(pks, dtype=np.uint8).reshape(n, 32)
    else:
        n = len(pks)
        flat = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    out = np.zeros(n, dtype=np.uint8)
    if n:
        lib.at2_rlc_certify(
            flat.ctypes.data_as(U8P), ctypes.c_uint64(n),
            out.ctypes.data_as(U8P),
        )
    return out


def make_scalars(
    s_le: np.ndarray, h_le: np.ndarray, *, z_override: Sequence[int] | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Random z plus the derived mod-L products, as (n, 32) LE rows.

    ``s_le``/``h_le``: (n, 32) uint8 rows from ``base.prepare_batch``
    (s canonical-checked there; h already reduced mod L).
    Returns (z_rows, zh_rows, zs_rows) for the native call.
    """
    n = s_le.shape[0]
    z_rows = np.zeros((n, 32), dtype=np.uint8)
    zh_rows = np.zeros((n, 32), dtype=np.uint8)
    zs_rows = np.zeros((n, 32), dtype=np.uint8)
    s_bytes = np.ascontiguousarray(s_le, dtype=np.uint8)
    h_bytes = np.ascontiguousarray(h_le, dtype=np.uint8)
    for i in range(n):
        if z_override is not None:
            z = int(z_override[i])
        else:
            z = secrets.randbits(Z_BITS) | 1
        h = int.from_bytes(h_bytes[i].tobytes(), "little")
        s = int.from_bytes(s_bytes[i].tobytes(), "little")
        z_rows[i] = np.frombuffer(z.to_bytes(32, "little"), dtype=np.uint8)
        zh_rows[i] = np.frombuffer(
            (z * h % L).to_bytes(32, "little"), dtype=np.uint8
        )
        zs_rows[i] = np.frombuffer(
            (z * s % L).to_bytes(32, "little"), dtype=np.uint8
        )
    return z_rows, zh_rows, zs_rows


def rlc_check(
    r_rows: np.ndarray,
    a_rows: np.ndarray,
    s_le: np.ndarray,
    h_le: np.ndarray,
    valid: np.ndarray,
    *,
    k_rounds: int = TORSION_ROUNDS,
    z_override: Sequence[int] | None = None,
) -> tuple[bool, np.ndarray]:
    """One RLC check over the lanes with ``valid``.

    Returns ``(batch_ok, decomp_ok)``: when ``batch_ok`` the equation and
    every torsion round passed for all valid lanes that decompressed
    (those lanes are verified); lanes with ``decomp_ok[i] == False`` are
    individually invalid regardless of the batch verdict. When
    ``batch_ok`` is False at least one decompressable lane is bad (or a
    torsion round tripped) — callers bisect.
    """
    lib = _load()
    assert lib is not None, "call rlc_available() first"
    n = int(valid.shape[0])
    decomp_ok = np.ones(n, dtype=np.uint8)
    if n == 0 or not valid.any():
        return True, decomp_ok.astype(bool)
    r_c = _as_rows(r_rows, n)
    a_c = _as_rows(a_rows, n)
    z_rows, zh_rows, zs_rows = make_scalars(
        _as_rows(s_le, n), _as_rows(h_le, n), z_override=z_override
    )
    valid_u8 = np.ascontiguousarray(valid, dtype=np.uint8)
    tors = np.frombuffer(
        secrets.token_bytes(k_rounds * n), dtype=np.uint8
    ) & np.uint8(7)
    tors = np.ascontiguousarray(tors)
    ok = lib.at2_rlc_verify(
        r_c.ctypes.data_as(U8P),
        a_c.ctypes.data_as(U8P),
        z_rows.ctypes.data_as(U8P),
        zh_rows.ctypes.data_as(U8P),
        zs_rows.ctypes.data_as(U8P),
        valid_u8.ctypes.data_as(U8P),
        tors.ctypes.data_as(U8P),
        ctypes.c_uint64(k_rounds),
        ctypes.c_uint64(n),
        decomp_ok.ctypes.data_as(U8P),
    )
    return bool(ok), decomp_ok.astype(bool)


def scalarmul(enc: bytes, k: int) -> Optional[bytes]:
    """[k]P on a compressed point (test hook); None on bad encoding."""
    lib = _load()
    assert lib is not None, "call rlc_available() first"
    p = np.frombuffer(enc, dtype=np.uint8).copy()
    sc = np.frombuffer(
        (k % (1 << 256)).to_bytes(32, "little"), dtype=np.uint8
    ).copy()
    out = np.zeros(32, dtype=np.uint8)
    if not lib.at2_rlc_scalarmul(
        p.ctypes.data_as(U8P), sc.ctypes.data_as(U8P), out.ctypes.data_as(U8P)
    ):
        return None
    return out.tobytes()


def decompress_check(enc: bytes) -> bool:
    """RFC 8032 decompression verdict alone (test hook)."""
    lib = _load()
    assert lib is not None, "call rlc_available() first"
    p = np.frombuffer(enc, dtype=np.uint8).copy()
    return bool(lib.at2_rlc_decompress_check(p.ctypes.data_as(U8P)))
