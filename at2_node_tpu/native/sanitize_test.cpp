// Sanitizer harness for the native batch-prep library (ci.sh kernel
// tier builds this with -fsanitize=thread and -fsanitize=address).
//
// The library's only concurrency is at2_prep_batch's worker fan-out over
// disjoint output ranges; this harness proves (under TSAN) that the
// range partitioning really is race-free and (functionally) that the
// multithreaded result is bit-identical to the single-threaded one,
// plus pins SHA-512 to the FIPS 180-4 "abc" test vector.
//
// Build: g++ -std=c++17 -O1 -g -fsanitize=thread at2_prep.cpp \
//            sanitize_test.cpp -o sanitize_test -lpthread && ./sanitize_test

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
void at2_prep_batch(const uint8_t*, const uint64_t*, const uint8_t*,
                    const uint64_t*, const uint8_t*, const uint64_t*,
                    int64_t, int64_t, uint8_t*, uint8_t*, uint8_t*,
                    uint8_t*, uint8_t*);
void at2_sha512(const uint8_t*, int64_t, uint8_t*);
}

static const uint8_t kAbcDigest[64] = {
    0xdd, 0xaf, 0x35, 0xa1, 0x93, 0x61, 0x7a, 0xba, 0xcc, 0x41, 0x73,
    0x49, 0xae, 0x20, 0x41, 0x31, 0x12, 0xe6, 0xfa, 0x4e, 0x89, 0xa9,
    0x7e, 0xa2, 0x0a, 0x9e, 0xee, 0xe6, 0x4b, 0x55, 0xd3, 0x9a, 0x21,
    0x92, 0x99, 0x2a, 0x27, 0x4f, 0xc1, 0xa8, 0x36, 0xba, 0x3c, 0x23,
    0xa3, 0xfe, 0xeb, 0xbd, 0x45, 0x4d, 0x44, 0x23, 0x64, 0x3c, 0xe8,
    0x0e, 0x2a, 0x9a, 0xc9, 0x4f, 0xa5, 0x4c, 0xa4, 0x9f};

int main() {
  // SHA-512("abc") vector
  uint8_t digest[64];
  at2_sha512(reinterpret_cast<const uint8_t*>("abc"), 3, digest);
  if (std::memcmp(digest, kAbcDigest, 64) != 0) {
    std::fprintf(stderr, "FAIL: sha512 abc vector mismatch\n");
    return 1;
  }

  // deterministic synthetic batch (contents need not be valid signatures;
  // the comparison is single-thread vs multi-thread bit-identity)
  const int64_t n = 1024;
  std::vector<uint8_t> pks(n * 32), msgs(n * 40), sigs(n * 64);
  std::vector<uint64_t> pk_off(n + 1), msg_off(n + 1), sig_off(n + 1);
  uint64_t seed = 0x2545F4914F6CDD1DULL;
  auto next = [&seed]() {
    seed ^= seed << 13;
    seed ^= seed >> 7;
    seed ^= seed << 17;
    return static_cast<uint8_t>(seed);
  };
  for (auto& b : pks) b = next();
  for (auto& b : msgs) b = next();
  for (auto& b : sigs) b = next();
  for (int64_t i = 0; i <= n; i++) {
    pk_off[i] = static_cast<uint64_t>(i) * 32;
    msg_off[i] = static_cast<uint64_t>(i) * 40;
    sig_off[i] = static_cast<uint64_t>(i) * 64;
  }

  auto run = [&](int64_t threads) {
    std::vector<uint8_t> out(n * 32 * 4 + n, 0);
    uint8_t* a = out.data();
    uint8_t* r = a + n * 32;
    uint8_t* s = r + n * 32;
    uint8_t* h = s + n * 32;
    uint8_t* valid = h + n * 32;
    at2_prep_batch(pks.data(), pk_off.data(), msgs.data(), msg_off.data(),
                   sigs.data(), sig_off.data(), n, threads, a, r, s, h,
                   valid);
    return out;
  };

  auto serial = run(1);
  for (int64_t threads : {2, 4, 8}) {
    if (run(threads) != serial) {
      std::fprintf(stderr, "FAIL: %lld-thread result differs from serial\n",
                   static_cast<long long>(threads));
      return 1;
    }
  }
  std::printf("sanitize_test: OK\n");
  return 0;
}
