"""ctypes binding for the native channel reader (at2_ingest.cpp).

One C++ thread per inbound mesh connection owns the socket reads, the
per-frame ChaCha20-Poly1305 decryption, and frame assembly; Python is
woken through a pipe ONCE per batch of frames instead of once per frame
— the event-loop wakeup collapse that `BENCH_E2E.json`'s profiling
identified as the message plane's asyncio floor. Decrypted frames then
enter the existing `Broadcast.on_frame` path (inbox byte budget, native
chunk parsing, catchup plane — all unchanged).

The reader serves the responder role only: in the mesh's
one-connection-per-ordered-pair design (`net/peers.py`), inbound
connections are read-only, so the fd can be handed to the C++ thread
wholesale after the (rare, Python-side) handshake.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Tuple

import numpy as np

from ._build import U64P, ptr8
from .ingest import _load

# Queue/copy-out sizing: matches kReaderQueueBytes' spirit — one take()
# drains up to this much; the C++ queue holds at most 32 MiB.
TAKE_BUF_BYTES = 4 * 1024 * 1024
TAKE_MAX_FRAMES = 4096

STATUS_OPEN = 0
STATUS_EOF = 1
STATUS_PROTOCOL_ERROR = 2

_bound = False


def _lib_with_reader():
    global _bound
    lib = _load()
    if lib is None:
        return None
    if not _bound:
        lib.at2_reader_start.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
        ]
        lib.at2_reader_start.restype = ctypes.c_void_p
        lib.at2_reader_take.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            U64P, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.at2_reader_take.restype = ctypes.c_int64
        lib.at2_reader_stop.argtypes = [ctypes.c_void_p]
        lib.at2_reader_stop.restype = None
        _bound = True
    return lib


def reader_default_on() -> bool:
    """Host-shape heuristic: reader threads need a core to land on. The
    round-4 A/B measured a PENALTY on a 1-core host in the multi-process
    shape (160.4 native vs 183.5 asyncio median, BENCH_E2E.json
    round4_note): with nowhere to run, the C++ threads only add
    cross-process context switching. Multi-core hosts (the deployment
    target — the reference sizes its plane to `num_cpus`,
    /root/reference/src/bin/server/rpc.rs:125) keep the reader ON."""
    try:
        # cores this process may actually RUN on (cgroup/affinity aware;
        # a 1-cpu container on a 64-core host must read as 1)
        count = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # non-Linux fallback
        count = os.cpu_count() or 0
    return count > 1


def reader_available() -> bool:
    if os.environ.get("AT2_NO_NATIVE_READER"):
        return False  # kill-switch (A/B benchmarking / incident triage)
    if not reader_default_on() and not os.environ.get(
        "AT2_FORCE_NATIVE_READER"
    ):
        return False  # 1-core host: asyncio plane measured faster
    return _lib_with_reader() is not None


class NativeChannelReader:
    """Owns one inbound connection's read side from handshake to close."""

    def __init__(self, fd: int, recv_key: bytes, wake_write_fd: int) -> None:
        assert len(recv_key) == 32
        lib = _lib_with_reader()
        assert lib is not None, "call reader_available() first"
        self._lib = lib
        key = (ctypes.c_uint8 * 32).from_buffer_copy(recv_key)
        self._handle: Optional[int] = lib.at2_reader_start(
            fd, key, wake_write_fd
        )
        self._buf = np.empty(TAKE_BUF_BYTES, dtype=np.uint8)
        self._offsets = np.empty(TAKE_MAX_FRAMES + 1, dtype=np.uint64)

    def take(self) -> Tuple[List[bytes], int, int]:
        """Drain queued frames: (frames, status, drops). Call repeatedly
        until it returns no frames (more may fit than one buffer)."""
        status = ctypes.c_int32(0)
        drops = ctypes.c_uint64(0)
        buf = self._buf
        while True:
            n = int(
                self._lib.at2_reader_take(
                    self._handle,
                    ptr8(buf),
                    buf.size,
                    self._offsets.ctypes.data_as(U64P),
                    TAKE_MAX_FRAMES,
                    ctypes.byref(status),
                    ctypes.byref(drops),
                )
            )
            if n >= 0:
                break
            # next frame alone exceeds the buffer (frames can be up to
            # transport.MAX_FRAME): use a TEMPORARY buffer for this take
            # so one oversized frame doesn't pin ~16 MiB per connection
            # for the rest of its life
            buf = np.empty(-n, dtype=np.uint8)
        offs = self._offsets[: n + 1].tolist()
        frames = [buf[offs[i] : offs[i + 1]].tobytes() for i in range(n)]
        return frames, int(status.value), int(drops.value)

    def stop(self) -> None:
        """Stop the thread and free the native state (idempotent); the
        caller still owns and closes the fd + pipe afterwards."""
        if self._handle is not None:
            self._lib.at2_reader_stop(self._handle)
            self._handle = None
