// Sanitizer harness for the native ingest library (ci.sh kernel tier
// builds this with -fsanitize=thread and -fsanitize=address).
//
// Proves, under TSAN, that at2_verify_bulk's thread fan-out is race-free
// (per-thread EVP contexts and pkey caches, disjoint output ranges) and
// bit-identical across thread counts; exercises at2_parse_frames over
// adversarial frames (truncations, unknown kinds, empty frames) under
// ASAN for memory safety; pins SHA-256 to the FIPS 180-4 "abc" vector
// via a known gossip-row content hash.
//
// Build: g++ -std=c++17 -O1 -g -fsanitize=thread at2_ingest.cpp \
//            sanitize_ingest_test.cpp -o t -lpthread -l:libcrypto.so.3 && ./t

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

extern "C" {
int64_t at2_parse_frames(const uint8_t*, const uint64_t*, int64_t, uint8_t*,
                         int64_t, uint32_t*, uint8_t*);
void at2_verify_bulk(const uint8_t*, const uint64_t*, const uint8_t*,
                     const uint64_t*, const uint8_t*, const uint64_t*,
                     int64_t, int64_t, uint8_t*);
int64_t at2_ingest_row_stride(void);
void* at2_reader_start(int fd, const uint8_t* key, int wake_fd);
int64_t at2_reader_take(void*, uint8_t*, int64_t, uint64_t*, int64_t,
                        int32_t*, uint64_t*);
void at2_reader_stop(void*);

// encrypt side for the reader test (stable libcrypto ABI)
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
typedef struct engine_st ENGINE;
const EVP_CIPHER* EVP_chacha20_poly1305(void);
EVP_CIPHER_CTX* EVP_CIPHER_CTX_new(void);
void EVP_CIPHER_CTX_free(EVP_CIPHER_CTX*);
int EVP_EncryptInit_ex(EVP_CIPHER_CTX*, const EVP_CIPHER*, ENGINE*,
                       const unsigned char*, const unsigned char*);
int EVP_CIPHER_CTX_ctrl(EVP_CIPHER_CTX*, int, int, void*);
int EVP_EncryptUpdate(EVP_CIPHER_CTX*, unsigned char*, int*,
                      const unsigned char*, int);
int EVP_EncryptFinal_ex(EVP_CIPHER_CTX*, unsigned char*, int*);
}

static constexpr int kSetIvlen = 0x9, kGetTag = 0x10;

// transport.py wire format: u32-LE ct length || ct (payload + 16B tag),
// nonce = LE counter || 4 zero bytes
static std::vector<uint8_t> encrypt_frame(const uint8_t key[32], uint64_t ctr,
                                          const std::vector<uint8_t>& pt) {
  EVP_CIPHER_CTX* ctx = EVP_CIPHER_CTX_new();
  uint8_t iv[12] = {0};
  for (int i = 0; i < 8; i++) iv[i] = uint8_t(ctr >> (8 * i));
  std::vector<uint8_t> ct(pt.size() + 16);
  int outl = 0, finl = 0;
  bool ok = EVP_EncryptInit_ex(ctx, EVP_chacha20_poly1305(), nullptr, nullptr,
                               nullptr) == 1 &&
            EVP_CIPHER_CTX_ctrl(ctx, kSetIvlen, 12, nullptr) == 1 &&
            EVP_EncryptInit_ex(ctx, nullptr, nullptr, key, iv) == 1 &&
            EVP_EncryptUpdate(ctx, ct.data(), &outl, pt.data(),
                              int(pt.size())) == 1 &&
            EVP_EncryptFinal_ex(ctx, ct.data() + outl, &finl) == 1 &&
            EVP_CIPHER_CTX_ctrl(ctx, kGetTag, 16,
                                ct.data() + pt.size()) == 1;
  EVP_CIPHER_CTX_free(ctx);
  if (!ok) { std::fprintf(stderr, "encrypt_frame failed\n"); std::exit(1); }
  std::vector<uint8_t> frame(4 + ct.size());
  uint32_t len = uint32_t(ct.size());
  for (int i = 0; i < 4; i++) frame[i] = uint8_t(len >> (8 * i));
  std::memcpy(frame.data() + 4, ct.data(), ct.size());
  return frame;
}

// drive the reader over a socketpair: frames round-trip byte-identical
// and in order; a tampered frame flips status to 2. Under TSAN this is
// the race check for the reader thread's queue/wake protocol.
static int reader_check() {
  uint8_t key[32];
  for (int i = 0; i < 32; i++) key[i] = uint8_t(i * 7 + 1);
  int socks[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, socks) != 0) return 1;
  int pipefd[2];
  if (pipe(pipefd) != 0) return 1;
  void* r = at2_reader_start(socks[1], key, pipefd[1]);

  std::vector<std::vector<uint8_t>> payloads;
  payloads.push_back({});  // empty frame is legal (tag-only ciphertext)
  for (int n = 1; n <= 64; n++)
    payloads.emplace_back(size_t(n * 37 % 3000), uint8_t(n));
  uint64_t ctr = 0;
  for (auto& p : payloads) {
    auto f = encrypt_frame(key, ctr++, p);
    if (::write(socks[0], f.data(), f.size()) != ssize_t(f.size())) return 1;
  }

  std::vector<uint8_t> buf(1 << 20);
  std::vector<uint64_t> offsets(4097);
  size_t got = 0;
  int32_t status = 0;
  uint64_t drops = 0;
  while (got < payloads.size()) {
    struct pollfd pfd{pipefd[0], POLLIN, 0};
    if (poll(&pfd, 1, 5000) <= 0) {
      std::fprintf(stderr, "reader never woke\n");
      return 1;
    }
    uint8_t scratch[256];
    (void)!::read(pipefd[0], scratch, sizeof scratch);
    for (;;) {
      int64_t n = at2_reader_take(r, buf.data(), int64_t(buf.size()),
                                  offsets.data(), 4096, &status, &drops);
      if (n <= 0) break;
      for (int64_t i = 0; i < n; i++) {
        const auto& want = payloads[got];
        size_t len = size_t(offsets[i + 1] - offsets[i]);
        if (len != want.size() ||
            std::memcmp(buf.data() + offsets[i], want.data(), len) != 0) {
          std::fprintf(stderr, "frame %zu mismatch\n", got);
          return 1;
        }
        got++;
      }
    }
  }
  if (status != 0 || drops != 0) return 1;

  // tamper: one flipped ciphertext bit must kill the channel (status 2)
  auto evil = encrypt_frame(key, ctr++, {1, 2, 3});
  evil[9] ^= 1;
  if (::write(socks[0], evil.data(), evil.size()) != ssize_t(evil.size()))
    return 1;
  for (int tries = 0; tries < 50 && status == 0; tries++) {
    struct pollfd pfd{pipefd[0], POLLIN, 0};
    poll(&pfd, 1, 200);
    uint8_t scratch[64];
    (void)!::read(pipefd[0], scratch, sizeof scratch);
    at2_reader_take(r, buf.data(), int64_t(buf.size()), offsets.data(), 4096,
                    &status, &drops);
  }
  if (status != 2) {
    std::fprintf(stderr, "tamper not detected: status=%d\n", status);
    return 1;
  }
  at2_reader_stop(r);
  close(socks[0]);
  close(socks[1]);
  close(pipefd[0]);
  close(pipefd[1]);
  return 0;
}

int main() {
  const int64_t stride = at2_ingest_row_stride();

  // -- parse: adversarial frame mix under ASAN ------------------------
  uint64_t seed = 0x9E3779B97F4A7C15ULL;
  auto next = [&seed]() {
    seed ^= seed << 13;
    seed ^= seed >> 7;
    seed ^= seed << 17;
    return static_cast<uint8_t>(seed);
  };
  std::vector<uint8_t> flat;
  std::vector<uint64_t> offsets{0};
  auto add_frame = [&](std::vector<uint8_t> f) {
    flat.insert(flat.end(), f.begin(), f.end());
    offsets.push_back(flat.size());
  };
  std::vector<uint8_t> gossip(141, 0);
  gossip[0] = 1;
  for (size_t i = 1; i < gossip.size(); i++) gossip[i] = next();
  std::vector<uint8_t> attest(165, 0);
  attest[0] = 2;
  for (size_t i = 1; i < attest.size(); i++) attest[i] = next();
  std::vector<uint8_t> request(69, 0);
  request[0] = 4;
  add_frame(gossip);
  add_frame(attest);
  add_frame(request);
  {
    auto both = gossip;
    both.insert(both.end(), attest.begin(), attest.end());
    add_frame(both);
  }
  add_frame({});                            // empty frame: ok, no messages
  add_frame({0xff, 0x01, 0x02});            // unknown kind
  add_frame(std::vector<uint8_t>(gossip.begin(), gossip.end() - 1));  // short
  add_frame({1});                           // kind byte only
  // catchup plane: HIST_IDX_REQ, HIST_BATCH carrying 2 payload bodies,
  // a HIST_BATCH whose count overruns the bytes (whole frame drops),
  // and a truncated HIST_IDX header
  add_frame({5, 1, 2, 3, 4, 5, 6, 7, 8});
  {
    std::vector<uint8_t> batch{8, 9, 9, 9, 9, 9, 9, 9, 9, 2, 0, 0, 0};
    for (int i = 0; i < 280; i++) batch.push_back(next());
    add_frame(batch);
    std::vector<uint8_t> overrun{8, 9, 9, 9, 9, 9, 9, 9, 9, 3, 0, 0, 0};
    for (int i = 0; i < 280; i++) overrun.push_back(next());
    add_frame(overrun);
  }
  add_frame({6, 1, 2, 3});                  // truncated HIST_IDX header
  // batched broadcast plane (kinds 9-12): a 2-entry TxBatch, a batch
  // whose count field overruns the cap (frame drops whole), a batch
  // attestation with an 8-byte bitmap, one with bm_len > 128 (drops),
  // and a BatchContentRequest
  {
    std::vector<uint8_t> batch{9};
    for (int i = 0; i < 40; i++) batch.push_back(next());  // origin+seq
    batch.push_back(2); batch.push_back(0); batch.push_back(0);
    batch.push_back(0);                                    // count = 2
    for (int i = 0; i < 64 + 2 * 140; i++) batch.push_back(next());
    add_frame(batch);
    std::vector<uint8_t> overcount{9};
    for (int i = 0; i < 40; i++) overcount.push_back(next());
    overcount.push_back(0x01); overcount.push_back(0x04);  // count 1025
    overcount.push_back(0); overcount.push_back(0);
    for (int i = 0; i < 64 + 140; i++) overcount.push_back(next());
    add_frame(overcount);
    std::vector<uint8_t> batt{10};
    for (int i = 0; i < 104; i++) batt.push_back(next());  // header pre-len
    batt.push_back(8); batt.push_back(0); batt.push_back(0);
    batt.push_back(0);                                     // bm_len = 8
    for (int i = 0; i < 8 + 64; i++) batt.push_back(next());
    add_frame(batt);
    std::vector<uint8_t> wide{11};
    for (int i = 0; i < 104; i++) wide.push_back(next());
    wide.push_back(0x81); wide.push_back(0);               // bm_len = 129
    wide.push_back(0); wide.push_back(0);
    for (int i = 0; i < 129 + 64; i++) wide.push_back(next());
    add_frame(wide);
    std::vector<uint8_t> breq(73, 0);
    breq[0] = 12;
    for (size_t i = 1; i < breq.size(); i++) breq[i] = next();
    add_frame(breq);
  }

  int64_t n_frames = int64_t(offsets.size()) - 1;
  int64_t cap = 64;
  std::vector<uint8_t> rows(size_t(cap) * size_t(stride), 0);
  std::vector<uint32_t> msg_frame(size_t(cap), 0);
  std::vector<uint8_t> frame_ok(size_t(n_frames), 9);
  int64_t n = at2_parse_frames(flat.data(), offsets.data(), n_frames,
                               rows.data(), cap, msg_frame.data(),
                               frame_ok.data());
  const uint8_t want_ok[17] = {1, 1, 1, 1, 1, 0, 0, 0, 1, 1, 0, 0,
                               1, 0, 1, 0, 1};
  if (n != 10 || std::memcmp(frame_ok.data(), want_ok, 17) != 0) {
    std::fprintf(stderr, "FAIL: parse results n=%lld\n", (long long)n);
    return 1;
  }

  // -- verify: thread-count bit-identity under TSAN -------------------
  // (contents are junk; identical verdicts across thread counts is the
  // property — EVP rejects junk deterministically)
  const int64_t k = 96;
  std::vector<uint8_t> pks(k * 32), msgs(k * 33), sigs(k * 64);
  std::vector<uint64_t> pk_off(k + 1), msg_off(k + 1), sig_off(k + 1);
  for (auto& b : pks) b = next();
  for (auto& b : msgs) b = next();
  for (auto& b : sigs) b = next();
  // repeat a few pubkeys to exercise the per-thread cache paths
  for (int64_t i = 8; i < k; i += 7)
    std::memcpy(&pks[i * 32], &pks[0], 32);
  for (int64_t i = 0; i <= k; i++) {
    pk_off[i] = uint64_t(i) * 32;
    msg_off[i] = uint64_t(i) * 33;
    sig_off[i] = uint64_t(i) * 64;
  }
  auto run = [&](int64_t threads) {
    std::vector<uint8_t> out(k, 7);
    at2_verify_bulk(pks.data(), pk_off.data(), msgs.data(), msg_off.data(),
                    sigs.data(), sig_off.data(), k, threads, out.data());
    return out;
  };
  auto serial = run(1);
  for (int64_t threads : {2, 4, 8}) {
    if (run(threads) != serial) {
      std::fprintf(stderr, "FAIL: %lld-thread verify differs\n",
                   (long long)threads);
      return 1;
    }
  }
  // -- native channel reader under TSAN/ASAN --------------------------
  if (reader_check() != 0) {
    std::fprintf(stderr, "FAIL: reader check\n");
    return 1;
  }

  std::printf("sanitize_ingest_test: OK\n");
  return 0;
}
