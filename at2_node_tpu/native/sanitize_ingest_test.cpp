// Sanitizer harness for the native ingest library (ci.sh kernel tier
// builds this with -fsanitize=thread and -fsanitize=address).
//
// Proves, under TSAN, that at2_verify_bulk's thread fan-out is race-free
// (per-thread EVP contexts and pkey caches, disjoint output ranges) and
// bit-identical across thread counts; exercises at2_parse_frames over
// adversarial frames (truncations, unknown kinds, empty frames) under
// ASAN for memory safety; pins SHA-256 to the FIPS 180-4 "abc" vector
// via a known gossip-row content hash.
//
// Build: g++ -std=c++17 -O1 -g -fsanitize=thread at2_ingest.cpp \
//            sanitize_ingest_test.cpp -o t -lpthread -l:libcrypto.so.3 && ./t

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
int64_t at2_parse_frames(const uint8_t*, const uint64_t*, int64_t, uint8_t*,
                         int64_t, uint32_t*, uint8_t*);
void at2_verify_bulk(const uint8_t*, const uint64_t*, const uint8_t*,
                     const uint64_t*, const uint8_t*, const uint64_t*,
                     int64_t, int64_t, uint8_t*);
int64_t at2_ingest_row_stride(void);
}

int main() {
  const int64_t stride = at2_ingest_row_stride();

  // -- parse: adversarial frame mix under ASAN ------------------------
  uint64_t seed = 0x9E3779B97F4A7C15ULL;
  auto next = [&seed]() {
    seed ^= seed << 13;
    seed ^= seed >> 7;
    seed ^= seed << 17;
    return static_cast<uint8_t>(seed);
  };
  std::vector<uint8_t> flat;
  std::vector<uint64_t> offsets{0};
  auto add_frame = [&](std::vector<uint8_t> f) {
    flat.insert(flat.end(), f.begin(), f.end());
    offsets.push_back(flat.size());
  };
  std::vector<uint8_t> gossip(141, 0);
  gossip[0] = 1;
  for (size_t i = 1; i < gossip.size(); i++) gossip[i] = next();
  std::vector<uint8_t> attest(165, 0);
  attest[0] = 2;
  for (size_t i = 1; i < attest.size(); i++) attest[i] = next();
  std::vector<uint8_t> request(69, 0);
  request[0] = 4;
  add_frame(gossip);
  add_frame(attest);
  add_frame(request);
  {
    auto both = gossip;
    both.insert(both.end(), attest.begin(), attest.end());
    add_frame(both);
  }
  add_frame({});                            // empty frame: ok, no messages
  add_frame({0xff, 0x01, 0x02});            // unknown kind
  add_frame(std::vector<uint8_t>(gossip.begin(), gossip.end() - 1));  // short
  add_frame({1});                           // kind byte only
  // catchup plane: HIST_IDX_REQ, HIST_BATCH carrying 2 payload bodies,
  // a HIST_BATCH whose count overruns the bytes (whole frame drops),
  // and a truncated HIST_IDX header
  add_frame({5, 1, 2, 3, 4, 5, 6, 7, 8});
  {
    std::vector<uint8_t> batch{8, 9, 9, 9, 9, 9, 9, 9, 9, 2, 0, 0, 0};
    for (int i = 0; i < 280; i++) batch.push_back(next());
    add_frame(batch);
    std::vector<uint8_t> overrun{8, 9, 9, 9, 9, 9, 9, 9, 9, 3, 0, 0, 0};
    for (int i = 0; i < 280; i++) overrun.push_back(next());
    add_frame(overrun);
  }
  add_frame({6, 1, 2, 3});                  // truncated HIST_IDX header

  int64_t n_frames = int64_t(offsets.size()) - 1;
  int64_t cap = 64;
  std::vector<uint8_t> rows(size_t(cap) * size_t(stride), 0);
  std::vector<uint32_t> msg_frame(size_t(cap), 0);
  std::vector<uint8_t> frame_ok(size_t(n_frames), 9);
  int64_t n = at2_parse_frames(flat.data(), offsets.data(), n_frames,
                               rows.data(), cap, msg_frame.data(),
                               frame_ok.data());
  const uint8_t want_ok[12] = {1, 1, 1, 1, 1, 0, 0, 0, 1, 1, 0, 0};
  if (n != 7 || std::memcmp(frame_ok.data(), want_ok, 12) != 0) {
    std::fprintf(stderr, "FAIL: parse results n=%lld\n", (long long)n);
    return 1;
  }

  // -- verify: thread-count bit-identity under TSAN -------------------
  // (contents are junk; identical verdicts across thread counts is the
  // property — EVP rejects junk deterministically)
  const int64_t k = 96;
  std::vector<uint8_t> pks(k * 32), msgs(k * 33), sigs(k * 64);
  std::vector<uint64_t> pk_off(k + 1), msg_off(k + 1), sig_off(k + 1);
  for (auto& b : pks) b = next();
  for (auto& b : msgs) b = next();
  for (auto& b : sigs) b = next();
  // repeat a few pubkeys to exercise the per-thread cache paths
  for (int64_t i = 8; i < k; i += 7)
    std::memcpy(&pks[i * 32], &pks[0], 32);
  for (int64_t i = 0; i <= k; i++) {
    pk_off[i] = uint64_t(i) * 32;
    msg_off[i] = uint64_t(i) * 33;
    sig_off[i] = uint64_t(i) * 64;
  }
  auto run = [&](int64_t threads) {
    std::vector<uint8_t> out(k, 7);
    at2_verify_bulk(pks.data(), pk_off.data(), msgs.data(), msg_off.data(),
                    sigs.data(), sig_off.data(), k, threads, out.data());
    return out;
  };
  auto serial = run(1);
  for (int64_t threads : {2, 4, 8}) {
    if (run(threads) != serial) {
      std::fprintf(stderr, "FAIL: %lld-thread verify differs\n",
                   (long long)threads);
      return 1;
    }
  }
  std::printf("sanitize_ingest_test: OK\n");
  return 0;
}
