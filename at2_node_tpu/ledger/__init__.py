"""The ledger: per-account state machine and single-writer actors."""

from .account import Account, AccountError, INITIAL_BALANCE
from .accounts import Accounts, AccountModificationError
from .recent import RecentTransactions

__all__ = [
    "Account",
    "AccountError",
    "INITIAL_BALANCE",
    "Accounts",
    "AccountModificationError",
    "RecentTransactions",
]
