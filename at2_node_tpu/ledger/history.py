"""Committed-payload history store: the serving side of ledger catchup.

The reference leaves "catchup mechanism" as an open roadmap item
(`/root/reference/README.md:53`); this build closes it. A rejoining (or
long-partitioned) node cannot reconstruct balances from a peer's ledger
SNAPSHOT safely — in a consensus-free ledger an account's balance is a
function of the full committed history (credits arrive without bumping
the recipient's sequence, so (sequence, balance) pairs from different
peers are not comparable at a point in time). What IS safely
transferable is the history itself: committed payloads are client-signed
(unforgeable) and sieve guarantees at most one committed content per
(sender, sequence) slot, so replaying quorum-confirmed history through
the normal sequence gate deterministically re-converges the ledger.

Every node therefore retains its recently committed payloads here
(recorded by the commit pass in `node.service.Service._drain_to_fixpoint_locked`) and serves them to
catching-up peers over the mesh (`HIST_IDX_REQ`/`HIST_REQ` messages,
`broadcast/messages.py`). Retention is bounded: beyond ``cap`` total
payloads the oldest are evicted FIFO, and a request older than the
horizon is answered with whatever suffix survives — the requester
detects the gap (its frontier stays behind) and the operator restores
from a fresher checkpoint, which is the honest limit of a bounded store.

Per-sender sequences are contiguous by construction (the account gate
admits only last+1, `ledger/account.py`), so each sender's retained
range is a contiguous suffix ``[evicted+1 .. last]``.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Tuple

# Serving bounds (requests are clamped, never rejected): one HIST_REQ
# yields at most MAX_RANGE payloads, batched MAX_BATCH per wire message
# so a response frame stays far under the transport's 16 MiB frame cap.
MAX_RANGE = 4096
MAX_BATCH = 1024
# One HIST_IDX message carries at most this many frontier entries
# (36 bytes each). Truncation keeps the first N in ledger-dict insertion
# order (arbitrary, not recency); a requester behind on >N senders still
# converges over multiple sessions as its own frontier advances.
MAX_IDX_ENTRIES = 100_000

DEFAULT_CAP = 1 << 17


class CommittedHistory:
    """Bounded FIFO store of committed payloads, indexed by slot."""

    def __init__(self, cap: int = DEFAULT_CAP) -> None:
        self.cap = cap
        self._by_sender: Dict[bytes, Dict[int, object]] = {}
        self._order: Deque[Tuple[bytes, int]] = deque()

    def __len__(self) -> int:
        return len(self._order)

    def record(self, payload) -> None:
        """Retain one committed payload (idempotent per slot)."""
        sender_map = self._by_sender.setdefault(payload.sender, {})
        if payload.sequence in sender_map:
            return
        sender_map[payload.sequence] = payload
        self._order.append((payload.sender, payload.sequence))
        while len(self._order) > self.cap:
            old_sender, old_seq = self._order.popleft()
            old_map = self._by_sender.get(old_sender)
            if old_map is not None:
                old_map.pop(old_seq, None)
                if not old_map:
                    del self._by_sender[old_sender]

    def get_range(self, sender: bytes, lo: int, hi: int) -> List:
        """Retained payloads for ``sender`` with lo <= seq <= hi, in
        sequence order, clamped to MAX_RANGE."""
        sender_map = self._by_sender.get(sender)
        if not sender_map:
            return []
        hi = min(hi, lo + MAX_RANGE - 1)
        return [
            sender_map[seq]
            for seq in range(lo, hi + 1)
            if seq in sender_map
        ]
