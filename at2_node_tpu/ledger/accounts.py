"""Single-writer accounts actor owning the ledger map.

Equivalent of the reference's `Accounts`/`AccountsHandler` actor
(`/root/reference/src/bin/server/accounts/mod.rs:28-214`): all mutations are
serialized through one asyncio task consuming a command queue (the tokio
``mpsc::channel(32)`` + oneshot pattern at `accounts/mod.rs:126-153`),
preserving per-account linearizability without locks.

Observable semantics reproduced exactly (pinned by the reference's tests at
`accounts/mod.rs:216-301`):

* unknown accounts read as fresh (balance 100 000, sequence 0)
  (`accounts/mod.rs:155-163,207-213`);
* self-transfer is a zero-amount debit: bumps the sequence, keeps the
  balance (`accounts/mod.rs:175-182`);
* a transfer debits then credits; the sender's account state is persisted
  even when the debit fails, so a failed overdraft still consumes the
  sender's sequence number (`accounts/mod.rs:184-196`).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, Tuple

from .account import Account, AccountException

logger = logging.getLogger(__name__)

_QUEUE_DEPTH = 32  # accounts/mod.rs:127


class AccountModificationError(Exception):
    """Wraps an account-level failure; the delivery loop retries only this
    error kind (gap filling, `/root/reference/src/bin/server/rpc.rs:195-205`)."""

    def __init__(self, source: AccountException):
        super().__init__(f"account modification: {source}")
        self.source = source


class Accounts:
    """Client handle to the single-writer ledger actor."""

    def __init__(self) -> None:
        self._ledger: Dict[bytes, Account] = {}
        self._queue: asyncio.Queue[
            Tuple[Callable[[], object], asyncio.Future]
        ] = asyncio.Queue(_QUEUE_DEPTH)
        self._closed = False
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while True:
            op, fut = await self._queue.get()
            if fut.cancelled():
                continue
            try:
                fut.set_result(op())
            except Exception as exc:  # delivered to the caller, actor lives on
                fut.set_exception(exc)

    async def _call(self, op: Callable[[], object]) -> object:
        if self._closed:
            raise RuntimeError("accounts actor is closed")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((op, fut))
        return await fut

    def close(self) -> None:
        """Stop the actor; fail queued callers instead of hanging them."""
        self._closed = True
        self._task.cancel()
        while not self._queue.empty():
            _, fut = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(RuntimeError("accounts actor is closed"))

    async def get_balance(self, user: bytes) -> int:
        return await self._call(lambda: self._get_balance(user))  # type: ignore[return-value]

    async def get_last_sequence(self, user: bytes) -> int:
        return await self._call(lambda: self._get_last_sequence(user))  # type: ignore[return-value]

    async def transfer(
        self, sender: bytes, sender_sequence: int, receiver: bytes, amount: int
    ) -> None:
        await self._call(
            lambda: self._transfer(sender, sender_sequence, receiver, amount)
        )

    # -- actor-side ops (only ever run on the single writer task) --

    def _get_balance(self, user: bytes) -> int:
        account = self._ledger.get(user)
        return account.balance if account is not None else Account().balance

    def _get_last_sequence(self, user: bytes) -> int:
        account = self._ledger.get(user)
        return account.last_sequence if account is not None else 0

    def _transfer(
        self, sender: bytes, sender_sequence: int, receiver: bytes, amount: int
    ) -> None:
        if sender == receiver:
            logger.warning("transfer to itself: %s", sender.hex())
            account = self._ledger.setdefault(sender, Account())
            try:
                account.debit(sender_sequence, 0)
            except AccountException as exc:
                raise AccountModificationError(exc) from exc
            return

        sender_account = self._ledger.get(sender) or Account()
        receiver_account = self._ledger.get(receiver) or Account()

        try:
            sender_account.debit(sender_sequence, amount)
        except AccountException as exc:
            # Persist the (sequence-consumed) sender state even on failure
            # (accounts/mod.rs:190-194).
            self._ledger[sender] = sender_account
            raise AccountModificationError(exc) from exc
        self._ledger[sender] = sender_account

        try:
            receiver_account.credit(amount)
        except AccountException as exc:
            raise AccountModificationError(exc) from exc
        self._ledger[receiver] = receiver_account
