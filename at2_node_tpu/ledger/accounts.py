"""Single-writer accounts guard owning the ledger map.

Equivalent of the reference's `Accounts`/`AccountsHandler` actor
(`/root/reference/src/bin/server/accounts/mod.rs:28-214`). The reference
needs a tokio task + mpsc/oneshot channels because its mutations come from
many OS threads; in a single-threaded asyncio node the same single-writer
linearizability falls out of serializing all mutations through one
``asyncio.Lock`` critical section — no channel machinery, no close-time
future bookkeeping (the sibling :class:`RecentTransactions` uses the same
pattern).

Observable semantics reproduced exactly (pinned by the reference's tests at
`accounts/mod.rs:216-301`):

* unknown accounts read as fresh (balance 100 000, sequence 0)
  (`accounts/mod.rs:155-163,207-213`);
* self-transfer is a zero-amount debit: bumps the sequence, keeps the
  balance (`accounts/mod.rs:175-182`);
* a transfer debits then credits; the sender's account state is persisted
  even when the debit fails, so a failed overdraft still consumes the
  sender's sequence number (`accounts/mod.rs:184-196`).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict

from ..obs.audit import LedgerDigest
from .account import Account, AccountException

logger = logging.getLogger(__name__)


class AccountModificationError(Exception):
    """Wraps an account-level failure; the delivery loop retries only this
    error kind (gap filling, `/root/reference/src/bin/server/rpc.rs:195-205`)."""

    def __init__(self, source: AccountException):
        super().__init__(f"account modification: {source}")
        self.source = source


class Accounts:
    """Async facade over the ledger; all mutations serialize on one lock."""

    def __init__(self) -> None:
        self._ledger: Dict[bytes, Account] = {}
        self._lock = asyncio.Lock()
        # Fleet-audit digest lanes (obs/audit.py): folded at every
        # mutation site below so they are always an O(1)-maintained pure
        # function of the current ledger state — the beacon plane reads
        # them without ever scanning the ledger.
        self.digest = LedgerDigest()

    def close(self) -> None:
        """Kept for API symmetry with heavier backends; nothing to stop."""

    async def export_state(self) -> dict:
        """Snapshot for checkpointing: {hex pubkey: [last_sequence, balance]}."""
        async with self._lock:
            return {
                user.hex(): [a.last_sequence, a.balance]
                for user, a in self._ledger.items()
            }

    async def import_state(self, data: dict) -> None:
        """Replace the ledger with a checkpoint snapshot (resume-on-start)."""
        async with self._lock:
            self._ledger = {
                bytes.fromhex(user): Account(last_sequence=seq, balance=bal)
                for user, (seq, bal) in data.items()
            }
            self.digest.reseed(
                (user, a.last_sequence, a.balance)
                for user, a in self._ledger.items()
            )

    def frontier_nowait(self) -> Dict[bytes, int]:
        """Point-in-time {sender: last_sequence} map, lock-free.

        Safe on the event loop: every mutation happens synchronously
        inside a lock-held critical section on this same loop, so a
        single synchronous read can never observe a torn update. Used by
        the catchup plane, whose handlers run in broadcast workers and
        must not await the actor lock. O(ledger) — hot paths that need a
        single sender use :meth:`last_sequence_nowait` instead.
        """
        return {
            user: a.last_sequence
            for user, a in self._ledger.items()
            if a.last_sequence > 0
        }

    def last_sequence_nowait(self, user: bytes) -> int:
        """Single-sender lock-free read (same safety argument as
        :meth:`frontier_nowait`); O(1) for the delivery drain's per-entry
        staleness check."""
        account = self._ledger.get(user)
        return account.last_sequence if account is not None else 0

    async def get_balance(self, user: bytes) -> int:
        async with self._lock:
            account = self._ledger.get(user)
            return account.balance if account is not None else Account().balance

    async def get_last_sequence(self, user: bytes) -> int:
        async with self._lock:
            account = self._ledger.get(user)
            return account.last_sequence if account is not None else 0

    async def transfer(
        self, sender: bytes, sender_sequence: int, receiver: bytes, amount: int
    ) -> None:
        async with self._lock:
            self._transfer(sender, sender_sequence, receiver, amount)

    async def run_exclusive(self, fn):
        """Run a synchronous multi-item ledger transaction under the
        single-writer lock: ``fn(self)`` may call ``_transfer`` and the
        ``*_nowait`` readers but MUST NOT await. One lock round-trip per
        delivery-batch instead of per transfer — the commit path's cost
        at batched-plane rates (BENCH_E2E.json batched_plane), with the
        same linearizability argument: nothing interleaves a synchronous
        critical section on a single event loop."""
        async with self._lock:
            return fn(self)

    def _touch(self, key: bytes, old: tuple, account: Account) -> None:
        """Fold one row's (sequence, balance) change into the audit
        digest; no-op when the observable state did not change."""
        if old != (account.last_sequence, account.balance):
            self.digest.touch(
                key, old[0], old[1], account.last_sequence, account.balance
            )

    def _tamper(self, user: bytes, delta: int) -> None:
        """Failpoint back door (sim/campaign.py planted-divergence
        episodes): misapply ``delta`` to ``user``'s balance exactly as a
        buggy apply would. The digest folds the corrupted post-state —
        which is precisely what lets peers' auditors catch it."""
        account = self._ledger.setdefault(user, Account())
        old = (account.last_sequence, account.balance)
        account.balance += delta
        self._touch(user, old, account)

    def _transfer(
        self, sender: bytes, sender_sequence: int, receiver: bytes, amount: int
    ) -> None:
        if sender == receiver:
            logger.warning("transfer to itself: %s", sender.hex())
            account = self._ledger.setdefault(sender, Account())
            old = (account.last_sequence, account.balance)
            try:
                account.debit(sender_sequence, 0)
            except AccountException as exc:
                self._touch(sender, old, account)
                raise AccountModificationError(exc) from exc
            self._touch(sender, old, account)
            return

        sender_account = self._ledger.get(sender) or Account()
        receiver_account = self._ledger.get(receiver) or Account()
        sender_old = (sender_account.last_sequence, sender_account.balance)
        receiver_old = (
            receiver_account.last_sequence,
            receiver_account.balance,
        )

        try:
            sender_account.debit(sender_sequence, amount)
        except AccountException as exc:
            # Persist the (sequence-consumed) sender state even on failure
            # (accounts/mod.rs:190-194).
            self._ledger[sender] = sender_account
            self._touch(sender, sender_old, sender_account)
            raise AccountModificationError(exc) from exc
        self._ledger[sender] = sender_account
        self._touch(sender, sender_old, sender_account)

        try:
            receiver_account.credit(amount)
        except AccountException as exc:
            raise AccountModificationError(exc) from exc
        self._ledger[receiver] = receiver_account
        self._touch(receiver, receiver_old, receiver_account)
