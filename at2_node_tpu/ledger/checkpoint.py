"""Ledger checkpoint/resume: periodic atomic snapshots of node state.

The reference keeps ALL state in RAM and lists "store state on disk to
restart after crash" as an open roadmap item
(`/root/reference/README.md:52`); this build implements it. A checkpoint
is one JSON document holding the accounts map and the recent-transactions
ring, written atomically (tmp + rename on the same filesystem) so a crash
mid-write can never leave a torn file.

Scope: the checkpoint restores LEDGER state (balances, per-sender
sequences, the last-10 ring). Broadcast-layer state (in-flight slots,
Echo/Ready votes) is deliberately NOT persisted — it is rebuilt from the
network: peers re-gossip undelivered payloads and the content-pull
catch-up (`broadcast.stack._request_content`) recovers anything this node
missed while down. Re-delivered already-committed transfers are rejected
by the per-account sequence gate (`ledger.account.Account.debit`), so a
restart cannot double-apply.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import tempfile

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1


async def snapshot(accounts, recent, directory=None) -> dict:
    """Collect a consistent point-in-time snapshot of the ledger actors.

    ``directory`` (node/directory.py ClientDirectory) rides along when the
    node runs the broker ingress tier: the id -> pubkey mappings this node
    assigned or learned survive restarts, so registered clients keep their
    ids without re-registering. The key is optional — checkpoints written
    before the directory existed (or by directory-less configs) load fine.
    """
    doc = {
        "version": FORMAT_VERSION,
        "accounts": await accounts.export_state(),
        "recent": await recent.export_state(),
    }
    if directory is not None:
        doc["directory"] = directory.export()
    return doc


def write_atomic(path: str, doc: dict) -> None:
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".ckpt-")
    try:
        with os.fdopen(fd, "w") as fp:
            json.dump(doc, fp)
            fp.flush()
            os.fsync(fp.fileno())
        os.replace(tmp, path)
        # fsync the containing directory too: the rename itself must be
        # durable, or a crash can leave the old (or no) checkpoint after
        # the caller was told the save completed
        try:
            dfd = os.open(directory, os.O_RDONLY)
        except OSError:
            pass  # platform/filesystem without directory fds
        else:
            try:
                os.fsync(dfd)
            except OSError:
                pass
            finally:
                os.close(dfd)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


async def save(path: str, accounts, recent, directory=None) -> None:
    doc = await snapshot(accounts, recent, directory)
    # serialization + fsync off the event loop: a large ledger must not
    # stall delivery/RPC handling for the duration of a snapshot
    await asyncio.to_thread(write_atomic, path, doc)


async def load(path: str, accounts, recent, directory=None) -> bool:
    """Restore actors from ``path``; returns False when no checkpoint
    exists (fresh start). A corrupt file raises — silently starting from
    genesis after state loss would violate the sequence contract with the
    rest of the network."""
    try:
        with open(path) as fp:
            doc = json.load(fp)
    except FileNotFoundError:
        return False
    if doc.get("version") != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version: {doc.get('version')}")
    await accounts.import_state(doc["accounts"])
    await recent.import_state(doc["recent"])
    if directory is not None:
        directory.import_(doc.get("directory", ()))
    logger.info("restored checkpoint %s (%d accounts)", path, len(doc["accounts"]))
    return True
