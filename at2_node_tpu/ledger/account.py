"""Per-account balance + sequence state machine.

Reproduces the observable semantics of the reference's `Account`
(`/root/reference/src/bin/server/accounts/account.rs:12-54`), which its own
unit tests pin down (`account.rs:56-91`):

* accounts start with ``INITIAL_BALANCE`` (100 000) — the faucet TODO
  (`account.rs:17,24`);
* ``credit`` checks u64 overflow (`account.rs:29-33`);
* ``debit`` requires ``sequence == last_sequence + 1`` and bumps
  ``last_sequence`` BEFORE the balance check, so a failed (underflow)
  debit still consumes the sequence number (`account.rs:36-43`).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

INITIAL_BALANCE = 100_000
_U64_MAX = (1 << 64) - 1


class AccountError(enum.Enum):
    INCONSECUTIVE_SEQUENCE = "inconsecutive sequence"
    OVERFLOW = "overflow"
    UNDERFLOW = "underflow"


class AccountException(Exception):
    def __init__(self, kind: AccountError):
        super().__init__(kind.value)
        self.kind = kind


def _check_u64(amount: int) -> None:
    # Rust's u64 type makes negative/oversized amounts unrepresentable
    # (account.rs:14); Python ints need the bound enforced explicitly.
    if not 0 <= amount <= _U64_MAX:
        raise ValueError("amount must fit in u64")


@dataclass
class Account:
    last_sequence: int = 0
    balance: int = INITIAL_BALANCE

    def credit(self, amount: int) -> None:
        _check_u64(amount)
        new = self.balance + amount
        if new > _U64_MAX:
            raise AccountException(AccountError.OVERFLOW)
        self.balance = new

    def debit(self, sequence: int, amount: int) -> None:
        _check_u64(amount)
        if self.last_sequence + 1 != sequence:
            raise AccountException(AccountError.INCONSECUTIVE_SEQUENCE)
        # Sequence is consumed even if the balance check below fails
        # (account.rs:38-41) — observable via the reference's own test
        # `debit_too_much_fails` (account.rs:61-70).
        self.last_sequence = sequence
        if amount > self.balance:
            raise AccountException(AccountError.UNDERFLOW)
        self.balance -= amount
