"""Recent-transactions ring actor (last 10, Pending/Success/Failure).

Equivalent of the reference's `RecentTransactions` actor
(`/root/reference/src/bin/server/recent_transactions.rs:38-201`):

* capacity-10 ring (`recent_transactions.rs:7`), oldest evicted
  (`:173-177`);
* ``put`` stamps the current UTC time, starts Pending, and is a NOP when a
  transaction with the same (sender, sequence) is already present
  (`:149-180`);
* ``update`` finds the latest matching (sender, sequence) and flips its
  state; NOP when absent because a transaction may resolve after eviction
  (`:182-196`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime
from collections import deque
from typing import Deque, List

from ..types import FullTransaction, ThinTransaction, TransactionState

LATEST_TRANSACTIONS_MAX_SIZE = 10  # recent_transactions.rs:7


class RecentTransactions:
    """Single-writer actor over the last-N transactions ring."""

    def __init__(self) -> None:
        self._ring: Deque[FullTransaction] = deque()
        self._lock = asyncio.Lock()

    def _put_locked(
        self, sender: bytes, sender_sequence: int, thin: ThinTransaction
    ) -> None:
        for tx in self._ring:
            if tx.sender_sequence == sender_sequence and tx.sender == sender:
                return
        if len(self._ring) == LATEST_TRANSACTIONS_MAX_SIZE:
            self._ring.popleft()
        self._ring.append(
            FullTransaction(
                timestamp=datetime.datetime.now(datetime.timezone.utc),
                sender=sender,
                sender_sequence=sender_sequence,
                recipient=thin.recipient,
                amount=thin.amount,
                state=TransactionState.PENDING,
            )
        )

    async def put(
        self, sender: bytes, sender_sequence: int, thin: ThinTransaction
    ) -> None:
        async with self._lock:
            self._put_locked(sender, sender_sequence, thin)

    async def put_many(self, rows: list) -> None:
        """Insert many Pending records under ONE lock round-trip
        (SendAssetBatch ingress): rows are ``(sender, sequence, thin)``,
        per-row semantics identical to :meth:`put`."""
        async with self._lock:
            for sender, seq, thin in rows:
                self._put_locked(sender, seq, thin)

    def _update_locked(
        self, sender: bytes, sender_sequence: int, state: TransactionState
    ) -> None:
        """Flip the latest matching entry's state (caller holds the lock;
        NOP when absent — a transaction may resolve after eviction)."""
        for tx in reversed(self._ring):
            if tx.sender_sequence == sender_sequence and tx.sender == sender:
                tx.state = state
                return

    def _mark_failure_locked(
        self, sender: bytes, sender_sequence: int
    ) -> None:
        """TTL marking for a stale (already-consumed-sequence) heap entry
        (caller holds the lock): a catchup/delivery duplicate of a
        COMMITTED transfer must not flip its twin's SUCCESS record, while
        a genuinely failed transfer (its own debit consumed the sequence)
        still gets the reference's FAILURE record
        (`/root/reference/src/bin/server/rpc.rs:183-193`)."""
        for tx in reversed(self._ring):
            if tx.sender_sequence == sender_sequence and tx.sender == sender:
                if tx.state is not TransactionState.SUCCESS:
                    tx.state = TransactionState.FAILURE
                return

    async def update(
        self, sender: bytes, sender_sequence: int, state: TransactionState
    ) -> None:
        async with self._lock:
            self._update_locked(sender, sender_sequence, state)

    async def mark_failure_unless_success(
        self, sender: bytes, sender_sequence: int
    ) -> None:
        async with self._lock:
            self._mark_failure_locked(sender, sender_sequence)

    async def apply_many(self, ops: list) -> None:
        """Apply an ordered batch of ring mutations under ONE lock
        round-trip (the delivery loop collects a whole drain pass's
        updates): ops are ``("update", sender, seq, state)`` or
        ``("unless_success", sender, seq)`` rows, with exactly the same
        per-op semantics as :meth:`update` /
        :meth:`mark_failure_unless_success`."""
        async with self._lock:
            for op in ops:
                if op[0] == "update":
                    self._update_locked(op[1], op[2], op[3])
                else:
                    self._mark_failure_locked(op[1], op[2])

    async def export_state(self) -> list:
        """Snapshot for checkpointing (JSON-safe rows, oldest first)."""
        from ..types import rfc3339

        async with self._lock:
            return [
                [
                    rfc3339(tx.timestamp),
                    tx.sender.hex(),
                    tx.sender_sequence,
                    tx.recipient.hex(),
                    tx.amount,
                    tx.state.value,
                ]
                for tx in self._ring
            ]

    async def import_state(self, rows: list) -> None:
        from ..types import parse_rfc3339

        async with self._lock:
            self._ring = deque(
                FullTransaction(
                    timestamp=parse_rfc3339(ts),
                    sender=bytes.fromhex(sender),
                    sender_sequence=seq,
                    recipient=bytes.fromhex(recipient),
                    amount=amount,
                    state=TransactionState(state),
                )
                for ts, sender, seq, recipient, amount, state in rows
            )

    async def get_all(self) -> List[FullTransaction]:
        async with self._lock:
            # Deep snapshot, like the reference's `self.0.clone()`
            # (recent_transactions.rs:198-200): later state updates must not
            # mutate an already-returned list, nor callers corrupt the ring.
            return [dataclasses.replace(tx) for tx in self._ring]
