"""Typed AT2 client library over the gRPC surface.

Equivalent of `at2_node::client::Client`
(`/root/reference/src/client.rs:44-144`): a thin wrapper around the
`at2.AT2` stub that signs transfers client-side
(`client.rs:77-78`) and decodes replies into the shared types. Used by
the client CLI and the benchmark load generators.

Like the reference, the channel is lazy: nothing connects until the first
RPC (`client.rs:61`, tonic `connect_lazy`).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import grpc

from .broadcast.messages import MAX_BATCH_ENTRIES as _RPC_BATCH_CAP
from .crypto.keys import SignKeyPair
from .node.overload import parse_retry_after_ms
from .proto import at2_pb2 as pb
from .proto.rpc import At2Stub
from .types import (
    FullTransaction,
    TransactionState,
    parse_rfc3339,
    transfer_signing_bytes,
)


@dataclass
class RetryPolicy:
    """Jittered-exponential retry budget for RESOURCE_EXHAUSTED refusals
    (overload sheds, broker brownout — the [overload] ladder).

    The backoff honors the server's typed ``retry_after_ms`` hint: the
    delay is never shorter than the hint, so a shedding fleet paces its
    own retry wave instead of the wave becoming a second flash crowd.
    Jitter spreads synchronized clients over ``jitter`` of the delay
    (full-window decorrelation is what keeps retries from re-bunching).
    ``budget`` bounds attempts per logical call; once spent, the last
    refusal propagates to the caller unchanged.

    ``rng`` / ``sleep`` are injectable for deterministic tests."""

    budget: int = 4
    base_ms: float = 100.0
    max_ms: float = 5000.0
    multiplier: float = 2.0
    jitter: float = 0.5
    rng: Callable[[], float] = field(default=random.random)
    sleep: Callable[[float], "asyncio.Future"] = field(default=asyncio.sleep)

    def delay_s(self, attempt: int, hint_ms: Optional[int] = None) -> float:
        """Delay before retry number ``attempt`` (0-based), seconds."""
        backoff = min(self.max_ms, self.base_ms * self.multiplier ** attempt)
        if hint_ms is not None:
            backoff = min(self.max_ms, max(backoff, float(hint_ms)))
        spread = 1.0 - self.jitter / 2.0 + self.jitter * self.rng()
        return backoff * spread / 1e3

    async def run(self, attempt_fn):
        """Run ``attempt_fn()`` with the retry budget. Retries only
        RESOURCE_EXHAUSTED — anything else (bad signature, malformed
        request) is not load-induced and must not be re-offered."""
        attempt = 0
        while True:
            try:
                return await attempt_fn()
            except grpc.aio.AioRpcError as exc:
                if exc.code() != grpc.StatusCode.RESOURCE_EXHAUSTED:
                    raise
                if attempt >= self.budget:
                    raise
                hint = parse_retry_after_ms(exc.details())
                await self.sleep(self.delay_s(attempt, hint))
                attempt += 1


def _target(uri: str) -> str:
    """grpc.aio targets are host:port; accept http:// URLs for parity with
    the reference's Uri-based config (`client.rs:51-64`)."""
    for prefix in ("http://", "https://"):
        if uri.startswith(prefix):
            uri = uri[len(prefix):]
    return uri.rstrip("/")


class Client:
    def __init__(self, uri: str, retry: Optional[RetryPolicy] = None) -> None:
        self._channel = grpc.aio.insecure_channel(_target(uri))
        self._stub = At2Stub(self._channel)
        self._retry = retry

    async def _submit(self, attempt_fn):
        """Submission-path RPCs go through the retry budget when one is
        configured; read-path RPCs never retry (a refused read is not
        load the client should re-offer)."""
        if self._retry is None:
            return await attempt_fn()
        return await self._retry.run(attempt_fn)

    async def close(self) -> None:
        await self._channel.close()

    async def __aenter__(self) -> "Client":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def send_asset(
        self,
        keypair: SignKeyPair,
        sequence: int,
        recipient: bytes,
        amount: int,
    ) -> None:
        """Sign and submit a transfer (`client.rs:70-91`). The signature
        covers the v2 tagged transfer form — sender and sequence bound
        in (types.py ``transfer_signing_bytes``) — so no middleman
        (broker or ingress node) can re-submit it at another slot."""
        signature = keypair.sign(
            transfer_signing_bytes(keypair.public, sequence, recipient, amount)
        )
        request = pb.SendAssetRequest(
            sender=keypair.public,
            sequence=sequence,
            recipient=recipient,
            amount=amount,
            signature=signature,
        )
        await self._submit(lambda: self._stub.SendAsset(request))

    async def send_asset_many(
        self,
        keypair: SignKeyPair,
        transfers: List[tuple],
    ) -> None:
        """Sign and submit MANY transfers in one RPC (`SendAssetBatch`,
        a beyond-parity extension — at2.proto documents it). ``transfers``
        is ``[(sequence, recipient, amount), ...]``; each entry is signed
        individually exactly like :meth:`send_asset`, so the node-side
        semantics are identical — only the ingress round-trips amortize.
        Lists beyond the server's per-request cap are chunked
        transparently (one RPC per chunk, in order)."""
        requests = []
        for sequence, recipient, amount in transfers:
            requests.append(
                pb.SendAssetRequest(
                    sender=keypair.public,
                    sequence=sequence,
                    recipient=recipient,
                    amount=amount,
                    signature=keypair.sign(
                        transfer_signing_bytes(
                            keypair.public, sequence, recipient, amount
                        )
                    ),
                )
            )
        for lo in range(0, len(requests), _RPC_BATCH_CAP):
            chunk = pb.SendAssetBatchRequest(
                transactions=requests[lo : lo + _RPC_BATCH_CAP]
            )
            await self._submit(lambda: self._stub.SendAssetBatch(chunk))

    async def register(self, public_key: bytes) -> int:
        """Register a client pubkey into the node's gossiped directory
        (broker ingress tier, at2.proto `Register`). Idempotent — returns
        the same dense client-id on every call."""
        reply = await self._stub.Register(
            pb.RegisterRequest(public_key=public_key)
        )
        return reply.client_id

    async def send_distilled(self, frame: bytes) -> None:
        """Submit one distilled batch frame (proto/distill.py format) —
        the broker's forwarding path; also handy for tests driving the
        node's distilled ingress directly."""
        request = pb.SendDistilledBatchRequest(frame=frame)
        await self._submit(lambda: self._stub.SendDistilledBatch(request))

    async def get_balance(self, user: bytes) -> int:
        reply = await self._stub.GetBalance(pb.GetBalanceRequest(sender=user))
        return reply.amount

    async def get_last_sequence(self, user: bytes) -> int:
        reply = await self._stub.GetLastSequence(
            pb.GetLastSequenceRequest(sender=user)
        )
        return reply.sequence

    async def get_certificates(self) -> tuple:
        """The node's finality-certificate chain tail.

        Returns ``(enabled, epoch, node_commits, certs)`` where *certs*
        are decoded ``finality.Certificate`` objects, oldest first.
        ``enabled`` is False when the node runs without a ``[finality]``
        table — the other fields are still meaningful (``node_commits``
        tracks the commit frontier either way).
        """
        from .finality import Certificate
        from .proto import finality_pb2 as fpb

        reply = await self._stub.GetCertificate(fpb.GetCertificateRequest())
        certs = [Certificate.decode(raw) for raw in reply.certificates]
        return reply.enabled, reply.epoch, reply.node_commits, certs

    async def wait_final(
        self,
        sender: bytes,
        sequence: int,
        *,
        verifier=None,
        timeout_s: float = 30.0,
        poll_s: float = 0.25,
    ) -> "Certificate":
        """Block until ``sender``'s transfer ``sequence`` is covered by a
        finality certificate, and return that certificate.

        Two-phase: first poll ``GetLastSequence`` until the node has
        committed the transfer, noting the node's commit frontier at
        that instant; then poll ``GetCertificate`` until a certificate
        whose ``commits`` reaches that frontier arrives — every commit
        the node had applied (including ours) is inside the certified
        watermark by the additive-digest contract.

        Pass a ``finality.LightVerifier`` as *verifier* to refuse
        certificates the client cannot verify itself (stateless
        trust: f+1 known public keys suffice). Raises ``TimeoutError``
        when the deadline passes, ``RuntimeError`` when the node runs
        without finality certificates.
        """
        from .finality import Certificate  # noqa: F401  (return type)

        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        frontier = None
        while True:
            if frontier is None:
                seq = await self.get_last_sequence(sender)
                if seq >= sequence:
                    _, _, frontier, _ = await self.get_certificates()
            if frontier is not None:
                enabled, _, _, certs = await self.get_certificates()
                if not enabled:
                    raise RuntimeError(
                        "node has no [finality] table; wait_final needs "
                        "certificate production enabled fleet-side"
                    )
                for cert in reversed(certs):
                    if cert.commits < frontier:
                        continue
                    if verifier is not None and not verifier.verify(cert)["ok"]:
                        continue
                    return cert
            if loop.time() >= deadline:
                raise TimeoutError(
                    f"no finality certificate covering seq {sequence} "
                    f"within {timeout_s}s"
                )
            await asyncio.sleep(poll_s)

    async def get_latest_transactions(self) -> List[FullTransaction]:
        reply = await self._stub.GetLatestTransactions(
            pb.GetLatestTransactionsRequest()
        )
        return [
            FullTransaction(
                timestamp=parse_rfc3339(tx.timestamp),
                sender=tx.sender,
                sender_sequence=tx.sender_sequence,
                recipient=tx.recipient,
                amount=tx.amount,
                state=TransactionState(tx.state),
            )
            for tx in reply.transactions
        ]
