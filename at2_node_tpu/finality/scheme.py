"""The attestation seam: how co-signatures are made, aggregated, and
checked.

A certificate is scheme-agnostic above this line — the assembler and
the light client only ever call the four methods below, so swapping the
multi-signature for a real aggregate (ROADMAP item 4's BLS mode, per
the EdDSA-vs-BLS committee study) is a registry entry plus a scheme id,
not a wire or verifier redesign.

``multi_eddsa`` (the only built-in) is the trivial aggregate: the
member co-signatures, 64 bytes each, concatenated in member-bitmap bit
order. Verification is per-signature ed25519 (crypto/keys.verify_one),
so it needs no pairing library and the light client stays pure Python.
"""

from __future__ import annotations

from typing import Dict, List

from ..crypto.keys import verify_one


class AttestationScheme:
    """One way of turning member co-signatures into a checkable blob.

    ``name`` keys the registry (and the wire scheme id via
    :data:`SCHEME_IDS`); ``sig_bytes`` is the fixed per-member
    co-signature width this scheme emits on kind-16 frames."""

    name: str = ""
    sig_bytes: int = 64

    def cosign(self, keypair, preimage: bytes) -> bytes:
        raise NotImplementedError

    def verify_cosig(self, public: bytes, preimage: bytes, sig: bytes) -> bool:
        raise NotImplementedError

    def aggregate(self, sigs: List[bytes]) -> bytes:
        """Fold per-member co-signatures (bitmap bit order) into the
        certificate's signature blob."""
        raise NotImplementedError

    def split(self, blob: bytes) -> List[bytes]:
        """Inverse of :meth:`aggregate` for schemes where the blob is
        separable (the light client checks members one by one)."""
        raise NotImplementedError


class MultiEddsa(AttestationScheme):
    name = "multi_eddsa"
    sig_bytes = 64

    def cosign(self, keypair, preimage: bytes) -> bytes:
        return keypair.sign(preimage)

    def verify_cosig(self, public: bytes, preimage: bytes, sig: bytes) -> bool:
        if len(sig) != self.sig_bytes:
            return False
        return verify_one(public, preimage, sig)

    def aggregate(self, sigs: List[bytes]) -> bytes:
        return b"".join(sigs)

    def split(self, blob: bytes) -> List[bytes]:
        w = self.sig_bytes
        if len(blob) % w:
            raise ValueError("multi_eddsa blob not a multiple of 64 bytes")
        return [blob[i : i + w] for i in range(0, len(blob), w)]


_SCHEMES: Dict[str, AttestationScheme] = {}

# wire/manifest scheme ids: append-only (certificates persist across
# versions); 0 is reserved so an all-zero header never looks valid
SCHEME_IDS: Dict[str, int] = {"multi_eddsa": 1}


def register_scheme(scheme: AttestationScheme) -> None:
    if not scheme.name:
        raise ValueError("attestation scheme needs a name")
    if scheme.name not in SCHEME_IDS:
        SCHEME_IDS[scheme.name] = max(SCHEME_IDS.values(), default=0) + 1
    _SCHEMES[scheme.name] = scheme


def get_scheme(name: str) -> AttestationScheme:
    try:
        return _SCHEMES[name]
    except KeyError:
        raise ValueError(f"unknown attestation scheme {name!r}") from None


def scheme_by_id(scheme_id: int) -> AttestationScheme:
    for name, sid in SCHEME_IDS.items():
        if sid == scheme_id and name in _SCHEMES:
            return _SCHEMES[name]
    raise ValueError(f"unknown attestation scheme id {scheme_id}")


register_scheme(MultiEddsa())
