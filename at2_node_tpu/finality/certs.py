"""Quorum certificates over canonical commit frontiers.

A :class:`Certificate` is the portable artifact: the canonical tuple
(epoch, watermark digest, 16 account-range lanes, directory digest) a
quorum of member nodes co-signed, plus WHO signed (a bitmap over the
epoch's member list in sorted-key order) and the scheme's signature
blob. Everything in it is externally checkable — no field depends on
the serving node being honest.

The :class:`CertAssembler` is the node-side collector: it buckets
incoming kind-16 co-signatures by (epoch, watermark digest), verifies
each against the claimed member key, latches *equivocation* — one
member co-signing two different ledger states for the same committed
set — with the two signed preimages as evidence, and assembles a
certificate the moment any bucket reaches quorum. Assembly is
deterministic: signatures are ordered by member rank, never by
arrival, so every node that sees the same co-signature set produces a
byte-identical certificate.
"""

from __future__ import annotations

import struct
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..broadcast.messages import CertSig, cert_signing_bytes
from .scheme import SCHEME_IDS, get_scheme, scheme_by_id

CERT_VERSION = 1

# version(u8) scheme_id(u8) epoch(u64) commits(u64) wm(16) ranges(128)
# dir(8) bitmap_len(u16) blob_len(u32); then bitmap + blob
_CERT_HDR = struct.Struct("<BBQQ16s128s8sHI")

# pending co-signature buckets kept per assembler: frontiers older than
# this many distinct (epoch, wm) coordinates are evicted oldest-first —
# a straggler beyond that re-converges at the next frontier instead
_PENDING_CAP = 64

# sanity bounds for decode (a certificate names at most one signature
# per member; fleets are small)
_MAX_MEMBERS = 4096


@dataclass(frozen=True)
class Certificate:
    """One assembled quorum certificate (externally verifiable)."""

    epoch: int  # membership epoch the frontier was certified under
    commits: int  # max contributor commit count (informational)
    wm_digest: bytes  # 16B additive watermark digest — the coordinate
    ranges: bytes  # 16 u64 account-range lanes (128B)
    dir_digest: bytes  # 8B additive client-directory digest
    scheme: str  # attestation scheme name (scheme.py registry)
    bitmap: bytes  # little-endian member bitmap, sorted-key rank order
    sigs: bytes  # scheme signature blob (rank order for multi_eddsa)

    def preimage(self) -> bytes:
        """The bytes every co-signature in this certificate covers."""
        return cert_signing_bytes(
            self.epoch, self.wm_digest, self.ranges, self.dir_digest
        )

    def signer_count(self) -> int:
        return bin(int.from_bytes(self.bitmap, "little")).count("1")

    def signer_ranks(self) -> List[int]:
        bits = int.from_bytes(self.bitmap, "little")
        return [i for i in range(len(self.bitmap) * 8) if (bits >> i) & 1]

    def encode(self) -> bytes:
        return (
            _CERT_HDR.pack(
                CERT_VERSION,
                SCHEME_IDS[self.scheme],
                self.epoch,
                self.commits,
                self.wm_digest,
                self.ranges,
                self.dir_digest,
                len(self.bitmap),
                len(self.sigs),
            )
            + self.bitmap
            + self.sigs
        )

    @staticmethod
    def decode(raw: bytes) -> "Certificate":
        if len(raw) < _CERT_HDR.size:
            raise ValueError("truncated certificate header")
        (
            version, scheme_id, epoch, commits, wm, ranges, dird,
            bitmap_len, blob_len,
        ) = _CERT_HDR.unpack_from(raw)
        if version != CERT_VERSION:
            raise ValueError(f"unknown certificate version {version}")
        if bitmap_len > (_MAX_MEMBERS + 7) // 8:
            raise ValueError("certificate bitmap too wide")
        scheme = scheme_by_id(scheme_id)  # raises on unknown id
        if blob_len > _MAX_MEMBERS * scheme.sig_bytes:
            raise ValueError("certificate signature blob too large")
        total = _CERT_HDR.size + bitmap_len + blob_len
        if len(raw) != total:
            raise ValueError("certificate length mismatch")
        bitmap = raw[_CERT_HDR.size : _CERT_HDR.size + bitmap_len]
        sigs = raw[_CERT_HDR.size + bitmap_len : total]
        return Certificate(
            epoch, commits, wm, ranges, dird, scheme.name, bitmap, sigs
        )

    def to_doc(self) -> dict:
        """JSON-safe form (/certz, store manifest)."""
        return {
            "v": CERT_VERSION,
            "scheme": self.scheme,
            "epoch": self.epoch,
            "commits": self.commits,
            "wm": self.wm_digest.hex(),
            "ranges": self.ranges.hex(),
            "dir": self.dir_digest.hex(),
            "bitmap": self.bitmap.hex(),
            "sigs": self.sigs.hex(),
        }

    @staticmethod
    def from_doc(doc: dict) -> "Certificate":
        if int(doc.get("v", 0)) != CERT_VERSION:
            raise ValueError("unknown certificate doc version")
        scheme = str(doc["scheme"])
        if scheme not in SCHEME_IDS:
            raise ValueError(f"unknown attestation scheme {scheme!r}")
        return Certificate(
            int(doc["epoch"]),
            int(doc["commits"]),
            bytes.fromhex(doc["wm"]),
            bytes.fromhex(doc["ranges"]),
            bytes.fromhex(doc["dir"]),
            scheme,
            bytes.fromhex(doc["bitmap"]),
            bytes.fromhex(doc["sigs"]),
        )


class CertAssembler:
    """Collects kind-16 co-signatures into quorum certificates.

    ``members`` is the epoch's node sign-key set; rank order (and so
    bitmap bit assignment) is the sorted key order, which every node
    derives identically from the same membership view. ``quorum=0``
    derives the AT2 default 2f+1 with f=(n-1)//3."""

    def __init__(
        self,
        members,
        *,
        epoch: int = 0,
        scheme: str = "multi_eddsa",
        quorum: int = 0,
        history: int = 8,
    ):
        self.scheme = get_scheme(scheme)
        self.history = max(1, int(history))
        self.epoch = int(epoch)
        self.chain: List[Certificate] = []
        # latched first equivocation (culprit attribution + evidence);
        # like the auditor's divergence latch it never self-clears
        self.equivocation: Optional[dict] = None
        self.counters: Dict[str, int] = {
            "cosigs": 0,
            "foreign": 0,
            "epoch_skew": 0,
            "bad_sig": 0,
            "duplicates": 0,
            "equivocations": 0,
            "assembled": 0,
        }
        self._configured_quorum = int(quorum)
        self._set_members(members)
        # (epoch, wm) -> {(ranges, dir) -> {origin -> CertSig}}
        self._pending: "OrderedDict[Tuple[int, bytes], dict]" = OrderedDict()
        self._certified: set = set()  # (epoch, wm) already assembled

    # -- membership -------------------------------------------------------

    def _set_members(self, members) -> None:
        ranked = sorted(set(bytes(m) for m in members))
        self._ranks: Dict[bytes, int] = {k: i for i, k in enumerate(ranked)}
        self._members: List[bytes] = ranked
        n = len(ranked)
        if self._configured_quorum > 0:
            self.quorum = min(self._configured_quorum, max(1, n))
        else:
            f = (n - 1) // 3 if n else 0
            self.quorum = 2 * f + 1 if n else 1

    def reconfigure(self, members, epoch: int) -> None:
        """Epoch transition: new member set, pending buckets from the
        old epoch dropped (their co-signatures name the old epoch and
        can never reach quorum under the new one). The assembled chain
        survives — certificates name their epoch."""
        self.epoch = int(epoch)
        self._set_members(members)
        for key in [k for k in self._pending if k[0] != self.epoch]:
            del self._pending[key]

    @property
    def members(self) -> List[bytes]:
        return list(self._members)

    # -- collection -------------------------------------------------------

    def add(self, cosig: CertSig) -> Optional[Certificate]:
        """Fold one co-signature; returns a Certificate when this one
        completes a quorum, else None."""
        self.counters["cosigs"] += 1
        rank = self._ranks.get(cosig.origin)
        if rank is None:
            self.counters["foreign"] += 1
            return None
        if cosig.epoch != self.epoch:
            # stale (pre-reconfig) or future-epoch co-signature: either
            # way it cannot join this epoch's quorum
            self.counters["epoch_skew"] += 1
            return None
        preimage = cosig.to_sign()
        if not self.scheme.verify_cosig(
            cosig.origin, preimage, cosig.signature
        ):
            self.counters["bad_sig"] += 1
            return None

        key = (cosig.epoch, cosig.wm_digest)
        groups = self._pending.get(key)
        if groups is None:
            groups = self._pending[key] = {}
            while len(self._pending) > _PENDING_CAP:
                self._pending.popitem(last=False)
        state = (cosig.ranges, cosig.dir_digest)

        # Equivocation: equal watermark digest ⇔ equal committed set
        # (AT2 gap-free per-sender sequencing), so one origin signing
        # two different (ranges, dir) states at the same (epoch, wm) is
        # cryptographic proof of misbehavior — latch it with both
        # signed statements as evidence.
        for other_state, sigs in groups.items():
            if other_state != state and cosig.origin in sigs:
                self.counters["equivocations"] += 1
                if self.equivocation is None:
                    prev = sigs[cosig.origin]
                    self.equivocation = {
                        "origin": cosig.origin.hex(),
                        "epoch": cosig.epoch,
                        "wm": cosig.wm_digest.hex(),
                        "first": {
                            "ranges": prev.ranges.hex(),
                            "dir": prev.dir_digest.hex(),
                            "sig": prev.signature.hex(),
                        },
                        "second": {
                            "ranges": cosig.ranges.hex(),
                            "dir": cosig.dir_digest.hex(),
                            "sig": cosig.signature.hex(),
                        },
                    }
                return None

        sigs = groups.setdefault(state, {})
        if cosig.origin in sigs:
            self.counters["duplicates"] += 1
            return None
        sigs[cosig.origin] = cosig

        if len(sigs) >= self.quorum and key not in self._certified:
            self._certified.add(key)
            cert = self._assemble(cosig.epoch, cosig.wm_digest, state, sigs)
            del self._pending[key]
            return cert
        return None

    def _assemble(
        self,
        epoch: int,
        wm: bytes,
        state: Tuple[bytes, bytes],
        sigs: Dict[bytes, CertSig],
    ) -> Certificate:
        ranked = sorted(sigs, key=lambda k: self._ranks[k])
        bits = 0
        for origin in ranked:
            bits |= 1 << self._ranks[origin]
        width = (len(self._members) + 7) // 8
        cert = Certificate(
            epoch=epoch,
            commits=max(sigs[o].commits for o in ranked),
            wm_digest=wm,
            ranges=state[0],
            dir_digest=state[1],
            scheme=self.scheme.name,
            bitmap=bits.to_bytes(max(1, width), "little"),
            sigs=self.scheme.aggregate(
                [sigs[o].signature for o in ranked]
            ),
        )
        self.counters["assembled"] += 1
        self.chain.append(cert)
        del self.chain[: -self.history]
        return cert

    # -- views / persistence ---------------------------------------------

    @property
    def latest(self) -> Optional[Certificate]:
        return self.chain[-1] if self.chain else None

    def status(self) -> dict:
        latest = self.latest
        out = {
            "epoch": self.epoch,
            "quorum": self.quorum,
            "members": len(self._members),
            "chain_len": len(self.chain),
            "pending": len(self._pending),
            **self.counters,
        }
        if latest is not None:
            out["latest"] = {
                "epoch": latest.epoch,
                "commits": latest.commits,
                "wm": latest.wm_digest.hex(),
                "signers": latest.signer_count(),
            }
        if self.equivocation is not None:
            out["equivocation"] = dict(self.equivocation)
        return out

    def stats(self) -> dict:
        """Flat numeric counters for the metrics registry."""
        return {
            **self.counters,
            "chain_len": len(self.chain),
            "latest_commits": self.latest.commits if self.chain else 0,
        }

    def export(self) -> dict:
        """Manifest persistence: the assembled chain tail plus the
        equivocation latch (evidence must survive a restart)."""
        doc: dict = {"chain": [c.to_doc() for c in self.chain]}
        if self.equivocation is not None:
            doc["equivocation"] = dict(self.equivocation)
        return doc

    def restore(self, doc: Optional[dict]) -> None:
        if not doc:
            return
        chain = []
        for cert_doc in doc.get("chain", []):
            try:
                chain.append(Certificate.from_doc(cert_doc))
            except (ValueError, KeyError, TypeError):
                continue  # skip corrupt entries, keep the rest
        if chain:
            self.chain = chain[-self.history :]
            # re-assembling an already-certified frontier after restart
            # would fork the chain ordering; remember what we served
            self._certified.update(
                (c.epoch, c.wm_digest) for c in self.chain
            )
        eq = doc.get("equivocation")
        if eq and self.equivocation is None:
            self.equivocation = dict(eq)
