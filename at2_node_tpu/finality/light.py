"""The stateless light client: verify finality with f+1 known keys.

No node state, no gRPC stream, no trust in the serving node — a
:class:`LightVerifier` holds nothing but public keys from the genesis
epoch config and checks certificates fetched from ANY node's
``GET /certz`` (or the ``GetCertificate`` RPC). Two modes:

* **subset** (the wallet case): the client knows only ``keys`` — at
  least f+1 member public keys — and accepts a certificate once
  ``threshold`` distinct known keys have valid co-signatures over the
  canonical preimage. With threshold ≥ f+1, at least one co-signer is
  honest, and an honest node only co-signs a frontier its own ledger
  reached — so the certified state is real finality, not a story the
  serving node made up.

* **full** (node/CI audit): ``members`` is the complete epoch member
  list in canonical (sorted-key) rank order; every set bitmap bit must
  carry a valid co-signature from exactly that member and the popcount
  must reach ``quorum`` (2f+1 by default). This is the strict check the
  assembler's own output always passes; any mutation — forged bitmap
  bit, swapped signature, altered digest — fails it.

Pure Python on purpose: the only dependency is the ed25519 verify the
package already carries, so the verifier runs anywhere the wire format
is known.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

from .certs import Certificate
from .scheme import get_scheme


def _to_key(k) -> bytes:
    if isinstance(k, str):
        return bytes.fromhex(k)
    return bytes(k)


def default_threshold(total: int) -> int:
    """f+1 for an n-node fleet with f=(n-1)//3: the smallest count that
    guarantees an honest co-signer."""
    return (max(1, int(total)) - 1) // 3 + 1


class LightVerifier:
    def __init__(
        self,
        keys: Iterable,
        *,
        threshold: Optional[int] = None,
        total: Optional[int] = None,
        members: Optional[Sequence] = None,
        quorum: Optional[int] = None,
    ):
        """``keys``: the member public keys this client trusts (bytes or
        hex). ``total``: fleet size from the genesis epoch config, used
        to derive the default f+1 ``threshold``; without it the default
        demands every known key co-sign. ``members`` (full rank-ordered
        member list) switches on full-quorum mode with ``quorum``
        signers required (default 2f+1)."""
        self.keys: List[bytes] = [_to_key(k) for k in keys]
        if not self.keys and members is None:
            raise ValueError("light verifier needs at least one key")
        if threshold is not None:
            self.threshold = max(1, int(threshold))
        elif total is not None:
            self.threshold = default_threshold(total)
        else:
            self.threshold = max(1, len(self.keys))
        self.members: Optional[List[bytes]] = (
            sorted(_to_key(m) for m in members) if members is not None
            else None
        )
        if self.members is not None:
            n = len(self.members)
            f = (n - 1) // 3
            self.quorum = int(quorum) if quorum else 2 * f + 1
        else:
            self.quorum = int(quorum) if quorum else 0

    def verify(self, cert: Certificate) -> dict:
        """Returns a verdict dict: ``ok`` plus ``valid`` (distinct
        members with verified co-signatures), ``need``, and a
        ``reason`` when rejected."""
        try:
            scheme = get_scheme(cert.scheme)
            sigs = scheme.split(cert.sigs)
        except ValueError as exc:
            return {"ok": False, "valid": 0, "need": 0, "reason": str(exc)}
        preimage = cert.preimage()

        if self.members is not None:
            ranks = cert.signer_ranks()
            if len(ranks) != len(sigs):
                return {
                    "ok": False, "valid": 0, "need": self.quorum,
                    "reason": "bitmap popcount != signature count",
                }
            if ranks and ranks[-1] >= len(self.members):
                return {
                    "ok": False, "valid": 0, "need": self.quorum,
                    "reason": "bitmap names a rank outside the member list",
                }
            valid = 0
            for rank, sig in zip(ranks, sigs):
                if not scheme.verify_cosig(
                    self.members[rank], preimage, sig
                ):
                    return {
                        "ok": False, "valid": valid, "need": self.quorum,
                        "reason": f"invalid co-signature at rank {rank}",
                    }
                valid += 1
            if valid < self.quorum:
                return {
                    "ok": False, "valid": valid, "need": self.quorum,
                    "reason": "below quorum",
                }
            return {"ok": True, "valid": valid, "need": self.quorum}

        # subset mode: each known key may claim at most one signature,
        # each signature at most one key
        unmatched = list(self.keys)
        valid = 0
        for sig in sigs:
            for i, key in enumerate(unmatched):
                if scheme.verify_cosig(key, preimage, sig):
                    unmatched.pop(i)
                    valid += 1
                    break
            if valid >= self.threshold:
                return {"ok": True, "valid": valid, "need": self.threshold}
        return {
            "ok": False, "valid": valid, "need": self.threshold,
            "reason": "not enough known co-signers",
        }


def verify_chain(certs: Sequence[Certificate], verifier: LightVerifier) -> dict:
    """Verify an ordered certificate chain (oldest first): every
    certificate must pass the verifier, and the informational progress
    coordinates (epoch, commits) must be non-decreasing — a served
    chain that rolls either back is evidence of tampering."""
    prev_epoch = -1
    prev_commits = -1
    for i, cert in enumerate(certs):
        verdict = verifier.verify(cert)
        if not verdict["ok"]:
            return {"ok": False, "index": i, **verdict}
        if cert.epoch < prev_epoch or (
            cert.epoch == prev_epoch and cert.commits < prev_commits
        ):
            return {
                "ok": False, "index": i, "valid": verdict["valid"],
                "need": verdict["need"],
                "reason": "chain progress rolled back",
            }
        prev_epoch, prev_commits = cert.epoch, cert.commits
    return {"ok": True, "count": len(certs)}
