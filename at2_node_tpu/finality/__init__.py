"""Succinct finality certificates (TECHNICAL.md "Finality certificates").

Turns the fleet-internal audit beacons (obs/audit.py) into signed,
externally-portable evidence: every node co-signs the canonical
(epoch, watermark digest, account-range lanes, directory digest) tuple
at each ``audit_every`` commit frontier (wire kind 16,
broadcast/messages.CertSig); the :class:`~.certs.CertAssembler` folds
2f+1 co-signatures into a quorum :class:`~.certs.Certificate` behind
the pluggable :mod:`~.scheme` seam; :mod:`~.light` verifies one with
nothing but a handful of known member public keys — no node state, no
gRPC stream, no trust in the serving node.
"""

from .certs import CertAssembler, Certificate  # noqa: F401
from .light import LightVerifier, verify_chain  # noqa: F401
from .scheme import AttestationScheme, get_scheme, register_scheme  # noqa: F401
