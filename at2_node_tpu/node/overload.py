"""Closed-loop overload control: pressure sensing + admission shedding.

Every overload signal this fleet produces already existed — the verifier
exports queue depth and queue-wait histograms, the broadcast plane
exports undelivered-slot backlog, the service knows its commit-tail age,
and obs/slo.py computes multi-window burn rates — but nothing *acted* on
any of it: a flash crowd rode straight into unbounded queueing and a
collapsed p99 for every client, well-behaved or not. This module closes
the loop, the way Chop Chop's broker tier sustains network-limit load
only because ingress sheds adaptively (arXiv:2304.07081 §5).

The controller is a pure sampler + ladder, deliberately free of timers
and RNG so it is safe on the deterministic simulator: callers feed it
``clock.monotonic()`` at ingress, it re-samples at most every
``sample_interval`` seconds, and fractional shedding uses an error
accumulator instead of random draws — (seed, config, events) still fully
determine the wire trace.

Design:

* **Pressure** is the worst of five normalized signals — verifier queue
  occupancy, verifier sojourn (windowed mean queue-wait vs a CoDel-style
  target), plane backlog, commit-tail age, and SLO fast-window burn —
  folded through an EWMA so one deep batch doesn't flap the ladder.
  The sojourn signal is additionally *armed*: it must stay above target
  for ``sojourn_arm_s`` continuous seconds before it counts, and
  disarms below half the target (CoDel's interval/hysteresis shape).
* **Shedding** ramps linearly from ``shed_start`` to ``shed_full``
  pressure. Senders already in the gossiped client directory get
  ``registered_grace`` extra headroom — the crowd is, almost by
  definition, the senders the fleet has never seen. Newest-first is
  inherent: shedding happens at admission, so queued work already
  accepted is never discarded.
* **Protocol traffic is exempt.** Echo/Ready attestations, catchup
  sessions and audit beacons ride the inter-node mesh, not client
  ingress — they are the machinery that *drains* the backlog, so
  shedding them would turn overload into livelock. Only SendAsset /
  SendAssetBatch / SendDistilledBatch entries are ever shed.
* **Shed responses are typed.** Every shed aborts RESOURCE_EXHAUSTED
  with a machine-parseable ``retry_after_ms=N`` detail that client.py's
  RetryPolicy honors with jittered exponential backoff, so retries
  cannot become their own flash crowd.

Sheds are accounted separately from signature rejections
(``rejected_at_ingress``) and never charge a sender's admission fail
bucket: an overloaded node refusing valid work is the *node's* state,
not evidence against the sender.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Optional

from .config import OverloadConfig

#: a single pathological signal saturates at 2x full scale — pressure is
#: a control input, not an unbounded gauge
SIGNAL_CAP = 2.0

#: pressure thresholds are expressed relative to the shed ramp; the
#: "elevated" grade (surfaced, not yet shedding) starts at this fraction
#: of shed_start
ELEVATED_FRAC = 0.75

LEVELS = ("normal", "elevated", "shedding", "saturated")

_RETRY_RE = re.compile(r"retry_after_ms=(\d+)")


def format_shed_details(message: str, retry_after_ms: int) -> str:
    """The typed shed/refusal detail string: human text first, then the
    machine hint — parseable from grpc.aio error details and from the
    sim's SimRpcError alike."""
    return f"{message}; retry_after_ms={int(retry_after_ms)}"


def parse_retry_after_ms(details: Optional[str]) -> Optional[int]:
    """Extract the ``retry_after_ms=N`` hint from an error detail string,
    or None when the error carries no hint."""
    if not details:
        return None
    m = _RETRY_RE.search(details)
    return int(m.group(1)) if m else None


def clamp(x: float, lo: float, hi: float) -> float:
    return lo if x < lo else hi if x > hi else x


class OverloadController:
    """Samples pressure signals and decides, deterministically, which
    ingress work to shed. One instance per Service (node-side); the
    broker reuses only the config ladder + detail formatting.

    The signal sources are zero-arg callables so the controller stays
    decoupled from Service internals (and trivially testable): each may
    return None when its subsystem isn't running yet.

    ``verifier_stats``  -> dict with ``queue_depth`` (int), optional.
    ``stage_hists``     -> dict of stage histogram snapshots; the
                           ``queue_wait`` entry's cumulative count/sum_ms
                           are differenced into a windowed mean sojourn.
    ``backlog``         -> undelivered broadcast-slot count.
    ``tail_age``        -> age (s) of the oldest pending payload.
    ``burns``           -> {objective: fast-window burn} from SloEngine.
    """

    def __init__(
        self,
        cfg: OverloadConfig,
        clock,
        *,
        verifier_stats: Optional[Callable[[], Optional[dict]]] = None,
        stage_hists: Optional[Callable[[], Optional[dict]]] = None,
        backlog: Optional[Callable[[], Optional[float]]] = None,
        tail_age: Optional[Callable[[], Optional[float]]] = None,
        burns: Optional[Callable[[], Optional[Dict[str, float]]]] = None,
        on_transition: Optional[Callable[[str, str, float], None]] = None,
    ) -> None:
        self.cfg = cfg
        self.clock = clock
        self._verifier_stats = verifier_stats
        self._stage_hists = stage_hists
        self._backlog = backlog
        self._tail_age = tail_age
        self._burns = burns
        self._on_transition = on_transition

        self.pressure = 0.0
        self.level = 0
        self.samples = 0
        self._last_sample: Optional[float] = None
        self._signals: Dict[str, float] = {}
        # sojourn windowing + CoDel arming state
        self._qw_snap: Optional[tuple] = None  # (count, sum_ms)
        self._sojourn_ms = 0.0
        self._over_since: Optional[float] = None
        self.armed = False
        # drain detection: pressure signals saturate identically while a
        # standing queue builds and while it drains, but only the former
        # justifies shedding the registered tier (their marginal load is
        # not what built the queue)
        self._last_depth = 0.0
        self.draining = False
        # deterministic fractional shedding: per-class error accumulators
        self._debt = {"registered": 0.0, "new": 0.0}

    # -- sampling ---------------------------------------------------------

    def maybe_sample(self, now: Optional[float] = None) -> None:
        """Re-sample at most every ``sample_interval`` seconds. Cheap to
        call on every ingress request; a no-op while disabled."""
        if not self.cfg.enabled:
            return
        if now is None:
            now = self.clock.monotonic()
        if (
            self._last_sample is not None
            and now - self._last_sample < self.cfg.sample_interval
        ):
            return
        self.sample(now)

    def sample(self, now: float) -> float:
        """Take one pressure sample and fold it into the EWMA score."""
        cfg = self.cfg
        sig: Dict[str, float] = {}

        stats = self._verifier_stats() if self._verifier_stats else None
        depth = float((stats or {}).get("queue_depth", 0) or 0)
        sig["occupancy"] = clamp(depth / cfg.queue_target, 0.0, SIGNAL_CAP)
        self.draining = depth < self._last_depth or depth == 0.0
        self._last_depth = depth

        sig["sojourn"] = self._sample_sojourn(now, depth)

        backlog = self._backlog() if self._backlog else None
        sig["backlog"] = clamp(
            float(backlog or 0.0) / cfg.backlog_target, 0.0, SIGNAL_CAP
        )

        tail = self._tail_age() if self._tail_age else None
        sig["tail"] = clamp(
            float(tail or 0.0) / cfg.tail_target_s, 0.0, SIGNAL_CAP
        )

        burns = self._burns() if self._burns else None
        worst_burn = max(burns.values(), default=0.0) if burns else 0.0
        # burn 1.0 = exactly consuming the error budget; treat that as
        # full-scale pressure from the SLO signal
        sig["burn"] = clamp(worst_burn, 0.0, SIGNAL_CAP)

        raw = max(sig.values())
        # fast attack, slow release: rising load must register within a
        # sample or two, but a momentary dip (the queue between retry
        # waves) must not re-open admission while the backlog's
        # downstream work is still in flight — a quarter-rate release
        # makes re-admission wait for sustained calm, not one quiet tick
        a = cfg.smoothing if raw >= self.pressure else cfg.smoothing * 0.25
        self.pressure = a * raw + (1.0 - a) * self.pressure
        self._signals = sig
        self._last_sample = now
        self.samples += 1
        self._set_level(self._level_for(self.pressure))
        return self.pressure

    def _sample_sojourn(self, now: float, depth: float = 0.0) -> float:
        """Windowed mean verifier queue-wait vs the sojourn target, gated
        by CoDel-style arming: above target for ``sojourn_arm_s``
        continuous seconds arms the signal; below half the target
        disarms and resets."""
        cfg = self.cfg
        hists = self._stage_hists() if self._stage_hists else None
        qw = (hists or {}).get("queue_wait")
        if not qw:
            return 0.0
        count = float(qw.get("count", 0) or 0)
        sum_ms = float(qw.get("sum_ms", 0.0) or 0.0)
        if self._qw_snap is None:
            self._qw_snap = (count, sum_ms)
            return 0.0
        d_count = count - self._qw_snap[0]
        d_sum = sum_ms - self._qw_snap[1]
        self._qw_snap = (count, sum_ms)
        if d_count > 0:
            self._sojourn_ms = d_sum / d_count
        elif depth <= 0.0:
            # no completions AND nothing queued: the stale high reading
            # would otherwise hold the signal armed forever after a
            # drain — an empty queue is zero sojourn by definition
            self._sojourn_ms = 0.0
            self._over_since = None
            self.armed = False
            return 0.0
        # no completions with a standing queue: no fresh evidence either
        # way; keep the last reading
        over = self._sojourn_ms > cfg.sojourn_target_ms
        if over:
            if self._over_since is None:
                self._over_since = now
            if now - self._over_since >= cfg.sojourn_arm_s:
                self.armed = True
        else:
            self._over_since = None
            if self._sojourn_ms < cfg.sojourn_target_ms * 0.5:
                self.armed = False
        if not self.armed:
            return 0.0
        return clamp(
            self._sojourn_ms / cfg.sojourn_target_ms, 0.0, SIGNAL_CAP
        )

    def _level_for(self, p: float) -> int:
        cfg = self.cfg
        if p >= cfg.shed_full:
            return 3
        if p >= cfg.shed_start:
            return 2
        if p >= cfg.shed_start * ELEVATED_FRAC:
            return 1
        return 0

    def _set_level(self, level: int) -> None:
        if level == self.level:
            return
        old, self.level = self.level, level
        if self._on_transition is not None:
            self._on_transition(LEVELS[old], LEVELS[level], self.pressure)

    # -- the shed decision ------------------------------------------------

    def shed_fraction(self, *, registered: bool) -> float:
        """The fraction of this class's traffic the current pressure
        says to shed. Linear ramp over [shed_start, shed_full];
        directory-registered senders start their ramp
        ``registered_grace`` later AND are exempt unless the verifier
        queue itself is both past target and growing — a falling or
        sub-target queue means the fleet absorbs their marginal load,
        and the saturated pressure score is the ghost of a burst the
        newcomer tier caused (shedding the steady tier then would
        trade fairness for nothing). Strict priority, in other words:
        newcomers shed to extinction before the registered ramp ever
        engages."""
        cfg = self.cfg
        if registered and (
            self.draining or self._signals.get("occupancy", 0.0) < 1.0
        ):
            return 0.0
        start = cfg.shed_start + (cfg.registered_grace if registered else 0.0)
        span = cfg.shed_full - cfg.shed_start
        return clamp((self.pressure - start) / span, 0.0, 1.0)

    def admit(
        self, *, registered: bool, now: Optional[float] = None
    ) -> Optional[int]:
        """One admission decision. Returns None to admit, or the
        ``retry_after_ms`` hint when the unit of work should be shed.
        Deterministic: a per-class error accumulator turns the shed
        fraction into an exact long-run rate with no RNG."""
        if not self.cfg.enabled:
            return None
        self.maybe_sample(now)
        frac = self.shed_fraction(registered=registered)
        if frac <= 0.0:
            return None
        key = "registered" if registered else "new"
        self._debt[key] += frac
        if self._debt[key] < 1.0:
            return None
        self._debt[key] -= 1.0
        return self.retry_after_ms(registered=registered)

    def retry_after_ms(self, *, registered: bool = False) -> int:
        """Back-off hint scaled with pressure beyond the shed ramp's
        start — deeper overload, longer hold-off. A registered sender's
        shed is a transient growth-window event, so its hint stays at
        the base: it should come right back and land in the next drain
        window, not queue up behind the crowd's long hold-offs."""
        cfg = self.cfg
        if registered:
            return int(cfg.retry_after_ms)
        over = max(0.0, self.pressure - cfg.shed_start)
        ms = cfg.retry_after_ms * (1.0 + 4.0 * over)
        return int(clamp(ms, cfg.retry_after_ms, cfg.retry_after_max_ms))

    # -- surfaces ---------------------------------------------------------

    @property
    def overloaded(self) -> bool:
        """True while the controller is actively shedding — the
        'overloaded' (still serving, non-503) health grade."""
        return self.cfg.enabled and self.level >= 2

    def snapshot(self) -> dict:
        """The /statusz ``pressure`` block."""
        return {
            "enabled": self.cfg.enabled,
            "pressure": round(self.pressure, 4),
            "level": LEVELS[self.level],
            "armed": self.armed,
            "draining": self.draining,
            "sojourn_ms": round(self._sojourn_ms, 3),
            "signals": {k: round(v, 4) for k, v in self._signals.items()},
            "shed_fraction": {
                "registered": round(self.shed_fraction(registered=True), 4),
                "new": round(self.shed_fraction(registered=False), 4),
            },
            "retry_after_ms": self.retry_after_ms(),
            "samples": self.samples,
        }


def broker_retry_after_ms(cfg: OverloadConfig, ratio: float) -> int:
    """The broker's retry-after hint from its buffer-fill ratio — same
    shape as the node ladder (deeper fill, longer hold-off) without
    needing a sampled pressure score."""
    ms = cfg.retry_after_ms * (1.0 + 4.0 * clamp(ratio, 0.0, 1.0))
    return int(clamp(ms, cfg.retry_after_ms, cfg.retry_after_max_ms))


__all__ = [
    "LEVELS",
    "OverloadController",
    "broker_retry_after_ms",
    "format_shed_details",
    "parse_retry_after_ms",
]
