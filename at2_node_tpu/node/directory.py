"""Dense client directory: client-id -> ed25519 pubkey.

The broker ingress tier (Chop Chop's distillation, arXiv:2304.07081)
replaces the per-entry 32-byte pubkey with a varint client-id, which
needs a mapping every node agrees on *enough* to resolve ids — but the
mapping is deliberately NOT consensus state:

* Ids are assigned **strided by node rank**: node ``rank`` of ``total``
  hands out ``rank, rank + total, rank + 2*total, ...``. Any node can
  register a client without coordination, ids never collide, and the id
  space stays dense (the directory is a flat array, not a hash map).
* Assignments are gossiped via ``DirectoryAnnounce`` (wire kind 13) over
  the authenticated node mesh and persisted through the checkpoint.
* A wrong or missing mapping can only make an entry FAIL signature
  verification on the affected node (the entry's signature binds the
  real key) — degrading liveness for that id, handled by the existing
  per-entry attestation bitmaps and poison-entry resolution. Safety
  never depends on directory agreement, so no consensus is needed.

The pubkey table is a contiguous ``(cap, 32)`` uint8 numpy array so the
native distilled-frame parser can resolve every id in one GIL-released
pass (``at2_distill_parse`` takes the base pointer + row count). An
all-zero row means "unassigned" — the zero key is not a usable ed25519
verification key, so the sentinel cannot shadow a real client.

Because the table is dense, the id space must stay bounded even against
a byzantine mesh peer: ids are u64 on the wire, and without a bound one
``DirectoryAnnounce`` claiming id ~2^60 (in the announcer's own stride,
so it passes the stride check) would force an exabyte-scale allocation
on every correct receiver. Two limits close that:

* ``MAX_CLIENTS_PER_RANK`` — hard cap on the stride multiplier ``k``
  (``client_id = rank + total * k``), bounding the table at
  ``total * MAX_CLIENTS_PER_RANK`` rows no matter what arrives;
* ``APPLY_GAP_SLACK`` — an accepted id may run at most this many
  registrations ahead of the mappings already installed for its stride.
  Announces arrive roughly in assignment order (and checkpoint imports
  are id-sorted), so honest traffic always fits; a forged far-ahead id
  is refused without allocating. A legitimate mapping dropped for an
  out-of-order gap is liveness-only and repairs once the gap fills (the
  assigning node re-announces on client Register retries).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..obs.audit import directory_contrib

_ZERO32 = b"\x00" * 32
_M64 = (1 << 64) - 1

# Per-stride registration cap: bounds the dense table (and every peer's
# copy, and the checkpoint) at total * cap rows of 32 bytes. 2^18 rows
# = 8 MiB per stride — far above any bench or deployment here.
MAX_CLIENTS_PER_RANK = 1 << 18

# How far beyond a stride's installed-mapping count an applied id may
# reach (out-of-order gossip tolerance; see module docstring).
APPLY_GAP_SLACK = 1024


class DirectoryFullError(RuntimeError):
    """This node's stride hit MAX_CLIENTS_PER_RANK; no ids left."""


class ClientDirectory:
    def __init__(self, rank: int = 0, total: int = 1) -> None:
        if total < 1 or not (0 <= rank < total):
            raise ValueError(f"bad directory stride rank={rank} total={total}")
        self.rank = rank
        self.total = total
        self._keys = np.zeros((1024, 32), dtype=np.uint8)
        self._limit = 0  # rows [0, _limit) may be assigned
        self._ids: Dict[bytes, int] = {}
        self._next_k = 0  # next own-stride multiplier
        # installed mappings per stride rank, the anchor of the
        # APPLY_GAP_SLACK bound (assign and apply both advance it)
        self._rank_applied: Dict[int, int] = {}
        # Additive fleet-audit digest over installed bindings
        # (obs/audit.py): bindings are install-once (first wins), so a
        # u64 sum of per-binding contributions is order-independent and
        # O(1) to maintain. Informational in beacon comparisons —
        # directory gossip is eventually consistent.
        self.digest = 0

    def __len__(self) -> int:
        return len(self._ids)

    def _ensure(self, client_id: int) -> None:
        if client_id >= len(self._keys):
            cap = len(self._keys)
            while cap <= client_id:
                cap *= 2
            grown = np.zeros((cap, 32), dtype=np.uint8)
            grown[: self._limit] = self._keys[: self._limit]
            self._keys = grown
        if client_id >= self._limit:
            self._limit = client_id + 1

    def assign(self, pubkey: bytes) -> Tuple[int, bool]:
        """Register ``pubkey`` in this node's stride; idempotent.

        Returns ``(client_id, created)`` — ``created`` is False when the
        key was already registered (here or via gossip)."""
        if len(pubkey) != 32 or pubkey == _ZERO32:
            raise ValueError("pubkey must be 32 nonzero bytes")
        existing = self._ids.get(pubkey)
        if existing is not None:
            return existing, False
        if self._next_k >= MAX_CLIENTS_PER_RANK:
            raise DirectoryFullError(
                f"stride {self.rank} is full ({MAX_CLIENTS_PER_RANK} ids)"
            )
        client_id = self.rank + self.total * self._next_k
        self._next_k += 1
        self._ensure(client_id)
        self._keys[client_id] = np.frombuffer(pubkey, dtype=np.uint8)
        self._ids[pubkey] = client_id
        self._rank_applied[self.rank] = self._rank_applied.get(self.rank, 0) + 1
        self.digest = (self.digest + directory_contrib(client_id, pubkey)) & _M64
        return client_id, True

    def apply(self, client_id: int, pubkey: bytes, rank: Optional[int] = None) -> bool:
        """Install a gossiped mapping. Returns False (without mutating)
        when the mapping is rejected: malformed key, id outside the
        announcing node's stride (``rank`` given), id beyond the growth
        bounds (MAX_CLIENTS_PER_RANK / APPLY_GAP_SLACK — the allocation
        DoS guard, refused before any array growth), or the id is
        already bound to a DIFFERENT key (first binding wins — a
        conflicting re-announce is exactly the liveness-only poisoning
        the trust argument allows, so it is dropped, not honored)."""
        if len(pubkey) != 32 or pubkey == _ZERO32 or client_id < 0:
            return False
        if rank is not None and client_id % self.total != rank:
            return False
        current = self.get(client_id)
        if current is not None:
            return current == pubkey
        r = client_id % self.total
        k = client_id // self.total
        if k >= MAX_CLIENTS_PER_RANK:
            return False
        if k > self._rank_applied.get(r, 0) + APPLY_GAP_SLACK:
            return False
        self._ensure(client_id)
        self._keys[client_id] = np.frombuffer(pubkey, dtype=np.uint8)
        self._ids.setdefault(pubkey, client_id)
        self._rank_applied[r] = self._rank_applied.get(r, 0) + 1
        self.digest = (self.digest + directory_contrib(client_id, pubkey)) & _M64
        if r == self.rank:
            self._next_k = max(self._next_k, k + 1)
        return True

    def get(self, client_id: int) -> Optional[bytes]:
        if not (0 <= client_id < self._limit):
            return None
        row = self._keys[client_id].tobytes()
        return None if row == _ZERO32 else row

    def id_of(self, pubkey: bytes) -> Optional[int]:
        return self._ids.get(pubkey)

    def keys_view(self) -> Tuple[np.ndarray, int]:
        """(contiguous uint8 table, assigned-row count) for the native
        parser; rows at id >= count are misses by construction."""
        return self._keys, self._limit

    def export(self) -> List[List[str]]:
        """Checkpoint form: ``[[id_as_str, pubkey_hex], ...]`` sorted by
        id (ids can exceed 2^53, so they travel as strings in JSON)."""
        pairs = sorted((cid, key) for key, cid in self._ids.items())
        out = [[str(cid), key.hex()] for cid, key in pairs]
        # ids bound by gossip under a key that later got a second id are
        # only in the array; export those rows too so restore is exact
        known = {cid for cid, _ in pairs}
        for cid in range(self._limit):
            if cid in known:
                continue
            row = self._keys[cid].tobytes()
            if row != _ZERO32:
                out.append([str(cid), row.hex()])
        out.sort(key=lambda p: int(p[0]))
        return out

    def import_(self, entries: Iterable[Iterable[str]]) -> int:
        """Restore from :meth:`export` output; returns mappings applied."""
        applied = 0
        for cid_s, key_hex in entries:
            if self.apply(int(cid_s), bytes.fromhex(key_hex)):
                applied += 1
        return applied
