"""Server TOML configuration.

Same schema and operator workflow as the reference
(`/root/reference/src/bin/server/config.rs:6-38`): a `Config{addresses
{node, rpc}, keys{sign, network}, nodes = [{address, public_key}]}` TOML
document piped via stdin/stdout, peers appended by textually concatenating
`config get-node` fragments (`/root/reference/README.md:26-27`,
`/root/reference/tests/cli.rs:172-184`).

Two conscious additions over the reference schema:

* each `[[nodes]]` row also carries `sign_public_key` — this build's nodes
  sign their own Echo/Ready attestations (the work the TPU verifier
  batches), so peers must know each other's ed25519 keys, not only the
  channel (X25519) keys;
* an optional `[verifier]` table — `kind = "cpu" | "tpu"`, `batch_size`,
  `max_delay` — the plugin selection the BASELINE north star requires
  (SURVEY.md §5 "config/flag system");
* an optional `[observability]` table — `stats_interval` (seconds between
  structured stats log lines; 0 disables), `profile_dir` (when set, a
  `jax.profiler` trace of the verifier's device work is written there),
  `endpoints` (GET /metrics /healthz /statusz on the public RPC port),
  and `trace_sample` / `trace_cap` (tx-lifecycle tracer sampling and
  cardinality bounds, obs/trace.py), plus the fleet-audit knobs
  `audit_every` / `audit_interval` / `audit_history` / `capture_cap`
  (state-digest beacons and the wire-capture ring, obs/audit.py) —
  SURVEY.md §5's "per-stage counters + jax.profiler from day 1";
* an optional `[slo]` table — declarative service-level objectives
  (commit-latency p99 ceiling, throughput floor, rejection-rate ceiling,
  quorum-stall budget) evaluated with multi-window burn rates and served
  on GET /sloz (see `SloConfig` and obs/slo.py);
* an optional `[checkpoint]` table — `path` (ledger snapshot file;
  restored on start when present) and `interval` (seconds between
  snapshots) — implements the reference's open "store state on disk to
  restart after crash" roadmap item (`/root/reference/README.md:52`);
* an optional `[catchup]` table — `enabled`, `quorum`, `after`, `window`,
  `history_cap` (see `CatchupConfig`) — implements the reference's open
  "catchup mechanism" roadmap item (`/root/reference/README.md:53`);
* an optional `[batching]` table — `enabled`, `max_entries`, `window`
  (see `BatchingConfig`) — ingress transaction batching over the batched
  broadcast plane (broadcast/stack.py); `enabled = false` restores the
  reference's one-transaction-per-broadcast-slot behavior exactly;
* an optional `[admission]` table — `preverify`, `fail_limit`,
  `fail_window` (see `AdmissionConfig`) — ingress pre-verification of
  client signatures at the RPC boundary plus a per-source rate limit on
  entries that FAIL it; `preverify = false` restores the previous
  admit-then-verify-in-broadcast behavior exactly;
* an optional `[overload]` table — closed-loop overload control (see
  `OverloadConfig` and node/overload.py): a smoothed pressure score over
  the live signals drives adaptive admission shedding and broker
  brownout; `enabled = false` (the default) is fully inert and keeps
  every same-seed wire schedule byte-identical.
"""

from __future__ import annotations

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is the same parser
    import tomli as tomllib
from dataclasses import dataclass, field
from typing import List, Optional, TextIO

from ..crypto.keys import ExchangeKeyPair, SignKeyPair
from ..net.peers import Peer


@dataclass
class VerifierConfig:
    kind: str = "cpu"
    batch_size: int = 256
    max_delay: float = 0.002
    # Amortized verification (ISSUE 10): "auto" routes big clean batches
    # to one RLC check per flush (CPU kind; the TPU kind keeps the
    # per-sig kernel unless forced — its on-chip crossover favors
    # per-sig, see ops/roofline.model_rlc), "rlc" forces the RLC path,
    # "per_sig" pins the historical behavior.
    mode: str = "auto"
    # Smallest flush worth one RLC check. None keeps each verifier's
    # default: 128 on the CPU engine, opt-out (1<<30) on TPU where the
    # per-sig kernel wins on-chip — setting it is the operator's opt-in.
    rlc_min_batch: Optional[int] = None

    def make(self):
        from ..crypto.verifier import make_verifier

        rlc_kw = (
            {} if self.rlc_min_batch is None
            else {"rlc_min_batch": self.rlc_min_batch}
        )
        # Route every kind through make_verifier so "pool" works from
        # config and an unknown kind raises instead of silently degrading
        # the north-star path to per-signature CPU verification.
        if self.kind == "cpu":
            return make_verifier("cpu", mode=self.mode, **rlc_kw)
        if self.kind == "pool":
            # the sharded mesh verifier predates RLC routing; it keeps
            # its per-sig kernel shards regardless of mode
            return make_verifier(
                self.kind, batch_size=self.batch_size, max_delay=self.max_delay
            )
        return make_verifier(
            self.kind,
            batch_size=self.batch_size,
            max_delay=self.max_delay,
            mode=self.mode,
            **rlc_kw,
        )


@dataclass
class ObservabilityConfig:
    """Runtime telemetry (obs/ package, TECHNICAL.md "Observability").
    ``endpoints`` serves GET /metrics, /healthz, /statusz on the node's
    public RPC port through the mux (on by default: the endpoints are
    read-only views and share the mux's connection caps).
    ``trace_sample`` = trace every Nth ingress transaction through the
    lifecycle tracker (1 = all, 0 = off); ``trace_cap`` bounds live
    (uncommitted) traces — see obs/trace.py for the eviction policy.
    ``trace_done_cap`` bounds the completed-trace ring served on
    /tracez; ``recorder_cap`` sizes the protocol flight-recorder ring
    served on /debugz (obs/recorder.py; 0 disables recording).

    Continuous profiler (obs/profiler.py, TECHNICAL.md "Continuous
    profiling & plane time-accounting"): ``profilez`` is the kill-switch
    for GET /profilez and the healthz degraded-edge stack capture;
    ``profiler_hz``/``profiler_max_nodes`` size the sampling stack
    profiler; ``profiler_duration`` is the default capture length for
    on-demand and edge-triggered captures; ``lag_probe_interval`` paces
    the event-loop lag probe (0 disables; the standing loop only runs on
    served nodes — never under sim); ``phase_accounting`` arms the plane
    time-accounting seam (phase counters accumulate under sim too — they
    never feed the wire trace).

    Fleet audit plane (obs/audit.py, TECHNICAL.md "Fleet audit &
    incident capture"): ``audit_every`` emits a signed state-digest
    beacon every Nth committed transfer (0 disables; commit-count
    triggered, so emission is deterministic under sim and identical
    across plane shard counts); ``audit_interval`` additionally paces a
    wall-clock beacon on served nodes so an idle fleet still
    cross-checks (0 disables; never runs under sim); ``audit_history``
    bounds the local audit-point ring beacons are compared against.
    ``capture_cap`` sizes the real Mesh's inbound wire-capture ring
    ((mono_ns, peer, kind, frame) records served on /capturez, the
    input to tools/capture_replay.py; 0 disables — the flight-recorder
    kill-switch shape)."""

    stats_interval: float = 0.0  # seconds between stats lines; 0 = off
    profile_dir: str = ""  # jax.profiler trace output dir; "" = off
    endpoints: bool = True  # GET /metrics /healthz /statusz on the mux
    trace_sample: int = 1  # trace every Nth ingress tx; 0 disables
    trace_cap: int = 8192  # max live (uncommitted) traces
    trace_done_cap: int = 1024  # completed traces retained for /tracez
    recorder_cap: int = 2048  # flight-recorder ring size; 0 disables
    profilez: bool = True  # GET /profilez + degraded-edge capture
    profiler_hz: float = 97.0  # stack sampler frequency
    profiler_max_nodes: int = 20000  # stack-tree node budget
    profiler_duration: float = 10.0  # default capture length, seconds
    lag_probe_interval: float = 0.05  # event-loop lag probe pace; 0 = off
    phase_accounting: bool = True  # plane time-accounting seam
    audit_every: int = 256  # beacon every Nth commit; 0 disables
    audit_interval: float = 5.0  # idle-fleet beacon pace (served); 0 = off
    audit_history: int = 512  # local audit points kept for comparison
    capture_cap: int = 512  # inbound wire-capture ring size; 0 disables

    def __post_init__(self) -> None:
        if self.trace_sample < 0:
            raise ValueError("observability.trace_sample must be >= 0")
        if self.trace_cap < 1:
            raise ValueError("observability.trace_cap must be >= 1")
        if self.trace_done_cap < 1:
            raise ValueError("observability.trace_done_cap must be >= 1")
        if self.recorder_cap < 0:
            raise ValueError("observability.recorder_cap must be >= 0")
        if self.profiler_hz <= 0:
            raise ValueError("observability.profiler_hz must be > 0")
        if self.profiler_max_nodes < 1:
            raise ValueError("observability.profiler_max_nodes must be >= 1")
        if self.profiler_duration <= 0:
            raise ValueError("observability.profiler_duration must be > 0")
        if self.lag_probe_interval < 0:
            raise ValueError("observability.lag_probe_interval must be >= 0")
        if self.audit_every < 0:
            raise ValueError("observability.audit_every must be >= 0")
        if self.audit_interval < 0:
            raise ValueError("observability.audit_interval must be >= 0")
        if self.audit_history < 8:
            raise ValueError("observability.audit_history must be >= 8")
        if self.capture_cap < 0:
            raise ValueError("observability.capture_cap must be >= 0")


@dataclass
class SloConfig:
    """Service-level objectives (obs/slo.py): declarative targets
    evaluated live with multi-window burn rates, served on GET /sloz and
    folded into /healthz. ``probe_interval`` is the sampling cadence of
    the background probe (only runs on a real served node; the simulator
    evaluates cells offline). The default targets are deliberately
    lenient — they flag a broken node, not a slow one; tighten per
    deployment. A target <= 0 disables that objective; ``enabled =
    false`` disables probing and /sloz reports no_data forever."""

    enabled: bool = True
    fast_window: float = 30.0  # fast burn window, seconds
    slow_window: float = 300.0  # slow burn window, seconds
    probe_interval: float = 2.0  # seconds between probe samples
    latency_p99_ms: float = 2000.0  # ingress→commit p99 ceiling
    throughput_floor_tps: float = 0.0  # committed tx/s floor; 0 = off
    rejection_ratio_max: float = 0.95  # rejected/(rej+committed) ceiling
    stall_budget: float = 0.5  # commit-stalled fraction of window

    def __post_init__(self) -> None:
        if self.fast_window <= 0 or self.slow_window <= 0:
            raise ValueError("slo windows must be > 0")
        if self.probe_interval <= 0:
            raise ValueError("slo.probe_interval must be > 0")


@dataclass
class CheckpointConfig:
    path: str = ""  # ledger snapshot file; "" disables checkpointing
    interval: float = 30.0  # seconds between periodic snapshots


@dataclass
class StoreConfig:
    """Durable sharded store (at2_node_tpu/store/): per-account-range
    segment files + a write-ahead delta log of committed slots, committed
    atomically by a manifest rename. Supersedes the monolithic
    ``[checkpoint]`` snapshot (still honored: when only ``[checkpoint]``
    is configured the old path runs unchanged, and when BOTH are set an
    existing monolithic snapshot seeds an uninitialized store — the
    one-shot migration). ``flush_interval`` is the seconds between
    incremental flushes (dirty shards only); ``shards`` fixes the
    account-range partition width at store creation; ``sync`` is the WAL
    append discipline (``"buffered"`` = durable at next flush,
    ``"always"`` = fsync per commit); ``history_cap`` bounds retained
    per-sender history bodies (mirrors catchup.history_cap)."""

    dir: str = ""  # store directory; "" disables the sharded store
    flush_interval: float = 5.0  # seconds between incremental flushes
    shards: int = 16
    sync: str = "buffered"  # "buffered" | "always"
    history_cap: int = 1 << 17

    def __post_init__(self) -> None:
        if self.sync not in ("buffered", "always"):
            raise ValueError('store.sync must be "buffered" or "always"')
        if self.shards < 1:
            raise ValueError("store.shards must be >= 1")
        if self.flush_interval <= 0:
            raise ValueError("store.flush_interval must be > 0")


@dataclass
class MembershipConfig:
    """Epoch-based membership reconfiguration (node/membership.py).
    ``admin_public`` is the hex ed25519 key every CONFIG_TX must verify
    against; "" disables reconfiguration entirely (config transactions
    are dropped). ``grace`` is the window, in seconds after an epoch
    transition, during which messages stamped with the PREVIOUS epoch
    are still accepted — covers transactions already in flight when the
    transition lands."""

    admin_public: str = ""  # hex ed25519 admin key; "" disables
    grace: float = 5.0

    def __post_init__(self) -> None:
        if self.grace < 0:
            raise ValueError("membership.grace must be >= 0")


@dataclass
class CatchupConfig:
    """Ledger-history catchup (ledger/history.py): a rejoining node pulls
    quorum-confirmed committed history from peers and replays it through
    the sequence gate. ``quorum`` = peers that must agree on a slot's
    content hash before it is applied (0 → the node's ready threshold;
    set >= f+1 for byzantine tolerance). ``after`` = seconds a sequence
    gap must persist in the retry heap before a catchup session starts.
    ``window`` = seconds a session waits for index/batch responses.
    ``history_cap`` = committed payloads retained for serving peers —
    the catchup HORIZON: a node absent for more commits than every
    peer's history_cap cannot re-converge via catchup alone (sessions
    back off exponentially rather than churn). The supported operator
    path is a LOCAL checkpoint ([checkpoint] table) whose frontier is
    within the horizon: restore-from-own-checkpoint + catchup-of-the-
    tail is tested end-to-end (tests/test_faults.py
    TestBeyondHorizonRejoin). Peer checkpoints cannot be transplanted
    safely (ledger/history.py docstring: balances are functions of full
    history in a consensus-free ledger), so size history_cap to cover
    the longest absence your checkpoint cadence allows."""

    enabled: bool = True
    quorum: int = 0
    after: float = 3.0
    window: float = 1.0
    history_cap: int = 1 << 17


@dataclass
class BatchingConfig:
    """Ingress transaction batching (broadcast/stack.py module docstring:
    the batched broadcast plane). ``max_entries`` caps one batch slot
    (wire hard cap 1024); ``window`` is the flush timer — the latency a
    lone transaction pays for batching. ``enabled = false`` restores the
    reference's one-payload-per-slot surface
    (`/root/reference/src/bin/server/rpc.rs:275-284`) exactly; relayed
    batches from peers are always understood either way."""

    enabled: bool = True
    max_entries: int = 256
    window: float = 0.005

    def __post_init__(self) -> None:
        from ..broadcast.messages import MAX_BATCH_ENTRIES

        if not 1 <= self.max_entries <= MAX_BATCH_ENTRIES:
            raise ValueError(
                f"batching.max_entries must be in [1, {MAX_BATCH_ENTRIES}]"
            )


@dataclass
class AdmissionConfig:
    """Ingress admission control (node/service.py SendAsset /
    SendAssetBatch). With ``preverify`` on, every admission batch runs
    its client signatures through ONE ``Verifier.verify_many`` call (the
    same CPU/TPU seam the broadcast plane uses) and entries that fail
    are rejected at the RPC boundary with a per-entry status —
    unauthenticated spam never enters the gossip plane at all.
    ``fail_limit`` / ``fail_window`` shape a per-source token bucket
    charged ONLY for entries that fail pre-verification, so a hostile
    client cannot use the verifier itself as a DoS lever: up to
    ``fail_limit`` failed entries per source are tolerated per bucket,
    refilling continuously over ``fail_window`` seconds; beyond that the
    source's requests are rejected outright (RESOURCE_EXHAUSTED) without
    spending any verifier throughput. Honest clients never pay: valid
    entries cost zero tokens. ``preverify = false`` restores the
    previous behavior (admit everything, verification happens inside the
    broadcast workers).

    ``register_limit`` / ``register_window`` shape a SEPARATE per-source
    bucket charged one token per NEW directory assignment (Register,
    node/service.py): unlike a failed signature, a registration grows
    every node's directory and checkpoint permanently, so even
    well-formed calls are rate-bounded. The defaults (1024 per 2 s)
    clear a broker warming up thousands of clients in seconds while
    keeping a flooder's permanent-growth rate bounded; the hard backstop
    is the per-stride cap (node/directory.py MAX_CLIENTS_PER_RANK)."""

    preverify: bool = True
    fail_limit: int = 64
    fail_window: float = 10.0
    register_limit: int = 1024
    register_window: float = 2.0

    def __post_init__(self) -> None:
        if self.fail_limit < 1:
            raise ValueError("admission.fail_limit must be >= 1")
        if self.fail_window <= 0:
            raise ValueError("admission.fail_window must be > 0")
        if self.register_limit < 1:
            raise ValueError("admission.register_limit must be >= 1")
        if self.register_window <= 0:
            raise ValueError("admission.register_window must be > 0")


@dataclass
class PlaneConfig:
    """The `[plane]` table: broadcast-plane sharding (broadcast/shards.py).

    ``shards = 1`` (the default) keeps the monolithic single-loop plane —
    the production-safe configuration every existing deployment runs.
    ``shards > 1`` partitions slot state per origin key across that many
    shard cores; ``executor`` picks where their drain work runs:
    ``"thread"`` (one OS thread per shard; scaling comes from the
    GIL-released native kernels), ``"process"`` (one spawn worker
    process per shard over shared-memory rings — true parallelism for
    the Python-level admission/quorum/verify work, see
    parallel/plane_worker.py), or ``"inline"`` (synchronous on the
    event loop — the deterministic mode the sim forces, also useful to
    measure sharding overhead without threads). ``workers`` is the
    owner-loop drain task count for the sharded ingress.

    ``ring_slots`` / ``ring_slot_bytes`` size the per-shard
    shared-memory rings process mode uses (parallel/ring.py): each of
    the two rings per shard is ``ring_slots * ring_slot_bytes`` of
    /dev/shm. A record that does not fit is DROPPED with producer-side
    accounting (``plane_shard_effects_dropped`` on /metrics), so
    undersizing degrades visibly rather than blocking the plane. The
    defaults (4096 x 1 KiB = 4 MiB per direction per shard) hold ~20 ms
    of a saturated shard's traffic."""

    shards: int = 1
    executor: str = "thread"
    workers: int = 4
    ring_slots: int = 4096
    ring_slot_bytes: int = 1024

    def __post_init__(self) -> None:
        if self.shards < 1:
            raise ValueError("plane.shards must be >= 1")
        if self.executor not in ("thread", "inline", "process"):
            raise ValueError(
                "plane.executor must be 'thread', 'inline' or 'process'"
            )
        if self.workers < 1:
            raise ValueError("plane.workers must be >= 1")
        if self.ring_slots < 1:
            raise ValueError("plane.ring_slots must be >= 1")
        if self.ring_slot_bytes < 16:
            raise ValueError("plane.ring_slot_bytes must be >= 16")


@dataclass
class WanConfig:
    """The `[wan]` table: WAN-finality latency levers (ISSUE 14).

    Every knob defaults OFF so the wire schedule — and therefore every
    same-seed sim/campaign hash — is byte-identical to a build without
    this table. Turn them on per deployment:

    ``overlap_ready`` lets a node piggyback its Ready attestation in the
    same frame as its Echo (broadcast/stack.py), collapsing the serial
    echo-quorum -> ready-broadcast round trip into one propagation. Safe
    because the per-slot single-Ready binding and the delivery gate
    (ready quorum AND own ready sent AND content known) are unchanged;
    what is relaxed is only the non-load-bearing "Ready implies an echo
    quorum was locally observed" ordering.

    ``region_fanout`` orders broadcast fanout nearest-first: the sim
    mesh sorts peers by fabric link latency; the real mesh sorts by a
    per-peer RTT EWMA (fed from dial timing) with ``region`` hints as
    the coarse tiebreak. Quorum then forms from the near-region majority
    while far links are still in flight.

    ``region`` is this node's own region hint (free-form string, ""
    means unhinted) compared against each peer's declared region.

    ``verify_ahead`` verifies parked catchup payloads DURING the quorum
    wait when verifier occupancy is low (node/service.py), so delivery
    after ready-quorum never blocks on signature checks.

    ``eager_broker`` anchors the broker's flush deadline at the FIRST
    buffered entry and shrinks it when the queue is shallow
    (broker.py), so a lone WAN tx never waits out a full batch window.
    """

    overlap_ready: bool = False
    region_fanout: bool = False
    region: str = ""
    verify_ahead: bool = False
    eager_broker: bool = False

    def __post_init__(self) -> None:
        if not isinstance(self.region, str):
            raise ValueError("wan.region must be a string")


@dataclass
class OverloadConfig:
    """The `[overload]` table: closed-loop overload control (ISSUE 16).

    ``enabled = false`` (the default) keeps the controller fully inert:
    no samples are taken, no requests are shed, and the wire schedule —
    and therefore every same-seed sim/campaign hash — is byte-identical
    to a build without this table (hash-gated in CI, same bar as
    `[wan]`).

    When enabled, node/overload.py samples the live pressure signals
    (verifier queue depth and sojourn, plane backlog, commit-tail age,
    SLO fast-window burn) at most every ``sample_interval`` seconds,
    folds the worst normalized signal into an EWMA pressure score
    (``smoothing`` is the EWMA alpha), and sheds client ingress when
    pressure crosses the ladder:

    * ``sojourn_target_ms`` / ``sojourn_arm_s`` — CoDel-style gate on
      the verifier queue-wait signal: sojourn must stay above target
      for ``sojourn_arm_s`` continuous seconds before that signal
      counts, and disarms once it falls below half the target, so a
      single deep batch never triggers shedding.
    * ``queue_target`` / ``backlog_target`` / ``tail_target_s`` —
      full-scale normalization for verifier queue depth, undelivered
      broadcast slots, and the oldest pending payload's age.
    * ``shed_start`` .. ``shed_full`` — the shed ramp: unregistered
      senders shed a fraction that rises linearly from 0 at
      ``shed_start`` to 1.0 at ``shed_full``; senders already in the
      gossiped client directory get ``registered_grace`` extra pressure
      headroom before their ramp begins. Protocol traffic (echo/ready/
      catchup/beacons) is never shed — it is what drains the backlog.
    * ``retry_after_ms`` / ``retry_after_max_ms`` — the typed hint shed
      responses carry (``retry_after_ms=N`` in the gRPC status detail),
      scaled up with pressure and honored by client.py's RetryPolicy.
    * ``brownout_frac`` / ``refuse_frac`` — the broker's graduated
      ladder as fractions of PENDING_CAP: above ``brownout_frac`` the
      broker shrinks its flush deadline (the eager-flush machinery),
      above ``refuse_frac`` it refuses new submissions with the
      retry-after hint instead of riding into the hard cap.
    """

    enabled: bool = False
    sample_interval: float = 0.25
    smoothing: float = 0.3
    sojourn_target_ms: float = 150.0
    sojourn_arm_s: float = 0.5
    queue_target: int = 4096
    backlog_target: int = 1024
    tail_target_s: float = 5.0
    shed_start: float = 0.5
    shed_full: float = 0.95
    registered_grace: float = 0.25
    retry_after_ms: int = 250
    retry_after_max_ms: int = 5000
    brownout_frac: float = 0.5
    refuse_frac: float = 0.9

    def __post_init__(self) -> None:
        if self.sample_interval <= 0:
            raise ValueError("overload.sample_interval must be > 0")
        if not 0.0 < self.smoothing <= 1.0:
            raise ValueError("overload.smoothing must be in (0, 1]")
        if self.sojourn_target_ms <= 0:
            raise ValueError("overload.sojourn_target_ms must be > 0")
        if self.sojourn_arm_s < 0:
            raise ValueError("overload.sojourn_arm_s must be >= 0")
        if self.queue_target < 1:
            raise ValueError("overload.queue_target must be >= 1")
        if self.backlog_target < 1:
            raise ValueError("overload.backlog_target must be >= 1")
        if self.tail_target_s <= 0:
            raise ValueError("overload.tail_target_s must be > 0")
        if not 0.0 < self.shed_start < self.shed_full:
            raise ValueError(
                "overload needs 0 < shed_start < shed_full"
            )
        if self.registered_grace < 0:
            raise ValueError("overload.registered_grace must be >= 0")
        if self.retry_after_ms < 1:
            raise ValueError("overload.retry_after_ms must be >= 1")
        if self.retry_after_max_ms < self.retry_after_ms:
            raise ValueError(
                "overload.retry_after_max_ms must be >= retry_after_ms"
            )
        if not 0.0 < self.brownout_frac < self.refuse_frac <= 1.0:
            raise ValueError(
                "overload needs 0 < brownout_frac < refuse_frac <= 1"
            )


@dataclass
class FinalityConfig:
    """The `[finality]` table: succinct finality certificates
    (finality/, TECHNICAL.md "Finality certificates").

    ``enabled = false`` (the default) keeps the subsystem fully inert:
    no kind-16 co-signatures are emitted, no assembler state is kept,
    and the wire schedule — and therefore every same-seed sim/campaign
    hash — is byte-identical to a build without this table (hash-gated
    in CI, same bar as `[wan]` and `[overload]`).

    When enabled, every ``observability.audit_every`` commit frontier
    the node broadcasts a co-signature over the canonical
    (epoch, watermark digest, range lanes, directory digest) tuple;
    the assembler folds ``quorum`` of them (0 derives the AT2 default
    2f+1 from the member count) into a certificate under the named
    attestation ``scheme`` (finality/scheme.py registry — multi_eddsa
    today, the BLS aggregate slots in here later). ``history`` bounds
    the certificate chain tail retained in memory, the store manifest,
    and /certz."""

    enabled: bool = False
    scheme: str = "multi_eddsa"
    quorum: int = 0  # 0 = derive 2f+1 from the membership size
    history: int = 8

    def __post_init__(self) -> None:
        if not isinstance(self.scheme, str) or not self.scheme:
            raise ValueError("finality.scheme must be a non-empty string")
        if self.quorum < 0:
            raise ValueError("finality.quorum must be >= 0")
        if self.history < 1:
            raise ValueError("finality.history must be >= 1")


@dataclass
class Config:
    node_address: str
    rpc_address: str
    sign_key: SignKeyPair
    network_key: ExchangeKeyPair
    nodes: List[Peer] = field(default_factory=list)
    verifier: VerifierConfig = field(default_factory=VerifierConfig)
    observability: ObservabilityConfig = field(
        default_factory=ObservabilityConfig
    )
    slo: SloConfig = field(default_factory=SloConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    store: StoreConfig = field(default_factory=StoreConfig)
    membership: MembershipConfig = field(default_factory=MembershipConfig)
    catchup: CatchupConfig = field(default_factory=CatchupConfig)
    batching: BatchingConfig = field(default_factory=BatchingConfig)
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    plane: PlaneConfig = field(default_factory=PlaneConfig)
    wan: WanConfig = field(default_factory=WanConfig)
    overload: OverloadConfig = field(default_factory=OverloadConfig)
    finality: FinalityConfig = field(default_factory=FinalityConfig)
    echo_threshold: Optional[int] = None
    ready_threshold: Optional[int] = None

    # -- TOML -------------------------------------------------------------

    def dumps(self) -> str:
        lines = []
        # top-level keys must precede any table header in TOML
        if self.echo_threshold is not None:
            lines.append(f"echo_threshold = {self.echo_threshold}")
        if self.ready_threshold is not None:
            lines.append(f"ready_threshold = {self.ready_threshold}")
        lines += [
            "[addresses]",
            f'node = "{self.node_address}"',
            f'rpc = "{self.rpc_address}"',
            "",
            "[keys]",
            f'sign = "{self.sign_key.to_hex()}"',
            f'network = "{self.network_key.to_hex()}"',
            "",
            "[verifier]",
            f'kind = "{self.verifier.kind}"',
            f"batch_size = {self.verifier.batch_size}",
            f"max_delay = {self.verifier.max_delay}",
            f'mode = "{self.verifier.mode}"',
        ]
        if self.verifier.rlc_min_batch is not None:
            lines.append(f"rlc_min_batch = {self.verifier.rlc_min_batch}")
        obs = self.observability
        if obs != ObservabilityConfig():
            lines += [
                "",
                "[observability]",
                f"stats_interval = {obs.stats_interval}",
                f'profile_dir = "{obs.profile_dir}"',
                f"endpoints = {'true' if obs.endpoints else 'false'}",
                f"trace_sample = {obs.trace_sample}",
                f"trace_cap = {obs.trace_cap}",
                f"trace_done_cap = {obs.trace_done_cap}",
                f"recorder_cap = {obs.recorder_cap}",
                f"profilez = {'true' if obs.profilez else 'false'}",
                f"profiler_hz = {obs.profiler_hz}",
                f"profiler_max_nodes = {obs.profiler_max_nodes}",
                f"profiler_duration = {obs.profiler_duration}",
                f"lag_probe_interval = {obs.lag_probe_interval}",
                "phase_accounting = "
                + ("true" if obs.phase_accounting else "false"),
                f"audit_every = {obs.audit_every}",
                f"audit_interval = {obs.audit_interval}",
                f"audit_history = {obs.audit_history}",
                f"capture_cap = {obs.capture_cap}",
            ]
        slo = self.slo
        if slo != SloConfig():
            lines += [
                "",
                "[slo]",
                f"enabled = {'true' if slo.enabled else 'false'}",
                f"fast_window = {slo.fast_window}",
                f"slow_window = {slo.slow_window}",
                f"probe_interval = {slo.probe_interval}",
                f"latency_p99_ms = {slo.latency_p99_ms}",
                f"throughput_floor_tps = {slo.throughput_floor_tps}",
                f"rejection_ratio_max = {slo.rejection_ratio_max}",
                f"stall_budget = {slo.stall_budget}",
            ]
        if self.checkpoint.path:
            lines += [
                "",
                "[checkpoint]",
                f'path = "{self.checkpoint.path}"',
                f"interval = {self.checkpoint.interval}",
            ]
        st = self.store
        if st != StoreConfig():
            lines += [
                "",
                "[store]",
                f'dir = "{st.dir}"',
                f"flush_interval = {st.flush_interval}",
                f"shards = {st.shards}",
                f'sync = "{st.sync}"',
                f"history_cap = {st.history_cap}",
            ]
        mb = self.membership
        if mb != MembershipConfig():
            lines += [
                "",
                "[membership]",
                f'admin_public = "{mb.admin_public}"',
                f"grace = {mb.grace}",
            ]
        cu = self.catchup
        if cu != CatchupConfig():
            lines += [
                "",
                "[catchup]",
                f"enabled = {'true' if cu.enabled else 'false'}",
                f"quorum = {cu.quorum}",
                f"after = {cu.after}",
                f"window = {cu.window}",
                f"history_cap = {cu.history_cap}",
            ]
        ba = self.batching
        if ba != BatchingConfig():
            lines += [
                "",
                "[batching]",
                f"enabled = {'true' if ba.enabled else 'false'}",
                f"max_entries = {ba.max_entries}",
                f"window = {ba.window}",
            ]
        ad = self.admission
        if ad != AdmissionConfig():
            lines += [
                "",
                "[admission]",
                f"preverify = {'true' if ad.preverify else 'false'}",
                f"fail_limit = {ad.fail_limit}",
                f"fail_window = {ad.fail_window}",
            ]
        pl = self.plane
        if pl != PlaneConfig():
            lines += [
                "",
                "[plane]",
                f"shards = {pl.shards}",
                f'executor = "{pl.executor}"',
                f"workers = {pl.workers}",
                f"ring_slots = {pl.ring_slots}",
                f"ring_slot_bytes = {pl.ring_slot_bytes}",
            ]
        wa = self.wan
        if wa != WanConfig():
            lines += [
                "",
                "[wan]",
                f"overlap_ready = {'true' if wa.overlap_ready else 'false'}",
                f"region_fanout = {'true' if wa.region_fanout else 'false'}",
                f'region = "{wa.region}"',
                f"verify_ahead = {'true' if wa.verify_ahead else 'false'}",
                f"eager_broker = {'true' if wa.eager_broker else 'false'}",
            ]
        ov = self.overload
        if ov != OverloadConfig():
            lines += [
                "",
                "[overload]",
                f"enabled = {'true' if ov.enabled else 'false'}",
                f"sample_interval = {ov.sample_interval}",
                f"smoothing = {ov.smoothing}",
                f"sojourn_target_ms = {ov.sojourn_target_ms}",
                f"sojourn_arm_s = {ov.sojourn_arm_s}",
                f"queue_target = {ov.queue_target}",
                f"backlog_target = {ov.backlog_target}",
                f"tail_target_s = {ov.tail_target_s}",
                f"shed_start = {ov.shed_start}",
                f"shed_full = {ov.shed_full}",
                f"registered_grace = {ov.registered_grace}",
                f"retry_after_ms = {ov.retry_after_ms}",
                f"retry_after_max_ms = {ov.retry_after_max_ms}",
                f"brownout_frac = {ov.brownout_frac}",
                f"refuse_frac = {ov.refuse_frac}",
            ]
        fi = self.finality
        if fi != FinalityConfig():
            lines += [
                "",
                "[finality]",
                f"enabled = {'true' if fi.enabled else 'false'}",
                f'scheme = "{fi.scheme}"',
                f"quorum = {fi.quorum}",
                f"history = {fi.history}",
            ]
        for peer in self.nodes:
            lines += [
                "",
                "[[nodes]]",
                f'address = "{peer.address}"',
                f'public_key = "{peer.exchange_public.hex()}"',
                f'sign_public_key = "{peer.sign_public.hex()}"',
            ]
            if peer.region:
                lines.append(f'region = "{peer.region}"')
        return "\n".join(lines) + "\n"

    @staticmethod
    def loads(text: str) -> "Config":
        doc = tomllib.loads(text)
        verifier = VerifierConfig(**doc.get("verifier", {}))
        observability = ObservabilityConfig(**doc.get("observability", {}))
        slo = SloConfig(**doc.get("slo", {}))
        ckpt = CheckpointConfig(**doc.get("checkpoint", {}))
        store = StoreConfig(**doc.get("store", {}))
        membership = MembershipConfig(**doc.get("membership", {}))
        catchup = CatchupConfig(**doc.get("catchup", {}))
        batching = BatchingConfig(**doc.get("batching", {}))
        admission = AdmissionConfig(**doc.get("admission", {}))
        plane = PlaneConfig(**doc.get("plane", {}))
        wan = WanConfig(**doc.get("wan", {}))
        overload = OverloadConfig(**doc.get("overload", {}))
        finality = FinalityConfig(**doc.get("finality", {}))
        return Config(
            node_address=doc["addresses"]["node"],
            rpc_address=doc["addresses"]["rpc"],
            sign_key=SignKeyPair.from_hex(doc["keys"]["sign"]),
            network_key=ExchangeKeyPair.from_hex(doc["keys"]["network"]),
            nodes=[
                Peer(
                    address=n["address"],
                    exchange_public=bytes.fromhex(n["public_key"]),
                    sign_public=bytes.fromhex(n["sign_public_key"]),
                    region=n.get("region", ""),
                )
                for n in doc.get("nodes", [])
            ],
            verifier=verifier,
            observability=observability,
            slo=slo,
            checkpoint=ckpt,
            store=store,
            membership=membership,
            catchup=catchup,
            batching=batching,
            admission=admission,
            plane=plane,
            wan=wan,
            overload=overload,
            finality=finality,
            echo_threshold=doc.get("echo_threshold"),
            ready_threshold=doc.get("ready_threshold"),
        )

    @staticmethod
    def load(fp: TextIO) -> "Config":
        return Config.loads(fp.read())

    def node_fragment(self) -> str:
        """The shareable `config get-node` output: this node's address and
        public identities, as a `[[nodes]]` TOML fragment
        (`/root/reference/src/bin/server/main.rs:74-88`)."""
        return "\n".join(
            [
                "[[nodes]]",
                f'address = "{self.node_address}"',
                f'public_key = "{self.network_key.public.hex()}"',
                f'sign_public_key = "{self.sign_key.public.hex()}"',
            ]
        ) + "\n"
