"""The AT2 node: broadcast wiring + delivery→commit loop + gRPC surface.

Equivalent of the reference's `rpc::Service`
(`/root/reference/src/bin/server/rpc.rs:61-344`): bring up the encrypted
node mesh, run the three-phase broadcast with the configured Verifier,
drain deliveries into the ledger with the reference's exact ordering /
retry / TTL semantics, and serve the four `at2.AT2` RPCs to clients.

Delivery→commit loop parity (`rpc.rs:149-211`):

* delivered payloads enter a min-heap ordered by (sequence, sender,
  content) with their arrival time (`rpc.rs:163-173`);
* the heap is drained to a fixpoint — a pass that commits anything
  re-sorts and retries, so out-of-order sequences gap-fill
  (`rpc.rs:176-208`);
* only sequence/balance failures (`AccountModificationError`) are retried;
  anything else is logged and dropped (`rpc.rs:195-205`);
* a payload older than ``TRANSACTION_TTL`` (60 s) is marked Failure —
  and then still falls through to processing, so it can later flip to
  Success: the reference has no `continue` after its TTL branch
  (`rpc.rs:183-193`), and that observable quirk is kept deliberately;
* leftovers carry into the next delivery batch (`rpc.rs:207`).
"""

from __future__ import annotations

import asyncio
import hashlib
import heapq
import json
import logging
import secrets
from typing import Dict, List, Optional, Set, Tuple

import grpc

from ..broadcast.messages import (
    MAX_BATCH_ENTRIES,
    CertSig,
    DirectoryAnnounce,
    HistoryBatch,
    HistoryIndex,
    HistoryIndexRequest,
    HistoryRequest,
    Payload,
    StateBeacon,
    TxBatch,
)
from ..broadcast.stack import Broadcast
from ..crypto.keys import verify_one
from ..crypto.verifier import Verifier
from ..finality import CertAssembler
from ..ledger import checkpoint as ckpt
from ..ledger import history as hist
from ..ledger.accounts import AccountModificationError, Accounts
from ..ledger.recent import RecentTransactions
from ..net.peers import Mesh, Peer
from ..net.webmux import PortMux
from ..obs.audit import FleetAuditor
from ..obs.profiler import (
    EventLoopLagProbe,
    PhaseAccounting,
    StackSampler,
    build_info,
)
from ..obs.recorder import FlightRecorder
from ..obs.registry import Registry
from ..obs.slo import SloEngine, default_objectives
from ..obs.trace import REJECTED, TxTrace
from ..proto import at2_pb2 as pb
from ..proto import distill
from ..proto import finality_pb2 as fpb
from ..proto.rpc import At2Servicer, add_to_server
from ..types import (
    TRANSFER_SIG_TAG,
    ThinTransaction,
    TransactionState,
    rfc3339,
)
from ..store import RecoveryProgress, ShardedStore
from .config import Config
from .directory import ClientDirectory, DirectoryFullError
from .membership import MembershipManager
from .overload import OverloadController, format_shed_details

logger = logging.getLogger(__name__)

# Dedicated stats logger with its own INFO handler: operator-enabled stats
# must be visible even under the reference-parity WARN default
# (/root/reference/src/bin/server/main.rs:94-99). Configured lazily by
# _enable_stats_logging so library users keep full control otherwise.
stats_logger = logging.getLogger("at2_node_tpu.stats")

TRANSACTION_TTL = 60.0  # seconds, rpc.rs:35

# A catchup session holds at most this many candidate payloads (bounds a
# byzantine peer flooding HistoryBatch junk into an open session).
MAX_SESSION_PAYLOADS = 1 << 17

# Serving-side catchup budgets, per peer per second: a 9-byte
# HistoryIndexRequest triggers an O(ledger) frontier snapshot and a
# response of up to megabytes, and a 49-byte HistoryRequest up to
# MAX_RANGE payload encodes — without a budget an authenticated byzantine
# peer has a huge amplification lever into the broadcast workers. A real
# catchup session needs ONE index and one range request per gapped
# sender; the budgets are far above that and refill every second, so a
# throttled legitimate requester just retries next session.
SERVE_IDX_PER_SEC = 4
SERVE_ROWS_PER_SEC = 4 * 4096

# Distinct ingress sources tracked by the admission rate limiter (one
# token bucket per gRPC peer string). A source evicted at the cap simply
# starts a fresh, full bucket — the cap bounds memory, not correctness.
ADMISSION_SOURCES_CAP = 4096

# Recently-ingested (client_id, sequence) pairs remembered by the
# distilled-batch path: a byzantine broker can replay an entry across
# frames (WITHIN a frame duplicates are unrepresentable — the wire's
# delta coding is strictly increasing). A replay that slips past the cap
# is still harmless — the ledger's per-account sequence gate rejects it
# at commit — so this memory only keeps replays off the broadcast plane.
DISTILL_SEEN_CAP = 1 << 16


class _CatchupSession:
    """In-flight catchup state: peers' frontiers and served payloads,
    grouped for quorum confirmation. Filled synchronously by the
    broadcast workers' handler; consumed by `Service._catchup_once`."""

    __slots__ = ("nonce", "per_peer_cap", "indexes", "votes", "payloads",
                 "stored_by_peer", "prechecked")

    def __init__(self, nonce: int, n_peers: int) -> None:
        self.nonce = nonce
        # The storage cap is per SENDING peer: one byzantine peer
        # flooding junk payloads exhausts only its own share and can
        # neither evict nor block honest peers' copies. Vote accrual on
        # already-stored keys is never capped (votes are one set entry,
        # and blocking them would let the flooder starve quorum).
        self.per_peer_cap = max(1, MAX_SESSION_PAYLOADS // max(1, n_peers))
        # peer sign key -> ((sender, last_seq), ...)
        self.indexes: Dict[bytes, tuple] = {}
        # ((sender, seq), content_hash) -> peer sign keys vouching for it
        self.votes: Dict[tuple, Set[bytes]] = {}
        # ((sender, seq), content_hash) -> the payload itself
        self.payloads: Dict[tuple, Payload] = {}
        self.stored_by_peer: Dict[bytes, int] = {}
        # [wan] verify_ahead verdict cache: vote_key -> signature ok,
        # filled speculatively during the quorum wait so the post-quorum
        # apply only verifies what the speculation missed
        self.prechecked: Dict[tuple, bool] = {}


# module-level latch: repeated Service.start in one process (tests, bench
# tools, multi-node harnesses) must configure the stats logger exactly
# once — the handler check alone would re-attach after a caller's
# removeHandler/clear, silently doubling every line
_stats_logging_enabled = False


def _enable_stats_logging() -> None:
    global _stats_logging_enabled
    if _stats_logging_enabled:
        return
    _stats_logging_enabled = True
    if not stats_logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(message)s")
        )
        stats_logger.addHandler(handler)
        stats_logger.setLevel(logging.INFO)
        stats_logger.propagate = False


class Service(At2Servicer):
    """One AT2 node. `await Service.start(config)`, then `serve_forever`."""

    def __init__(self, config: Config, clock=None) -> None:
        from ..clock import SYSTEM_CLOCK

        self.config = config
        self.clock = SYSTEM_CLOCK if clock is None else clock
        self.accounts = Accounts()
        self.recent = RecentTransactions()
        # Per-Service metrics registry (obs/registry.py): every counter,
        # gauge, and histogram this node exposes lives here, and
        # snapshot_stats() / the GET endpoints are pure views over it.
        # Per-instance, not process-global: tests and bench tools run
        # many Services in one process.
        self.registry = Registry()
        obs = config.observability
        self.tx_trace = TxTrace(
            self.registry,
            sample_every=obs.trace_sample,
            cap=obs.trace_cap,
            done_cap=obs.trace_done_cap,
            clock=self.clock,
        )
        # protocol flight recorder (obs/recorder.py): always on (bounded
        # ring), dumped via /debugz, auto-snapshotted on anomalies
        # (healthz flipping to degraded, a stall kick)
        self.recorder = FlightRecorder(
            cap=obs.recorder_cap, clock=self.clock
        )
        self.registry.gauge(
            "recorder_events", "protocol events ever flight-recorded",
            fn=lambda: self.recorder.recorded,
        )
        self.registry.gauge(
            "recorder_snapshots", "anomaly snapshots captured",
            fn=lambda: self.recorder.snapshots_taken,
        )
        self._health_was_ok = True
        self._started_at = self.clock.monotonic()
        self._started_wall = self.clock.wall()
        # continuous profiler (obs/profiler.py). Phase accounting is
        # plain counters/histograms — safe to arm everywhere, sim
        # included (registry values never feed the wire trace). The
        # stack sampler is a REAL thread and the lag-probe loop a
        # standing timer, so neither auto-starts here: the sampler runs
        # on demand (/profilez?start, the healthz degraded edge, bench
        # harnesses) and start() spawns the lag loop only on served
        # (real-time) nodes.
        self.phases = (
            PhaseAccounting(self.registry) if obs.phase_accounting else None
        )
        self.sampler = StackSampler(
            hz=obs.profiler_hz, max_nodes=obs.profiler_max_nodes
        )
        self.lag_probe = (
            EventLoopLagProbe(
                self.registry, self.clock, interval=obs.lag_probe_interval
            )
            if obs.lag_probe_interval > 0
            else None
        )
        self._config_hash = hashlib.sha256(
            config.dumps().encode()
        ).hexdigest()[:12]
        # SLO engine (obs/slo.py): declarative objectives from the [slo]
        # config table, probed periodically (start() spawns the loop on
        # served nodes), served at GET /sloz, folded into /healthz.
        # Constructed unconditionally — snapshot_stats()'s key set must
        # not depend on traffic or config — the probe task is what the
        # enabled flag gates.
        slo_cfg = config.slo
        self.slo = SloEngine(
            default_objectives(
                latency_p99_ms=slo_cfg.latency_p99_ms,
                throughput_floor_tps=slo_cfg.throughput_floor_tps,
                rejection_ratio_max=slo_cfg.rejection_ratio_max,
                stall_budget=slo_cfg.stall_budget,
            ),
            windows=(slo_cfg.fast_window, slo_cfg.slow_window),
            clock=self.clock,
        )
        self._slo_task: Optional[asyncio.Task] = None
        self._audit_task: Optional[asyncio.Task] = None
        # the probe reads the commit-latency histogram TxTrace already
        # feeds; get-or-create by name returns that same instrument
        self._slo_hist = self.registry.histogram("tx_ingress_to_committed")
        self.registry.gauge(
            "slo_breaching", "objectives burning above 1.0 in every window",
            fn=lambda: len(self.slo.breaching()),
        )
        self.registry.gauge(
            "slo_samples", "probe samples held by the SLO engine",
            fn=lambda: self.slo.sample_count,
        )
        # per-objective fast-window burn as scrapeable gauges (the signal
        # /sloz buried in JSON; also the overload controller's SLO input)
        self.registry.register_provider(
            "slo_burn_", lambda: self.slo.fast_burns()
        )
        # closed-loop overload controller (node/overload.py, config
        # [overload]): constructed unconditionally so /statusz always
        # carries a pressure block, fully inert while disabled — no
        # samples, no sheds, byte-identical wire schedules
        self.overload = OverloadController(
            config.overload,
            self.clock,
            verifier_stats=self._verifier_stats,
            stage_hists=self._overload_stage_hists,
            backlog=self._plane_backlog,
            tail_age=self._commit_tail_age,
            burns=lambda: self.slo.fast_burns(),
            on_transition=self._overload_transition,
        )
        self.overload_stats = self.registry.counter_group(
            (
                "overload_shed_requests",
                "overload_shed_entries",
                "overload_shed_distilled",
            )
        )
        self.registry.gauge(
            "overload_pressure", "smoothed overload pressure score",
            fn=lambda: self.overload.pressure,
        )
        self.registry.gauge(
            "overload_level",
            "overload ladder position (0 normal .. 3 saturated)",
            fn=lambda: float(self.overload.level),
        )
        # durable sharded store (store/sharded.py): None when [store] dir
        # is unset — the node then falls back to the legacy monolithic
        # checkpoint (ledger/checkpoint.py), exactly as before
        self.store: Optional[ShardedStore] = None
        self._store_task: Optional[asyncio.Task] = None
        # recovery state machine (store/recovery.py): starts "cold"; a
        # store-backed restart walks loading_segments -> replaying_wal ->
        # catchup -> live and /healthz reports "recovering" on the way
        self.recovery = RecoveryProgress()
        # epoch-based membership (node/membership.py): None when no
        # [membership] admin key is configured
        self.membership: Optional[MembershipManager] = None
        self._membership_task: Optional[asyncio.Task] = None
        self.verifier: Optional[Verifier] = None
        self.mesh: Optional[Mesh] = None
        self.broadcast: Optional[Broadcast] = None
        self._grpc_server: Optional[grpc.aio.Server] = None
        self._mux: Optional[PortMux] = None
        self._delivery_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._checkpoint_task: Optional[asyncio.Task] = None
        self._profiling = False
        self._owns_verifier = True
        self.committed = 0  # payloads committed to the ledger
        # leftovers: (key, arrival, tiebreak, payload) carried across batches
        self._heap: List[tuple] = []
        self._push_count = 0  # monotonic heap tiebreaker
        self._heap_keys: set = set()  # keys currently in _heap (dedup)
        # the delivery loop, catchup task, and close() all drain the heap;
        # serialize the fixpoint passes so two drains never interleave
        self._drain_lock = asyncio.Lock()
        # heap keys that entered via catchup: their TTL expiry must NOT
        # write FAILURE into the recent ring — the slot was committed
        # network-wide (quorum-confirmed), so a local gap-block is an
        # "unresolved" condition, not a failed transfer (ADVICE r4)
        self._catchup_keys: set = set()
        # commits of catchup-keyed payloads: the runner's progress
        # signal. The global `committed` counter won't do — unrelated
        # live traffic keeps it rising and would reset the backoff
        # forever on a beyond-horizon gap.
        self._catchup_commits = 0
        self._closing = False
        # ledger-history catchup (the reference's open roadmap item,
        # README.md:53): serving store + at most one in-flight session
        self.history = hist.CommittedHistory(config.catchup.history_cap)
        self._catchup_session: Optional[_CatchupSession] = None
        self._catchup_task: Optional[asyncio.Task] = None
        # registry-backed with the dict call-site surface intact
        # (obs/registry.py CounterGroup docstring)
        self.catchup_stats = self.registry.counter_group(
            (
                "catchup_sessions",
                "catchup_applied",
                "catchup_idx_req_rx",
                "catchup_hist_req_rx",
                "catchup_served",
                "catchup_throttled",
                # [wan] verify_ahead: payloads signature-checked during
                # the quorum wait instead of after it
                "catchup_preverified",
            )
        )
        # per-(peer, kind) serving budgets: [window_start, used]
        self._serve_budget: Dict[tuple, list] = {}
        self._idx_serve_offset = 0  # rotating HistoryIndex window
        # ingress batcher (broadcast/stack.py batched plane): SendAsset
        # payloads accumulate here and flush as ONE TxBatch slot on size
        # or window. batch_seq is time-seeded so a restarted node never
        # reuses a (node, batch_seq) slot peers may still remember (batch
        # slots need uniqueness, not continuity — the ledger's per-client
        # sequence gate is what orders transfers).
        self._batch_buf: List[Payload] = []
        self._batch_flush_task: Optional[asyncio.Task] = None
        self._batch_seq = int(self.clock.wall() * 1000) << 20
        # catchup session nonce source: secrets by default; the simulator
        # swaps in a seeded rng so session frames replay bit-identically
        self._nonce_bits = secrets.randbits
        # ingress admission (config [admission]): per-source token
        # buckets charged ONLY for entries that fail pre-verification —
        # source -> [tokens, refill_stamp]
        self._admission_buckets: Dict[str, list] = {}
        # Register charges its own per-source bucket (config [admission]
        # register_limit/register_window): registrations grow every
        # node's directory and checkpoint PERMANENTLY, so unlike the
        # fail-only signature bucket each new assignment costs a token.
        self._register_buckets: Dict[str, list] = {}
        self.admission_stats = self.registry.counter_group(
            ("rejected_at_ingress", "admission_throttled")
        )
        # broker ingress tier (node/directory.py, proto/distill.py):
        # ranks come from the sorted set of ALL node sign keys — every
        # correctly-configured node derives the same ranking, so id
        # strides never collide without any coordination round
        ranked = sorted(
            [config.sign_key.public] + [p.sign_public for p in config.nodes]
        )
        self.directory = ClientDirectory(
            rank=ranked.index(config.sign_key.public), total=len(ranked)
        )
        self._node_ranks = {key: i for i, key in enumerate(ranked)}
        self._distill_seen: Dict[Tuple[int, int], None] = {}
        self.distill_stats = self.registry.counter_group(
            ("distilled_batches_rx", "directory_misses", "dedup_drops")
        )
        self.registry.gauge(
            "directory_size", "client-directory mappings known",
            fn=lambda: len(self.directory),
        )
        # commit progress + queue depths as lazy gauges; transport /
        # verifier stats() dicts as prefixed providers — together these
        # make registry.snapshot() reproduce the exact key families the
        # hand-rolled snapshot_stats() used to assemble
        self.registry.gauge(
            "committed", "payloads committed to the ledger",
            fn=lambda: self.committed,
        )
        self.registry.gauge(
            "pending", "payloads parked in the commit retry heap",
            fn=lambda: len(self._heap),
        )
        self.registry.gauge(
            "history_retained", "payloads retained for peer catchup",
            fn=lambda: len(self.history),
        )
        self.registry.register_provider("verifier_", self._verifier_stats)
        # verifier per-stage latency as REAL histograms (bucket/sum/count
        # on /metrics — the plain provider above only carries its stats()
        # spot values), so external scrapers can aggregate across nodes
        self.registry.register_histogram_provider(
            "verifier_stage_", self._verifier_stage_hists
        )
        self.registry.register_provider(
            "mesh_",
            lambda: self.mesh.stats() if self.mesh is not None else {},
        )
        self.registry.register_provider(
            "rpc_",
            lambda: self._mux.stats() if self._mux is not None else {},
        )
        self.registry.register_provider("store_", self._store_stats_view)
        self.registry.register_provider(
            "membership_",
            lambda: (
                self.membership.stats() if self.membership is not None else {}
            ),
        )
        self.store_stats = self.registry.counter_group(
            ("store_flushes", "store_segments_written", "store_segment_bytes")
        )
        # Fleet consistency auditor (obs/audit.py): the additive digest
        # lanes live on Accounts/ClientDirectory (maintained at the
        # mutation sites); the auditor owns the chain head, the local
        # audit-point history, peer-beacon comparison, and divergence
        # attribution. Beacon emission: every `audit_every` commits
        # (_commit_tail, deterministic under sim) plus a wall timer on
        # served nodes (start()).
        self.auditor = FleetAuditor(
            self.accounts.digest, history_cap=obs.audit_history,
            clock=self.clock,
        )
        # sim failpoint (sim/campaign.py planted_divergence_episode):
        # callable (payload) -> balance delta misapplied to the
        # recipient after a successful transfer; None = off
        self.ledger_failpoint = None
        self.registry.register_provider("audit_", self.auditor.stats)
        self.registry.gauge(
            "audit_divergence",
            "1 when the auditor holds a confirmed peer divergence",
            fn=lambda: 1 if self.auditor.divergence is not None else 0,
        )
        self.registry.gauge(
            "audit_commits", "commits folded into the local digest chain",
            fn=lambda: self.auditor.commits,
        )
        # Finality certificates (finality/, config [finality]): the
        # assembler collects kind-16 co-signatures into quorum certs.
        # None when the table is absent/disabled — the subsystem is
        # fully inert and the wire schedule stays byte-identical.
        fin = config.finality
        self.certs: Optional[CertAssembler] = None
        if fin.enabled:
            self.certs = CertAssembler(
                list(self._node_ranks),
                epoch=0,
                scheme=fin.scheme,
                quorum=fin.quorum,
                history=fin.history,
            )
            self.registry.register_provider("finality_", self.certs.stats)
            self.registry.gauge(
                "finality_equivocation",
                "1 when the assembler holds a latched cert equivocation",
                fn=lambda: (
                    1 if self.certs.equivocation is not None else 0
                ),
            )

    # -- lifecycle --------------------------------------------------------

    @staticmethod
    async def start(
        config: Config,
        verifier: Optional[Verifier] = None,
        *,
        clock=None,
        mesh_factory=None,
        serve_rpc: bool = True,
    ) -> "Service":
        """Bring up one node. ``verifier`` injects a SHARED verifier (the
        BASELINE config-5 shape: many nodes feeding one device pool —
        `parallel.pool.PoolVerifier`); the caller keeps ownership and
        closes it after every sharing node is down.

        ``clock`` / ``mesh_factory`` / ``serve_rpc`` are the simulator's
        seams (at2_node_tpu/sim): an injected virtual clock, a transport
        factory ``(config, on_frame) -> Mesh``-compatible object replacing
        the real socket mesh, and a switch to skip the gRPC/PortMux
        surface (the sim drives the handlers directly). Defaults preserve
        production behavior exactly."""
        service = Service(config, clock=clock)
        if verifier is not None:
            service.verifier = verifier
            service._owns_verifier = False
        else:
            service.verifier = config.verifier.make()
            # Compile the device verifier BEFORE binding the RPC port: a
            # node is not ready while its first signature check would stall
            # tens of seconds behind XLA compilation (readiness probes poll
            # the port — tests/shell/lib.sh, reference tests/cli.rs:119-131).
            try:
                await service.verifier.warmup()
            except Exception:
                await service.verifier.close()
                raise
        # Resume ledger state BEFORE joining the network: peers judge this
        # node by its per-account sequence answers from the first message.
        try:
            await service._restore_state()
        except Exception:
            if service._owns_verifier:
                await service.verifier.close()
            raise
        # Everything past the verifier is brought up under one guard:
        # close() tolerates partially-initialized state, so ANY bring-up
        # failure (mesh bind, broadcast start, profiler, grpc/mux bind)
        # releases the warmed-up verifier, mesh tasks, and background
        # loops instead of leaking them.
        try:
            on_frame = lambda peer, frame: service.broadcast.on_frame(peer, frame)  # noqa: E731
            if mesh_factory is not None:
                service.mesh = mesh_factory(config, on_frame)
            else:
                service.mesh = Mesh(
                    config.node_address,
                    config.network_key,
                    config.nodes,
                    on_frame=on_frame,
                    clock=service.clock,
                    region_fanout=config.wan.region_fanout,
                    region=config.wan.region,
                    capture_cap=config.observability.capture_cap,
                )
            plane_cfg = config.plane
            if plane_cfg.shards > 1:
                # sharded broadcast plane (broadcast/shards.py). Under a
                # non-system clock the executor is forced inline: the sim
                # owns the schedule and shard threads/processes would
                # race it — inline keeps shards=N byte-identical on the
                # wire regardless of the configured executor (the CI
                # campaign-hash sweep pins this across all three).
                from ..broadcast.shards import ShardedPlane
                from ..clock import SYSTEM_CLOCK

                executor = plane_cfg.executor
                if service.clock is not SYSTEM_CLOCK:
                    executor = "inline"
                service.broadcast = ShardedPlane(
                    config.sign_key,
                    service.mesh,
                    service.verifier,
                    shards=plane_cfg.shards,
                    executor=executor,
                    workers=plane_cfg.workers,
                    ring_slots=plane_cfg.ring_slots,
                    ring_slot_bytes=plane_cfg.ring_slot_bytes,
                    echo_threshold=config.echo_threshold,
                    ready_threshold=config.ready_threshold,
                    registry=service.registry,
                    trace=service.tx_trace,
                    recorder=(
                        service.recorder if service.recorder.enabled else None
                    ),
                    clock=service.clock,
                    phases=service.phases,
                    overlap_ready=config.wan.overlap_ready,
                    worker_profiler=config.observability.profilez,
                    profiler_hz=config.observability.profiler_hz,
                    profiler_max_nodes=config.observability.profiler_max_nodes,
                )
            else:
                service.broadcast = Broadcast(
                    config.sign_key,
                    service.mesh,
                    service.verifier,
                    echo_threshold=config.echo_threshold,
                    ready_threshold=config.ready_threshold,
                    registry=service.registry,
                    trace=service.tx_trace,
                    recorder=(
                        service.recorder if service.recorder.enabled else None
                    ),
                    clock=service.clock,
                    phases=service.phases,
                    overlap_ready=config.wan.overlap_ready,
                )
            # flight-record the verifier's flush decisions too (duck-typed
            # attach; a SHARED verifier keeps its first owner's recorder)
            if (
                service.recorder.enabled
                and getattr(service.verifier, "recorder", ()) is None
            ):
                service.verifier.recorder = service.recorder
            # phase-account the verifier's flush decisions the same way
            # (a SHARED verifier keeps its first owner's seam)
            if (
                service.phases is not None
                and getattr(service.verifier, "phases", ()) is None
            ):
                service.verifier.phases = service.phases
            service.broadcast.catchup_handler = service._on_catchup
            service.broadcast.directory_handler = service._on_directory
            service.broadcast.beacon_handler = service._on_beacon
            service.broadcast.cert_handler = service._on_cert_sig
            if service.store is not None:
                # broadcast-safety floors: the slots this node attested
                # before the crash are fenced — a restarted node never
                # signs a conflicting echo/ready for them
                service.broadcast.restore_watermarks(service.store.watermarks)
            mcfg = config.membership
            if mcfg.admin_public:
                service.membership = MembershipManager(
                    admin_public=bytes.fromhex(mcfg.admin_public),
                    clock=service.clock,
                    grace=mcfg.grace,
                    epoch=service.store.epoch if service.store else 0,
                    mesh=service.mesh,
                    on_thresholds=service._on_thresholds,
                    own_sign_public=config.sign_key.public,
                )
                service.recovery.epoch = service.membership.epoch
                service.broadcast.config_handler = service._on_config_tx
            if config.catchup.enabled:
                # broadcast GC signal: a slot stalled past push-
                # retransmission recovers via the ledger-catchup plane
                # (peers replay the committed payload from history)
                service.broadcast.stall_handler = service._kick_catchup
            await service.mesh.start()
            await service.broadcast.start()
            service._delivery_task = asyncio.create_task(service._delivery_loop())

            # Rejoin catchup: a node starting into an existing network may
            # have missed committed history (crash without checkpoint, or
            # checkpoint lag); one session shortly after the mesh dials
            # re-converges the ledger without waiting for new traffic to
            # expose the gap.
            if config.catchup.enabled and service.mesh.peers:
                service._catchup_task = asyncio.create_task(
                    service._catchup_runner(initial_delay=config.catchup.after)
                )
            if service.recovery.state == "catchup" and not (
                config.catchup.enabled and service.mesh.peers
            ):
                # nothing to catch up FROM: a peerless (or catchup-
                # disabled) store restart is as live as it will ever be
                service.recovery.mark_live(service.clock.monotonic())

            # incremental store flush loop (config [store] flush_interval).
            # Like the SLO probe, only on SERVED nodes: the sim flushes
            # and sweeps explicitly at deterministic points instead.
            if (
                serve_rpc
                and service.store is not None
                and config.store.flush_interval > 0
            ):
                service._store_task = asyncio.create_task(
                    service._store_flush_loop(config.store.flush_interval)
                )
            if serve_rpc and service.membership is not None:
                service._membership_task = asyncio.create_task(
                    service._membership_loop()
                )

            # interval <= 0 means snapshot-on-shutdown only (consistent with
            # the observability convention where 0 disables the periodic
            # task). The legacy monolithic loop is superseded entirely by
            # the sharded store when [store] dir is configured.
            if (
                service.store is None
                and config.checkpoint.path
                and config.checkpoint.interval > 0
            ):
                service._checkpoint_task = asyncio.create_task(
                    service._checkpoint_loop(
                        config.checkpoint.path, config.checkpoint.interval
                    )
                )

            obs = config.observability
            if obs.stats_interval > 0:
                _enable_stats_logging()
                service._stats_task = asyncio.create_task(
                    service._stats_loop(obs.stats_interval)
                )
            # SLO probe loop only on SERVED nodes: the simulator runs
            # with serve_rpc=False and evaluates scenario cells offline
            # (sim adding a standing periodic timer would also blunt its
            # deadlock detection); live /sloz needs the samples.
            if serve_rpc and config.slo.enabled:
                service._slo_task = asyncio.create_task(
                    service._slo_loop(config.slo.probe_interval)
                )
            # event-loop lag probe loop: served nodes only, same reasoning
            # as the SLO probe (a standing timer under sim virtual time
            # would blunt SimScheduler's deadlock detection; sim tests
            # drive probe_once() manually instead)
            if serve_rpc and service.lag_probe is not None:
                service.lag_probe.start()
            # idle-fleet audit beacons: served nodes only, same reasoning
            # as the SLO probe (sim emission is commit-count triggered in
            # _commit_tail, keeping every sim schedule timer-free)
            if serve_rpc and config.observability.audit_interval > 0:
                service._audit_task = asyncio.create_task(
                    service._audit_beacon_loop(
                        config.observability.audit_interval
                    )
                )
            if obs.profile_dir:
                import jax

                jax.profiler.start_trace(obs.profile_dir)
                service._profiling = True

            if serve_rpc:
                # The public RPC port is a mux (reference parity: tonic serves
                # native gRPC AND grpc-web/HTTP1/CORS on one port, main.rs:110-114):
                # grpc.aio binds an internal loopback port; the mux splices HTTP/2
                # clients to it and answers grpc-web itself.
                server = grpc.aio.server()
                add_to_server(service, server)
                # assigned BEFORE start: if start() (or anything after) raises,
                # the guard's close() must stop this server, not leak its port
                service._grpc_server = server
                internal_port = server.add_insecure_port("127.0.0.1:0")
                if internal_port == 0:
                    raise OSError("cannot bind internal grpc port")
                await server.start()
                service._mux = PortMux(config.rpc_address, internal_port, service)
                try:
                    await service._mux.start()
                except OSError as exc:
                    raise OSError(
                        f"cannot bind rpc address {config.rpc_address}"
                    ) from exc
        except BaseException:
            await service.close()
            raise
        logger.info(
            "node up: mesh on %s, rpc on %s, %d peers, verifier=%s",
            config.node_address,
            config.rpc_address,
            len(service.mesh.peers),
            config.verifier.kind,
        )
        return service

    async def serve_forever(self) -> None:
        await self._grpc_server.wait_for_termination()

    async def close(self) -> None:
        self._closing = True
        if self._catchup_task is not None:
            self._catchup_task.cancel()
            try:
                await self._catchup_task
            except asyncio.CancelledError:
                pass
        if self._profiling:
            import jax

            jax.profiler.stop_trace()
            self._profiling = False
        if self._stats_task is not None:
            self._stats_task.cancel()
            try:
                await self._stats_task
            except asyncio.CancelledError:
                pass
        if self._slo_task is not None:
            self._slo_task.cancel()
            try:
                await self._slo_task
            except asyncio.CancelledError:
                pass
        if self._audit_task is not None:
            self._audit_task.cancel()
            try:
                await self._audit_task
            except asyncio.CancelledError:
                pass
        if self.lag_probe is not None:
            await self.lag_probe.stop()
        self.sampler.stop()
        if self._checkpoint_task is not None:
            self._checkpoint_task.cancel()
            try:
                await self._checkpoint_task
            except asyncio.CancelledError:
                pass
        if self._store_task is not None:
            self._store_task.cancel()
            try:
                await self._store_task
            except asyncio.CancelledError:
                pass
        if self._membership_task is not None:
            self._membership_task.cancel()
            try:
                await self._membership_task
            except asyncio.CancelledError:
                pass
        if self._mux is not None:
            await self._mux.close()
        if self._grpc_server is not None:
            try:
                await self._grpc_server.stop(grace=0.5)
            except Exception:
                # stop() on a server whose start() never completed (failed
                # bring-up path) can raise; the socket dies with the object
                logger.exception("grpc server stop failed")
        # AFTER the RPC surface is down (no SendAsset can respawn it):
        # cancel the flush timer. ACK is not a commit receipt (rpc.rs:286)
        # — an unflushed ingress buffer may drop on shutdown, like any
        # pre-broadcast payload in the reference. SendAsset also gates on
        # _closing, so a handler mid-await cannot recreate the task.
        if self._batch_flush_task is not None:
            self._batch_flush_task.cancel()
            try:
                await self._batch_flush_task
            except asyncio.CancelledError:
                pass
        if self._delivery_task is not None:
            self._delivery_task.cancel()
            try:
                await self._delivery_task
            except asyncio.CancelledError:
                pass
        if self.broadcast is not None:
            await self.broadcast.close()
        if self.mesh is not None:
            await self.mesh.close()
        if self.verifier is not None and self._owns_verifier:
            await self.verifier.close()
        # Graceful-shutdown drain: payloads still sitting in
        # broadcast.delivered or the retry heap were already delivered
        # NETWORK-WIDE (peers commit and compact them — nothing will ever
        # re-gossip them to us). Dropping them here would permanently
        # desync this node's per-account sequence gate after restart, so
        # commit them before the final snapshot. Crash shutdown remains
        # best-effort by design (ledger/checkpoint.py docstring).
        if self.broadcast is not None:
            now = self.clock.monotonic()
            while True:
                try:
                    p = self.broadcast.delivered.get_nowait()
                except asyncio.QueueEmpty:
                    break
                self._push_pending(p, now)
        if self._heap:
            await self._drain_to_fixpoint()
        # Final snapshot LAST — ingress, delivery, and broadcast are all
        # stopped, so no commit can land after (and be missing from) it.
        if self.store is not None:
            try:
                await self._store_flush()
            except OSError:
                logger.exception("final store flush failed")
            self.store.close()
        elif self.config.checkpoint.path:
            try:
                await ckpt.save(
                    self.config.checkpoint.path,
                    self.accounts,
                    self.recent,
                    self.directory,
                )
            except OSError:
                logger.exception("final checkpoint failed")

    # -- checkpoint ------------------------------------------------------

    async def _checkpoint_loop(self, path: str, interval: float) -> None:
        while True:
            await self.clock.sleep(interval)
            try:
                await ckpt.save(path, self.accounts, self.recent, self.directory)
            except OSError:
                logger.exception("periodic checkpoint failed")

    # -- durable sharded store (store/) ----------------------------------

    async def _restore_state(self) -> None:
        """Resume ledger state at start. With [store] dir configured this
        opens (or initializes) the sharded store — migrating a legacy
        monolithic checkpoint one-shot if one exists and the store does
        not yet — and walks the recovery machine through
        loading_segments/replaying_wal; without it, the legacy full-
        snapshot path loads exactly as before."""
        scfg = self.config.store
        ccfg = self.config.checkpoint
        if not scfg.dir:
            if ccfg.path:
                await ckpt.load(
                    ccfg.path, self.accounts, self.recent, self.directory
                )
            return
        self.recovery.started_at = self.clock.monotonic()
        legacy = None
        if ccfg.path:
            # parsed, not loaded: the store decides whether to migrate
            # (only when no manifest exists yet)
            try:
                with open(ccfg.path) as fp:
                    legacy = json.load(fp)
            except FileNotFoundError:
                legacy = None

        def _on_segment(loaded: int, total: int) -> None:
            self.recovery.advance("loading_segments")
            self.recovery.segments_loaded = loaded
            self.recovery.segments_total = total

        def _on_wal_record(count: int) -> None:
            self.recovery.advance("replaying_wal")
            self.recovery.wal_records_replayed = count

        self.recovery.advance("loading_segments")
        store = ShardedStore.open(
            scfg.dir,
            n_shards=scfg.shards,
            sync=scfg.sync,
            history_cap=scfg.history_cap,
            legacy_checkpoint=legacy,
            on_segment=_on_segment,
            on_wal_record=_on_wal_record,
        )
        self.store = store
        self.recovery.segments_total = max(
            self.recovery.segments_total, store.segments_loaded
        )
        self.recovery.wal_records_replayed = store.wal_replayed
        self.recovery.migrated = store.migrated
        self.recovery.epoch = store.epoch
        await self.accounts.import_state(store.accounts_state())
        await self.recent.import_state(store.recent_rows)
        self.directory.import_(store.directory_rows)
        # the additive digest lanes were reseeded by the imports above
        # (Accounts.import_state / ClientDirectory.apply maintain them);
        # resume the persisted local chain head with a restart marker
        self.auditor.restore(store.audit)
        if self.certs is not None:
            # resume the persisted certificate chain (and any latched
            # equivocation evidence) at the epoch the store reached
            self.certs.restore(store.finality)
            self.certs.epoch = store.epoch
        # refill the catchup serving store from persisted history so a
        # restarted node can serve peers (and the conservation invariant
        # can replay) without waiting for new commits
        for payload in store.iter_history():
            self.history.record(payload)
        # the distilled-batch dedup window survives restart (a replaying
        # broker must not get a second pass at the broadcast plane just
        # because this node bounced)
        for row in store.distill_seen:
            self._distill_seen[(int(row[0]), int(row[1]))] = None
        # re-enqueue delivered-but-uncommitted payloads at the sequence
        # gate: the broadcast never retransmits a delivered slot, and
        # catchup can only confirm it while `quorum` full-history peers
        # are alive — the parked set is this node's own durable copy.
        # Fresh `now`: a restart grants the slot a new TTL window.
        now = self.clock.monotonic()
        restored_parked = 0
        for payload in store.iter_parked():
            if self._push_pending(payload, now):
                restored_parked += 1
        self.recovery.advance("catchup")
        logger.info(
            "store restored: gen=%d %d accounts, %d segments, "
            "%d wal records, %d parked%s",
            store.gen,
            store.account_count(),
            store.segments_loaded,
            store.wal_replayed,
            restored_parked,
            " (migrated legacy checkpoint)" if store.migrated else "",
        )

    def _store_stats_view(self) -> dict:
        if self.store is None:
            return {}
        return {
            "gen": self.store.gen,
            "accounts": self.store.account_count(),
            "history": self.store.history_count(),
            "parked": self.store.parked_count(),
        }

    async def _store_flush(self) -> None:
        """One incremental flush: refresh the manifest's small state
        (directory, recent ring, broadcast-safety watermarks, dedup
        window, epoch), then write dirty shards + rotate the WAL.
        Synchronous on the event loop by design — the mirror the flush
        walks is mutated by the commit path on this same loop, so
        off-thread flushing would race it; cost is bounded by the delta
        since the last flush (BENCH_DURABILITY.json)."""
        if self.store is None:
            return
        watermarks = (
            self.broadcast.export_watermarks()
            if self.broadcast is not None
            else None
        )
        seen = list(self._distill_seen)[-4096:]
        self.store.set_meta(
            directory_rows=self.directory.export(),
            recent_rows=await self.recent.export_state(),
            watermarks=watermarks,
            distill_seen=[[cid, seq] for cid, seq in seen],
            epoch=self.membership.epoch if self.membership else None,
            audit=self.auditor.export(),
            finality=(
                self.certs.export() if self.certs is not None else None
            ),
        )
        stats = self.store.flush()
        if stats:
            self.store_stats["store_flushes"] += 1
            self.store_stats["store_segments_written"] += stats[
                "segments_written"
            ]
            self.store_stats["store_segment_bytes"] += stats["segment_bytes"]

    async def _store_flush_loop(self, interval: float) -> None:
        while True:
            await self.clock.sleep(interval)
            try:
                await self._store_flush()
            except OSError:
                logger.exception("store flush failed")

    # -- membership reconfiguration (node/membership.py) ------------------

    async def _membership_loop(self) -> None:
        """Finalize expired eviction grace windows (mesh removal + ban)."""
        while True:
            await self.clock.sleep(1.0)
            try:
                self.membership.sweep()
            except Exception:
                logger.exception("membership sweep failed")

    def _on_thresholds(
        self, echo: Optional[int], ready: Optional[int]
    ) -> None:
        """Quorum re-weighting hook: a ConfigTx naming new thresholds
        re-weights the broadcast stack's echo/ready quorums in place."""
        if self.broadcast is None:
            return
        if echo is not None:
            self.broadcast.echo_threshold = echo
        if ready is not None:
            self.broadcast.ready_threshold = ready

    def _on_config_tx(self, peer, tx) -> None:
        """Broadcast-worker hook (synchronous): validate/apply a gossiped
        ConfigTx. ``peer`` is None for admin-local injection. A NEWLY
        applied transition is re-gossiped so the fleet converges
        regardless of arrival topology, and the epoch is persisted so a
        restart rejoins at the epoch it had reached."""
        if self.membership is None:
            return
        if not self.membership.handle(tx):
            return
        self.recovery.epoch = self.membership.epoch
        if self.certs is not None:
            # certificates name their epoch: pending co-signature
            # buckets from the old epoch can never reach quorum under
            # the new one, so the assembler drops them; the assembled
            # chain survives the transition
            self.certs.reconfigure(self.certs.members, self.membership.epoch)
        if self.store is not None:
            self.store.set_meta(epoch=self.membership.epoch)
        self.recorder.record("config_apply", (self.membership.epoch,))
        if self.mesh is not None and self.mesh.peers:
            self.mesh.broadcast(tx.encode())

    # -- observability ---------------------------------------------------

    def _verifier_stats(self) -> dict:
        if self.verifier is None:
            return {}
        fn = getattr(self.verifier, "stats", None)
        return fn() if callable(fn) else {}

    def _verifier_stage_hists(self) -> dict:
        """Expose the TPU verifier's stage Histograms to the registry's
        histogram-provider path (full _bucket/_sum/_count exposition).
        CpuVerifier has no stage histograms — empty dict, no families."""
        if self.verifier is None:
            return {}
        out = {}
        for name in ("queue_wait", "prep", "launch", "finish", "dispatch"):
            h = getattr(self.verifier, f"h_{name}", None)
            if h is not None:
                out[name] = h
        return out

    # -- overload-controller signal sources (node/overload.py) ----------

    def _overload_stage_hists(self) -> Optional[dict]:
        """Verifier stage snapshots for the controller's sojourn signal
        — the TPU verifier's stage_histograms(), or a sim model's."""
        if self.verifier is None:
            return None
        fn = getattr(self.verifier, "stage_histograms", None)
        return fn() if callable(fn) else None

    def _plane_backlog(self) -> float:
        """Live undelivered broadcast slots, across shard cores when the
        plane is sharded — the same number the ``slots_undelivered``
        gauge exports."""
        b = self.broadcast
        if b is None:
            return 0.0
        und = getattr(b, "_undelivered", None)
        if und is not None:
            return float(und)
        cores = getattr(b, "_cores", None)
        if cores is not None:
            return float(sum(c._undelivered for c in cores))
        return 0.0

    def _commit_tail_age(self) -> float:
        """Age of the oldest payload parked in the commit retry heap —
        the commit-tail-lag pressure signal."""
        oldest = min((e[1] for e in self._heap), default=None)
        if oldest is None:
            return 0.0
        return max(0.0, self.clock.monotonic() - oldest)

    def _overload_transition(
        self, old: str, new: str, pressure: float
    ) -> None:
        """Ladder transitions are flight-recorded so incident bundles
        capture when and why the controller engaged."""
        self.recorder.record(
            "overload_level", (old, new, round(pressure, 4))
        )

    def snapshot_stats(self) -> dict:
        """One structured stats record: broadcast per-stage counters +
        verifier batch metrics + commit progress (SURVEY.md §5). Now a
        pure registry view — every key comes from exactly one instrument
        or provider, so nothing is counted twice."""
        return self.registry.snapshot()

    async def _stats_loop(self, interval: float) -> None:
        while True:
            await self.clock.sleep(interval)
            snap = self.snapshot_stats()
            # one JSON object per line, keys sorted: machine-parseable
            # (jq / pandas) where the old space-joined k=v repr was not
            stats_logger.info(
                "%s", json.dumps(snap, sort_keys=True, default=float)
            )

    def _stalled_now(self, now: float) -> bool:
        """Commit-stall predicate shared by /healthz and the SLO probe:
        some pending payload has been gap-blocked past the catchup
        trigger horizon."""
        oldest = min((e[1] for e in self._heap), default=None)
        stall_horizon = max(self.config.catchup.after * 2, 5.0)
        return oldest is not None and now - oldest > stall_horizon

    def slo_probe(self) -> None:
        """Take one SLO probe sample from the registry/TxTrace state the
        node already maintains. Called by the background loop on served
        nodes; tests and offline tooling may call it directly."""
        now = self.clock.monotonic()
        self.slo.observe(
            {
                "t": now,
                "committed": self.committed,
                "rejected": self.admission_stats["rejected_at_ingress"],
                "pending": len(self._heap),
                "stalled": self._stalled_now(now),
                "latency": self._slo_hist.buckets(),
            }
        )

    async def _slo_loop(self, interval: float) -> None:
        while True:
            await self.clock.sleep(interval)
            try:
                self.slo_probe()
                # piggyback the overload pressure sample: served nodes
                # keep a fresh score even when ingress is idle (the sim
                # has no probe loop — there the sample is taken lazily
                # at ingress, keeping schedules deterministic)
                self.overload.maybe_sample()
            except Exception:
                logger.exception("slo probe failed")

    def sloz(self) -> dict:
        """Burn-rate verdicts for GET /sloz."""
        return {
            "node": self.config.sign_key.public.hex()[:16],
            **self.slo.evaluate(),
        }

    # HTTP GET surface, served through PortMux's HTTP/1 keep-alive loop
    # (net/webmux.py): the mux routes GETs here, so scrapes share the
    # grpc-web path's _MAX_HTTP1_CONNS / per-connection request cap /
    # per-request timeout — a scrape flood cannot pin handler tasks
    # beyond what grpc-web traffic already could.

    _OBS_JSON = "application/json; charset=utf-8"
    _OBS_PROM = "text/plain; version=0.0.4; charset=utf-8"

    def obs_http(self, path: str):
        """Route one GET. Returns (status, content_type, body) or None
        for 404 (unknown path, or endpoints disabled in config). ``path``
        may carry a query string (the mux passes it through verbatim);
        only /tracez reads one (``?limit=N`` bounds the completed-trace
        payload)."""
        if not self.config.observability.endpoints:
            return None
        route, _, query = path.partition("?")
        if route == "/metrics":
            body = self.registry.render_prometheus().encode()
            return 200, self._OBS_PROM, body
        if route == "/healthz":
            verdict = self.health_verdict()
            # "overloaded" is still-serving by design: the controller is
            # shedding excess ingress, not failing probes
            status = 200 if verdict["status"] in ("ok", "overloaded") else 503
            body = json.dumps(verdict, sort_keys=True).encode()
            return status, self._OBS_JSON, body
        if route == "/statusz":
            body = json.dumps(
                self.statusz(), sort_keys=True, default=float
            ).encode()
            return 200, self._OBS_JSON, body
        if route == "/tracez":
            limit = None
            for part in query.split("&"):
                if part.startswith("limit="):
                    try:
                        limit = max(0, int(part[6:]))
                    except ValueError:
                        pass
            body = json.dumps(
                self.tracez(limit), sort_keys=True, default=float
            ).encode()
            return 200, self._OBS_JSON, body
        if route == "/debugz":
            body = json.dumps(
                self.debugz(), sort_keys=True, default=float
            ).encode()
            return 200, self._OBS_JSON, body
        if route == "/sloz":
            body = json.dumps(
                self.sloz(), sort_keys=True, default=float
            ).encode()
            return 200, self._OBS_JSON, body
        if route == "/profilez":
            # [observability] kill-switch, same contract as the other
            # gated surfaces: switched off means 404, not 403 — the
            # endpoint does not exist on this node
            if not self.config.observability.profilez:
                return None
            params: dict[str, str] = {}
            for part in query.split("&"):
                if part:
                    k, _, v = part.partition("=")
                    params[k] = v
            return self.profilez(params)
        if route == "/certz":
            # finality certificate chain (finality/): kill-switched by
            # the [finality] table — disabled means 404, the endpoint
            # does not exist on this node
            if self.certs is None:
                return None
            body = json.dumps(self.certz(), sort_keys=True).encode()
            return 200, self._OBS_JSON, body
        if route == "/capturez":
            # inbound wire-capture ring (net/peers.py): kill-switched
            # like the flight recorder — capture_cap=0 (or a sim mesh,
            # which has no ring) means the endpoint does not exist
            dump = getattr(self.mesh, "capture_dump", None)
            if dump is None or getattr(self.mesh, "_capture", None) is None:
                return None
            body = json.dumps(
                {
                    "node": self.config.sign_key.public.hex()[:16],
                    **dump(),
                },
                sort_keys=True,
            ).encode()
            return 200, self._OBS_JSON, body
        return None

    def profilez(self, params: dict | None = None):
        """GET /profilez: the sampling profiler's control + view surface.

        ``?start[&duration=S]`` resets the tree and begins a bounded
        capture (default length [observability] profiler_duration);
        ``?stop`` ends one early; ``?fmt=folded[&limit=N]`` serves
        collapsed-stack text for flamegraph tooling; the default GET
        serves JSON — sampler state, the stack tree, folded lines, the
        build block, and the phase-accounting totals (so one scrape
        carries the whole plane decomposition input)."""
        params = params or {}
        obs = self.config.observability
        plane = self._plane_obs()
        if "start" in params:
            try:
                duration = float(
                    params.get("duration") or obs.profiler_duration
                )
            except ValueError:
                duration = obs.profiler_duration
            self.sampler.reset()
            started = self.sampler.start(duration=duration)
            workers = (
                plane.profiler_start(duration) if plane is not None else False
            )
            body = json.dumps(
                {
                    "started": started,
                    "workers_started": workers,
                    **self.sampler.stats(),
                },
                sort_keys=True, default=float,
            ).encode()
            return 200, self._OBS_JSON, body
        if "stop" in params:
            self.sampler.stop()
            if plane is not None:
                plane.profiler_stop()
            body = json.dumps(
                {"stopped": True, **self.sampler.stats()},
                sort_keys=True, default=float,
            ).encode()
            return 200, self._OBS_JSON, body
        limit = None
        if "limit" in params:
            try:
                limit = max(0, int(params["limit"]))
            except ValueError:
                pass
        if params.get("fmt") == "folded":
            body = self._merged_folded(plane, limit).encode()
            return 200, "text/plain; charset=utf-8", body
        folded = self._merged_folded(plane, limit)
        sampler_stats = self.sampler.stats()
        if plane is not None:
            sampler_stats["worker_samples"] = plane.worker_fold_samples()
        body = json.dumps(
            {
                "node": self.config.sign_key.public.hex()[:16],
                "build": self.build_block(),
                "sampler": sampler_stats,
                "phases": (
                    self.phases.totals() if self.phases is not None else {}
                ),
                "folded": folded.splitlines(),
                "tree": self.sampler.tree(),
            },
            sort_keys=True, default=float,
        ).encode()
        return 200, self._OBS_JSON, body

    def _plane_obs(self):
        """The sharded plane, iff it runs the process-mode obs shipping
        lane (otherwise the single-interpreter surfaces are complete on
        their own and nothing needs merging)."""
        b = self.broadcast
        if b is not None and getattr(b, "_obs_ship", False):
            return b
        return None

    def _merged_folded(self, plane, limit: int | None) -> str:
        """Owner folded stacks merged with every shard worker's shipped
        increments, worker frames prefixed ``shardN/``. With no obs lane
        this is exactly the owner sampler's folded() output."""
        if plane is None:
            return self.sampler.folded(limit)
        from ..obs.profiler import merge_folded

        parts = [("", self.sampler.folded())]
        parts.extend(plane.worker_folds())
        return merge_folded(parts, limit)

    def tracez(self, limit: int | None = None) -> dict:
        """Live + completed lifecycle traces plus a paired clock reading
        (tools/trace_collect.py joins records by (sender, seq) across
        nodes and normalizes on the wall stamps)."""
        return {
            "node": self.config.sign_key.public.hex()[:16],
            "clock": {
                "monotonic": round(self.clock.monotonic(), 9),
                "wall": round(self.clock.wall(), 9),
            },
            **self.tx_trace.tracez(limit),
        }

    def debugz(self) -> dict:
        """The flight recorder's ring + anomaly snapshots. In process
        mode, shard workers' shipped recorder events are interleaved
        into the event list by mono timestamp (codes are ``shardN/``-
        prefixed), so one dump reads as one fleet-of-processes
        timeline."""
        dump = self.recorder.dump()
        plane = self._plane_obs()
        if plane is not None:
            worker_events = plane.worker_events()
            if worker_events:
                dump["worker_events"] = len(worker_events)
                dump["events"] = sorted(
                    dump["events"] + worker_events, key=lambda e: e[0]
                )
        return {
            "node": self.config.sign_key.public.hex()[:16],
            "recorder": dump,
        }

    def health_verdict(self) -> dict:
        """Liveness + quorum/stall verdict. ``status`` is "ok" only when
        the node is not shutting down, enough peer channels are up that
        a broadcast can reach its ready quorum, and no pending payload
        has been gap-blocked past the catchup trigger horizon."""
        now = self.clock.monotonic()
        peers_total = len(self.config.nodes)
        channels = 0
        if self.mesh is not None:
            try:
                channels = int(self.mesh.stats().get("channels", 0))
            except Exception:
                pass
        need = peers_total
        if self.broadcast is not None:
            # ready quorum counts this node's own attestation, so
            # peers_needed = threshold - 1 remote channels
            need = max(0, self.broadcast.ready_threshold - 1)
        quorum_ok = peers_total == 0 or channels >= min(need, peers_total)
        stalled = self._stalled_now(now)
        # SLO degradation folds into the verdict: an objective burning
        # above 1.0 in BOTH windows (obs/slo.py multi-window AND — a
        # transient spike cannot flip this) marks the node degraded even
        # when quorum and the commit heap look healthy.
        slo_breach = self.slo.breaching(now)
        # a latched audit divergence is a safety signal, not a liveness
        # one: the ledgers have provably forked at a shared coordinate
        # (obs/audit.py zero-false-positive compare), so the node must
        # fail probes until an operator intervenes
        diverged = self.auditor.divergence is not None
        # a dead plane-shard worker process (process executor only) is a
        # permanent capacity loss: that shard's origins stop making
        # progress while everything else stays live. Degraded with shard
        # attribution — never a silent hang.
        plane_crashed = dict(
            getattr(self.broadcast, "worker_crashed", None) or {}
        )
        ok = (
            quorum_ok
            and not stalled
            and not slo_breach
            and not diverged
            and not plane_crashed
            and not self._closing
        )
        # a store-backed restart reports "recovering" until catchup lag
        # hits zero: healthy-but-behind, distinct from degraded (top.py
        # tolerates it within its deadline; probes still get 503 — the
        # node is not a full quorum participant yet)
        recovering = self.recovery.recovering
        # anomaly-triggered capture: the moment health flips ok->degraded
        # (for a real reason, not shutdown or recovery), freeze the flight
        # recorder so the lead-up survives ring rollover. Edge-triggered
        # on the transition, so a poll loop hammering a degraded node
        # takes ONE snapshot per incident, not one per scrape.
        if not ok and self._health_was_ok and not self._closing:
            if diverged:
                reason = "diverged"
            elif stalled:
                reason = "stalled"
            elif not quorum_ok:
                reason = "quorum_lost"
            elif plane_crashed:
                reason = "plane_worker:" + ",".join(
                    f"shard={sid}" for sid in sorted(plane_crashed)
                )
            else:
                reason = "slo:" + ",".join(slo_breach)
            self.recorder.snapshot("healthz_degraded:" + reason)
            # same edge, stack capture: one bounded profiler run per
            # incident, so the burn that degraded the node is
            # attributable from /profilez afterwards. Served nodes only
            # (the sampler is a real thread — never auto-started under
            # sim) and never clobbering an operator-started capture.
            if (
                self.config.observability.profilez
                and self._mux is not None
                and not self.sampler.running
            ):
                self.sampler.reset()
                self.sampler.start(
                    duration=self.config.observability.profiler_duration
                )
        self._health_was_ok = ok
        if diverged:
            # distinct from "degraded": liveness may be perfect while
            # the state has forked, and operators triage the two very
            # differently (restart vs incident bundle + capture replay)
            status = "diverged"
        elif not ok:
            status = "degraded"
        elif recovering:
            status = "recovering"
        elif self.overload.overloaded:
            # actively shedding but otherwise healthy: still serving,
            # NOT a 503 — load balancers must keep routing here (pulling
            # an overloaded node only concentrates the crowd on the
            # rest); operators see the ladder on /statusz
            status = "overloaded"
        else:
            status = "ok"
        return {
            "status": status,
            "overload_level": self.overload.level,
            "pressure": round(self.overload.pressure, 4),
            "recovering": recovering,
            "epoch": self.membership.epoch if self.membership else 0,
            "closing": self._closing,
            "peers_configured": peers_total,
            "peers_connected": channels,
            "quorum_ok": quorum_ok,
            "stalled": stalled,
            "slo_breach": slo_breach,
            "plane_workers_crashed": {
                str(sid): code for sid, code in sorted(plane_crashed.items())
            },
            "divergence": self.auditor.divergence,
            "pending": len(self._heap),
            "committed": self.committed,
            "uptime_s": round(now - self._started_at, 3),
        }

    def build_block(self) -> dict:
        """The /statusz ``build`` block: exactly what is running — the
        static identity (git SHA, Python/JAX versions) plus this
        process's config hash, start time, and uptime. profile_collect
        and regress.py stamp their reports with the static half."""
        return {
            **build_info(),
            "config_hash": self._config_hash,
            "started_wall": round(self._started_wall, 3),
            "uptime_s": round(self.clock.monotonic() - self._started_at, 3),
        }

    def statusz(self) -> dict:
        """Full JSON snapshot for /statusz and tools/top.py: flat stats
        + tx-lifecycle percentiles + verifier pipeline stage histograms."""
        stages = {}
        routing = {}
        if self.verifier is not None:
            fn = getattr(self.verifier, "stage_histograms", None)
            if callable(fn):
                stages = fn()
            router = getattr(self.verifier, "router", None)
            if router is not None:
                # the LIVE routing decision (ISSUE 10): which path the
                # last flush took, why (batch size vs expected bad), and
                # how many sources the failure EWMA currently tracks
                routing = {
                    "mode": router.mode,
                    **router.stats(),
                    "hot_sources": router.hot_sources(),
                }
        return {
            "node": self.config.sign_key.public.hex()[:16],
            "rpc_address": self.config.rpc_address,
            "build": self.build_block(),
            "health": self.health_verdict(),
            "stats": self.snapshot_stats(),
            "tx_lifecycle": self.tx_trace.snapshot(),
            "verifier_stages": stages,
            "verifier_routing": routing,
            "slo": self.slo.evaluate(),
            # overload-controller block (node/overload.py): the smoothed
            # pressure score, ladder position, per-signal readings, and
            # the live shed fractions / retry-after hint
            "pressure": self.overload.snapshot(),
            "recovery": self.recovery.to_dict(self.clock.monotonic()),
            "membership": (
                self.membership.stats() if self.membership else {}
            ),
            # fleet-audit block (obs/audit.py): digest lanes, chain
            # head, peer beacon summaries, and any latched divergence
            "audit": self.auditor.status(self.directory.digest),
            # finality block (finality/certs.py): assembler counters,
            # latest certificate, and the certified-vs-commit lag the
            # top.py finality column renders
            "finality": self._finality_status(),
            # sharded-plane block (tools/top.py `shards` column); the
            # monolithic plane has no plane_info and reports shards=1
            "plane": (
                self.broadcast.plane_info()
                if hasattr(self.broadcast, "plane_info")
                else {"shards": 1, "executor": "loop"}
            ),
        }

    # -- delivery → commit loop ------------------------------------------

    def _push_pending(
        self, p: Payload, now: float, from_catchup: bool = False
    ) -> bool:
        """Push one delivered payload onto the retry heap — the ONE place
        the heap key is built (delivery loop, catchup, and shutdown drain
        share it: the commit order must not depend on which path
        enqueued). Exact duplicates already pending are skipped: catchup
        can race normal delivery of the same slot, and the loser of the
        sequence gate would otherwise park in the heap forever. Returns
        True only when the payload was NEWLY enqueued (catchup uses this
        to count real progress, not dedup hits)."""
        key = (p.sequence, p.sender, p.transaction.recipient, p.transaction.amount)
        if from_catchup:
            # quorum-confirmed regardless of which path enqueued it first
            # (an ingress duplicate may already sit in the heap): the TTL
            # branch must never FAILURE-mark a network-committed slot
            self._catchup_keys.add(key)
        if key in self._heap_keys:
            return False
        self._heap_keys.add(key)
        self._push_count += 1
        heapq.heappush(self._heap, (key, now, self._push_count, p))
        if self.store is not None:
            # a delivered payload is never retransmitted by the
            # broadcast: losing the heap at a crash would strand slots
            # whose full-history copies dip below the catchup quorum, so
            # park it durably until it commits or times out
            try:
                self.store.note_parked(p)
            except OSError:
                logger.exception("store parked append failed")
        return True

    async def _delivery_loop(self) -> None:
        queue = self.broadcast.delivered
        while True:
            payload = await queue.get()
            batch = [payload]
            while True:  # greedy drain: one pass per delivered batch
                try:
                    batch.append(queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            now = self.clock.monotonic()
            for p in batch:
                self._push_pending(p, now)
            await self._drain_to_fixpoint()

    async def _drain_to_fixpoint(self) -> None:
        # Mirrors rpc.rs:176-208: keep passing over the (sorted) pending
        # set while progress is made; retry only AccountModification
        # errors so a sequence gap fills once its predecessor lands.
        async with self._drain_lock:
            await self._drain_to_fixpoint_locked()

    async def _drain_to_fixpoint_locked(self) -> None:
        while True:
            # Take ownership of everything currently pending; the loop
            # body awaits, and _push_pending (delivery loop, catchup) runs
            # WITHOUT the drain lock — concurrent pushes land in the fresh
            # self._heap and are picked up by the next pass instead of
            # mutating the list this pass is iterating (a heappush mid-
            # iteration could sift an entry behind the iterator, and the
            # end-of-pass rebuild would silently discard it forever).
            batch = self._heap
            if not batch:
                break
            self._heap = []
            before = len(batch)
            batch.sort()
            now = self.clock.monotonic()
            catchup_keys = self._catchup_keys

            def _apply_pass(accounts) -> tuple:
                """One synchronous pass over the sorted batch under the
                accounts lock (Accounts.run_exclusive): per-item stale /
                TTL / transfer semantics identical to the reference's
                loop (rpc.rs:176-208), but ONE lock round-trip for the
                whole pass and the ring mutations collected for one bulk
                apply — the commit path's per-tx actor overhead was the
                top in-window cost at batched-plane rates."""
                retry: List[tuple] = []
                ring_ops: List[tuple] = []
                commits: List[tuple] = []
                drops: List[Payload] = []  # gave up: unpark from the store
                for key, added, tiebreak, payload in batch:
                    # An already-consumed sequence can never commit (the
                    # gate admits exactly last+1 and last only grows);
                    # keep it retrying until the reference's TTL so the
                    # ring records stay bit-identical with the
                    # reference, then drop it instead of parking it.
                    stale = payload.sequence <= accounts.last_sequence_nowait(
                        payload.sender
                    )
                    if now - added > TRANSACTION_TTL:
                        logger.warning(
                            "transaction timed out: (%s, %d)",
                            payload.sender.hex()[:16],
                            payload.sequence,
                        )
                        if stale:
                            # catchup/delivery duplicate of a committed
                            # slot, or a transfer whose own failed debit
                            # consumed the sequence: FAILURE-mark the
                            # latter, never flip a committed twin's
                            # SUCCESS, and drop
                            ring_ops.append(
                                (
                                    "unless_success",
                                    payload.sender,
                                    payload.sequence,
                                )
                            )
                            drops.append(payload)
                            continue
                        if key not in catchup_keys:
                            # catchup-sourced payloads are quorum-
                            # confirmed committed network-wide; a local
                            # gap-block must not record FAILURE for a
                            # transfer every peer reports SUCCESS
                            # (ADVICE r4)
                            ring_ops.append(
                                (
                                    "update",
                                    payload.sender,
                                    payload.sequence,
                                    TransactionState.FAILURE,
                                )
                            )
                        # NO continue — TTL-expired payloads still
                        # process and may flip to Success (reference
                        # quirk, rpc.rs:183-205)
                    try:
                        accounts._transfer(
                            payload.sender,
                            payload.sequence,
                            payload.transaction.recipient,
                            payload.transaction.amount,
                        )
                    except AccountModificationError as exc:
                        logger.debug(
                            "retrying payload (%s, %d): %s",
                            payload.sender.hex()[:16],
                            payload.sequence,
                            exc,
                        )
                        retry.append((key, added, tiebreak, payload))
                        continue
                    except Exception as exc:
                        logger.warning("dropping bad payload: %s", exc)
                        drops.append(payload)
                        continue
                    if self.ledger_failpoint is not None:
                        # sim-only corruption seam (sim/campaign.py
                        # "misapply" event): misapply a balance delta to
                        # the recipient AFTER a successful transfer,
                        # BEFORE the post-commit balance capture — the
                        # WAL, the ring, and the digest all see the
                        # corrupted state consistently, so only peers'
                        # auditors can catch it (which is the point).
                        delta = self.ledger_failpoint(payload)
                        if delta:
                            accounts._tamper(
                                payload.transaction.recipient, delta
                            )
                    ring_ops.append(
                        (
                            "update",
                            payload.sender,
                            payload.sequence,
                            TransactionState.SUCCESS,
                        )
                    )
                    # POST-commit balances captured here, inside the
                    # exclusive section, so the WAL record the store
                    # appends is exactly the ledger state this transfer
                    # left behind (a later read could see a newer value)
                    s_bal = accounts._ledger[payload.sender].balance
                    recipient = payload.transaction.recipient
                    r_bal = (
                        accounts._ledger[recipient].balance
                        if recipient != payload.sender
                        else None
                    )
                    commits.append((key, payload, s_bal, r_bal))
                return retry, ring_ops, commits, drops

            retry, ring_ops, commits, drops = await self.accounts.run_exclusive(
                _apply_pass
            )
            if drops and self.store is not None:
                for p in drops:
                    try:
                        self.store.note_unparked(p)
                    except OSError:
                        logger.exception("store unpark append failed")
            if commits or ring_ops:
                # the accounts mutation already happened inside
                # run_exclusive: a cancellation landing between it and the
                # history/ring bookkeeping would leave committed transfers
                # invisible to catchup peers and stuck Pending in the
                # recent ring. Shield the tail so close()'s task
                # cancellation can interrupt the DRAIN but never split a
                # commit from its record.
                await asyncio.shield(self._commit_tail(commits, ring_ops))
            # merge the leftovers with anything that arrived mid-pass; no
            # awaits between here and the key rebuild, so the set and the
            # heap cannot diverge
            arrivals = len(self._heap)
            self._heap.extend(retry)
            heapq.heapify(self._heap)
            self._heap_keys = {entry[0] for entry in self._heap}
            self._catchup_keys &= self._heap_keys  # prune committed/dropped
            progressed = len(retry) < before
            if not self._heap or not (progressed or arrivals):
                break
        # Anything still pending after a fixpoint pass is gap-blocked: its
        # predecessor is not in flight anywhere local, so if it doesn't
        # resolve within cfg.after (the runner's initial delay), it was
        # committed network-wide while this node was away — pull it from
        # peers. The kick is single-flight and the runner paces itself,
        # so kicking on every drain with leftovers is cheap; kicking ONLY
        # when entries are already old would miss gaps entirely (drains
        # run on delivery, and a quiet net delivers nothing after the
        # gapped payload — the age condition would never be re-checked).
        cfg = self.config.catchup
        if (
            cfg.enabled
            and self._heap
            and not self._closing
            and self.mesh is not None
            and self.mesh.peers
        ):
            self._kick_catchup()

    async def _commit_tail(self, commits: list, ring_ops: list) -> None:
        """Post-apply commit bookkeeping, always run to completion (the
        caller shields it): history retention, counters, equivocation-
        registry release, and the recent-ring flips."""
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        for key, payload, s_bal, r_bal in commits:
            logger.info(
                "new payload: seq=%d sender=%s",
                payload.sequence,
                payload.sender.hex()[:16],
            )
            self.committed += 1
            if self.store is not None:
                # WAL append first (durability), then the in-memory fold;
                # a store I/O failure must not split a commit from its
                # ring/history bookkeeping
                try:
                    self.store.note_commit(
                        payload, payload.sequence, s_bal, r_bal
                    )
                except OSError:
                    logger.exception("store wal append failed")
            self.tx_trace.stamp(
                (payload.sender, payload.sequence), "committed"
            )
            if key in self._catchup_keys:
                self._catchup_commits += 1
            # retain for peers' ledger catchup (ledger/history.py)
            self.history.record(payload)
            if self.broadcast is not None:
                # the ledger's per-client sequence gate now owns this
                # (sender, sequence) binding — release the broadcast
                # plane's equivocation-registry entry eagerly so the
                # registry's working set tracks in-flight entries only
                self.broadcast.release_entry(payload.sender, payload.sequence)
        if ring_ops:
            await self.recent.apply_many(ring_ops)
        if commits:
            # commit-count-triggered audit beacon: every node emits at
            # the same committed-transfer strides, so the sim exercises
            # the full beacon/compare path without any standing timer
            # (and identically at plane shards 1 vs 4 — the commit order
            # is identical, hence so are the emission points).
            every = self.config.observability.audit_every
            before = self.auditor.commits
            self.auditor.note_commit(len(commits))
            if every > 0 and before // every != self.auditor.commits // every:
                self._emit_beacon()
        if ph is not None:
            ph.add("commit_tail", t0)

    # -- fleet consistency audit (obs/audit.py) ---------------------------

    def _emit_beacon(self) -> None:
        """Fold a local audit point and gossip it as a signed
        StateBeacon (wire kind 15). Called from _commit_tail at
        audit_every commit strides and from the wall timer on served
        nodes; safe pre-mesh (the point still lands in local history,
        so late peers' beacons at that watermark remain comparable)."""
        epoch = self.membership.epoch if self.membership is not None else 0
        point = self.auditor.snapshot(epoch, self.directory.digest)
        # finality rides the same frontier: the co-signature covers the
        # canonical subset of this very audit point (before the peer
        # check — a single-node fleet still certifies locally)
        self._emit_cert_sig(epoch, point)
        if self.mesh is None or not self.mesh.peers:
            return
        beacon = StateBeacon.create(
            self.config.sign_key,
            epoch,
            point["commits"],
            point["wm"],
            point["ranges"],
            point["dir"],
            point["chain"],
        )
        self.auditor.counters["beacons_tx"] += 1
        self.mesh.broadcast(beacon.encode())

    def _on_beacon(self, peer: Peer, msg: StateBeacon) -> None:
        """Broadcast-plane hook for inbound StateBeacons. The origin must
        be a KNOWN member sign key but deliberately not the transport
        peer: a relayed or replayed beacon (tools/capture_replay.py
        injects captures through a synthetic identity) still exercises
        the auditor, and the ed25519 signature alone binds the claims."""
        origin = bytes(msg.origin)
        if (
            origin not in self._node_ranks
            or origin == self.config.sign_key.public
            or not verify_one(origin, msg.to_sign(), msg.signature)
        ):
            self.auditor.counters["beacon_invalid"] += 1
            return
        divergence = self.auditor.observe(
            origin.hex(),
            {
                "epoch": msg.epoch,
                "commits": msg.commits,
                "wm": bytes(msg.wm_digest),
                "ranges": bytes(msg.ranges),
                "dir": bytes(msg.dir_digest),
                "chain": bytes(msg.chain),
            },
        )
        if divergence is not None:
            logger.warning(
                "fleet divergence: peer=%s ranges=%s wm=%s",
                divergence["peer"][:16],
                divergence["ranges"],
                divergence["wm"][:16],
            )
            self.recorder.snapshot("audit_divergence")

    # -- finality certificates (finality/) --------------------------------

    def _emit_cert_sig(self, epoch: int, point: dict) -> None:
        """Co-sign the canonical frontier tuple of a freshly-folded
        audit point and gossip it (wire kind 16). The local co-signature
        is folded into our own assembler first — we never hear our own
        broadcast — which also lets a single-node fleet (quorum 1)
        certify without any wire traffic."""
        if self.certs is None:
            return
        cosig = CertSig.create(
            self.config.sign_key,
            epoch,
            point["commits"],
            point["wm"],
            point["ranges"],
            point["dir"],
        )
        self.certs.epoch = epoch
        cert = self.certs.add(cosig)
        if cert is not None:
            self._note_certificate(cert)
        if self.mesh is not None and self.mesh.peers:
            self.mesh.broadcast(cosig.encode())

    def _on_cert_sig(self, peer: Peer, msg: CertSig) -> None:
        """Broadcast-plane hook for inbound cert co-signatures. Like
        beacons, the TRANSPORT peer is deliberately not authenticated
        against the origin — the assembler verifies the co-signature
        against the claimed member key, and that signature alone binds
        the claims (replayed captures still exercise the assembler)."""
        if self.certs is None:
            return
        had_eq = self.certs.equivocation is not None
        cert = self.certs.add(msg)
        if cert is not None:
            self._note_certificate(cert)
        if not had_eq and self.certs.equivocation is not None:
            eq = self.certs.equivocation
            logger.warning(
                "certificate equivocation: origin=%s epoch=%d wm=%s",
                eq["origin"][:16], eq["epoch"], eq["wm"][:16],
            )
            self.recorder.snapshot("cert_equivocation")

    def _note_certificate(self, cert) -> None:
        logger.info(
            "finality certificate: epoch=%d commits=%d signers=%d",
            cert.epoch, cert.commits, cert.signer_count(),
        )
        self.recorder.record(
            "certificate", (cert.epoch, cert.commits, cert.signer_count())
        )

    def _finality_status(self) -> dict:
        """The /statusz finality block (tools/top.py finality column)."""
        if self.certs is None:
            return {"enabled": False}
        latest = self.certs.latest
        certified = latest.commits if latest is not None else 0
        return {
            "enabled": True,
            "audit_every": self.config.observability.audit_every,
            "frontier": self.auditor.commits,
            "certified": certified,
            "lag": max(0, self.auditor.commits - certified),
            **self.certs.status(),
        }

    def certz(self) -> dict:
        """GET /certz: the full light-client bundle — member keys,
        quorum rule, and the retained certificate chain (oldest first).
        Everything here is verifiable; nothing needs to be trusted."""
        return {
            "node": self.config.sign_key.public.hex(),
            "epoch": self.certs.epoch,
            "scheme": self.certs.scheme.name,
            "quorum": self.certs.quorum,
            "members": [k.hex() for k in self.certs.members],
            "commits": self.auditor.commits,
            "chain": [c.to_doc() for c in self.certs.chain],
            "equivocation": self.certs.equivocation,
        }

    async def _audit_beacon_loop(self, interval: float) -> None:
        """Wall-timer beacon emission for served nodes: an idle fleet
        (no commits, so no stride triggers) still cross-checks state."""
        while True:
            await self.clock.sleep(interval)
            try:
                self._emit_beacon()
            except Exception:
                logger.exception("audit beacon emission failed")

    # -- ledger-history catchup ------------------------------------------
    #
    # The reference's open "catchup mechanism" roadmap item
    # (/root/reference/README.md:53). Protocol (messages in
    # broadcast/messages.py, serving store in ledger/history.py):
    #
    #   1. broadcast HistoryIndexRequest(nonce); peers answer with their
    #      commit frontier (sender -> last committed sequence);
    #   2. for every sender some peer reports ahead of us, broadcast
    #      HistoryRequest for the missing range; peers serve
    #      HistoryBatch from their bounded history stores;
    #   3. apply a slot only when `quorum` distinct peers returned the
    #      same content hash for it (>= f+1 peers means at least one
    #      correct peer vouches the content was committed — and sieve
    #      guarantees committed content is unique per slot) AND the
    #      client signature verifies; then replay through the normal
    #      sequence gate, which makes the whole path idempotent.
    #
    # Snapshot transfer would be unsound here: in a consensus-free ledger
    # a balance is a function of full history (credits don't bump the
    # recipient's sequence), so point-in-time (sequence, balance) pairs
    # from different peers cannot be safely reconciled. Replaying signed,
    # quorum-confirmed history can, deterministically.

    def _catchup_quorum(self, n_peers: int) -> int:
        cfg = self.config.catchup
        quorum = cfg.quorum
        if quorum <= 0:
            quorum = (
                self.config.ready_threshold
                if self.config.ready_threshold is not None
                else n_peers
            )
        return max(1, min(quorum, n_peers))

    def _serve_allow(self, peer: Peer, kind: str, cost: int, cap: int) -> bool:
        """1-second token window per (peer, kind); drops beyond the cap
        (the requester's session loop simply retries next second)."""
        now = self.clock.monotonic()
        budget = self._serve_budget.setdefault(
            (peer.sign_public, kind), [now, 0]
        )
        if now - budget[0] >= 1.0:
            budget[0] = now
            budget[1] = 0
        if budget[1] + cost > cap:
            self.catchup_stats["catchup_throttled"] += 1
            return False
        budget[1] += cost
        return True

    def _on_catchup(self, peer: Peer, msg) -> None:
        """Broadcast-worker hook (synchronous): serve peers' catchup
        requests and collect responses for our own session."""
        if isinstance(msg, HistoryIndexRequest):
            self.catchup_stats["catchup_idx_req_rx"] += 1
            if not self._serve_allow(peer, "idx", 1, SERVE_IDX_PER_SEC):
                return
            entries = list(self.accounts.frontier_nowait().items())
            if len(entries) > hist.MAX_IDX_ENTRIES:
                # rotate the served window across requests: a fixed
                # first-N slice (dict insertion order) would make senders
                # past the cap permanently invisible to every requester —
                # rotation guarantees coverage within ceil(N/cap) sessions
                start = self._idx_serve_offset % len(entries)
                self._idx_serve_offset = start + hist.MAX_IDX_ENTRIES
                end = start + hist.MAX_IDX_ENTRIES
                entries = entries[start:end] + entries[: max(0, end - len(entries))]
                logger.warning(
                    "history index truncated to %d entries (rotating window)",
                    hist.MAX_IDX_ENTRIES,
                )
            self.mesh.send(peer, HistoryIndex(msg.nonce, tuple(entries)).encode())
        elif isinstance(msg, HistoryRequest):
            self.catchup_stats["catchup_hist_req_rx"] += 1
            # budget BEFORE the store lookup, charged at the clamped
            # request size: the O(range) work is the amplification lever,
            # so a throttled request must cost nothing (over-charging a
            # partially-retained range is the cheap, safe side)
            cost = min(max(msg.to_seq - msg.from_seq + 1, 0), hist.MAX_RANGE)
            if cost == 0 or not self._serve_allow(
                peer, "rows", cost, SERVE_ROWS_PER_SEC
            ):
                return
            payloads = self.history.get_range(msg.sender, msg.from_seq, msg.to_seq)
            for i in range(0, len(payloads), hist.MAX_BATCH):
                chunk = tuple(payloads[i : i + hist.MAX_BATCH])
                self.mesh.send(peer, HistoryBatch(msg.nonce, chunk).encode())
            self.catchup_stats["catchup_served"] += len(payloads)
        elif isinstance(msg, HistoryIndex):
            session = self._catchup_session
            if session is not None and msg.nonce == session.nonce:
                session.indexes[peer.sign_public] = msg.entries
        elif isinstance(msg, HistoryBatch):
            session = self._catchup_session
            if session is not None and msg.nonce == session.nonce:
                stored = session.stored_by_peer.get(peer.sign_public, 0)
                for p in msg.payloads:
                    vote_key = ((p.sender, p.sequence), p.content_hash())
                    if vote_key in session.payloads:
                        # vote accrual is never capped (see _CatchupSession)
                        session.votes[vote_key].add(peer.sign_public)
                        continue
                    if stored >= session.per_peer_cap:
                        logger.warning(
                            "catchup payload cap reached for peer %s",
                            peer.address,
                        )
                        break
                    stored += 1
                    session.votes.setdefault(vote_key, set()).add(
                        peer.sign_public
                    )
                    session.payloads[vote_key] = p
                session.stored_by_peer[peer.sign_public] = stored

    def _kick_catchup(self) -> None:
        if self._catchup_task is None or self._catchup_task.done():
            # a stall kick IS an anomaly: freeze the flight recorder so
            # the 2s before the stall are inspectable after the fact.
            # Single-flight gated (like the runner itself), so a stall
            # persisting across GC passes takes one snapshot per session.
            self.recorder.snapshot("stall_kick")
            # the initial delay gives a transient gap (predecessor still
            # in flight through the broadcast) time to resolve without a
            # session, and paces back-to-back kicks
            self._catchup_task = asyncio.create_task(
                self._catchup_runner(initial_delay=self.config.catchup.after)
            )

    # Sessions that heard from no peer retry at least this many times:
    # right after a restart, peers' redial backoff (net/peers.py, capped
    # at 5s) can delay their replies past several session windows.
    _CATCHUP_MIN_ATTEMPTS = 8
    # After this many consecutive sessions without commit progress the
    # runner backs off exponentially (doubling per session) up to the
    # max. A gap beyond every peer's history horizon can NEVER resolve
    # via catchup (ledger/history.py:19-23 — operator action required);
    # without backoff each session re-broadcasts HistoryRequests and
    # re-verifies up to MAX_RANGE payloads per peer every cfg.after
    # seconds forever (ADVICE r4 medium).
    _CATCHUP_BACKOFF_AFTER = 3
    _CATCHUP_MAX_BACKOFF = 60.0

    async def _catchup_runner(self, initial_delay: float = 0.0) -> None:
        """Run catchup sessions until the ledger is caught up: no stale
        sequence gap remains AND at least one peer has answered (or the
        attempt budget for unanswered sessions is spent). Sessions that
        stop producing COMMIT progress back off exponentially."""
        cfg = self.config.catchup
        if initial_delay:
            await self.clock.sleep(initial_delay)
        attempts = 0
        no_progress = 0  # consecutive sessions with no commit progress
        try:
            while not self._closing:
                commits_before = self._catchup_commits
                responses, applied = await self._catchup_once()
                attempts += 1
                # progress = catchup-sourced work only: new payloads
                # enqueued, or catchup-keyed payloads committed. The
                # global commit counter would count unrelated live
                # traffic and keep resetting the backoff forever.
                progressed = (
                    applied > 0 or self._catchup_commits > commits_before
                )
                no_progress = 0 if progressed else no_progress + 1
                now = self.clock.monotonic()
                gap_remains = any(
                    now - entry[1] > cfg.after for entry in self._heap
                )
                if applied == 0 and not gap_remains and (
                    responses > 0 or attempts >= self._CATCHUP_MIN_ATTEMPTS
                ):
                    if self.recovery.state == "catchup":
                        self.recovery.mark_live(now)
                    return
                if applied == 0 and gap_remains:
                    logger.log(
                        logging.WARNING if attempts <= 3 else logging.DEBUG,
                        "catchup made no progress (attempt %d, %d peers "
                        "answered); gap persists",
                        attempts,
                        responses,
                    )
                delay = cfg.after
                if no_progress > self._CATCHUP_BACKOFF_AFTER:
                    delay = min(
                        cfg.after
                        * 2 ** (no_progress - self._CATCHUP_BACKOFF_AFTER),
                        self._CATCHUP_MAX_BACKOFF,
                    )
                await self.clock.sleep(delay)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("catchup runner failed")

    async def _catchup_once(self) -> Tuple[int, int]:
        """One catchup session; returns (peer index responses, applied)."""
        cfg = self.config.catchup
        peers = self.mesh.peers if self.mesh is not None else []
        if not peers or self._catchup_session is not None:
            return 0, 0
        quorum = self._catchup_quorum(len(peers))
        session = _CatchupSession(self._nonce_bits(64), len(peers))
        self._catchup_session = session
        self.catchup_stats["catchup_sessions"] += 1
        self.recovery.catchup_sessions += 1
        try:
            self.mesh.broadcast(HistoryIndexRequest(session.nonce).encode())
            await self.clock.sleep(cfg.window)
            responses = len(session.indexes)
            local = self.accounts.frontier_nowait()
            needed: Dict[bytes, int] = {}
            for frontier in session.indexes.values():
                for sender, seq in frontier:
                    if seq > local.get(sender, 0) and seq > needed.get(sender, 0):
                        needed[sender] = seq
            # catchup lag = missing slots vs the fleet frontier; a session
            # where peers answered and reported nothing missing is the
            # recovery machine's "caught up to live" signal
            if self.recovery.state == "catchup":
                self.recovery.catchup_lag = sum(
                    top - local.get(sender, 0)
                    for sender, top in needed.items()
                )
                if responses > 0 and not needed:
                    self.recovery.mark_live(self.clock.monotonic())
            if not needed:
                return responses, 0
            for sender, top in needed.items():
                lo = local.get(sender, 0) + 1
                self.mesh.broadcast(
                    HistoryRequest(session.nonce, sender, lo, top).encode()
                )
            if self.config.wan.verify_ahead:
                await self._verify_ahead_wait(session, cfg.window)
            else:
                await self.clock.sleep(cfg.window)
            quorate = [
                (vote_key, payload)
                for vote_key, payload in session.payloads.items()
                if len(session.votes.get(vote_key, ())) >= quorum
            ]
            if not quorate:
                return responses, 0
            # verify only what the speculative pass (if any) missed —
            # with verify_ahead on and an idle verifier this list is
            # empty and delivery never blocks on signature checks
            unchecked = [
                (k, p) for k, p in quorate if k not in session.prechecked
            ]
            if unchecked:
                fresh = await self.verifier.verify_many(
                    [(p.sender, p.to_sign(), p.signature)
                     for _, p in unchecked]
                )
                for (k, _), ok in zip(unchecked, fresh):
                    session.prechecked[k] = ok
            candidates = [p for _, p in quorate]
            results = [session.prechecked[k] for k, _ in quorate]
            now = self.clock.monotonic()
            frontier = self.accounts.frontier_nowait()
            applied = 0
            for p, ok in zip(candidates, results):
                if ok and p.sequence > frontier.get(p.sender, 0):
                    # only NEWLY-enqueued payloads count as progress: a
                    # dedup hit on a heap entry parked since the last
                    # session is churn, not advancement (ADVICE r4 —
                    # counting those kept `applied > 0` forever and
                    # defeated the runner's termination condition)
                    if self._push_pending(p, now, from_catchup=True):
                        applied += 1
                elif not ok:
                    logger.warning(
                        "catchup payload failed signature check: (%s, %d)",
                        p.sender.hex()[:16],
                        p.sequence,
                    )
            if applied:
                self.catchup_stats["catchup_applied"] += applied
                logger.info("catchup applied %d historical payloads", applied)
                await self._drain_to_fixpoint()
            return responses, applied
        finally:
            self._catchup_session = None

    async def _verify_ahead_wait(
        self, session: _CatchupSession, window: float
    ) -> None:
        """[wan] speculative verify-ahead: slice the quorum wait and
        spend idle verifier capacity pre-verifying parked payloads as
        they stream in, caching verdicts in ``session.prechecked`` so
        the post-quorum apply step never blocks on signature checks.
        Occupancy-gated: a busy verifier (nonzero queue depth — the
        device pool reports one; CpuVerifier has no queue and always
        reads idle) keeps its capacity for live traffic."""
        slices = 4
        for _ in range(slices):
            await self.clock.sleep(window / slices)
            stats_fn = getattr(self.verifier, "stats", None)
            if stats_fn is not None and stats_fn().get("queue_depth", 0):
                continue
            pending = [
                (k, p) for k, p in list(session.payloads.items())
                if k not in session.prechecked
            ]
            if not pending:
                continue
            verdicts = await self.verifier.verify_many(
                [(p.sender, p.to_sign(), p.signature) for _, p in pending]
            )
            for (k, _), ok in zip(pending, verdicts):
                session.prechecked[k] = ok
            self.catchup_stats["catchup_preverified"] += len(pending)

    # -- ingress batching (broadcast/stack.py batched plane) --------------

    async def _flush_batch(self) -> None:
        """Flush the accumulated SendAsset payloads as batch slots (one
        per max_entries chunk — SendAssetBatch can land more than one
        slot's worth at once; a slot must never exceed the wire's entry
        cap). Synchronous SNAPSHOT at entry: concurrent flushes (size
        trigger racing the window timer) see an empty buffer, and
        payloads that arrive while a broadcast_batch below is suspended
        wait for their own window/size trigger instead of leaking out as
        undersized slots (or keeping this flush looping unboundedly)."""
        buf, self._batch_buf = self._batch_buf, []
        limit = self.config.batching.max_entries
        for lo in range(0, len(buf), limit):
            chunk = buf[lo : lo + limit]
            self._batch_seq += 1
            entries_raw = b"".join(p.encode()[1:] for p in chunk)
            batch = TxBatch.create(
                self.config.sign_key, self._batch_seq, entries_raw
            )
            await self.broadcast.broadcast_batch(batch)

    async def _delayed_flush(self, window: float) -> None:
        # Loop until the buffer is observed empty: a payload that arrived
        # while the flush below was suspended (inbox backpressure) saw
        # this task not-done and did NOT schedule a new timer — it relies
        # on this loop picking it up. The empty-check and the task
        # completing are atomic (no await between them, single event
        # loop), so nothing can slip in after the last check.
        while True:
            await self.clock.sleep(window)
            await self._flush_batch()
            if not self._batch_buf:
                return

    # -- gRPC handlers (rpc.rs:256-344) ----------------------------------

    @staticmethod
    async def _validated_payload(request, context, where: str = "") -> Payload:
        if len(request.sender) != 32 or len(request.recipient) != 32:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"keys must be 32 bytes{where}",
            )
        if len(request.signature) != 64:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"signature must be 64 bytes{where}",
            )
        try:
            thin = ThinTransaction(request.recipient, request.amount)
        except ValueError as exc:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"{exc}{where}"
            )
        return Payload(request.sender, request.sequence, thin, request.signature)

    async def _ingest(self, payloads: List[Payload]) -> None:
        """Common ingress tail for SendAsset / SendAssetBatch: ring
        Pending records, then the batcher (or the per-tx plane).
        Fire-and-forget: the ACK is not a commit receipt (rpc.rs:286)."""
        await self.recent.put_many(
            [(p.sender, p.sequence, p.transaction) for p in payloads]
        )
        bcfg = self.config.batching
        if not bcfg.enabled or self._closing:
            # during shutdown, skip the batcher: a flush timer spawned
            # after close() cancelled the old one would be orphaned
            for p in payloads:
                await self.broadcast.broadcast(p)
            return
        self._batch_buf.extend(payloads)
        if len(self._batch_buf) >= bcfg.max_entries:
            await self._flush_batch()
        elif self._batch_flush_task is None or self._batch_flush_task.done():
            self._batch_flush_task = asyncio.create_task(
                self._delayed_flush(bcfg.window)
            )

    # -- ingress admission (config [admission]) --------------------------

    @staticmethod
    def _bucket_refill(
        buckets: Dict[str, list],
        source: str,
        now: float,
        limit: float,
        window: float,
    ) -> list:
        """The source's token bucket ``[tokens, stamp]`` in ``buckets``,
        refilled continuously to ``limit`` over ``window`` seconds. All
        buckets in one dict share (limit, window) — the eviction scan
        below depends on that. Refill is clamped at zero elapsed time:
        a clock stepping backwards (NTP slew, a test's fake clock) must
        neither mint tokens nor DRAIN them via a negative delta."""
        rate = limit / window
        bucket = buckets.get(source)
        if bucket is None:
            if len(buckets) >= ADMISSION_SOURCES_CAP:
                # evict fully-refilled buckets first (they carry no
                # throttling state); if every source is actively failing,
                # drop the oldest — it restarts with a full bucket
                full = [
                    k
                    for k, (t, s) in buckets.items()
                    if t + max(0.0, now - s) * rate >= limit
                ]
                for k in full:
                    del buckets[k]
                if len(buckets) >= ADMISSION_SOURCES_CAP:
                    buckets.pop(next(iter(buckets)))
            bucket = [float(limit), now]
            buckets[source] = bucket
        else:
            elapsed = max(0.0, now - bucket[1])
            bucket[0] = min(float(limit), bucket[0] + elapsed * rate)
            # the stamp never moves backwards: re-crediting an interval
            # the bucket already refilled over would mint free tokens
            bucket[1] = max(bucket[1], now)
        return bucket

    def _admission_refill(self, source: str, now: float) -> list:
        ad = self.config.admission
        return self._bucket_refill(
            self._admission_buckets, source, now, ad.fail_limit, ad.fail_window
        )

    def _register_refill(self, source: str, now: float) -> list:
        ad = self.config.admission
        return self._bucket_refill(
            self._register_buckets,
            source,
            now,
            ad.register_limit,
            ad.register_window,
        )

    async def _admit(self, payloads: List[Payload], context) -> None:
        """Pre-verify client signatures at the RPC boundary: ONE
        ``Verifier.verify_many`` call per admission batch (the same
        CPU/TPU seam the broadcast workers use). Entries failing it are
        rejected HERE — they never reach the gossip plane, so one
        poisoned entry can no longer stall a whole broadcast slot. The
        per-source bucket is charged only for FAILED entries; a source
        that exhausted it is refused before any verifier work.

        Overload shedding (node/overload.py, config [overload]) happens
        FIRST: a shed request costs no verifier work and must NOT charge
        the sender's fail bucket — refusing valid work under pressure is
        the node's state, not evidence against the sender. Shed
        responses carry a typed ``retry_after_ms`` hint."""
        await self._overload_gate(payloads, context)
        ad = self.config.admission
        if not ad.preverify or self.verifier is None:
            return
        peer_fn = getattr(context, "peer", None)
        source = peer_fn() if callable(peer_fn) else "local"
        bucket = self._admission_refill(source, self.clock.monotonic())
        if bucket[0] < 1.0:
            self.admission_stats["admission_throttled"] += 1
            # terminal trace stamp + flight-record BEFORE the abort
            # raises: a throttled tx's trace must retire into the
            # completed ring, not linger until cap eviction
            self._trace_stamp(payloads, REJECTED)
            self.recorder.record(
                "admit_throttle", (len(payloads), source)
            )
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                "too many invalid signatures from this source; retry later",
            )
        results = await self.verifier.verify_many(
            [(p.sender, p.to_sign(), p.signature) for p in payloads]
        )
        bad = [i for i, ok in enumerate(results) if not ok]
        if not bad:
            return
        self.admission_stats["rejected_at_ingress"] += len(bad)
        bucket[0] = max(0.0, bucket[0] - len(bad))
        # admission is all-or-nothing: the whole request aborts, so EVERY
        # entry's trace terminates here (the bad ones failed verification,
        # the good ones were refused alongside them and may retry under a
        # fresh ingress)
        self._trace_stamp(payloads, REJECTED)
        self.recorder.record("admit_reject", (len(bad), source))
        await context.abort(
            grpc.StatusCode.INVALID_ARGUMENT,
            "client signature verification failed"
            + (f" (entries {bad})" if len(payloads) > 1 else ""),
        )

    async def _overload_gate(self, payloads: List[Payload], context) -> None:
        """The adaptive-admission actuator: one deterministic shed
        decision per client request, taken before any verifier work.
        Senders already in the gossiped directory get the configured
        grace (the crowd is, almost by definition, unknown senders).
        Protocol traffic never passes through here — only client
        ingress is sheddable."""
        ov = self.overload
        if not ov.cfg.enabled:
            return
        now = self.clock.monotonic()
        # lazy sample: the sim has no standing probe loop, so ingress is
        # where the pressure score stays fresh (rate-limited inside)
        ov.maybe_sample(now)
        registered = all(
            self.directory.id_of(p.sender) is not None for p in payloads
        )
        retry_ms = ov.admit(registered=registered, now=now)
        if retry_ms is None:
            return
        self.overload_stats["overload_shed_requests"] += 1
        self.overload_stats["overload_shed_entries"] += len(payloads)
        self._trace_stamp(payloads, REJECTED)
        self.recorder.record(
            "overload_shed",
            (
                len(payloads),
                "registered" if registered else "new",
                round(ov.pressure, 4),
                retry_ms,
            ),
        )
        await context.abort(
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            format_shed_details("ingress shed under overload", retry_ms),
        )

    def _trace_begin(self, payloads: List[Payload]) -> None:
        if self.tx_trace.enabled:
            now = self.clock.monotonic()
            for p in payloads:
                self.tx_trace.begin((p.sender, p.sequence), now)

    def _trace_stamp(self, payloads: List[Payload], stage: str) -> None:
        if self.tx_trace.enabled:
            now = self.clock.monotonic()
            for p in payloads:
                self.tx_trace.stamp((p.sender, p.sequence), stage, now)

    async def SendAsset(self, request, context):
        payload = await self._validated_payload(request, context)
        self._trace_begin([payload])
        await self._admit([payload], context)
        self._trace_stamp([payload], "admitted")
        await self._ingest([payload])
        return pb.SendAssetReply()

    async def SendAssetBatch(self, request, context):
        """Beyond-parity bulk ingress (at2.proto documents the contract):
        semantically identical to one SendAsset per entry, one RPC
        round-trip. The whole request is validated — shape first, then
        client signatures via ingress pre-verification (config
        [admission]) — before any entry is admitted (all-or-nothing
        admission with per-entry rejection detail; commit outcomes stay
        per-entry, exactly like separate SendAssets)."""
        if not request.transactions:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "empty batch"
            )
        if len(request.transactions) > MAX_BATCH_ENTRIES:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"batch exceeds {MAX_BATCH_ENTRIES} transactions",
            )
        payloads = []
        for i, req in enumerate(request.transactions):
            payloads.append(
                await self._validated_payload(req, context, f" (entry {i})")
            )
        self._trace_begin(payloads)
        await self._admit(payloads, context)
        self._trace_stamp(payloads, "admitted")
        await self._ingest(payloads)
        return pb.SendAssetReply()

    async def GetBalance(self, request, context):
        amount = await self.accounts.get_balance(request.sender)
        return pb.GetBalanceReply(amount=amount)

    async def GetLastSequence(self, request, context):
        sequence = await self.accounts.get_last_sequence(request.sender)
        return pb.GetLastSequenceReply(sequence=sequence)

    async def GetCertificate(self, request, context):
        """Finality lane (finality/): the retained certificate chain in
        binary form plus this node's LIVE commit frontier — the frontier
        lets wait_final() know when a future certificate must cover its
        transfer (certificates are emitted at audit_every strides, so
        one more stride always closes the gap)."""
        if self.certs is None:
            return fpb.GetCertificateReply(
                enabled=False, node_commits=self.auditor.commits
            )
        return fpb.GetCertificateReply(
            enabled=True,
            epoch=self.certs.epoch,
            node_commits=self.auditor.commits,
            certificates=[c.encode() for c in self.certs.chain],
        )

    async def GetLatestTransactions(self, request, context):
        txs = await self.recent.get_all()
        return pb.GetLatestTransactionsReply(
            transactions=[
                pb.FullTransaction(
                    timestamp=rfc3339(tx.timestamp),
                    sender=tx.sender,
                    recipient=tx.recipient,
                    amount=tx.amount,
                    state=tx.state.value,
                    sender_sequence=tx.sender_sequence,
                )
                for tx in txs
            ]
        )

    # -- broker ingress tier (node/directory.py, proto/distill.py) --------

    def _on_directory(self, peer: Peer, msg: DirectoryAnnounce) -> None:
        """Broadcast-worker hook (synchronous): install gossiped
        directory mappings. The stride check runs against the CHANNEL
        peer's rank — authenticated by the mesh handshake — not the
        frame's origin field, so a byzantine peer can only announce into
        its own id stride (and even there, only poison liveness: wrong
        keys just fail entry signature verification locally)."""
        rank = self._node_ranks.get(peer.sign_public)
        if rank is None:
            return
        applied = 0
        for client_id, pubkey in msg.entries:
            if self.directory.apply(client_id, pubkey, rank=rank):
                applied += 1
        if applied:
            self.recorder.record("dir_apply", (applied, rank))

    async def Register(self, request, context):
        """Directory registration (at2.proto): assign — or look up — the
        dense client-id for a pubkey and announce the mapping to peers.

        A NEW assignment permanently grows every node's directory array,
        pubkey map, and checkpoint, so it is charged against the source's
        register token bucket (config [admission] register_limit/
        register_window) and refused outright once this node's stride is
        full (node/directory.py MAX_CLIENTS_PER_RANK). Idempotent
        re-registration of a known key is free.

        The announce goes out on every call whose id falls in THIS
        node's stride, not just first assignment: a client retrying
        Register doubles as a gossip repair for mappings peers may have
        missed. Ids learned via gossip from another node's stride are
        NOT re-announced — receivers validate announce ids against the
        announcing peer's stride and would silently drop them; repair
        for those belongs to their assigning node."""
        key = bytes(request.public_key)
        if len(key) != 32 or key == b"\x00" * 32:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "public_key must be 32 nonzero bytes",
            )
        client_id = self.directory.id_of(key)
        if client_id is None:
            peer_fn = getattr(context, "peer", None)
            source = peer_fn() if callable(peer_fn) else "local"
            bucket = self._register_refill(source, self.clock.monotonic())
            if bucket[0] < 1.0:
                self.admission_stats["admission_throttled"] += 1
                await context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    "registration rate exceeded for this source; retry later",
                )
            try:
                client_id, created = self.directory.assign(key)
            except DirectoryFullError:
                await context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    "client directory full on this node",
                )
            if created:
                bucket[0] = max(0.0, bucket[0] - 1.0)
        if (
            self.mesh is not None
            and self.mesh.peers
            and client_id % self.directory.total == self.directory.rank
        ):
            self.mesh.broadcast(
                DirectoryAnnounce(
                    self.config.sign_key.public, ((client_id, key),)
                ).encode()
            )
        return pb.RegisterReply(client_id=client_id)

    def _expand_distilled(self, frame: bytes):
        """Parse + directory-expand one distilled frame: a single
        GIL-released native call when the library is ready, the Python
        reference codec otherwise (identical acceptance set —
        differential-tested). Returns ``(bodies, ids, ok)`` lists or
        ``None`` for a malformed frame."""
        from ..native.ingest import distill_parse_native, ingest_ready_or_kick

        if ingest_ready_or_kick():
            res = distill_parse_native(frame, *self.directory.keys_view())
            if res is None:
                return None
            bodies, ids_arr, ok_arr = res
            return bodies, ids_arr.tolist(), ok_arr.tolist()
        try:
            bodies_ba, ids, ok = distill.expand_py(frame, self.directory.get)
        except distill.DistillError:
            return None
        return bytes(bodies_ba), ids, ok

    async def SendDistilledBatch(self, request, context):
        """Broker-built distilled batch (proto/distill.py wire format).

        Unlike `_admit`'s all-or-nothing contract, admission here is
        PER-ENTRY: one frame aggregates many mutually-independent
        clients, so a bad signature drops alone — charged to its OWN
        client-id's token bucket, never the broker's — and cannot censor
        co-batched traffic. The broker's identity stays entirely outside
        the trust boundary: it can withhold or replay, but every entry
        it forwards is still client-signed over canonical bytes.
        ACK means "accepted what survived", never a commit receipt."""
        if self._closing:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, "node shutting down"
            )
        expanded = self._expand_distilled(bytes(request.frame))
        if expanded is None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "malformed distilled frame"
            )
        bodies, ids, ok = expanded
        self.distill_stats["distilled_batches_rx"] += 1
        self.recorder.record("distill_rx", (len(ok),))
        misses = len(ok) - sum(ok)
        if misses:
            self.distill_stats["directory_misses"] += misses
            # a miss means this node's gossiped directory lags the
            # assigning node — the usual explanation for broker-era
            # "frames arrive but nothing commits" stalls, so it earns a
            # flight-recorder event, not just a counter
            self.recorder.record("dir_miss", (misses, len(ok)))
        now = self.clock.monotonic()
        seen = self._distill_seen
        E = distill.ENTRY_WIRE
        ad = self.config.admission
        preverify = ad.preverify and self.verifier is not None
        ov = self.overload
        ov_on = ov.cfg.enabled
        if ov_on:
            ov.maybe_sample(now)
        n_dedup = 0
        n_shed = 0
        kept: List[int] = []
        keys: List[Tuple[int, int]] = []
        for i, cid in enumerate(ids):
            if not ok[i]:
                continue
            base = i * E
            k = (cid, int.from_bytes(bodies[base + 32 : base + 36], "little"))
            if k in seen:
                self.distill_stats["dedup_drops"] += 1
                n_dedup += 1
                continue
            # overload shedding is per-entry here (the frame is a
            # many-sender aggregate; all-or-nothing would punish every
            # broker client for pressure one caused). Distilled entries
            # are directory-resolved by construction, so they shed on
            # the registered (graced) ramp — and a shed must NOT charge
            # the cid's fail bucket, so it runs before the refill.
            if ov_on and ov.admit(registered=True, now=now) is not None:
                n_shed += 1
                continue
            if preverify:
                bucket = self._admission_refill(f"cid:{cid}", now)
                if bucket[0] < 1.0:
                    self.admission_stats["admission_throttled"] += 1
                    continue
            kept.append(i)
            keys.append(k)
        if n_dedup:
            # aggregated per frame (not per entry): a replaying broker
            # must not be able to flood the ring via its own dups
            self.recorder.record("dedup_drop", (n_dedup, len(ok)))
        if n_shed:
            self.overload_stats["overload_shed_distilled"] += n_shed
            self.recorder.record(
                "overload_shed_distilled",
                (n_shed, len(ok), round(ov.pressure, 4)),
            )
            if not kept:
                # the whole frame was shed: surface typed backpressure
                # to the broker instead of a silent empty ACK, so its
                # forwarding loop (and its clients' retry budgets) can
                # back off on the hint
                await context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    format_shed_details(
                        "distilled ingress shed under overload",
                        ov.retry_after_ms(),
                    ),
                )
        if preverify and kept:
            # the v2 transfer preimage is TAG + the first 76 body bytes
            # (sender || seq || recipient || amount — types.py), so a
            # broker re-encoding a captured signature at another sequence
            # changes the preimage and fails right here
            results = await self.verifier.verify_many(
                [
                    (
                        bodies[i * E : i * E + 32],
                        TRANSFER_SIG_TAG + bodies[i * E : i * E + 76],
                        bodies[i * E + 76 : i * E + 140],
                    )
                    for i in kept
                ]
            )
            good, good_keys, n_bad = [], [], 0
            for i, k, okv in zip(kept, keys, results):
                if okv:
                    good.append(i)
                    good_keys.append(k)
                else:
                    n_bad += 1
                    bucket = self._admission_refill(f"cid:{k[0]}", now)
                    bucket[0] = max(0.0, bucket[0] - 1.0)
            if n_bad:
                self.admission_stats["rejected_at_ingress"] += n_bad
                self.recorder.record("distill_reject", (n_bad, len(kept)))
            kept, keys = good, good_keys
        if kept:
            # mark seen only for entries actually ingested: a client whose
            # signature failed (or who was throttled) may legitimately
            # resubmit the same (id, seq) corrected later
            for k in keys:
                if len(seen) >= DISTILL_SEEN_CAP:
                    seen.pop(next(iter(seen)))
                seen[k] = None
            await self._ingest_distilled(bodies, kept)
        return pb.SendAssetReply()

    async def _ingest_distilled(self, bodies: bytes, kept: List[int]) -> None:
        """Ingress tail for surviving distilled entries. The expanded
        bodies already ARE the batched plane's ``entries_raw`` layout, so
        the hot path slices them straight into TxBatch slots — decoding
        per-entry Payload objects here would reintroduce exactly the
        per-entry Python cost the distilled format exists to avoid."""
        E = distill.ENTRY_WIRE
        bcfg = self.config.batching
        if not bcfg.enabled or self._closing:
            # the slow path mirrors _ingest's semantics exactly (sim
            # configs disable batching; shutdown must not spawn timers)
            payloads = [
                Payload.decode_body(bodies[i * E : (i + 1) * E]) for i in kept
            ]
            await self.recent.put_many(
                [(p.sender, p.sequence, p.transaction) for p in payloads]
            )
            for p in payloads:
                await self.broadcast.broadcast(p)
            return
        if self.tx_trace.enabled:
            now = self.clock.monotonic()
            for i in kept:
                base = i * E
                key = (
                    bodies[base : base + 32],
                    int.from_bytes(bodies[base + 32 : base + 36], "little"),
                )
                self.tx_trace.begin(key, now)
                self.tx_trace.stamp(key, "admitted", now)
        # the recent ring holds 10 entries (ledger/recent.py): feeding it
        # the batch tail leaves observably identical ring state without
        # per-entry decode of the whole frame
        tail = [
            Payload.decode_body(bodies[i * E : (i + 1) * E])
            for i in kept[-10:]
        ]
        await self.recent.put_many(
            [(p.sender, p.sequence, p.transaction) for p in tail]
        )
        if len(kept) * E == len(bodies):
            entries_raw = bodies  # nothing dropped: zero-copy
        else:
            entries_raw = b"".join(
                bodies[i * E : (i + 1) * E] for i in kept
            )
        limit = bcfg.max_entries * E
        for lo in range(0, len(entries_raw), limit):
            self._batch_seq += 1
            batch = TxBatch.create(
                self.config.sign_key, self._batch_seq, entries_raw[lo : lo + limit]
            )
            await self.broadcast.broadcast_batch(batch)
