"""The AT2 node: broadcast wiring + delivery→commit loop + gRPC surface.

Equivalent of the reference's `rpc::Service`
(`/root/reference/src/bin/server/rpc.rs:61-344`): bring up the encrypted
node mesh, run the three-phase broadcast with the configured Verifier,
drain deliveries into the ledger with the reference's exact ordering /
retry / TTL semantics, and serve the four `at2.AT2` RPCs to clients.

Delivery→commit loop parity (`rpc.rs:149-211`):

* delivered payloads enter a min-heap ordered by (sequence, sender,
  content) with their arrival time (`rpc.rs:163-173`);
* the heap is drained to a fixpoint — a pass that commits anything
  re-sorts and retries, so out-of-order sequences gap-fill
  (`rpc.rs:176-208`);
* only sequence/balance failures (`AccountModificationError`) are retried;
  anything else is logged and dropped (`rpc.rs:195-205`);
* a payload older than ``TRANSACTION_TTL`` (60 s) is marked Failure —
  and then still falls through to processing, so it can later flip to
  Success: the reference has no `continue` after its TTL branch
  (`rpc.rs:183-193`), and that observable quirk is kept deliberately;
* leftovers carry into the next delivery batch (`rpc.rs:207`).
"""

from __future__ import annotations

import asyncio
import heapq
import logging
import time
from typing import List, Optional, Tuple

import grpc

from ..broadcast.messages import Payload
from ..broadcast.stack import Broadcast
from ..crypto.verifier import Verifier
from ..ledger import checkpoint as ckpt
from ..ledger.accounts import AccountModificationError, Accounts
from ..ledger.recent import RecentTransactions
from ..net.peers import Mesh
from ..net.webmux import PortMux
from ..proto import at2_pb2 as pb
from ..proto.rpc import At2Servicer, add_to_server
from ..types import ThinTransaction, TransactionState, rfc3339
from .config import Config

logger = logging.getLogger(__name__)

# Dedicated stats logger with its own INFO handler: operator-enabled stats
# must be visible even under the reference-parity WARN default
# (/root/reference/src/bin/server/main.rs:94-99). Configured lazily by
# _enable_stats_logging so library users keep full control otherwise.
stats_logger = logging.getLogger("at2_node_tpu.stats")

TRANSACTION_TTL = 60.0  # seconds, rpc.rs:35


def _enable_stats_logging() -> None:
    if not stats_logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(message)s")
        )
        stats_logger.addHandler(handler)
        stats_logger.setLevel(logging.INFO)
        stats_logger.propagate = False


class Service(At2Servicer):
    """One AT2 node. `await Service.start(config)`, then `serve_forever`."""

    def __init__(self, config: Config) -> None:
        self.config = config
        self.accounts = Accounts()
        self.recent = RecentTransactions()
        self.verifier: Optional[Verifier] = None
        self.mesh: Optional[Mesh] = None
        self.broadcast: Optional[Broadcast] = None
        self._grpc_server: Optional[grpc.aio.Server] = None
        self._mux: Optional[PortMux] = None
        self._delivery_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._checkpoint_task: Optional[asyncio.Task] = None
        self._profiling = False
        self._owns_verifier = True
        self.committed = 0  # payloads committed to the ledger
        # leftovers: (key, arrival, tiebreak, payload) carried across batches
        self._heap: List[tuple] = []
        self._push_count = 0  # monotonic heap tiebreaker

    # -- lifecycle --------------------------------------------------------

    @staticmethod
    async def start(config: Config, verifier: Optional[Verifier] = None) -> "Service":
        """Bring up one node. ``verifier`` injects a SHARED verifier (the
        BASELINE config-5 shape: many nodes feeding one device pool —
        `parallel.pool.PoolVerifier`); the caller keeps ownership and
        closes it after every sharing node is down."""
        service = Service(config)
        if verifier is not None:
            service.verifier = verifier
            service._owns_verifier = False
        else:
            service.verifier = config.verifier.make()
            # Compile the device verifier BEFORE binding the RPC port: a
            # node is not ready while its first signature check would stall
            # tens of seconds behind XLA compilation (readiness probes poll
            # the port — tests/shell/lib.sh, reference tests/cli.rs:119-131).
            try:
                await service.verifier.warmup()
            except Exception:
                await service.verifier.close()
                raise
        # Resume ledger state BEFORE joining the network: peers judge this
        # node by its per-account sequence answers from the first message.
        if config.checkpoint.path:
            try:
                await ckpt.load(
                    config.checkpoint.path, service.accounts, service.recent
                )
            except Exception:
                if service._owns_verifier:
                    await service.verifier.close()
                raise
        # Everything past the verifier is brought up under one guard:
        # close() tolerates partially-initialized state, so ANY bring-up
        # failure (mesh bind, broadcast start, profiler, grpc/mux bind)
        # releases the warmed-up verifier, mesh tasks, and background
        # loops instead of leaking them.
        try:
            service.mesh = Mesh(
                config.node_address,
                config.network_key,
                config.nodes,
                on_frame=lambda peer, frame: service.broadcast.on_frame(peer, frame),
            )
            service.broadcast = Broadcast(
                config.sign_key,
                service.mesh,
                service.verifier,
                echo_threshold=config.echo_threshold,
                ready_threshold=config.ready_threshold,
            )
            await service.mesh.start()
            await service.broadcast.start()
            service._delivery_task = asyncio.create_task(service._delivery_loop())

            # interval <= 0 means snapshot-on-shutdown only (consistent with
            # the observability convention where 0 disables the periodic task)
            if config.checkpoint.path and config.checkpoint.interval > 0:
                service._checkpoint_task = asyncio.create_task(
                    service._checkpoint_loop(
                        config.checkpoint.path, config.checkpoint.interval
                    )
                )

            obs = config.observability
            if obs.stats_interval > 0:
                _enable_stats_logging()
                service._stats_task = asyncio.create_task(
                    service._stats_loop(obs.stats_interval)
                )
            if obs.profile_dir:
                import jax

                jax.profiler.start_trace(obs.profile_dir)
                service._profiling = True

            # The public RPC port is a mux (reference parity: tonic serves
            # native gRPC AND grpc-web/HTTP1/CORS on one port, main.rs:110-114):
            # grpc.aio binds an internal loopback port; the mux splices HTTP/2
            # clients to it and answers grpc-web itself.
            server = grpc.aio.server()
            add_to_server(service, server)
            # assigned BEFORE start: if start() (or anything after) raises,
            # the guard's close() must stop this server, not leak its port
            service._grpc_server = server
            internal_port = server.add_insecure_port("127.0.0.1:0")
            if internal_port == 0:
                raise OSError("cannot bind internal grpc port")
            await server.start()
            service._mux = PortMux(config.rpc_address, internal_port, service)
            try:
                await service._mux.start()
            except OSError as exc:
                raise OSError(
                    f"cannot bind rpc address {config.rpc_address}"
                ) from exc
        except BaseException:
            await service.close()
            raise
        logger.info(
            "node up: mesh on %s, rpc on %s, %d peers, verifier=%s",
            config.node_address,
            config.rpc_address,
            len(service.mesh.peers),
            config.verifier.kind,
        )
        return service

    async def serve_forever(self) -> None:
        await self._grpc_server.wait_for_termination()

    async def close(self) -> None:
        if self._profiling:
            import jax

            jax.profiler.stop_trace()
            self._profiling = False
        if self._stats_task is not None:
            self._stats_task.cancel()
            try:
                await self._stats_task
            except asyncio.CancelledError:
                pass
        if self._checkpoint_task is not None:
            self._checkpoint_task.cancel()
            try:
                await self._checkpoint_task
            except asyncio.CancelledError:
                pass
        if self._mux is not None:
            await self._mux.close()
        if self._grpc_server is not None:
            try:
                await self._grpc_server.stop(grace=0.5)
            except Exception:
                # stop() on a server whose start() never completed (failed
                # bring-up path) can raise; the socket dies with the object
                logger.exception("grpc server stop failed")
        if self._delivery_task is not None:
            self._delivery_task.cancel()
            try:
                await self._delivery_task
            except asyncio.CancelledError:
                pass
        if self.broadcast is not None:
            await self.broadcast.close()
        if self.mesh is not None:
            await self.mesh.close()
        if self.verifier is not None and self._owns_verifier:
            await self.verifier.close()
        # Graceful-shutdown drain: payloads still sitting in
        # broadcast.delivered or the retry heap were already delivered
        # NETWORK-WIDE (peers commit and compact them — nothing will ever
        # re-gossip them to us). Dropping them here would permanently
        # desync this node's per-account sequence gate after restart, so
        # commit them before the final snapshot. Crash shutdown remains
        # best-effort by design (ledger/checkpoint.py docstring).
        if self.broadcast is not None:
            now = time.monotonic()
            while True:
                try:
                    p = self.broadcast.delivered.get_nowait()
                except asyncio.QueueEmpty:
                    break
                self._push_pending(p, now)
        if self._heap:
            await self._drain_to_fixpoint()
        # Final snapshot LAST — ingress, delivery, and broadcast are all
        # stopped, so no commit can land after (and be missing from) it.
        if self.config.checkpoint.path:
            try:
                await ckpt.save(
                    self.config.checkpoint.path, self.accounts, self.recent
                )
            except OSError:
                logger.exception("final checkpoint failed")

    # -- checkpoint ------------------------------------------------------

    async def _checkpoint_loop(self, path: str, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            try:
                await ckpt.save(path, self.accounts, self.recent)
            except OSError:
                logger.exception("periodic checkpoint failed")

    # -- observability ---------------------------------------------------

    def snapshot_stats(self) -> dict:
        """One structured stats record: broadcast per-stage counters +
        verifier batch metrics + commit progress (SURVEY.md §5)."""
        out = {"committed": self.committed, "pending": len(self._heap)}
        if self.broadcast is not None:
            out.update(self.broadcast.stats)
        if self.verifier is not None:
            verifier_stats = getattr(self.verifier, "stats", None)
            if callable(verifier_stats):
                out.update(
                    {f"verifier_{k}": v for k, v in verifier_stats().items()}
                )
        if self.mesh is not None:
            out.update({f"mesh_{k}": v for k, v in self.mesh.stats().items()})
        if self._mux is not None:
            out.update({f"rpc_{k}": v for k, v in self._mux.stats().items()})
        return out

    async def _stats_loop(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            snap = self.snapshot_stats()
            stats_logger.info(
                "stats %s",
                " ".join(
                    f"{k}={v:.3f}" if isinstance(v, float) else f"{k}={v}"
                    for k, v in sorted(snap.items())
                ),
            )

    # -- delivery → commit loop ------------------------------------------

    def _push_pending(self, p: Payload, now: float) -> None:
        """Push one delivered payload onto the retry heap — the ONE place
        the heap key is built (delivery loop and shutdown drain share it:
        the commit order must not depend on which path enqueued)."""
        key = (p.sequence, p.sender, p.transaction.recipient, p.transaction.amount)
        self._push_count += 1
        heapq.heappush(self._heap, (key, now, self._push_count, p))

    async def _delivery_loop(self) -> None:
        queue = self.broadcast.delivered
        while True:
            payload = await queue.get()
            batch = [payload]
            while True:  # greedy drain: one pass per delivered batch
                try:
                    batch.append(queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            now = time.monotonic()
            for p in batch:
                self._push_pending(p, now)
            await self._drain_to_fixpoint()

    async def _drain_to_fixpoint(self) -> None:
        # Mirrors rpc.rs:176-208: keep passing over the (sorted) pending
        # set while progress is made; retry only AccountModification
        # errors so a sequence gap fills once its predecessor lands.
        pending = self._heap
        while True:
            before = len(pending)
            retry: List[tuple] = []
            pending.sort()
            for key, added, tiebreak, payload in pending:
                if time.monotonic() - added > TRANSACTION_TTL:
                    logger.warning(
                        "transaction timed out: (%s, %d)",
                        payload.sender.hex()[:16],
                        payload.sequence,
                    )
                    await self.recent.update(
                        payload.sender, payload.sequence, TransactionState.FAILURE
                    )
                    # NO continue — TTL-expired payloads still process and
                    # may flip to Success (reference quirk, rpc.rs:183-205)
                try:
                    await self._process_payload(payload)
                except AccountModificationError as exc:
                    logger.debug(
                        "retrying payload (%s, %d): %s",
                        payload.sender.hex()[:16],
                        payload.sequence,
                        exc,
                    )
                    retry.append((key, added, tiebreak, payload))
                except Exception as exc:
                    logger.warning("dropping bad payload: %s", exc)
            pending[:] = retry
            heapq.heapify(pending)
            if not pending or len(pending) >= before:
                return

    async def _process_payload(self, payload: Payload) -> None:
        # rpc.rs:213-237: commit to the ledger, then flip the ring entry.
        logger.info(
            "new payload: seq=%d sender=%s",
            payload.sequence,
            payload.sender.hex()[:16],
        )
        await self.accounts.transfer(
            payload.sender,
            payload.sequence,
            payload.transaction.recipient,
            payload.transaction.amount,
        )
        await self.recent.update(
            payload.sender, payload.sequence, TransactionState.SUCCESS
        )
        self.committed += 1

    # -- gRPC handlers (rpc.rs:256-344) ----------------------------------

    async def SendAsset(self, request, context):
        if len(request.sender) != 32 or len(request.recipient) != 32:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "keys must be 32 bytes"
            )
        if len(request.signature) != 64:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "signature must be 64 bytes"
            )
        try:
            thin = ThinTransaction(request.recipient, request.amount)
        except ValueError as exc:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        await self.recent.put(request.sender, request.sequence, thin)
        payload = Payload(request.sender, request.sequence, thin, request.signature)
        # fire-and-forget: the ACK is not a commit receipt (rpc.rs:286)
        await self.broadcast.broadcast(payload)
        return pb.SendAssetReply()

    async def GetBalance(self, request, context):
        amount = await self.accounts.get_balance(request.sender)
        return pb.GetBalanceReply(amount=amount)

    async def GetLastSequence(self, request, context):
        sequence = await self.accounts.get_last_sequence(request.sender)
        return pb.GetLastSequenceReply(sequence=sequence)

    async def GetLatestTransactions(self, request, context):
        txs = await self.recent.get_all()
        return pb.GetLatestTransactionsReply(
            transactions=[
                pb.FullTransaction(
                    timestamp=rfc3339(tx.timestamp),
                    sender=tx.sender,
                    recipient=tx.recipient,
                    amount=tx.amount,
                    state=tx.state.value,
                    sender_sequence=tx.sender_sequence,
                )
                for tx in txs
            ]
        )
