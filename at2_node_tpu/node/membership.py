"""Epoch-based membership reconfiguration.

AT2 needs no consensus for asset transfers, and this build keeps
membership changes consensus-free too: a fleet admin signs a
``ConfigTx`` (broadcast/messages.py) naming the NEXT epoch and the
change — nodes to add (address + both public keys), nodes to remove
(sign-key), and optional quorum re-weighting — and gossips it like any
other message. Epochs are strictly sequential (a transaction must name
exactly ``current + 1``), so every correct node applies the same
transitions in the same order regardless of gossip arrival order:
a transaction for a later epoch is simply ignored until its
predecessor arrives (re-gossip and the mesh's full fan-out make that
convergent without retry machinery).

Applying a transition is three local actions:

* mesh add (net/peers.py ``add_peer``) for joining nodes — the mesh
  starts dialing them immediately;
* threshold re-weighting via the ``on_thresholds`` hook (the broadcast
  stack's echo/ready quorums);
* recording the evicted sign keys with a GRACE deadline: attestations
  from an evicted origin keep counting for ``grace`` seconds after the
  transition (covering slots already in flight when the transition
  landed). Only when ``sweep`` finds the deadline expired is the peer
  removed from the mesh (``remove_peer``) and the key banned for good —
  the "old-epoch messages rejected after a grace window" contract.

The applied epoch is durable: the service persists it in the sharded
store's manifest, so a restarted node rejoins at the epoch it had
reached, not at genesis.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

from ..broadcast.messages import ConfigTx
from ..crypto.keys import verify_one
from ..net.peers import Peer

logger = logging.getLogger(__name__)


class MembershipManager:
    """Validates and applies ConfigTx transitions; answers the two
    questions the hot path asks: ``origin_allowed(sign_key)`` and
    ``epoch`` (for /statusz)."""

    def __init__(
        self,
        *,
        admin_public: bytes,
        clock,
        grace: float = 5.0,
        epoch: int = 0,
        mesh=None,
        on_thresholds: Optional[Callable[[Optional[int], Optional[int]], None]] = None,
        own_sign_public: bytes = b"",
    ) -> None:
        self.admin_public = admin_public
        self.clock = clock
        self.grace = grace
        self.epoch = epoch
        self.mesh = mesh
        self.on_thresholds = on_thresholds
        self.own_sign_public = own_sign_public
        # evicted sign key -> clock.monotonic() deadline after which its
        # attestations stop counting. Mesh removal is DEFERRED to
        # sweep(): the broadcast stack filters origins through
        # mesh.by_sign, so removing the peer at apply time would drop
        # in-flight attestations instantly and defeat the grace window.
        self._evicted: Dict[bytes, float] = {}
        # sign keys whose grace expired and whose mesh peer was removed:
        # origin_allowed stays False for them forever (re-add via a later
        # epoch clears the ban)
        self._banned: set = set()
        self.applied = 0  # transitions applied (stats)
        self.rejected = 0  # transactions dropped by validation (stats)
        self.evicted_self = False  # this node was removed from the fleet

    # -- hot-path queries --------------------------------------------------

    def origin_allowed(self, sign_public: bytes) -> bool:
        """False once an evicted origin's grace window has expired."""
        if sign_public in self._banned:
            return False
        deadline = self._evicted.get(sign_public)
        if deadline is None:
            return True
        return self.clock.monotonic() < deadline

    def sweep(self, now: Optional[float] = None) -> int:
        """Finalize evictions whose grace window has expired: remove the
        peer from the mesh (the stack's by_sign filter then drops its
        attestations) and move the key to the permanent ban set. Called
        from the service's periodic loop and after sim settles. Returns
        the number of evictions finalized."""
        if now is None:
            now = self.clock.monotonic()
        expired = [k for k, dl in self._evicted.items() if now >= dl]
        for key in expired:
            del self._evicted[key]
            self._banned.add(key)
            if self.mesh is not None:
                self.mesh.remove_peer(key)
        return len(expired)

    # -- transitions -------------------------------------------------------

    def handle(self, tx: ConfigTx) -> bool:
        """Validate and apply one config transaction. Returns True when
        the transaction was NEWLY applied (the caller re-gossips it so
        the fleet converges); False for duplicates, stale or gapped
        epochs, bad signatures, and malformed bodies."""
        if not self.admin_public:
            return False  # reconfiguration disabled
        if tx.epoch != self.epoch + 1:
            # duplicates/stale are normal gossip echo; a gapped future
            # epoch waits for its predecessor's re-gossip
            if tx.epoch > self.epoch + 1:
                self.rejected += 1
            return False
        if not verify_one(self.admin_public, tx.to_sign(), tx.signature):
            self.rejected += 1
            logger.warning("config tx epoch %d: bad admin signature", tx.epoch)
            return False
        try:
            change = tx.change()
            if not isinstance(change, dict):
                raise ValueError("change body must be an object")
            self._apply(change)
        except (ValueError, KeyError, TypeError) as exc:
            self.rejected += 1
            logger.warning("config tx epoch %d malformed: %s", tx.epoch, exc)
            return False
        self.epoch = tx.epoch
        self.applied += 1
        logger.info("membership epoch %d applied", self.epoch)
        # grace <= 0 means "no window": finalize the eviction now rather
        # than waiting for the next periodic sweep
        self.sweep()
        return True

    def _apply(self, change: dict) -> None:
        grace = float(change.get("grace", self.grace))
        deadline = self.clock.monotonic() + grace
        # validate everything before mutating anything: a half-applied
        # transition would diverge nodes that saw the same transaction
        adds = []
        for row in change.get("add", []):
            adds.append(
                Peer(
                    address=str(row["address"]),
                    exchange_public=bytes.fromhex(row["exchange_hex"]),
                    sign_public=bytes.fromhex(row["sign_hex"]),
                )
            )
        removes = [bytes.fromhex(h) for h in change.get("remove", [])]
        for peer in adds:
            if len(peer.exchange_public) != 32 or len(peer.sign_public) != 32:
                raise ValueError("membership add row: bad key length")
        for key in removes:
            if len(key) != 32:
                raise ValueError("membership remove row: bad key length")
        for peer in adds:
            # a re-added node sheds any pending eviction or ban
            self._evicted.pop(peer.sign_public, None)
            self._banned.discard(peer.sign_public)
            if self.mesh is not None:
                self.mesh.add_peer(peer)
        for key in removes:
            # mesh removal is deferred to sweep() so attestations from
            # the evicted origin keep counting through the grace window
            self._evicted[key] = deadline
            if key == self.own_sign_public:
                self.evicted_self = True
        echo = change.get("echo_threshold")
        ready = change.get("ready_threshold")
        if (echo is not None or ready is not None) and self.on_thresholds:
            self.on_thresholds(
                int(echo) if echo is not None else None,
                int(ready) if ready is not None else None,
            )

    def stats(self) -> dict:
        return {
            "epoch": self.epoch,
            "applied": self.applied,
            "rejected": self.rejected,
            "evicted_pending": len(self._evicted),
            "evicted_final": len(self._banned),
            "evicted_self": self.evicted_self,
        }
