"""Three-phase byzantine broadcast: gossip → Echo (consistency) → Ready
(totality), with every signature routed through the pluggable Verifier.

Re-implements, as one explicit state machine, what the reference composes
from its murmur / sieve / contagion crates
(`/root/reference/technical.md:7-15`, wired at
`/root/reference/src/bin/server/rpc.rs:108-125`):

* **gossip (murmur)** — a new payload is relayed to every peer
  (`murmur_gossip_size` = full network, `rpc.rs:115`; AllSampler parity,
  `rpc.rs:124`) after its *client* signature verifies.
* **Echo (sieve)** — a node Echoes at most ONE payload content per
  (sender, sequence) slot — the equivocation filter — and sieve-delivers a
  content once `echo_threshold` distinct peers echoed that same content
  (`rpc.rs:113`: threshold = peer count).
* **Ready (contagion)** — on sieve-delivery a node signs a Ready; a
  content is delivered to the application once `ready_threshold` distinct
  peers sent Ready for it (`rpc.rs:120`). A node that collects a full
  Ready quorum without having sieve-delivered joins the quorum
  (amplification) so delivery is total across correct nodes.

Totality assumption: final delivery additionally requires the payload
content itself, which arrives only via gossip — a node that collects a
full Ready quorum but never received the payload pulls it from the Ready
quorum's members (content re-request, see ``_request_content``). The
re-request rides the same best-effort plane as gossip; under permanent
message loss to a node, that node may still not deliver — matching the
reference's open "catchup mechanism" roadmap item
(`/root/reference/README.md:53`).

Thresholds count PEERS (self excluded — the reference's config lists the
N−1 other nodes, `/root/reference/tests/cli.rs:173-184`, and sets every
threshold to that count, so an empty peer list degenerates to immediate
self-delivery, matching the reference's standalone-node test
`/root/reference/tests/server-config-resolve-addrs`).

**Batched broadcast slots** (the 10k-tx/s lever): alongside the per-tx
plane above, a node may gossip a :class:`TxBatch` — ONE slot
((origin node, batch_seq)) carrying up to 1024 client transactions —
amortizing the per-slot protocol cost (1 gossip relay + n Echo + n Ready
messages and signatures) over the whole batch. The reference broadcasts
one transaction per sieve payload
(`/root/reference/src/bin/server/rpc.rs:275-284`); Chop Chop (PAPERS.md)
is the public precedent for batching the broadcast unit. Chop Chop sits
on a total-order layer, where batch-level conflict resolution is free;
AT2 is consensus-free, so batch slots alone would lose sieve's
per-(sender, sequence) guarantee — a byzantine CLIENT racing conflicting
same-sequence transfers into two different honest nodes' batches could
commit differently on different correct nodes. This design closes that
hole with **per-entry endorsement bitmaps**:

* every node keeps an *entry registry* binding each (client sender,
  sequence) to the FIRST 140-byte entry content it echo-endorsed, across
  BOTH planes (per-tx echoes bind it too);
* a batch Echo/Ready is one signature over (batch hash, bitmap) where
  bit i endorses entry i — a node endorses exactly the entries whose
  client signature verified and whose registry binding is
  unbound-or-equal, so one conflicting entry never poisons its batch;
* quorum is counted PER ENTRY (vectorized: per-origin monotone bitmap
  ints, numpy unpackbits into count vectors), so an entry is delivered
  exactly when `echo/ready_threshold` distinct nodes endorsed *it* —
  with intersecting quorums (threshold > n/2) two conflicting contents
  for one (sender, sequence) can never both quorate, the same argument
  as per-tx sieve;
* Ready bitmaps are monotone (an origin re-attests with a superset as
  more entries reach Echo quorum); delivered entries feed the service's
  commit heap as ordinary Payloads, so the ledger, catchup, and history
  planes are unchanged.

Verification is the hot path (BASELINE north star): each worker drains a
CHUNK of the inbox per iteration and runs a three-stage pipeline —
(1) synchronous pre-checks (dedup, slot caps, per-origin single-vote) that
also insert into the dedup sets so no other worker double-verifies;
(2) ONE ``verifier.verify_many`` call for every signature the chunk needs
(this is what fills the TPU batch accumulator in bulk — one asyncio
future per chunk instead of per message); (3) synchronous state
transitions, re-validated against races with other workers that awaited
concurrently. State mutations stay on the single event loop — the same
single-writer argument as the reference's actors (SURVEY.md §5).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from collections import defaultdict
from typing import Dict, Optional, Set, Tuple

import numpy as np

from ..crypto.keys import SignKeyPair
from ..crypto.verifier import Verifier
from ..net.peers import Mesh, Peer
from .messages import (
    BATCH,
    BATCH_ECHO,
    BATCH_READY,
    ECHO,
    GOSSIP,
    MAX_BITMAP_BYTES,
    READY,
    Attestation,
    BatchAttestation,
    BatchContentRequest,
    ConfigTx,
    ContentRequest,
    DirectoryAnnounce,
    HistoryBatch,
    HistoryIndex,
    HistoryIndexRequest,
    HistoryRequest,
    Payload,
    CertSig,
    StateBeacon,
    TxBatch,
    WireError,
    parse_frame,
)

# Catchup-plane messages are control traffic for the node service (ledger
# history catchup, ledger/history.py) — the broadcast stack just routes
# them to the registered handler; they carry no broadcast state.
_CATCHUP_KINDS = (HistoryIndexRequest, HistoryIndex, HistoryRequest, HistoryBatch)

logger = logging.getLogger(__name__)

Slot = Tuple[bytes, int]  # (sender public key, sequence)

# A byzantine sender can gossip many conflicting contents for one slot;
# only the first few are retained (one is enough for correctness — sieve
# echoes only the first — the margin just tolerates gossip races).
MAX_CONTENTS_PER_SLOT = 8

# Memory bounds: dedup sets evict FIFO at these caps, and slot states are
# garbage-collected (delivered slots after DELIVERED_RETENTION, dead slots
# after SLOT_MAX_AGE) so unauthenticated spam cannot grow RSS unboundedly.
DEDUP_CAP = 1 << 20
# Cap on undelivered slots: beyond this, new slots are dropped until
# delivery or GC frees room. Bounds RSS against spam from freshly generated
# keypairs, which pass signature verification but never reach quorum.
# Delivered slots retained for DELIVERED_RETENTION deliberately do NOT
# count: sustained legitimate throughput must never trip the cap.
MAX_LIVE_SLOTS = 1 << 17
DELIVERED_RETENTION = 120.0  # s after delivery before the slot compacts
SLOT_MAX_AGE = 3600.0  # s an undelivered slot may linger
GC_INTERVAL = 5.0
# Min seconds between content re-requests for a ready-quorate slot whose
# payload gossip never arrived (pull-based catch-up; see module docstring).
REQUEST_RETRY = 5.0
# Stalled-slot retransmission (liveness under message loss): the planes
# are best-effort (bounded queues drop under overload, burst measurements
# showed a single lost attestation gap-blocking a whole sender at
# thresholds = n_peers), so a slot still undelivered RETRANSMIT_AFTER
# seconds after creation re-broadcasts this node's content + own
# attestations, at most every RETRANSMIT_EVERY per slot. Receivers that
# already saw them dedup at the pre-verify stage for the cost of a set
# lookup (deterministic ed25519: a re-signed attestation is
# byte-identical, so _attest_seen absorbs it).
RETRANSMIT_AFTER = 5.0
RETRANSMIT_EVERY = 10.0
# Global per-GC-pass retransmission budget: after a mass stall (burst
# overflow parking thousands of slots) an unbounded pass would re-inject
# B x n_peers frames at once — re-creating the overload it heals.
# Skipped slots keep their old retransmitted_at, so subsequent passes
# rotate through them naturally.
RETRANSMIT_BUDGET_PER_PASS = 64
# An undelivered slot this old has outlived push-retransmission AND the
# helpers' delivered-state retention may be expiring: hand recovery to
# the ledger-catchup plane (stall_handler -> node.service._kick_catchup),
# which replays the committed slot from peers' history stores.
STALLED_CATCHUP_AFTER = 30.0
# Stall-storm damping (hysteresis on stall_handler): consecutive kicks
# are spaced at least STALL_KICK_MIN_INTERVAL apart, doubling up to
# STALL_KICK_MAX_INTERVAL while the stall persists, and the interval
# resets once a GC pass sees no stalled slot. Without this, ONE slot
# parked past STALLED_CATCHUP_AFTER fires a network-wide catchup kick
# every GC_INTERVAL for up to SLOT_MAX_AGE — the amplification lever the
# per-slot resolution tracking closes (ADVICE.md stack.py:1296).
STALL_KICK_MIN_INTERVAL = 30.0
STALL_KICK_MAX_INTERVAL = 300.0
# Entry-registry bound (see Broadcast._entry_registry): sized so FIFO
# eviction cannot reopen the equivocation window for LIVE slots — see
# the safety comment at the construction site.
ENTRY_REGISTRY_CAP = 1 << 22
# Max messages one worker drains from the inbox per iteration: the unit of
# bulk verification (one verify_many call -> one slice of the TPU batch).
WORKER_CHUNK = 256
# Byte budget for undrained inbox frames. The inbox's 65536-entry bound
# alone would admit ~1 TiB of parked 16 MiB frames from an authenticated
# byzantine peer; 64 MiB is >4x the largest legitimate frame and hundreds
# of typical attestation batches — overflow drops, like the entry cap.
INBOX_MAX_BYTES = 64 * 1024 * 1024


class _BoundedSet:
    """Insertion-ordered set with FIFO eviction at a fixed capacity."""

    __slots__ = ("_cap", "_items")

    def __init__(self, cap: int) -> None:
        self._cap = cap
        self._items: Dict = {}

    def add(self, key) -> None:
        if key in self._items:
            return
        self._items[key] = None
        if len(self._items) > self._cap:
            self._items.pop(next(iter(self._items)))

    def discard(self, key) -> None:
        self._items.pop(key, None)

    def __contains__(self, key) -> bool:
        return key in self._items

    def __len__(self) -> int:
        return len(self._items)


class _BoundedDict:
    """Insertion-ordered dict with FIFO eviction at a fixed capacity
    (the mapping twin of :class:`_BoundedSet`). ``evictions`` counts
    entries shed at the cap — nonzero on the entry registry means the
    sizing argument at its construction site was violated in practice
    (surfaced as the ``entry_evictions`` gauge; the fleet-audit beacons
    are the cross-node backstop for any divergence this could cause)."""

    __slots__ = ("_cap", "_items", "evictions")

    def __init__(self, cap: int) -> None:
        self._cap = cap
        self._items: Dict = {}
        self.evictions = 0

    def get(self, key, default=None):
        return self._items.get(key, default)

    def put(self, key, value) -> None:
        if key not in self._items:
            if len(self._items) >= self._cap:
                self._items.pop(next(iter(self._items)))
                self.evictions += 1
        self._items[key] = value

    def pop(self, key, default=None):
        return self._items.pop(key, default)

    def __contains__(self, key) -> bool:
        return key in self._items

    def __len__(self) -> int:
        return len(self._items)


_EMPTY_COUNTS = np.zeros(0, dtype=np.int32)

# Below this many entries the ctypes crossing costs more than the numpy
# ops it replaces; above it the native kernel wins AND releases the GIL,
# which is what lets ThreadPlaneExecutor shards actually overlap.
_NATIVE_QUORUM_MIN = 16


def _quorate_mask(counts: np.ndarray, threshold: int, nbits: int) -> int:
    """Bitmap int of entries whose vote count reached the threshold.

    Bit-identical on the native (at2_quorum_mask, GIL released) and numpy
    paths — differential-tested in tests/test_plane_shards.py — so which
    path runs never affects wire behavior or sim hashes."""
    if nbits <= 0:
        return 0
    if threshold <= 0:
        return (1 << nbits) - 1
    n = min(len(counts), nbits)
    if n == 0:
        return 0
    if n >= _NATIVE_QUORUM_MIN:
        from ..native.ingest import ingest_ready

        if ingest_ready():
            from ..native.ingest import quorum_mask_native

            return quorum_mask_native(counts, threshold, n)
    mask = counts[:n] >= threshold
    return int.from_bytes(
        np.packbits(mask, bitorder="little").tobytes(), "little"
    )


class _BatchVotes:
    """Per-(content hash, phase) vote accumulator: per-origin MONOTONE
    endorsement bitmaps (ints) plus a vectorized per-entry count vector.
    ``add`` is the only mutator: it ORs an origin's new bitmap in and
    bumps the counts at every newly-set bit position (numpy unpackbits —
    one vectorized op per attestation, not per entry)."""

    __slots__ = ("by_origin", "counts")

    def __init__(self) -> None:
        self.by_origin: Dict[bytes, int] = {}
        self.counts = _EMPTY_COUNTS

    def add(self, origin: bytes, bits: int, nbits: int) -> bool:
        """Returns True when the origin contributed at least one new bit."""
        old = self.by_origin.get(origin, 0)
        new = bits & ~old
        if not new:
            return False
        self.by_origin[origin] = old | bits
        if len(self.counts) < nbits:
            grown = np.zeros(nbits, dtype=np.int32)
            grown[: len(self.counts)] = self.counts
            self.counts = grown
        new_bytes = new.to_bytes((nbits + 7) // 8, "little")
        if nbits >= _NATIVE_QUORUM_MIN:
            from ..native.ingest import ingest_ready

            if ingest_ready():
                from ..native.ingest import counts_add_native

                # GIL-released tally fold (at2_counts_add); arithmetic
                # identical to the unpackbits path below
                counts_add_native(new_bytes, self.counts)
                return True
        delta = np.unpackbits(
            np.frombuffer(new_bytes, dtype=np.uint8),
            bitorder="little",
        )[:nbits]
        self.counts[:nbits] += delta
        return True


class _BatchState:
    """Broadcast state of one batch slot ((origin node, batch_seq)) —
    the batched twin of :class:`_SlotState`, with per-entry vote vectors
    instead of per-slot origin sets."""

    __slots__ = (
        "created",
        "birth",
        "content_requested_at",
        "retransmitted_at",
        "helped_at",
        "contents",
        "echoed_hash",
        "echo_by_origin",
        "ready_by_origin",
        "echo_votes",
        "ready_votes",
        "own_echo_bits",
        "ready_hash",
        "ready_sent_bits",
        "delivered_bits",
        "rejected_bits",
        "delivered_all",
        "retired",
        "nbits",
        "echo_q_marked",
    )

    def __init__(self, now: float) -> None:
        self.created = now
        self.birth = 0  # plane-wide creation ordinal (stamped by creator)
        self.content_requested_at = 0.0
        self.retransmitted_at = 0.0  # last stalled-slot retransmission
        self.helped_at: Dict[bytes, float] = {}  # per-peer help pacing
        self.contents: Dict[bytes, TxBatch] = {}  # batch hash -> batch
        self.echoed_hash: Optional[bytes] = None  # first content echoed here
        # first vote per origin per phase binds that origin to ONE batch
        # content (node-level equivocation guard, like *_by_origin above)
        self.echo_by_origin: Dict[bytes, bytes] = {}
        self.ready_by_origin: Dict[bytes, bytes] = {}
        self.echo_votes: Dict[bytes, _BatchVotes] = {}  # batch hash -> votes
        self.ready_votes: Dict[bytes, _BatchVotes] = {}
        # the entries WE echo-endorsed per content (sig valid + registry
        # agreed) — the delivery gate when thresholds degenerate to 0,
        # where no peer quorum exists to carry the verification argument
        self.own_echo_bits: Dict[bytes, int] = {}
        # slot-level Ready binding, mirroring per-tx _SlotState.ready_sent:
        # this node signs Ready for at most ONE content per batch slot
        self.ready_hash: Optional[bytes] = None
        self.ready_sent_bits = 0  # our cumulative Ready bits (ready_hash)
        self.delivered_bits: Dict[bytes, int] = {}  # hash -> delivered bits
        # entries WE rejected at echo time (bad client signature or an
        # equivocation-registry conflict) — the resolution complement of
        # delivered_bits: an entry is RESOLVED when delivered or rejected
        self.rejected_bits: Dict[bytes, int] = {}
        self.delivered_all = False  # some content fully delivered
        # every ready-quorate entry delivered, every remaining entry
        # locally resolved-rejected: the slot can never progress further
        # and must not count as stalled (see _maybe_retire_batch)
        self.retired = False
        self.nbits = 0  # widest entry count seen (content or bitmap bound)
        self.echo_q_marked = 0  # entries already echo_quorum-marked (trace)


class _SlotState:
    __slots__ = (
        "contents",
        "echoed_hash",
        "echoes",
        "readies",
        "echo_by_origin",
        "ready_by_origin",
        "ready_sent",
        "ready_hash",
        "sieve_delivered",
        "delivered",
        "created",
        "birth",
        "content_requested_at",
        "retransmitted_at",
        "helped_at",
    )

    def __init__(self, now: float) -> None:
        self.created = now
        self.birth = 0  # plane-wide creation ordinal (stamped by creator)
        self.content_requested_at = 0.0  # last pull request, 0 = never
        self.retransmitted_at = 0.0  # last stalled-slot retransmission
        self.helped_at: Dict[bytes, float] = {}  # per-peer help pacing
        self.ready_hash: Optional[bytes] = None  # content our READY covers
        self.contents: Dict[bytes, Payload] = {}  # content_hash -> payload
        self.echoed_hash: Optional[bytes] = None  # sieve: first content only
        self.echoes: Dict[bytes, Set[bytes]] = defaultdict(set)  # hash -> origins
        self.readies: Dict[bytes, Set[bytes]] = defaultdict(set)
        # first VERIFIED vote per origin per phase wins — a byzantine origin
        # cannot land in two contents' quorums (echo equivocation guard)
        self.echo_by_origin: Dict[bytes, bytes] = {}
        self.ready_by_origin: Dict[bytes, bytes] = {}
        self.ready_sent = False
        self.sieve_delivered = False
        self.delivered = False


class Broadcast:
    """The node's broadcast endpoint: submit via :meth:`broadcast`, consume
    committed payloads from :attr:`delivered` (an asyncio.Queue of
    :class:`Payload`, drained in batches by the service's delivery loop)."""

    # class-level default so partially-constructed instances (tests build
    # bare objects via __new__ to unit-test single methods) read "no
    # recorder" instead of raising AttributeError
    recorder = None
    # same contract for the plane time-accounting seam (obs/profiler.py)
    phases = None
    # same contract for the [wan] echo/ready phase-piggyback knob
    overlap_ready = False

    def __init__(
        self,
        keypair: SignKeyPair,
        mesh: Mesh,
        verifier: Verifier,
        echo_threshold: Optional[int] = None,
        ready_threshold: Optional[int] = None,
        workers: int = 16,
        registry=None,
        trace=None,
        recorder=None,
        clock=None,
        phases=None,
        overlap_ready: bool = False,
    ) -> None:
        from ..clock import SYSTEM_CLOCK

        self.keypair = keypair
        self.mesh = mesh
        self.verifier = verifier
        self.clock = SYSTEM_CLOCK if clock is None else clock
        n_peers = len(mesh.peers)
        # Reference parity: every threshold defaults to the peer count
        # (rpc.rs:112-120); configurable so f>0 setups are testable
        # (SURVEY.md §5 failure-detection note).
        self.echo_threshold = n_peers if echo_threshold is None else echo_threshold
        self.ready_threshold = n_peers if ready_threshold is None else ready_threshold
        self.workers = workers
        self.delivered: asyncio.Queue = asyncio.Queue()
        self._slots: Dict[Slot, _SlotState] = {}
        # batched plane (module docstring): batch slots keyed
        # (origin node sign key, batch_seq); the entry registry binds each
        # (client sender, client seq) to the first echo-endorsed 140-byte
        # entry content ACROSS both planes — sieve's per-slot guarantee
        self._batch_slots: Dict[Tuple[bytes, int], _BatchState] = {}
        self._delivered_batch_slots = _BoundedSet(DEDUP_CAP)
        # Registry retention is scoped to LIVE (uncommitted) sequences:
        # the service drops a binding via release_entry() once its
        # sequence passes the ledger gate, where the per-account sequence
        # check subsumes the registry's job (a conflicting content for a
        # committed seq can never commit again). Safety of the FIFO cap:
        # the theoretical live bound is MAX_LIVE_SLOTS x
        # MAX_BATCH_ENTRIES (2^17 x 2^10 = 2^27) bindings, far past what
        # fits in RAM — but per-tx slots bind at most one entry each
        # (<= MAX_LIVE_SLOTS = 2^17 total) and batch slots exist only
        # under the n known node identities, so 2^22 covers the per-tx
        # worst case plus ~4000 full in-flight batches (4M entries,
        # >> any real in-flight window at the 10k tx/s target). Eviction
        # at the cap therefore only ever sheds bindings under a workload
        # that already exceeds every other resource bound; committed
        # bindings are released eagerly and cost nothing.
        self._entry_registry = _BoundedDict(ENTRY_REGISTRY_CAP)
        self._inbox: asyncio.Queue = asyncio.Queue(maxsize=65536)
        # The inbox holds RAW frames (parsed in the worker chunk stage),
        # each up to transport MAX_FRAME (16 MiB) — so the entry-count
        # bound alone would let an authenticated-but-byzantine peer (in
        # model for BFT) park ~1 TiB of undrained bytes. Bound BYTES too:
        # admission debits the budget, the worker credits it back on
        # dequeue. Single-threaded (event loop) => plain int is race-free.
        self._inbox_bytes = 0
        self._tasks: list = []
        # inflight verification dedup: messages identical to one already
        # being verified are coalesced instead of re-verified
        self._gossip_seen = _BoundedSet(DEDUP_CAP)
        self._attest_seen = _BoundedSet(DEDUP_CAP)
        # slots compacted away after delivery; membership blocks re-delivery
        self._delivered_slots = _BoundedSet(DEDUP_CAP)
        # count of slots in _slots with delivered == False (the cap metric)
        self._undelivered = 0
        # node-service hook for catchup-plane messages (sync callable
        # (peer, msg) -> None); None drops them (a stack used standalone)
        self.catchup_handler = None
        # node-service hook for client-directory announces (sync callable
        # (peer, msg) -> None; node/directory.py) — same routing shape as
        # the catchup plane; None drops them (a stack used standalone)
        self.directory_handler = None
        # node-service hook for membership config transactions (sync
        # callable (peer, msg) -> None; node/membership.py) — same shape
        # as directory_handler; None drops them
        self.config_handler = None
        # node-service hook for fleet-audit state beacons (sync callable
        # (peer, msg) -> None; obs/audit.py) — same shape as
        # directory_handler; None drops them
        self.beacon_handler = None
        # node-service hook for finality cert co-signatures (sync
        # callable (peer, msg) -> None; finality/certs.py) — same shape
        # as beacon_handler; None drops them
        self.cert_handler = None
        # sim hook fired whenever this node SIGNS an attestation (either
        # plane): callable (phase, origin_or_sender, sequence, chash).
        # The simulator's no-post-restart-equivocation invariant records
        # every signing across a node's incarnations through this.
        self.on_attest = None
        # Broadcast-safety watermarks: the highest slot this node has
        # attested per origin, per plane. Persisted in the store manifest
        # and restored as FLOORS after a crash — _send_attestation /
        # _send_batch_attestation refuse to sign any slot at or below the
        # restored floor, so a restarted node can never sign a
        # CONFLICTING echo/ready for a slot it attested pre-crash (the
        # pre-crash vote may have reached peers even if nothing else
        # survived locally). Liveness: refused slots commit through
        # peers' quorums and reach this node via ledger catchup.
        self._wm_tx: Dict[bytes, int] = {}  # client sender -> max seq
        self._wm_batch: Dict[bytes, int] = {}  # batch origin -> max seq
        self._floor_tx: Dict[bytes, int] = {}
        self._floor_batch: Dict[bytes, int] = {}
        self.floor_refusals = 0  # attestations suppressed by a floor
        # node-service hook fired (once per GC pass) when some slot has
        # been stalled past STALLED_CATCHUP_AFTER: push-retransmission
        # has failed, recovery belongs to the ledger-catchup plane.
        # Kicks are damped with hysteresis (min interval + exponential
        # backoff, STALL_KICK_*) so a persistent stall cannot storm the
        # network with catchup sessions every GC pass.
        self.stall_handler = None
        self._stall_last_kick = float("-inf")
        self._stall_backoff = STALL_KICK_MIN_INTERVAL
        # slot-creation ordinal: dict insertion order made durable, so a
        # sharded plane (broadcast/shards.py shares ONE counter across
        # its cores) can reconstruct the global GC iteration order
        self._birth_seq = itertools.count()
        # observability (SURVEY.md §5: per-stage counters). The service
        # passes its registry + tx-lifecycle tracer; a standalone stack
        # (unit tests, bench harnesses) gets a private registry and no
        # tracing. CounterGroup keeps the ``stats["k"] += 1`` surface.
        from ..obs.registry import Registry

        self.registry = Registry() if registry is None else registry
        self.trace = trace
        # protocol flight recorder (obs/recorder.py); None = not recording.
        # Sites guard with ``is not None`` so the disabled path costs one
        # attribute read.
        self.recorder = recorder
        # plane time-accounting (obs/profiler.py PhaseAccounting); same
        # ``is not None`` guard discipline at every marked segment
        self.phases = phases
        # [wan] overlap_ready: emit Ready in the SAME frame as Echo
        # (phase piggybacking) instead of waiting out the echo-quorum
        # round trip. Safety is carried by what this knob does NOT
        # change: the per-slot single-Ready binding (ready_hash is set
        # exactly once, all sends go through _send_attestation's
        # watermark floors) and the delivery gate (ready quorum AND own
        # ready sent AND content known). What it relaxes is only the
        # scheduling claim "own Ready implies a locally-observed echo
        # quorum" — an opt-in latency/ordering trade, default off so the
        # wire schedule (and every same-seed sim hash) is unchanged.
        self.overlap_ready = overlap_ready
        self.registry.gauge(
            "slots_undelivered", "live undelivered broadcast slots",
            fn=lambda: self._undelivered,
        )
        self.registry.gauge(
            "inbox_depth", "raw frames queued for the broadcast workers",
            fn=lambda: self._inbox.qsize(),
        )
        self.registry.gauge(
            "entry_evictions",
            "entry-registry bindings shed at the FIFO cap (should be 0; "
            "see the sizing argument at the registry's construction)",
            fn=lambda: self._entry_registry.evictions,
        )
        self.stats = self.registry.counter_group((
            "gossip_rx",
            "echo_rx",
            "ready_rx",
            "invalid_sig",
            "delivered",
            "slots_dropped",
            "content_req_tx",
            "content_req_rx",
            "content_served",
            "batch_rx",
            "batch_echo_rx",
            "batch_ready_rx",
            "batch_entries_delivered",
            "retransmits",
            # robustness counters (poison-entry resolution, PR 1):
            # entries resolved by local rejection when their slot retired,
            # retired slots, and stall kicks absorbed by the hysteresis
            "poison_resolved",
            "slots_retired",
            "stall_kicks_suppressed",
        ))

    async def start(self) -> None:
        # Pre-build the native ingest library off-loop HERE — broadcast is
        # its consumer, so this covers every verifier configuration (the
        # lazy first-use g++ compile must never run on the event loop
        # inside a live worker chunk and freeze the node).
        from ..native import ingest_available

        await asyncio.get_running_loop().run_in_executor(None, ingest_available)
        for _ in range(self.workers):
            self._tasks.append(asyncio.create_task(self._worker()))
        self._tasks.append(asyncio.create_task(self._gc_loop()))

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()

    # -- inbound ----------------------------------------------------------

    async def on_frame(self, peer: Peer, frame: bytes) -> None:
        """Mesh callback: enqueue the RAW frame; parsing happens in the
        worker chunk stage (one native-ingest call per chunk when the C++
        library is available — frame parse + payload content hashes in
        one GIL-released pass). Drops (best-effort plane) when the inbox
        is saturated — by entry count OR byte budget — rather than
        back-pressuring the socket."""
        if self.recorder is not None and frame:
            self.recorder.record("rx", (frame[0], len(frame), peer.address))
        if self._inbox_bytes + len(frame) > INBOX_MAX_BYTES:
            logger.warning("inbox byte budget exhausted; dropping frame")
            if self.recorder is not None:
                self.recorder.record("rx_drop", ("bytes", len(frame)))
            return
        try:
            self._inbox.put_nowait((peer, frame))
        except asyncio.QueueFull:
            logger.warning("inbox overflow; dropping frame")
            if self.recorder is not None:
                self.recorder.record("rx_drop", ("depth", len(frame)))
        else:
            self._inbox_bytes += len(frame)

    async def broadcast(self, payload: Payload) -> None:
        """Local submission (the gRPC SendAsset handler calls this —
        reference: `handle.broadcast`, rpc.rs:275-284)."""
        await self._inbox.put((None, payload))

    async def broadcast_batch(self, batch: TxBatch) -> None:
        """Local submission of a signed batch slot (the service's ingress
        batcher calls this; see node/service.py `_flush_batch`)."""
        await self._inbox.put((None, batch))

    # -- workers ----------------------------------------------------------

    async def _gc_loop(self) -> None:
        """Compact delivered slots, expire dead ones (memory bound), and
        drive stalled-slot recovery (budgeted retransmission + the
        catchup-plane stall signal)."""
        while True:
            await self.clock.sleep(GC_INTERVAL)
            self._gc_pass(self.clock.monotonic())

    def _gc_pass(self, now: float) -> None:
        """One synchronous GC/recovery pass over this plane's slots.

        Split into per-slot steps (:meth:`_gc_tx_slot` /
        :meth:`_gc_batch_slot`) plus the stall-hysteresis epilogue
        (:meth:`_gc_resolve_stall`) so the sharded plane
        (broadcast/shards.py) can interleave EVERY shard's slots in
        global creation order under one shared retransmit budget — the
        exact iteration this monolithic pass performs — while this
        method keeps serving the monolithic plane and the threaded
        per-shard pass unchanged."""
        ph = self.phases
        t_gc = ph.t() if ph is not None else 0
        budget = [RETRANSMIT_BUDGET_PER_PASS]
        stalled_past_horizon = False
        for slot in list(self._slots):
            if self._gc_tx_slot(slot, now, budget):
                stalled_past_horizon = True
        for slot in list(self._batch_slots):
            if self._gc_batch_slot(slot, now, budget):
                stalled_past_horizon = True
        self._gc_resolve_stall(now, stalled_past_horizon)
        if ph is not None:
            ph.add("slot_gc", t_gc)

    def _gc_tx_slot(self, slot: Slot, now: float, budget: list) -> bool:
        """GC/recovery step for ONE per-tx slot; returns True when the
        slot is stalled past the catchup horizon. ``budget`` is a
        one-element mutable cell so one retransmission budget can span a
        whole pass (and, sharded, every shard in the pass)."""
        state = self._slots.get(slot)
        if state is None:
            return False
        age = now - state.created
        if state.delivered and age > DELIVERED_RETENTION:
            self._delivered_slots.add(slot)
            del self._slots[slot]
        elif age > SLOT_MAX_AGE:
            if not state.delivered:
                self._undelivered -= 1
            del self._slots[slot]
        elif not state.delivered:
            # periodic retry of the content pull for quorate slots
            # still missing their payload (lost request/response)
            for chash, origins in state.readies.items():
                if (
                    len(origins) >= self.ready_threshold
                    and chash not in state.contents
                ):
                    self._request_content(slot, state, chash)
            if budget[0] > 0 and self._retransmit_slot(slot, state, now):
                budget[0] -= 1
            if age > STALLED_CATCHUP_AFTER:
                return True
        return False

    def _gc_batch_slot(self, slot, now: float, budget: list) -> bool:
        """Batch-plane twin of :meth:`_gc_tx_slot`."""
        bstate = self._batch_slots.get(slot)
        if bstate is None:
            return False
        age = now - bstate.created
        if not (bstate.delivered_all or bstate.retired):
            # a slot can become retire-eligible between worker
            # transitions (e.g. the last quorate entry delivered
            # via another content's votes); settle it here so it
            # never sits through a pass as a false "stall"
            self._maybe_retire_batch(slot, bstate)
        resolved = bstate.delivered_all or bstate.retired
        if resolved and age > DELIVERED_RETENTION:
            self._delivered_batch_slots.add(slot)
            del self._batch_slots[slot]
        elif age > SLOT_MAX_AGE:
            if not resolved:
                self._undelivered -= 1
            del self._batch_slots[slot]
        elif not resolved:
            # retry the batch pull when quorate entries await content
            for chash, rv in bstate.ready_votes.items():
                if chash in bstate.contents:
                    continue
                quorate = _quorate_mask(
                    rv.counts, self.ready_threshold, bstate.nbits
                )
                if quorate & ~bstate.delivered_bits.get(chash, 0):
                    self._request_batch_content(slot, bstate, chash)
            if budget[0] > 0 and self._retransmit_batch_slot(
                slot, bstate, now
            ):
                budget[0] -= 1
            # "stalled awaiting quorum" vs "stalled with
            # unresolved poison": only the former can be healed
            # by the catchup plane (the slot may be committed
            # network-wide). A slot whose only undelivered
            # entries are ones WE rejected is poison-blocked —
            # a network-wide catchup kick cannot resolve it and
            # must not be fired for it.
            if age > STALLED_CATCHUP_AFTER and not (
                self._poison_blocked_only(bstate)
            ):
                return True
        return False

    def _gc_resolve_stall(self, now: float, stalled_past_horizon: bool) -> None:
        """Stall-kick hysteresis epilogue of a GC pass. Duck-typed: the
        sharded plane calls this unbound with itself as ``self`` so ONE
        plane-level hysteresis spans all shards (matching the monolithic
        plane), with per-shard stall state never consulted."""
        if stalled_past_horizon and self.stall_handler is not None:
            # beyond push-retransmission: the slot may be committed
            # network-wide with the helpers' delivered state expiring
            # — the ledger-catchup plane replays it from history.
            # Hysteresis: consecutive kicks are spaced at least
            # _stall_backoff apart (doubling while the stall
            # persists) so one misbehaving slot cannot trigger a
            # catchup session every GC pass network-wide.
            if now - self._stall_last_kick >= self._stall_backoff:
                self._stall_last_kick = now
                self._stall_backoff = min(
                    self._stall_backoff * 2, STALL_KICK_MAX_INTERVAL
                )
                if self.recorder is not None:
                    self.recorder.record("stall_kick", ())
                try:
                    self.stall_handler()
                except Exception:
                    logger.exception("stall handler error")
            else:
                self.stats["stall_kicks_suppressed"] += 1
                if self.recorder is not None:
                    self.recorder.record("stall_kick_suppressed", ())
        elif not stalled_past_horizon:
            # healthy pass: re-arm the hysteresis for the next storm
            self._stall_backoff = STALL_KICK_MIN_INTERVAL

    def _resend_slot(
        self, slot: Slot, state: _SlotState, peer: Optional[Peer]
    ) -> bool:
        """Re-emit this node's content copy + own attestations for a
        slot — broadcast (stalled-slot retransmission) or targeted
        (straggler help). Returns True when anything went out."""
        sent = False
        if state.echoed_hash is not None:
            payload = state.contents.get(state.echoed_hash)
            if payload is not None:
                if peer is not None:
                    self.mesh.send(peer, payload.encode())
                else:
                    self.mesh.broadcast(payload.encode())
            self._send_attestation(
                ECHO, slot[0], slot[1], state.echoed_hash, peer=peer
            )
            sent = True
        if state.ready_sent and state.ready_hash is not None:
            self._send_attestation(
                READY, slot[0], slot[1], state.ready_hash, peer=peer
            )
            sent = True
        if sent:
            self.stats["retransmits"] += 1
        return sent

    def _resend_batch_slot(
        self, slot, state: _BatchState, peer: Optional[Peer]
    ) -> bool:
        """Batch-plane twin of :meth:`_resend_slot`."""
        sent = False
        if state.echoed_hash is not None:
            batch = state.contents.get(state.echoed_hash)
            if batch is not None:
                if peer is not None:
                    self.mesh.send(peer, batch.encode())
                else:
                    self.mesh.broadcast(batch.encode())
                sent = True
            bits = state.own_echo_bits.get(state.echoed_hash, 0)
            nbits = batch.count if batch is not None else state.nbits
            if bits and nbits:
                self._send_batch_attestation(
                    BATCH_ECHO, slot, state.echoed_hash, bits, nbits, peer=peer
                )
                sent = True
        if state.ready_hash is not None and state.ready_sent_bits:
            rbatch = state.contents.get(state.ready_hash)
            nbits = rbatch.count if rbatch is not None else state.nbits
            if nbits:
                self._send_batch_attestation(
                    BATCH_READY,
                    slot,
                    state.ready_hash,
                    state.ready_sent_bits,
                    nbits,
                    peer=peer,
                )
                sent = True
        if sent:
            self.stats["retransmits"] += 1
        return sent

    def _help_paced(self, state, peer: Peer, now: float) -> bool:
        """Per-(slot, peer) pacing for straggler help: two stragglers on
        one slot must not serialize behind a shared timestamp."""
        last = state.helped_at.get(peer.sign_public, 0.0)
        if now - last < RETRANSMIT_EVERY:
            return False
        state.helped_at[peer.sign_public] = now
        return True

    def _help_straggler(
        self, peer: Optional[Peer], slot: Slot, state: _SlotState
    ) -> None:
        """Targeted repair: send our content copy + own attestations for
        a DELIVERED slot directly to the peer whose duplicate attestation
        marked it as stalled (see _pre_attestation)."""
        if peer is not None and self._help_paced(state, peer, self.clock.monotonic()):
            self._resend_slot(slot, state, peer)

    def _help_batch_straggler(
        self, peer: Optional[Peer], slot, state: _BatchState
    ) -> None:
        """Batch-plane twin of :meth:`_help_straggler`."""
        if peer is not None and self._help_paced(state, peer, self.clock.monotonic()):
            self._resend_batch_slot(slot, state, peer)

    def _retransmit_slot(self, slot: Slot, state: _SlotState, now: float) -> bool:
        """Stalled-slot liveness: re-broadcast this node's content copy
        and own attestations for a slot still undelivered past
        RETRANSMIT_AFTER (a lost echo/ready has no other recovery at
        thresholds = n_peers; receivers that saw them dedup pre-verify)."""
        if now - state.created < RETRANSMIT_AFTER:
            return False
        if now - state.retransmitted_at < RETRANSMIT_EVERY:
            return False
        if not self._resend_slot(slot, state, None):
            return False
        state.retransmitted_at = now
        return True

    def _retransmit_batch_slot(self, slot, state: _BatchState, now: float) -> bool:
        """Batch-plane twin of :meth:`_retransmit_slot`."""
        if now - state.created < RETRANSMIT_AFTER:
            return False
        if now - state.retransmitted_at < RETRANSMIT_EVERY:
            return False
        if not self._resend_batch_slot(slot, state, None):
            return False
        state.retransmitted_at = now
        return True

    async def _worker(self) -> None:
        while True:
            item = await self._inbox.get()
            chunk = [item]
            while len(chunk) < WORKER_CHUNK:
                try:
                    chunk.append(self._inbox.get_nowait())
                except asyncio.QueueEmpty:
                    break
            for _, payload in chunk:
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    self._inbox_bytes -= len(payload)
            # plane_total wraps the whole drain cycle (parse + process):
            # it is the denominator of the per-node plane decomposition
            # (obs/profiler.py); rx_decode covers the frame parse here,
            # the admission pre-checks inside _process_chunk chain onto
            # it. begin/end_plane (not a bare add_ns) so a cycle that
            # re-enters the plane in-context accounts its span ONCE.
            ph = self.phases
            t_plane = ph.begin_plane() if ph is not None else 0
            t0 = ph.t() if ph is not None else 0
            try:
                msgs = self._parse_chunk(chunk)
                if ph is not None:
                    ph.add("rx_decode", t0)
                await self._process_chunk(msgs)
            except Exception:
                logger.exception("broadcast worker error")
            if ph is not None:
                ph.end_plane(t_plane)

    def _parse_chunk(self, chunk) -> list:
        """Turn a drained inbox chunk into (peer, message) pairs.

        Inbox entries are raw wire frames (from the mesh) or already-built
        Payload objects (local gRPC submissions). Wire frames go through
        the native ingest library in ONE call per chunk when available
        (at2_ingest.cpp: kind dispatch, record extraction, and payload
        content hashes with the GIL released); malformed frames drop whole
        with a warning, exactly like the Python parse_frame path."""
        out = []
        frames: list = []  # parallel lists: frame bytes + source peer
        frame_peers: list = []
        for peer, item in chunk:
            if isinstance(item, (bytes, bytearray, memoryview)):
                frames.append(bytes(item))
                frame_peers.append(peer)
            else:
                out.append((peer, item))
        if not frames:
            return out
        from ..native import ingest_ready_or_kick, parse_frames_native

        # The native call has fixed setup cost (ndarray staging, one
        # ctypes crossing); it wins when a chunk actually batched. Tiny
        # chunks — one frame trickling in on an idle net — stay on the
        # Python parser, which is faster below this threshold.
        # ingest_ready_or_kick never builds: start() pre-builds off-loop,
        # a stack used without start() must not run g++ on the event loop.
        total_bytes = sum(len(f) for f in frames)
        if total_bytes >= 4096 and ingest_ready_or_kick():
            parsed, frame_ok = parse_frames_native(frames)
            for i, ok in enumerate(frame_ok):
                if not ok:
                    peer = frame_peers[i]
                    logger.warning(
                        "bad frame from %s",
                        peer.address if peer is not None else "local",
                    )
            out.extend((frame_peers[fi], msg) for fi, msg in parsed)
        else:
            for peer, frame in zip(frame_peers, frames):
                try:
                    out.extend((peer, m) for m in parse_frame(frame))
                except WireError as exc:
                    logger.warning(
                        "bad frame from %s: %s",
                        peer.address if peer is not None else "local",
                        exc,
                    )
        return out

    async def _process_chunk(self, chunk) -> None:
        """Three stages (module docstring): sync pre-checks -> one bulk
        verify -> sync state transitions (re-validated against races).
        Actions carry how many verify items they claimed: a TxBatch puts
        1 (origin) + count (client) signatures into the SAME bulk call."""
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        to_verify = []
        actions = []  # (kind, msg, n_sigs)
        for peer, msg in chunk:
            self._pre_msg(peer, msg, to_verify, actions)
        # admission pre-checks account to rx_decode (receive-side cost)
        if ph is not None:
            t0 = ph.add("rx_decode", t0)
        if not to_verify:
            return
        results = await self.verifier.verify_many(to_verify)
        if ph is not None:
            ph.add("verify_wait", t0)
        self._apply_actions(actions, results)

    def _pre_msg(self, peer, msg, to_verify: list, actions: list) -> None:
        """Stage 1 for ONE message: synchronous admission pre-checks and
        control-message dispatch. Verify-needing messages append their
        signature items to ``to_verify`` and an ``(kind, msg, n_sigs)``
        action; control messages (requests, catchup, directory, config)
        are handled inline and append nothing. The sharded plane calls
        this per message in ARRIVAL order (broadcast/shards.py), the
        monolithic plane from its chunk loop above — identical behavior
        either way."""
        if isinstance(msg, Payload):
            if self._pre_gossip(msg):  # noqa: SIM102 (kept parallel)
                to_verify.append(
                    (msg.sender, msg.to_sign(), msg.signature)
                )
                actions.append((GOSSIP, msg, 1))
        elif isinstance(msg, TxBatch):
            if self._pre_batch(msg):
                to_verify.append(
                    (msg.origin, msg.signing_bytes(), msg.signature)
                )
                entries = msg.entries()
                to_verify.extend(
                    (e.sender, e.to_sign(), e.signature) for e in entries
                )
                actions.append((BATCH, msg, 1 + len(entries)))
        elif isinstance(msg, BatchAttestation):
            if self._pre_batch_attestation(msg, peer):
                to_verify.append((msg.origin, msg.to_sign(), msg.signature))
                actions.append((msg.phase, msg, 1))
        elif isinstance(msg, ContentRequest):
            self._on_request(peer, msg)
        elif isinstance(msg, BatchContentRequest):
            self._on_batch_request(peer, msg)
        elif isinstance(msg, _CATCHUP_KINDS):
            # synchronous handler (service-side bookkeeping / replies
            # via mesh.send); heavy work happens in the service's
            # catchup task, never in this worker
            if self.catchup_handler is not None and peer is not None:
                try:
                    self.catchup_handler(peer, msg)
                except Exception:
                    logger.exception("catchup handler error")
        elif isinstance(msg, DirectoryAnnounce):
            # directory mappings are liveness-only service state
            # (node/directory.py); synchronous apply, bad mappings
            # are dropped by the handler's stride/conflict checks
            if self.directory_handler is not None and peer is not None:
                try:
                    self.directory_handler(peer, msg)
                except Exception:
                    logger.exception("directory handler error")
        elif isinstance(msg, ConfigTx):
            # admin-signed membership transitions (node/membership.py);
            # the handler validates the admin signature and epoch —
            # peer may be None (admin-side local injection)
            if self.config_handler is not None:
                try:
                    self.config_handler(peer, msg)
                except Exception:
                    logger.exception("config handler error")
        elif isinstance(msg, StateBeacon):
            # fleet-audit digests (obs/audit.py); the handler verifies
            # the origin signature — beacon rates are a few per second
            # per peer, so the sync verify never matters for the plane
            if self.beacon_handler is not None:
                try:
                    self.beacon_handler(peer, msg)
                except Exception:
                    logger.exception("beacon handler error")
        elif isinstance(msg, CertSig):
            # finality co-signatures (finality/certs.py); the assembler
            # verifies the scheme signature — same cadence and routing
            # shape as beacons
            if self.cert_handler is not None:
                try:
                    self.cert_handler(peer, msg)
                except Exception:
                    logger.exception("cert handler error")
        else:
            if self._pre_attestation(msg, peer):
                to_verify.append((msg.origin, msg.to_sign(), msg.signature))
                actions.append((msg.phase, msg, 1))

    def _apply_actions(self, actions, results) -> None:
        """Stage 3: walk the action list against the bulk-verify verdicts
        (each action consumed ``n_sigs`` consecutive results) and run the
        state transitions, in action order."""
        idx = 0
        for kind, msg, n_sigs in actions:
            ok = results[idx]
            entry_oks = (
                results[idx + 1 : idx + n_sigs] if kind == BATCH else None
            )
            idx += n_sigs
            self._post_action(kind, msg, ok, entry_oks)

    def _post_action(self, kind, msg, ok, entry_oks) -> None:
        """Stage 3 for ONE verified action: invalid-signature accounting
        or the kind-specific state transition."""
        if not ok:
            self.stats["invalid_sig"] += 1
            if kind == GOSSIP:
                logger.warning(
                    "invalid payload signature for slot (%s, %d)",
                    msg.sender.hex()[:16],
                    msg.sequence,
                )
            elif kind == BATCH:
                logger.warning(
                    "invalid batch origin signature from %s",
                    msg.origin.hex()[:16],
                )
            else:
                logger.warning(
                    "invalid %s signature from %s",
                    {
                        ECHO: "echo",
                        READY: "ready",
                        BATCH_ECHO: "batch-echo",
                        BATCH_READY: "batch-ready",
                    }.get(kind, "attestation"),
                    msg.origin.hex()[:16],
                )
            return
        if kind == GOSSIP:
            self._post_gossip(msg)
        elif kind == BATCH:
            self._post_batch(msg, entry_oks)
        elif kind in (BATCH_ECHO, BATCH_READY):
            self._post_batch_attestation(msg)
        else:
            self._post_attestation(msg)

    # -- stage 1: synchronous pre-checks (dedup inserts happen here, so no
    # other worker can double-verify the same message) --------------------

    def _pre_gossip(self, payload: Payload) -> bool:
        self.stats["gossip_rx"] += 1
        slot = payload.slot
        if slot in self._delivered_slots:
            return False  # already committed and compacted
        # Slot-cap check BEFORE the dedup insert and the verify stage: a
        # valid message dropped at the cap must stay retryable (its
        # deterministic retransmission would otherwise be dedup-suppressed
        # forever), and a message that will be dropped must not spend
        # verifier throughput. Concurrent workers may overshoot the cap by
        # at most the worker pool's chunk capacity — negligible vs the cap.
        if slot not in self._slots and self._undelivered >= MAX_LIVE_SLOTS:
            self.stats["slots_dropped"] += 1
            if self.recorder is not None:
                self.recorder.record("slot_drop", ("gossip", slot[1]))
            return False
        chash = payload.content_hash()
        key = (slot, chash)
        if key in self._gossip_seen:
            return False
        state = self._slots.get(slot)
        if state is not None:
            if chash in state.contents:
                return False
            # Content cap: a byzantine sender must not grow state.contents
            # unboundedly — but a content the network has already voted
            # toward quorum for is always admitted, or an equivocator
            # could fill the cap with junk contents and permanently block
            # the quorate payload (incl. the pull-based catch-up path).
            # NOTE: cap rejections deliberately do NOT enter _gossip_seen,
            # so a retransmission after the content becomes quorate (or
            # after GC) is processed, not dedup-suppressed.
            if (
                len(state.contents) >= MAX_CONTENTS_PER_SLOT
                and not self._content_wanted(state, chash)
            ):
                return False
        self._gossip_seen.add(key)
        return True

    def _content_wanted(self, state: _SlotState, chash: bytes) -> bool:
        """A content with quorum-level votes is stored regardless of the
        per-slot content cap (it may be the only deliverable content)."""
        return (
            len(state.readies.get(chash, ())) >= max(self.ready_threshold, 1)
            or len(state.echoes.get(chash, ())) >= max(self.echo_threshold, 1)
        )

    def _pre_attestation(
        self, att: Attestation, peer: Optional[Peer] = None
    ) -> bool:
        phase_key = "echo_rx" if att.phase == ECHO else "ready_rx"
        self.stats[phase_key] += 1
        if att.origin not in self.mesh.by_sign:
            logger.warning(
                "attestation from unknown origin %s", att.origin.hex()[:16]
            )
            return False
        slot = (att.sender, att.sequence)
        if slot in self._delivered_slots:
            return False
        # Slot-cap check before dedup/verify — same rationale as gossip:
        # capacity drops must not poison the dedup set or burn verifier time.
        if slot not in self._slots and self._undelivered >= MAX_LIVE_SLOTS:
            self.stats["slots_dropped"] += 1
            if self.recorder is not None:
                self.recorder.record("slot_drop", ("attestation", slot[1]))
            return False
        # Exact-duplicate suppression keyed INCLUDING the signature, so a
        # forged message can never shadow the origin's real (differently
        # signed) vote; per-origin single-vote enforcement happens after
        # verification via *_by_origin below.
        seen_key = (att.phase, att.origin, slot, att.content_hash, att.signature)
        if seen_key in self._attest_seen:
            # A DUPLICATE attestation for a slot we already delivered is
            # a straggler's retransmission beacon (_retransmit_slot): its
            # sender is stalled, and our vote may be the very one its
            # loss took out — we stopped retransmitting when we
            # delivered. Answer with our content + own attestations
            # (paced; fresh late attestations don't trigger this).
            state = self._slots.get(slot)
            if state is not None and state.delivered:
                self._help_straggler(peer, slot, state)
            return False
        self._attest_seen.add(seen_key)
        state = self._slots.get(slot)
        if state is not None:
            by_origin = (
                state.echo_by_origin if att.phase == ECHO else state.ready_by_origin
            )
            if att.origin in by_origin:
                return False  # this origin already cast a verified vote here
        return True

    # -- stage 3: synchronous state transitions (post-verify; every check
    # that another worker could have raced during the verify await is
    # re-validated here) ---------------------------------------------------

    def _post_gossip(self, payload: Payload) -> None:
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        slot = payload.slot
        if slot in self._delivered_slots:
            return
        chash = payload.content_hash()
        state = self._new_or_existing_slot(slot)
        if chash in state.contents:
            return
        if (
            len(state.contents) >= MAX_CONTENTS_PER_SLOT
            and not self._content_wanted(state, chash)
        ):
            # Another worker filled the slot to the cap during the verify
            # await. Un-poison the dedup set: _pre_gossip's NOTE promises
            # cap rejections stay retryable, so a later retransmission (or
            # the content-pull catch-up response, should this hash become
            # the quorate one) must be processed, not dedup-suppressed.
            self._gossip_seen.discard((slot, chash))
            return
        state.contents[chash] = payload
        # murmur: relay to everyone (gossip_size = full network)
        self.mesh.broadcast(payload.encode())
        # sieve: echo only the FIRST content seen for this slot — and only
        # if the cross-plane entry registry agrees (a conflicting content
        # for this (sender, seq) may already be bound via a BATCH entry;
        # endorsing both here and there would let two intersecting quorums
        # form for different contents — module docstring)
        if state.echoed_hash is None:
            body = payload.encode()[1:]
            bound = self._entry_registry.get(slot)
            if bound is None or bound == body:
                if bound is None:
                    self._entry_registry.put(slot, body)
                state.echoed_hash = chash
                if self.trace is not None:
                    self.trace.stamp(slot, "echoed")
                if self.recorder is not None:
                    self.recorder.record("echo", (payload.sequence,))
                self._send_attestation(
                    ECHO, payload.sender, payload.sequence, chash
                )
                if self.overlap_ready and not state.ready_sent:
                    # [wan] phase piggyback: bind and send the Ready in
                    # the same frame as the Echo (mesh coalescing packs
                    # both into one wire frame), collapsing the serial
                    # echo-quorum round trip out of the critical path
                    state.ready_sent = True
                    state.ready_hash = chash
                    self._send_attestation(
                        READY, payload.sender, payload.sequence, chash
                    )
        if ph is not None:
            t0 = ph.add("echo_apply", t0)
        self._advance(slot, state, chash)
        if ph is not None:
            ph.add("ready_deliver", t0)

    def _post_attestation(self, att: Attestation) -> None:
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        slot = (att.sender, att.sequence)
        if slot in self._delivered_slots:
            return
        state = self._new_or_existing_slot(slot)
        by_origin = (
            state.echo_by_origin if att.phase == ECHO else state.ready_by_origin
        )
        if att.origin in by_origin:
            return
        by_origin[att.origin] = att.content_hash
        votes = state.echoes if att.phase == ECHO else state.readies
        votes[att.content_hash].add(att.origin)
        if ph is not None:
            t0 = ph.add("quorum_bitmap", t0)
        self._advance(slot, state, att.content_hash)
        if ph is not None:
            ph.add("ready_deliver", t0)

    def _on_request(self, peer: Optional[Peer], req: ContentRequest) -> None:
        """Serve a peer's content pull (no verify: channel-authenticated)."""
        self.stats["content_req_rx"] += 1
        if peer is None:
            return  # requests only make sense from the wire
        state = self._slots.get((req.sender, req.sequence))
        if state is None:
            return  # unknown or already compacted; best-effort
        payload = state.contents.get(req.content_hash)
        if payload is not None:
            self.stats["content_served"] += 1
            self.mesh.send(peer, payload.encode())

    def _request_content(self, slot: Slot, state: _SlotState, chash: bytes) -> None:
        """Pull a ready-quorate slot's missing payload from its Ready voters
        (they either hold the content or know who gossiped it; falls back to
        all peers when no voter maps to a known peer)."""
        now = self.clock.monotonic()
        if now - state.content_requested_at < REQUEST_RETRY:
            return
        state.content_requested_at = now
        self.stats["content_req_tx"] += 1
        frame = ContentRequest(slot[0], slot[1], chash).encode()
        targets = [
            self.mesh.by_sign[origin]
            for origin in state.readies.get(chash, ())
            if origin in self.mesh.by_sign
        ]
        if targets:
            for peer in targets:
                self.mesh.send(peer, frame)
        else:
            self.mesh.broadcast(frame)

    def _new_or_existing_slot(self, slot: Slot) -> _SlotState:
        state = self._slots.get(slot)
        if state is None:
            state = self._slots[slot] = _SlotState(self.clock.monotonic())
            state.birth = next(self._birth_seq)
            self._undelivered += 1
        return state

    # -- batched plane (module docstring) ---------------------------------

    def release_entry(self, sender: bytes, sequence: int) -> None:
        """Drop the (sender, seq) -> content equivocation binding once the
        sequence has passed the LEDGER gate (the service's commit loop
        calls this). Safe because the per-account sequence gate now
        rejects ANY content for this sequence — committed or conflicting
        — so the registry's job for the slot is done. Eager release keeps
        the registry's working set proportional to in-flight
        (uncommitted) entries instead of all-time traffic, which is what
        makes the FIFO cap a dead-man's valve rather than a live
        eviction path (see the construction-site comment)."""
        self._entry_registry.pop((sender, sequence))

    def _new_or_existing_batch_slot(self, slot) -> _BatchState:
        state = self._batch_slots.get(slot)
        if state is None:
            state = self._batch_slots[slot] = _BatchState(self.clock.monotonic())
            state.birth = next(self._birth_seq)
            self._undelivered += 1
        return state

    def _pre_batch(self, batch: TxBatch) -> bool:
        self.stats["batch_rx"] += 1
        # batch slots exist only under KNOWN node identities (peers or
        # self) — an unauthenticated key cannot open batch slots at all
        if (
            batch.origin not in self.mesh.by_sign
            and batch.origin != self.keypair.public
        ):
            logger.warning(
                "batch from unknown origin %s", batch.origin.hex()[:16]
            )
            return False
        slot = batch.slot
        if slot in self._delivered_batch_slots:
            return False
        if slot not in self._batch_slots and self._undelivered >= MAX_LIVE_SLOTS:
            self.stats["slots_dropped"] += 1
            return False
        chash = batch.content_hash()
        key = (BATCH, slot, chash)  # distinct key-space from per-tx gossip
        if key in self._gossip_seen:
            return False
        state = self._batch_slots.get(slot)
        if state is not None:
            if chash in state.contents:
                return False
            # same cap/NOTE discipline as _pre_gossip: capacity rejections
            # stay retryable, quorate content is always admitted
            if (
                len(state.contents) >= MAX_CONTENTS_PER_SLOT
                and not self._batch_content_wanted(state, chash)
            ):
                return False
        self._gossip_seen.add(key)
        return True

    def _batch_content_wanted(self, state: _BatchState, chash: bytes) -> bool:
        rv = state.ready_votes.get(chash)
        if rv is not None and len(rv.by_origin) >= max(self.ready_threshold, 1):
            return True
        ev = state.echo_votes.get(chash)
        return ev is not None and len(ev.by_origin) >= max(self.echo_threshold, 1)

    def _pre_batch_attestation(
        self, att: BatchAttestation, peer: Optional[Peer] = None
    ) -> bool:
        key = "batch_echo_rx" if att.phase == BATCH_ECHO else "batch_ready_rx"
        self.stats[key] += 1
        if att.origin not in self.mesh.by_sign:
            logger.warning(
                "batch attestation from unknown origin %s",
                att.origin.hex()[:16],
            )
            return False
        if len(att.bitmap) > MAX_BITMAP_BYTES or not att.bitmap:
            return False
        slot = (att.batch_origin, att.batch_seq)
        if slot in self._delivered_batch_slots:
            return False
        if slot not in self._batch_slots and self._undelivered >= MAX_LIVE_SLOTS:
            self.stats["slots_dropped"] += 1
            return False
        seen_key = (
            att.phase, att.origin, slot, att.batch_hash, att.bitmap,
            att.signature,
        )
        if seen_key in self._attest_seen:
            # duplicate on a fully-delivered (or retired — resolved is
            # resolved) batch slot: straggler retransmission beacon —
            # help (see _pre_attestation)
            dstate = self._batch_slots.get(slot)
            if dstate is not None and (
                dstate.delivered_all or dstate.retired
            ):
                self._help_batch_straggler(peer, slot, dstate)
            return False
        self._attest_seen.add(seen_key)
        state = self._batch_slots.get(slot)
        if state is not None:
            by_origin = (
                state.echo_by_origin
                if att.phase == BATCH_ECHO
                else state.ready_by_origin
            )
            bound = by_origin.get(att.origin)
            if bound is not None and bound != att.batch_hash:
                return False  # origin already voted for a different content
            # monotone bitmaps: a subset of already-counted bits is noise;
            # don't spend a verify on it
            votes = (
                state.echo_votes
                if att.phase == BATCH_ECHO
                else state.ready_votes
            ).get(att.batch_hash)
            if votes is not None:
                old = votes.by_origin.get(att.origin, 0)
                if int.from_bytes(att.bitmap, "little") & ~old == 0:
                    return False
        return True

    def _post_batch(self, batch: TxBatch, entry_oks) -> None:
        # phase segments are chained (each add() returns the next t0) so
        # echo_apply / entry_registry / ready_deliver stay disjoint —
        # their sum never double-counts a nanosecond of this call
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        slot = batch.slot
        if slot in self._delivered_batch_slots:
            return
        chash = batch.content_hash()
        state = self._new_or_existing_batch_slot(slot)
        if chash in state.contents:
            return
        if (
            len(state.contents) >= MAX_CONTENTS_PER_SLOT
            and not self._batch_content_wanted(state, chash)
        ):
            self._gossip_seen.discard((BATCH, slot, chash))
            return
        state.contents[chash] = batch
        # the real entry count is now known: CLAMP nbits to the widest
        # known content rather than only growing it — oversized
        # attestation bitmaps received before any content landed must not
        # leave phantom entry positions behind (positions >= count can
        # never deliver, but could spuriously quorate and trigger content
        # pulls forever — ADVICE.md stack.py:1199)
        state.nbits = max(b.count for b in state.contents.values())
        # murmur: relay the batch to everyone
        self.mesh.broadcast(batch.encode())
        # sieve, batched: echo only the FIRST batch content for this slot,
        # endorsing exactly the entries whose client signature verified
        # AND whose (sender, seq) registry binding is unbound-or-equal
        if state.echoed_hash is None:
            state.echoed_hash = chash
            bits = 0
            rejected = 0
            if ph is not None:
                t0 = ph.add("echo_apply", t0)
            for i, ok in enumerate(entry_oks):
                if not ok:
                    self.stats["invalid_sig"] += 1
                    rejected |= 1 << i  # locally RESOLVED: rejected
                    continue
                entry = batch.entry_bytes(i)
                ekey = (entry[:32], int.from_bytes(entry[32:36], "little"))
                bound = self._entry_registry.get(ekey)
                if bound is None:
                    self._entry_registry.put(ekey, entry)
                elif bound != entry:
                    # conflicting content already endorsed: resolved too
                    rejected |= 1 << i
                    continue
                bits |= 1 << i
                if self.trace is not None:
                    self.trace.stamp(ekey, "echoed")
            if ph is not None:
                t0 = ph.add("entry_registry", t0)
            state.own_echo_bits[chash] = bits
            state.rejected_bits[chash] = rejected
            if self.recorder is not None:
                self.recorder.record(
                    "batch_echo",
                    (slot[1], bits.bit_count(), rejected.bit_count()),
                )
            if bits:
                self._send_batch_attestation(
                    BATCH_ECHO, slot, chash, bits, batch.count
                )
                if self.overlap_ready and state.ready_hash is None:
                    # [wan] phase piggyback, batched plane: bind the
                    # slot's single Ready hash now and ready exactly the
                    # entries just echoed; _advance_batch later tops up
                    # ready_sent_bits cumulatively as more entries
                    # quorate (to_ready masks off these initial bits)
                    state.ready_hash = chash
                    state.ready_sent_bits |= bits
                    if self.trace is not None:
                        self._stamp_batch_marker(batch, bits, "ready_sent")
                    self._send_batch_attestation(
                        BATCH_READY, slot, chash, bits, batch.count
                    )
        if ph is not None:
            t0 = ph.add("echo_apply", t0)
        self._advance_batch(slot, state, chash)
        self._maybe_retire_batch(slot, state)
        if ph is not None:
            ph.add("ready_deliver", t0)

    def _post_batch_attestation(self, att: BatchAttestation) -> None:
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        slot = (att.batch_origin, att.batch_seq)
        if slot in self._delivered_batch_slots:
            return
        state = self._new_or_existing_batch_slot(slot)
        by_origin = (
            state.echo_by_origin
            if att.phase == BATCH_ECHO
            else state.ready_by_origin
        )
        bound = by_origin.get(att.origin)
        if bound is not None and bound != att.batch_hash:
            return
        by_origin[att.origin] = att.batch_hash
        votes_map = (
            state.echo_votes if att.phase == BATCH_ECHO else state.ready_votes
        )
        votes = votes_map.get(att.batch_hash)
        if votes is None:
            votes = votes_map[att.batch_hash] = _BatchVotes()
        nbits = len(att.bitmap) * 8
        bits = int.from_bytes(att.bitmap, "little")
        if state.contents:
            # Clamp the claimed width to the batch's REAL entry count once
            # any slot content is known: bits at positions >= count are
            # phantom — they can never deliver, and without the clamp they
            # inflate state.nbits and the vote counts, spuriously quorate,
            # and drive pointless content pulls (ADVICE.md stack.py:1199).
            known = state.contents.get(att.batch_hash)
            count = (
                known.count
                if known is not None
                else max(b.count for b in state.contents.values())
            )
            if nbits > count:
                nbits = count
                bits &= (1 << count) - 1
                if not bits:
                    return
        if votes.add(att.origin, bits, nbits):
            state.nbits = max(state.nbits, nbits)
            if ph is not None:
                t0 = ph.add("quorum_bitmap", t0)
            self._advance_batch(slot, state, att.batch_hash)
            self._maybe_retire_batch(slot, state)
            if ph is not None:
                ph.add("ready_deliver", t0)
        elif ph is not None:
            ph.add("quorum_bitmap", t0)

    def _send_batch_attestation(
        self,
        phase: int,
        slot,
        chash: bytes,
        bits: int,
        nbits: int,
        peer: Optional[Peer] = None,
    ) -> None:
        """Sign and send our batch Echo/Ready — broadcast by default,
        targeted when ``peer`` is given (straggler help)."""
        floor = self._floor_batch.get(slot[0])
        if floor is not None and slot[1] <= floor:
            # same no-post-restart-equivocation discipline as the per-tx
            # plane (_send_attestation); batch_seq is time-seeded per
            # origin so fresh batches always clear a restored floor
            self.floor_refusals += 1
            return
        if slot[1] > self._wm_batch.get(slot[0], 0):
            self._wm_batch[slot[0]] = slot[1]
        bitmap = bits.to_bytes((nbits + 7) // 8, "little")
        sig = self.keypair.sign(
            BatchAttestation.signing_bytes(phase, slot[0], slot[1], chash, bitmap)
        )
        if self.on_attest is not None:
            self.on_attest(phase, slot[0], slot[1], chash)
        att = BatchAttestation(
            phase, self.keypair.public, slot[0], slot[1], chash, bitmap, sig
        )
        if self.recorder is not None:
            self.recorder.record(
                "tx", (phase, slot[1], 1 if peer is not None else 0)
            )
        if peer is not None:
            self.mesh.send(peer, att.encode())
        else:
            self.mesh.broadcast(att.encode())

    def _stamp_batch_marker(self, batch: TxBatch, bits: int, stage: str) -> None:
        """Stamp an order-free phase marker (obs/trace.py PHASE_MARKERS)
        on every set-bit entry of ``batch`` — unsampled keys cost one
        dict miss each."""
        entries = batch.entries()
        while bits:
            lsb = bits & -bits
            p = entries[lsb.bit_length() - 1]
            self.trace.stamp((p.sender, p.sequence), stage)
            bits ^= lsb

    def _advance_batch(self, slot, state: _BatchState, chash: bytes) -> None:
        """Drive per-entry phase transitions for one batch content."""
        batch = state.contents.get(chash)
        nbits = batch.count if batch is not None else state.nbits
        if nbits <= 0:
            return
        full = (1 << nbits) - 1
        ev = state.echo_votes.get(chash)
        rv = state.ready_votes.get(chash)
        # Degenerate thresholds (standalone node / explicit 0): no peer
        # quorum exists to carry the verification argument, so the gate
        # is this node's OWN endorsement bits — a full mask here would
        # deliver entries whose client signature FAILED (the per-tx
        # plane drops those at the verify stage; parity demands we do
        # too).
        if self.echo_threshold <= 0:
            echo_q = state.own_echo_bits.get(chash, 0)
        else:
            echo_q = _quorate_mask(
                ev.counts if ev is not None else _EMPTY_COUNTS,
                self.echo_threshold,
                nbits,
            )
        if self.ready_threshold <= 0:
            ready_q = echo_q
        else:
            ready_q = _quorate_mask(
                rv.counts if rv is not None else _EMPTY_COUNTS,
                self.ready_threshold,
                nbits,
            )
        # Ready an entry on its Echo quorum (sieve-deliver) OR on a full
        # Ready quorum (contagion amplification) — cumulative bitmap so a
        # late joiner always receives a superset of earlier attestations.
        # Slot-level binding (per-tx parity, _SlotState.ready_sent): this
        # node signs Ready for at most ONE content per slot — an honest
        # node must never be wire-indistinguishable from an equivocator.
        if self.trace is not None and batch is not None:
            new_eq = echo_q & ~state.echo_q_marked & full
            if new_eq:
                state.echo_q_marked |= new_eq
                self._stamp_batch_marker(batch, new_eq, "echo_quorum")
        wants_ready = (echo_q | ready_q) & full
        if state.ready_hash is None and wants_ready:
            state.ready_hash = chash
        if state.ready_hash == chash:
            to_ready = wants_ready & ~state.ready_sent_bits
            if to_ready:
                state.ready_sent_bits |= to_ready
                if self.trace is not None and batch is not None:
                    self._stamp_batch_marker(batch, to_ready, "ready_sent")
                self._send_batch_attestation(
                    BATCH_READY, slot, chash, state.ready_sent_bits, nbits
                )
        # deliver: entry-level Ready quorum, this node has cast its Ready
        # for the slot (per-tx parity: `... and state.ready_sent` — the
        # quorum needn't be for OUR content, amplification covers that),
        # content known, not yet delivered
        if state.ready_hash is None:
            return
        deliverable = ready_q & ~state.delivered_bits.get(chash, 0) & full
        if not deliverable:
            return
        if batch is None:
            # quorate but the gossip never landed here: pull the batch
            self._request_batch_content(slot, state, chash)
            return
        state.delivered_bits[chash] = (
            state.delivered_bits.get(chash, 0) | deliverable
        )
        if self.recorder is not None:
            # quorum edge: these entries just crossed their Ready quorum
            # (on the batched plane that IS the delivery condition)
            self.recorder.record(
                "batch_deliver", (slot[1], deliverable.bit_count())
            )
        entries = batch.entries()
        d = deliverable
        while d:
            lsb = d & -d
            i = lsb.bit_length() - 1
            p = entries[i]
            if self.trace is not None:
                # on the batched plane an entry's Ready quorum IS its
                # delivery condition, so the two stamps coincide here
                self.trace.stamp((p.sender, p.sequence), "ready_quorum")
                self.trace.stamp((p.sender, p.sequence), "delivered")
            self.delivered.put_nowait(p)
            self.stats["batch_entries_delivered"] += 1
            d ^= lsb
        if state.delivered_bits[chash] == (1 << batch.count) - 1:
            if not state.delivered_all:
                state.delivered_all = True
                # a retired slot already left the undelivered population
                if not state.retired:
                    self._undelivered -= 1
                self.stats["delivered"] += 1

    def _ready_quorate_bits(
        self, state: _BatchState, chash: bytes, nbits: int
    ) -> int:
        """Entries of ``chash`` holding a full Ready quorum — the
        deliverable set, mirroring _advance_batch's degenerate-threshold
        handling (thresholds <= 0 fall back to echo quorum / own bits)."""
        if self.ready_threshold <= 0:
            if self.echo_threshold <= 0:
                return state.own_echo_bits.get(chash, 0)
            ev = state.echo_votes.get(chash)
            return _quorate_mask(
                ev.counts if ev is not None else _EMPTY_COUNTS,
                self.echo_threshold,
                nbits,
            )
        rv = state.ready_votes.get(chash)
        return _quorate_mask(
            rv.counts if rv is not None else _EMPTY_COUNTS,
            self.ready_threshold,
            nbits,
        )

    def _maybe_retire_batch(self, slot, state: _BatchState) -> None:
        """Retire a batch slot that is complete-by-RESOLUTION: every
        ready-quorate entry is delivered and every remaining entry of the
        echoed content is locally resolved-rejected (invalid client
        signature or equivocation-registry conflict at echo time).

        Without retirement, a single never-deliverable poison entry held
        the slot "stalled" for SLOT_MAX_AGE — burning retransmission
        budget and firing network-wide stall kicks every GC pass (the
        byzantine amplification in ADVICE.md stack.py:1296). A retired
        slot leaves the undelivered population immediately and compacts
        after DELIVERED_RETENTION like a delivered one. Retirement does
        NOT gate delivery: while the slot is retained, a late Ready
        quorum for a rejected entry still delivers it through
        _advance_batch (our local rejection is not the network's
        verdict); after compaction, recovery belongs to the ledger
        catchup plane — the same contract as any expired slot."""
        if state.delivered_all or state.retired:
            return
        chash = state.echoed_hash
        if chash is None:
            return  # no content echoed yet: nothing is resolved
        batch = state.contents.get(chash)
        if batch is None:
            return
        full = (1 << batch.count) - 1
        delivered = state.delivered_bits.get(chash, 0)
        rejected = state.rejected_bits.get(chash, 0)
        if (delivered | rejected) & full != full:
            return  # unresolved entries remain: genuinely in progress
        # every ready-quorate entry — on ANY content with votes, not just
        # the echoed one (an equivocating origin's sibling content could
        # quorate if enough peers echoed it first) — must be delivered
        for h in set(state.ready_votes) | {chash}:
            b = state.contents.get(h)
            nb = b.count if b is not None else state.nbits
            if self._ready_quorate_bits(
                state, h, nb
            ) & ~state.delivered_bits.get(h, 0):
                return
        state.retired = True
        self._undelivered -= 1
        self.stats["slots_retired"] += 1
        poison = rejected & ~delivered
        self.stats["poison_resolved"] += poison.bit_count()
        if self.recorder is not None:
            self.recorder.record(
                "slot_retire", (slot[1], poison.bit_count())
            )

    def _poison_blocked_only(self, state: _BatchState) -> bool:
        """True when every undelivered entry is one this node rejected at
        echo time and nothing quorate is missing: the network never
        endorsed the poison, so a catchup session cannot heal the slot
        and the stall signal must not fire for it. (Such a slot is
        normally retired by _maybe_retire_batch; this guards the GC's
        stall classification in the window before retirement settles.)"""
        chash = state.echoed_hash
        if chash is None:
            return False
        batch = state.contents.get(chash)
        if batch is None:
            return False
        full = (1 << batch.count) - 1
        undelivered = full & ~state.delivered_bits.get(chash, 0)
        if undelivered & ~state.rejected_bits.get(chash, 0):
            return False  # an unresolved entry genuinely awaits quorum
        for h in set(state.ready_votes) | {chash}:
            b = state.contents.get(h)
            nb = b.count if b is not None else state.nbits
            if self._ready_quorate_bits(
                state, h, nb
            ) & ~state.delivered_bits.get(h, 0):
                return False
        return True

    def _on_batch_request(
        self, peer: Optional[Peer], req: BatchContentRequest
    ) -> None:
        """Serve a peer's batch content pull (channel-authenticated)."""
        self.stats["content_req_rx"] += 1
        if peer is None:
            return
        state = self._batch_slots.get((req.batch_origin, req.batch_seq))
        if state is None:
            return
        batch = state.contents.get(req.batch_hash)
        if batch is not None:
            self.stats["content_served"] += 1
            self.mesh.send(peer, batch.encode())

    def _request_batch_content(
        self, slot, state: _BatchState, chash: bytes
    ) -> None:
        now = self.clock.monotonic()
        if now - state.content_requested_at < REQUEST_RETRY:
            return
        state.content_requested_at = now
        self.stats["content_req_tx"] += 1
        frame = BatchContentRequest(slot[0], slot[1], chash).encode()
        rv = state.ready_votes.get(chash)
        targets = [
            self.mesh.by_sign[origin]
            for origin in (rv.by_origin if rv is not None else ())
            if origin in self.mesh.by_sign
        ]
        if targets:
            for peer in targets:
                self.mesh.send(peer, frame)
        else:
            self.mesh.broadcast(frame)

    # -- durability (store manifest round-trip, at2_node_tpu/store/) ------

    def export_watermarks(self) -> dict:
        """Per-origin max-attested slots, both planes — persisted in the
        store manifest on every flush."""
        return {
            "tx": {k.hex(): v for k, v in self._wm_tx.items()},
            "batch": {k.hex(): v for k, v in self._wm_batch.items()},
        }

    def restore_watermarks(self, doc: dict) -> None:
        """Install pre-crash watermarks as signing floors (and re-seed
        the live watermarks so the next flush persists at least them)."""
        for hx, seq in (doc.get("tx") or {}).items():
            key = bytes.fromhex(hx)
            self._floor_tx[key] = int(seq)
            self._wm_tx[key] = max(self._wm_tx.get(key, 0), int(seq))
        for hx, seq in (doc.get("batch") or {}).items():
            key = bytes.fromhex(hx)
            self._floor_batch[key] = int(seq)
            self._wm_batch[key] = max(self._wm_batch.get(key, 0), int(seq))

    # -- state transitions (synchronous; no awaits) -----------------------

    def _send_attestation(
        self,
        phase: int,
        sender: bytes,
        sequence: int,
        chash: bytes,
        peer: Optional[Peer] = None,
    ) -> None:
        """Sign and send our Echo/Ready — broadcast by default, targeted
        when ``peer`` is given (straggler help)."""
        floor = self._floor_tx.get(sender)
        if floor is not None and sequence <= floor:
            # no-post-restart-equivocation: this slot may hold a
            # pre-crash vote from this node that peers already counted;
            # signing again (possibly for different content) is the one
            # thing a restarted node must never do
            self.floor_refusals += 1
            return
        if sequence > self._wm_tx.get(sender, 0):
            self._wm_tx[sender] = sequence
        sig = self.keypair.sign(Attestation.signing_bytes(phase, sender, sequence, chash))
        if self.on_attest is not None:
            self.on_attest(phase, sender, sequence, chash)
        if phase == READY and self.trace is not None:
            # order-free phase marker (obs/trace.py PHASE_MARKERS): with
            # overlap_ready this lands BEFORE echo_quorum
            self.trace.stamp((sender, sequence), "ready_sent")
        att = Attestation(phase, self.keypair.public, sender, sequence, chash, sig)
        if self.recorder is not None:
            self.recorder.record(
                "tx", (phase, sequence, 1 if peer is not None else 0)
            )
        if peer is not None:
            self.mesh.send(peer, att.encode())
        else:
            self.mesh.broadcast(att.encode())

    def _advance(self, slot: Slot, state: _SlotState, chash: bytes) -> None:
        """Drive the slot's phase transitions for one content hash."""
        if state.delivered:
            return
        # sieve-deliver: enough echoes for this content (quorum-driven; the
        # per-origin single-vote rule above makes two quorums impossible
        # whenever echo_threshold > n_peers/2)
        if (
            not state.sieve_delivered
            and len(state.echoes[chash]) >= self.echo_threshold
        ):
            state.sieve_delivered = True
            if self.trace is not None:
                self.trace.stamp(slot, "echo_quorum")
            if self.recorder is not None:
                self.recorder.record("echo_quorum", (slot[1],))
            if not state.ready_sent:
                state.ready_sent = True
                state.ready_hash = chash
                self._send_attestation(READY, slot[0], slot[1], chash)
        # contagion amplification: a full Ready quorum convinces a node
        # that missed the Echo phase to join (keeps delivery total)
        if (
            not state.ready_sent
            and len(state.readies[chash]) >= max(self.ready_threshold, 1)
        ):
            state.ready_sent = True
            state.ready_hash = chash
            self._send_attestation(READY, slot[0], slot[1], chash)
        # deliver: enough readies AND the payload content is known
        if len(state.readies[chash]) >= self.ready_threshold and state.ready_sent:
            if self.trace is not None:
                # slot IS the tracer key (sender, sequence)
                self.trace.stamp(slot, "ready_quorum")
            if chash in state.contents:
                state.delivered = True
                self._undelivered -= 1
                self.stats["delivered"] += 1
                if self.trace is not None:
                    self.trace.stamp(slot, "delivered")
                if self.recorder is not None:
                    self.recorder.record("ready_quorum", (slot[1],))
                self.delivered.put_nowait(state.contents[chash])
            else:
                # quorum reached but the gossip never landed here: pull the
                # payload from the voters (totality catch-up)
                self._request_content(slot, state, chash)
