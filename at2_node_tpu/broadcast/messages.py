"""Wire messages of the three-phase broadcast (gossip / Echo / Ready).

The reference gets these from its murmur/sieve/contagion crates
(`/root/reference/technical.md:7-15` [dep-inferred]); here they are
explicit fixed-size binary records so a frame can carry many of them
back-to-back and batches parse with zero framing overhead:

* ``Payload`` — the gossiped unit: the client-signed transfer plus the
  sequence number the broadcast layer binds to it (the reference does the
  same binding via ``sieve::Payload::new(sender, seq, msg, signature)``,
  `/root/reference/src/bin/server/rpc.rs:277-282`).
* ``Attestation`` — an Echo or Ready: a node's signed vote that it saw a
  specific payload content for a given (sender, sequence) slot. Signing
  bytes carry a phase-specific domain tag so an Echo can never be replayed
  as a Ready.

All integers little-endian; keys/signatures raw (types.py's canonical
layout).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass

from ..types import ThinTransaction

GOSSIP = 1
ECHO = 2
READY = 3
REQUEST = 4
# Ledger-history catchup plane (the reference's open "catchup mechanism"
# roadmap item, /root/reference/README.md:53 — see ledger/history.py and
# node/service.py `_catchup_once` for the protocol):
HIST_IDX_REQ = 5  # "send me your commit frontier"
HIST_IDX = 6  # per-sender committed-sequence frontier
HIST_REQ = 7  # "send me sender X's committed payloads in [lo, hi]"
HIST_BATCH = 8  # a batch of committed payloads

_PAYLOAD = struct.Struct("<32sI32sQ64s")  # sender, seq, recipient, amount, sig
_ATTEST = struct.Struct("<32s32sI32s64s")  # origin, sender, seq, hash, sig
_REQUEST = struct.Struct("<32sI32s")  # sender, seq, hash
_HIST_IDX_REQ = struct.Struct("<Q")  # nonce
_HIST_HDR = struct.Struct("<QI")  # nonce, entry count (HIST_IDX / HIST_BATCH)
_HIST_IDX_ENTRY = struct.Struct("<32sI")  # sender, last committed sequence
_HIST_REQ = struct.Struct("<Q32sII")  # nonce, sender, from_seq, to_seq

PAYLOAD_WIRE = 1 + _PAYLOAD.size
ATTEST_WIRE = 1 + _ATTEST.size
REQUEST_WIRE = 1 + _REQUEST.size
HIST_IDX_REQ_WIRE = 1 + _HIST_IDX_REQ.size
HIST_REQ_WIRE = 1 + _HIST_REQ.size
HIST_HDR_WIRE = 1 + _HIST_HDR.size  # variable records: header + entries

# A legitimate frame coalesces at most MAX_BATCH_MSGS = 1024 messages
# (net/peers.py); 4x that is the malformed bound. Bounds the parse
# amplification of frames dense with the 9-byte catchup request (must
# match kMaxMsgsPerFrame in native/at2_ingest.cpp).
MAX_MSGS_PER_FRAME = 4096

_ECHO_TAG = b"at2-node-tpu/echo/v1"
_READY_TAG = b"at2-node-tpu/ready/v1"


class WireError(Exception):
    pass


@dataclass(frozen=True)
class Payload:
    """A transfer in flight: (sender, sequence) slot + signed content."""

    sender: bytes
    sequence: int
    transaction: ThinTransaction
    signature: bytes  # client's ed25519 over transaction.signing_bytes()

    @property
    def slot(self) -> tuple:
        return (self.sender, self.sequence)

    def encode(self) -> bytes:
        return bytes([GOSSIP]) + _PAYLOAD.pack(
            self.sender,
            self.sequence,
            self.transaction.recipient,
            self.transaction.amount,
            self.signature,
        )

    def content_hash(self) -> bytes:
        """Identifies the payload *content* within its slot — what Echo and
        Ready votes attest to (sieve's equivocation unit). Cached: the
        broadcast pipeline consults it several times per message."""
        cached = self.__dict__.get("_chash")
        if cached is None:
            cached = hashlib.sha256(
                _PAYLOAD.pack(
                    self.sender,
                    self.sequence,
                    self.transaction.recipient,
                    self.transaction.amount,
                    self.signature,
                )
            ).digest()
            object.__setattr__(self, "_chash", cached)
        return cached

    @staticmethod
    def decode_body(body: bytes) -> "Payload":
        sender, seq, recipient, amount, sig = _PAYLOAD.unpack(body)
        return Payload(sender, seq, ThinTransaction(recipient, amount), sig)


@dataclass(frozen=True)
class Attestation:
    """An Echo (phase=ECHO) or Ready (phase=READY) vote."""

    phase: int
    origin: bytes  # ed25519 sign key of the attesting node
    sender: bytes
    sequence: int
    content_hash: bytes
    signature: bytes

    @staticmethod
    def signing_bytes(
        phase: int, sender: bytes, sequence: int, content_hash: bytes
    ) -> bytes:
        tag = _ECHO_TAG if phase == ECHO else _READY_TAG
        return tag + sender + struct.pack("<I", sequence) + content_hash

    def to_sign(self) -> bytes:
        return self.signing_bytes(
            self.phase, self.sender, self.sequence, self.content_hash
        )

    def encode(self) -> bytes:
        return bytes([self.phase]) + _ATTEST.pack(
            self.origin, self.sender, self.sequence, self.content_hash, self.signature
        )

    @staticmethod
    def decode_body(phase: int, body: bytes) -> "Attestation":
        origin, sender, seq, chash, sig = _ATTEST.unpack(body)
        return Attestation(phase, origin, sender, seq, chash, sig)


@dataclass(frozen=True)
class ContentRequest:
    """Pull request for a payload whose Ready quorum was observed but whose
    gossip never arrived (contagion totality catch-up — the reference left
    this as the open "catchup mechanism" roadmap item,
    `/root/reference/README.md:53`). Carries no signature: requests are
    only ever accepted over the mesh's authenticated channels, so the
    transport identifies the requester."""

    sender: bytes
    sequence: int
    content_hash: bytes

    def encode(self) -> bytes:
        return bytes([REQUEST]) + _REQUEST.pack(
            self.sender, self.sequence, self.content_hash
        )

    @staticmethod
    def decode_body(body: bytes) -> "ContentRequest":
        sender, seq, chash = _REQUEST.unpack(body)
        return ContentRequest(sender, seq, chash)


@dataclass(frozen=True)
class HistoryIndexRequest:
    """Ask a peer for its commit frontier (first step of a catchup
    session). ``nonce`` ties responses to the requesting session; like
    ContentRequest, unsigned — accepted only over authenticated channels."""

    nonce: int

    def encode(self) -> bytes:
        return bytes([HIST_IDX_REQ]) + _HIST_IDX_REQ.pack(self.nonce)

    @staticmethod
    def decode_body(body: bytes) -> "HistoryIndexRequest":
        (nonce,) = _HIST_IDX_REQ.unpack(body)
        return HistoryIndexRequest(nonce)


@dataclass(frozen=True)
class HistoryIndex:
    """A peer's commit frontier: (sender, last committed sequence) pairs.
    Variable length: header carries the entry count."""

    nonce: int
    entries: tuple  # of (sender: bytes, last_seq: int)

    def encode(self) -> bytes:
        parts = [
            bytes([HIST_IDX]),
            _HIST_HDR.pack(self.nonce, len(self.entries)),
        ]
        parts.extend(
            _HIST_IDX_ENTRY.pack(sender, seq) for sender, seq in self.entries
        )
        return b"".join(parts)

    @staticmethod
    def decode_body(nonce: int, body: bytes) -> "HistoryIndex":
        n = len(body) // _HIST_IDX_ENTRY.size
        entries = tuple(
            _HIST_IDX_ENTRY.unpack_from(body, i * _HIST_IDX_ENTRY.size)
            for i in range(n)
        )
        return HistoryIndex(nonce, entries)


@dataclass(frozen=True)
class HistoryRequest:
    """Pull a sender's committed payloads for sequences [from_seq, to_seq]
    (inclusive); the server clamps the range (see ledger/history.py)."""

    nonce: int
    sender: bytes
    from_seq: int
    to_seq: int

    def encode(self) -> bytes:
        return bytes([HIST_REQ]) + _HIST_REQ.pack(
            self.nonce, self.sender, self.from_seq, self.to_seq
        )

    @staticmethod
    def decode_body(body: bytes) -> "HistoryRequest":
        nonce, sender, lo, hi = _HIST_REQ.unpack(body)
        return HistoryRequest(nonce, sender, lo, hi)


@dataclass(frozen=True)
class HistoryBatch:
    """Committed payloads served from a peer's history store. The
    receiving catchup session trusts NO single peer: a slot is applied
    only once `catchup quorum` peers returned the same content hash AND
    the client signature verifies (node/service.py `_catchup_once`)."""

    nonce: int
    payloads: tuple  # of Payload

    def encode(self) -> bytes:
        parts = [
            bytes([HIST_BATCH]),
            _HIST_HDR.pack(self.nonce, len(self.payloads)),
        ]
        parts.extend(p.encode()[1:] for p in self.payloads)
        return b"".join(parts)

    @staticmethod
    def decode_body(nonce: int, body: bytes) -> "HistoryBatch":
        n = len(body) // _PAYLOAD.size
        payloads = tuple(
            Payload.decode_body(
                body[i * _PAYLOAD.size : (i + 1) * _PAYLOAD.size]
            )
            for i in range(n)
        )
        return HistoryBatch(nonce, payloads)


def parse_frame(frame: bytes) -> list:
    """Split a frame into messages (frames may coalesce many)."""
    out = []
    view = memoryview(frame)
    while view:
        if len(out) >= MAX_MSGS_PER_FRAME:
            raise WireError("frame exceeds message cap")
        kind = view[0]
        if kind == GOSSIP:
            if len(view) < PAYLOAD_WIRE:
                raise WireError("truncated payload")
            out.append(Payload.decode_body(bytes(view[1:PAYLOAD_WIRE])))
            view = view[PAYLOAD_WIRE:]
        elif kind in (ECHO, READY):
            if len(view) < ATTEST_WIRE:
                raise WireError("truncated attestation")
            out.append(Attestation.decode_body(kind, bytes(view[1:ATTEST_WIRE])))
            view = view[ATTEST_WIRE:]
        elif kind == REQUEST:
            if len(view) < REQUEST_WIRE:
                raise WireError("truncated content request")
            out.append(ContentRequest.decode_body(bytes(view[1:REQUEST_WIRE])))
            view = view[REQUEST_WIRE:]
        elif kind == HIST_IDX_REQ:
            if len(view) < HIST_IDX_REQ_WIRE:
                raise WireError("truncated history index request")
            out.append(
                HistoryIndexRequest.decode_body(bytes(view[1:HIST_IDX_REQ_WIRE]))
            )
            view = view[HIST_IDX_REQ_WIRE:]
        elif kind == HIST_REQ:
            if len(view) < HIST_REQ_WIRE:
                raise WireError("truncated history request")
            out.append(HistoryRequest.decode_body(bytes(view[1:HIST_REQ_WIRE])))
            view = view[HIST_REQ_WIRE:]
        elif kind in (HIST_IDX, HIST_BATCH):
            if len(view) < HIST_HDR_WIRE:
                raise WireError("truncated history header")
            nonce, count = _HIST_HDR.unpack(bytes(view[1:HIST_HDR_WIRE]))
            entry = _HIST_IDX_ENTRY.size if kind == HIST_IDX else _PAYLOAD.size
            total = HIST_HDR_WIRE + count * entry
            if len(view) < total:
                raise WireError("truncated history entries")
            body = bytes(view[HIST_HDR_WIRE:total])
            if kind == HIST_IDX:
                out.append(HistoryIndex.decode_body(nonce, body))
            else:
                out.append(HistoryBatch.decode_body(nonce, body))
            view = view[total:]
        else:
            raise WireError(f"unknown message kind {kind}")
    return out
