"""Wire messages of the three-phase broadcast (gossip / Echo / Ready).

The reference gets these from its murmur/sieve/contagion crates
(`/root/reference/technical.md:7-15` [dep-inferred]); here they are
explicit fixed-size binary records so a frame can carry many of them
back-to-back and batches parse with zero framing overhead:

* ``Payload`` — the gossiped unit: the client-signed transfer plus the
  sequence number the broadcast layer binds to it (the reference does the
  same binding via ``sieve::Payload::new(sender, seq, msg, signature)``,
  `/root/reference/src/bin/server/rpc.rs:277-282`).
* ``Attestation`` — an Echo or Ready: a node's signed vote that it saw a
  specific payload content for a given (sender, sequence) slot. Signing
  bytes carry a phase-specific domain tag so an Echo can never be replayed
  as a Ready.

All integers little-endian; keys/signatures raw (types.py's canonical
layout).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass

from ..types import ThinTransaction

GOSSIP = 1
ECHO = 2
READY = 3
REQUEST = 4

_PAYLOAD = struct.Struct("<32sI32sQ64s")  # sender, seq, recipient, amount, sig
_ATTEST = struct.Struct("<32s32sI32s64s")  # origin, sender, seq, hash, sig
_REQUEST = struct.Struct("<32sI32s")  # sender, seq, hash

PAYLOAD_WIRE = 1 + _PAYLOAD.size
ATTEST_WIRE = 1 + _ATTEST.size
REQUEST_WIRE = 1 + _REQUEST.size

_ECHO_TAG = b"at2-node-tpu/echo/v1"
_READY_TAG = b"at2-node-tpu/ready/v1"


class WireError(Exception):
    pass


@dataclass(frozen=True)
class Payload:
    """A transfer in flight: (sender, sequence) slot + signed content."""

    sender: bytes
    sequence: int
    transaction: ThinTransaction
    signature: bytes  # client's ed25519 over transaction.signing_bytes()

    @property
    def slot(self) -> tuple:
        return (self.sender, self.sequence)

    def encode(self) -> bytes:
        return bytes([GOSSIP]) + _PAYLOAD.pack(
            self.sender,
            self.sequence,
            self.transaction.recipient,
            self.transaction.amount,
            self.signature,
        )

    def content_hash(self) -> bytes:
        """Identifies the payload *content* within its slot — what Echo and
        Ready votes attest to (sieve's equivocation unit). Cached: the
        broadcast pipeline consults it several times per message."""
        cached = self.__dict__.get("_chash")
        if cached is None:
            cached = hashlib.sha256(
                _PAYLOAD.pack(
                    self.sender,
                    self.sequence,
                    self.transaction.recipient,
                    self.transaction.amount,
                    self.signature,
                )
            ).digest()
            object.__setattr__(self, "_chash", cached)
        return cached

    @staticmethod
    def decode_body(body: bytes) -> "Payload":
        sender, seq, recipient, amount, sig = _PAYLOAD.unpack(body)
        return Payload(sender, seq, ThinTransaction(recipient, amount), sig)


@dataclass(frozen=True)
class Attestation:
    """An Echo (phase=ECHO) or Ready (phase=READY) vote."""

    phase: int
    origin: bytes  # ed25519 sign key of the attesting node
    sender: bytes
    sequence: int
    content_hash: bytes
    signature: bytes

    @staticmethod
    def signing_bytes(
        phase: int, sender: bytes, sequence: int, content_hash: bytes
    ) -> bytes:
        tag = _ECHO_TAG if phase == ECHO else _READY_TAG
        return tag + sender + struct.pack("<I", sequence) + content_hash

    def to_sign(self) -> bytes:
        return self.signing_bytes(
            self.phase, self.sender, self.sequence, self.content_hash
        )

    def encode(self) -> bytes:
        return bytes([self.phase]) + _ATTEST.pack(
            self.origin, self.sender, self.sequence, self.content_hash, self.signature
        )

    @staticmethod
    def decode_body(phase: int, body: bytes) -> "Attestation":
        origin, sender, seq, chash, sig = _ATTEST.unpack(body)
        return Attestation(phase, origin, sender, seq, chash, sig)


@dataclass(frozen=True)
class ContentRequest:
    """Pull request for a payload whose Ready quorum was observed but whose
    gossip never arrived (contagion totality catch-up — the reference left
    this as the open "catchup mechanism" roadmap item,
    `/root/reference/README.md:53`). Carries no signature: requests are
    only ever accepted over the mesh's authenticated channels, so the
    transport identifies the requester."""

    sender: bytes
    sequence: int
    content_hash: bytes

    def encode(self) -> bytes:
        return bytes([REQUEST]) + _REQUEST.pack(
            self.sender, self.sequence, self.content_hash
        )

    @staticmethod
    def decode_body(body: bytes) -> "ContentRequest":
        sender, seq, chash = _REQUEST.unpack(body)
        return ContentRequest(sender, seq, chash)


def parse_frame(frame: bytes) -> list:
    """Split a frame into messages (frames may coalesce many)."""
    out = []
    view = memoryview(frame)
    while view:
        kind = view[0]
        if kind == GOSSIP:
            if len(view) < PAYLOAD_WIRE:
                raise WireError("truncated payload")
            out.append(Payload.decode_body(bytes(view[1:PAYLOAD_WIRE])))
            view = view[PAYLOAD_WIRE:]
        elif kind in (ECHO, READY):
            if len(view) < ATTEST_WIRE:
                raise WireError("truncated attestation")
            out.append(Attestation.decode_body(kind, bytes(view[1:ATTEST_WIRE])))
            view = view[ATTEST_WIRE:]
        elif kind == REQUEST:
            if len(view) < REQUEST_WIRE:
                raise WireError("truncated content request")
            out.append(ContentRequest.decode_body(bytes(view[1:REQUEST_WIRE])))
            view = view[REQUEST_WIRE:]
        else:
            raise WireError(f"unknown message kind {kind}")
    return out
