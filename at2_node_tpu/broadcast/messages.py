"""Wire messages of the three-phase broadcast (gossip / Echo / Ready).

The reference gets these from its murmur/sieve/contagion crates
(`/root/reference/technical.md:7-15` [dep-inferred]); here they are
explicit fixed-size binary records so a frame can carry many of them
back-to-back and batches parse with zero framing overhead:

* ``Payload`` — the gossiped unit: one client transfer in its
  (sender, sequence) slot. The client signature covers the slot itself
  (types.py ``transfer_signing_bytes``: tag || sender || seq ||
  recipient || amount) — stronger than the reference, whose sieve layer
  binds the sequence outside the signature
  (`/root/reference/src/bin/server/rpc.rs:277-282`); see types.py for
  why the RPC-fronted design needs the binding inside.
* ``Attestation`` — an Echo or Ready: a node's signed vote that it saw a
  specific payload content for a given (sender, sequence) slot. Signing
  bytes carry a phase-specific domain tag so an Echo can never be replayed
  as a Ready.

All integers little-endian; keys/signatures raw (types.py's canonical
layout).
"""

from __future__ import annotations

import hashlib
import json
import struct
from dataclasses import dataclass

from ..types import ThinTransaction, transfer_signing_bytes

GOSSIP = 1
ECHO = 2
READY = 3
REQUEST = 4
# Ledger-history catchup plane (the reference's open "catchup mechanism"
# roadmap item, /root/reference/README.md:53 — see ledger/history.py and
# node/service.py `_catchup_once` for the protocol):
HIST_IDX_REQ = 5  # "send me your commit frontier"
HIST_IDX = 6  # per-sender committed-sequence frontier
HIST_REQ = 7  # "send me sender X's committed payloads in [lo, hi]"
HIST_BATCH = 8  # a batch of committed payloads
# Batched broadcast plane (see TxBatch below): one broadcast slot carries
# many client transactions, amortizing the per-slot protocol cost (the
# ~9 wire messages + ~7 verifies per tx at n=4 that cap the per-tx plane
# at a few hundred tx/s). Public precedent: Chop Chop's batched atomic
# broadcast (PAPERS.md); here adapted to AT2's consensus-free model with
# per-entry endorsement bitmaps so sieve's per-(sender, sequence)
# equivocation filtering is preserved exactly (stack.py docstring).
BATCH = 9  # a node-originated batch of client payloads (gossip unit)
BATCH_ECHO = 10  # Echo over a batch: endorsement bitmap + one signature
BATCH_READY = 11  # Ready over a batch: same shape as BATCH_ECHO
BATCH_REQ = 12  # content pull for a quorate batch never gossiped here
# Client-directory gossip (broker ingress tier, see node/directory.py):
# a node that assigned client-ids announces the id -> pubkey mappings to
# its peers so distilled batches resolve everywhere. Liveness-only state
# (a wrong mapping just fails the entry's signature check locally), so
# announces are unsigned and accepted only over authenticated channels,
# same trust shape as the catchup plane.
DIR_ANNOUNCE = 13  # (announcing node, [(client_id, pubkey)...])
# Membership reconfiguration (node/membership.py): an admin-signed epoch
# transition — add/remove nodes, re-weight quorum thresholds. Gossiped
# like any other message and re-gossiped on first acceptance so every
# node converges on the new epoch; messages from epochs older than the
# grace window are rejected (stack.py / membership.py).
CONFIG_TX = 14  # (epoch, admin signature, JSON change description)
# Fleet-consistency audit plane (obs/audit.py): each node periodically
# gossips a signed digest of its committed ledger state — additive
# (commutative) lanes over the account ranges, the per-sender commit
# watermarks, and the client directory, plus a local hash-chain head.
# Peers compare beacons taken at the *same watermark digest* (equal
# watermark vector ⇔ equal committed set under AT2's gap-free per-sender
# sequencing), so nodes that legitimately commit in different orders
# never false-positive, while a real ledger divergence conflicts at an
# identical coordinate and flips /healthz to `diverged` with attribution.
BEACON = 15  # (epoch, commits, wm/account/directory digests, chain head)
# Finality co-signature (finality/): a node's signature over the
# CANONICAL frontier tuple (epoch, watermark digest, account-range
# lanes, directory digest) — the subset of a beacon every correct node
# reproduces byte-identically at the same committed set. The node-local
# `commits` count rides along unsigned (a lag/progress coordinate for
# operators and wait_final(); it differs across correct nodes and must
# never enter the preimage). CertAssembler folds 2f+1 of these into a
# quorum certificate a stateless light client can verify offline.
CERT_SIG = 16  # (epoch, commits, wm/account/directory digests, co-sig)

_PAYLOAD = struct.Struct("<32sI32sQ64s")  # sender, seq, recipient, amount, sig
_ATTEST = struct.Struct("<32s32sI32s64s")  # origin, sender, seq, hash, sig
_REQUEST = struct.Struct("<32sI32s")  # sender, seq, hash
_HIST_IDX_REQ = struct.Struct("<Q")  # nonce
_HIST_HDR = struct.Struct("<QI")  # nonce, entry count (HIST_IDX / HIST_BATCH)
_HIST_IDX_ENTRY = struct.Struct("<32sI")  # sender, last committed sequence
_HIST_REQ = struct.Struct("<Q32sII")  # nonce, sender, from_seq, to_seq
_BATCH_HDR = struct.Struct("<32sQI64s")  # origin, batch_seq, count, origin sig
_BATCH_ATT = struct.Struct("<32s32sQ32sI")  # origin, b_origin, b_seq, hash, bm len
_BATCH_REQ = struct.Struct("<32sQ32s")  # batch origin, batch_seq, hash
_DIR_HDR = struct.Struct("<32sI")  # announcing node, entry count
_DIR_ENTRY = struct.Struct("<Q32s")  # client id, client pubkey
_CONFIG_HDR = struct.Struct("<QI64s")  # epoch, body length, admin sig
# origin, epoch, commits, wm digest (16B), 16 u64 account-range lanes
# (128B), directory digest (8B), local chain head (32B); + 64B signature
_BEACON_BODY = struct.Struct("<32sQQ16s128s8s32s")
# origin, epoch, commits, wm digest (16B), 16 u64 account-range lanes
# (128B), directory digest (8B); + 64B co-signature. No chain head: only
# the canonical (cross-node identical) fields belong in a certificate.
_CERT_BODY = struct.Struct("<32sQQ16s128s8s")
# The signed preimage of a co-signature covers ONLY the canonical tuple
# (epoch, wm, ranges, dir) — not origin (the multi-sig scheme binds the
# signer via its verification key) and not commits (node-local).
_CERT_PREIMAGE = struct.Struct("<Q16s128s8s")

PAYLOAD_WIRE = 1 + _PAYLOAD.size
ATTEST_WIRE = 1 + _ATTEST.size
REQUEST_WIRE = 1 + _REQUEST.size
HIST_IDX_REQ_WIRE = 1 + _HIST_IDX_REQ.size
HIST_REQ_WIRE = 1 + _HIST_REQ.size
HIST_HDR_WIRE = 1 + _HIST_HDR.size  # variable records: header + entries
ENTRY_WIRE = _PAYLOAD.size  # one batch entry = one 140-byte payload body
BATCH_HDR_WIRE = 1 + _BATCH_HDR.size  # variable: header + count entries
BATCH_ATT_WIRE = 1 + _BATCH_ATT.size + 64  # variable: + bitmap before sig
BATCH_REQ_WIRE = 1 + _BATCH_REQ.size
DIR_HDR_WIRE = 1 + _DIR_HDR.size  # variable: header + count entries
CONFIG_HDR_WIRE = 1 + _CONFIG_HDR.size  # variable: header + JSON body
BEACON_WIRE = 1 + _BEACON_BODY.size + 64  # fixed: body + origin signature
CERT_SIG_WIRE = 1 + _CERT_BODY.size + 64  # fixed: body + co-signature

# Bounds one announce's parse amplification (a full directory re-sync
# splits across several announces).
MAX_DIR_ENTRIES = 4096

# A config transaction describes a handful of membership rows; anything
# larger is malformed (must match kMaxConfigBytes in
# native/at2_ingest.cpp).
MAX_CONFIG_BYTES = 4096

# Hard cap on entries per batch (bounds bitmap width, parse amplification,
# and the per-slot verify burst); the ingress batcher flushes well below
# it (node/config.py BatchingConfig.max_entries).
MAX_BATCH_ENTRIES = 1024
MAX_BITMAP_BYTES = MAX_BATCH_ENTRIES // 8

# A legitimate frame coalesces at most MAX_BATCH_MSGS = 1024 messages
# (net/peers.py); 4x that is the malformed bound. Bounds the parse
# amplification of frames dense with the 9-byte catchup request (must
# match kMaxMsgsPerFrame in native/at2_ingest.cpp).
MAX_MSGS_PER_FRAME = 4096

_ECHO_TAG = b"at2-node-tpu/echo/v1"
_READY_TAG = b"at2-node-tpu/ready/v1"
_BATCH_TAG = b"at2-node-tpu/batch/v1"
_BECHO_TAG = b"at2-node-tpu/batch-echo/v1"
_BREADY_TAG = b"at2-node-tpu/batch-ready/v1"
_CONFIG_TAG = b"at2-node-tpu/config-tx/v1"
_BEACON_TAG = b"at2-node-tpu/beacon/v1"
_CERT_TAG = b"at2-node-tpu/cert/v1"


class WireError(Exception):
    pass


@dataclass(frozen=True)
class Payload:
    """A transfer in flight: (sender, sequence) slot + signed content."""

    sender: bytes
    sequence: int
    transaction: ThinTransaction
    signature: bytes  # client's ed25519 over to_sign() (types.py v2 tag)

    @property
    def slot(self) -> tuple:
        return (self.sender, self.sequence)

    def to_sign(self) -> bytes:
        """The client-signature preimage: the v2 tagged transfer form
        binding (sender, sequence, recipient, amount) — see types.py."""
        return transfer_signing_bytes(
            self.sender,
            self.sequence,
            self.transaction.recipient,
            self.transaction.amount,
        )

    @classmethod
    def create(
        cls, keypair, sequence: int, transaction: ThinTransaction
    ) -> "Payload":
        """Build and client-sign a payload (the one construction path
        clients, benches, and tests share)."""
        return cls(
            keypair.public,
            sequence,
            transaction,
            keypair.sign(
                transfer_signing_bytes(
                    keypair.public,
                    sequence,
                    transaction.recipient,
                    transaction.amount,
                )
            ),
        )

    def encode(self) -> bytes:
        return bytes([GOSSIP]) + _PAYLOAD.pack(
            self.sender,
            self.sequence,
            self.transaction.recipient,
            self.transaction.amount,
            self.signature,
        )

    def content_hash(self) -> bytes:
        """Identifies the payload *content* within its slot — what Echo and
        Ready votes attest to (sieve's equivocation unit). Cached: the
        broadcast pipeline consults it several times per message."""
        cached = self.__dict__.get("_chash")
        if cached is None:
            cached = hashlib.sha256(
                _PAYLOAD.pack(
                    self.sender,
                    self.sequence,
                    self.transaction.recipient,
                    self.transaction.amount,
                    self.signature,
                )
            ).digest()
            object.__setattr__(self, "_chash", cached)
        return cached

    @staticmethod
    def decode_body(body: bytes) -> "Payload":
        sender, seq, recipient, amount, sig = _PAYLOAD.unpack(body)
        return Payload(sender, seq, ThinTransaction(recipient, amount), sig)


@dataclass(frozen=True)
class Attestation:
    """An Echo (phase=ECHO) or Ready (phase=READY) vote."""

    phase: int
    origin: bytes  # ed25519 sign key of the attesting node
    sender: bytes
    sequence: int
    content_hash: bytes
    signature: bytes

    @staticmethod
    def signing_bytes(
        phase: int, sender: bytes, sequence: int, content_hash: bytes
    ) -> bytes:
        tag = _ECHO_TAG if phase == ECHO else _READY_TAG
        return tag + sender + struct.pack("<I", sequence) + content_hash

    def to_sign(self) -> bytes:
        return self.signing_bytes(
            self.phase, self.sender, self.sequence, self.content_hash
        )

    def encode(self) -> bytes:
        return bytes([self.phase]) + _ATTEST.pack(
            self.origin, self.sender, self.sequence, self.content_hash, self.signature
        )

    @staticmethod
    def decode_body(phase: int, body: bytes) -> "Attestation":
        origin, sender, seq, chash, sig = _ATTEST.unpack(body)
        return Attestation(phase, origin, sender, seq, chash, sig)


@dataclass(frozen=True)
class ContentRequest:
    """Pull request for a payload whose Ready quorum was observed but whose
    gossip never arrived (contagion totality catch-up — the reference left
    this as the open "catchup mechanism" roadmap item,
    `/root/reference/README.md:53`). Carries no signature: requests are
    only ever accepted over the mesh's authenticated channels, so the
    transport identifies the requester."""

    sender: bytes
    sequence: int
    content_hash: bytes

    def encode(self) -> bytes:
        return bytes([REQUEST]) + _REQUEST.pack(
            self.sender, self.sequence, self.content_hash
        )

    @staticmethod
    def decode_body(body: bytes) -> "ContentRequest":
        sender, seq, chash = _REQUEST.unpack(body)
        return ContentRequest(sender, seq, chash)


@dataclass(frozen=True)
class HistoryIndexRequest:
    """Ask a peer for its commit frontier (first step of a catchup
    session). ``nonce`` ties responses to the requesting session; like
    ContentRequest, unsigned — accepted only over authenticated channels."""

    nonce: int

    def encode(self) -> bytes:
        return bytes([HIST_IDX_REQ]) + _HIST_IDX_REQ.pack(self.nonce)

    @staticmethod
    def decode_body(body: bytes) -> "HistoryIndexRequest":
        (nonce,) = _HIST_IDX_REQ.unpack(body)
        return HistoryIndexRequest(nonce)


@dataclass(frozen=True)
class HistoryIndex:
    """A peer's commit frontier: (sender, last committed sequence) pairs.
    Variable length: header carries the entry count."""

    nonce: int
    entries: tuple  # of (sender: bytes, last_seq: int)

    def encode(self) -> bytes:
        parts = [
            bytes([HIST_IDX]),
            _HIST_HDR.pack(self.nonce, len(self.entries)),
        ]
        parts.extend(
            _HIST_IDX_ENTRY.pack(sender, seq) for sender, seq in self.entries
        )
        return b"".join(parts)

    @staticmethod
    def decode_body(nonce: int, body: bytes) -> "HistoryIndex":
        n = len(body) // _HIST_IDX_ENTRY.size
        entries = tuple(
            _HIST_IDX_ENTRY.unpack_from(body, i * _HIST_IDX_ENTRY.size)
            for i in range(n)
        )
        return HistoryIndex(nonce, entries)


@dataclass(frozen=True)
class HistoryRequest:
    """Pull a sender's committed payloads for sequences [from_seq, to_seq]
    (inclusive); the server clamps the range (see ledger/history.py)."""

    nonce: int
    sender: bytes
    from_seq: int
    to_seq: int

    def encode(self) -> bytes:
        return bytes([HIST_REQ]) + _HIST_REQ.pack(
            self.nonce, self.sender, self.from_seq, self.to_seq
        )

    @staticmethod
    def decode_body(body: bytes) -> "HistoryRequest":
        nonce, sender, lo, hi = _HIST_REQ.unpack(body)
        return HistoryRequest(nonce, sender, lo, hi)


@dataclass(frozen=True)
class HistoryBatch:
    """Committed payloads served from a peer's history store. The
    receiving catchup session trusts NO single peer: a slot is applied
    only once `catchup quorum` peers returned the same content hash AND
    the client signature verifies (node/service.py `_catchup_once`)."""

    nonce: int
    payloads: tuple  # of Payload

    def encode(self) -> bytes:
        parts = [
            bytes([HIST_BATCH]),
            _HIST_HDR.pack(self.nonce, len(self.payloads)),
        ]
        parts.extend(p.encode()[1:] for p in self.payloads)
        return b"".join(parts)

    @staticmethod
    def decode_body(nonce: int, body: bytes) -> "HistoryBatch":
        n = len(body) // _PAYLOAD.size
        payloads = tuple(
            Payload.decode_body(
                body[i * _PAYLOAD.size : (i + 1) * _PAYLOAD.size]
            )
            for i in range(n)
        )
        return HistoryBatch(nonce, payloads)


@dataclass(frozen=True)
class TxBatch:
    """A node-originated batch of client transactions: ONE broadcast slot
    ((origin node, batch_seq)) carrying many independently client-signed
    transfers. This is the protocol lever that amortizes the per-slot
    broadcast cost (gossip relay + n Echo + n Ready signatures) over
    ``count`` transactions — the reference broadcasts one transaction per
    sieve payload (`/root/reference/src/bin/server/rpc.rs:275-284`); this
    build generalizes that surface (Chop Chop precedent, PAPERS.md).

    ``entries_raw`` is ``count`` back-to-back 140-byte payload bodies
    (the exact GOSSIP body layout), so entries decode with the same
    structs, the catchup/history plane stores them unchanged, and the
    per-entry *client* signatures ride inside — verified in the same bulk
    ``verify_many`` call as the one origin signature.

    The origin signs (tag || origin || batch_seq || sha256(entries_raw)):
    relayed batches cannot be forged under another node's identity, and a
    byzantine origin equivocating two batch contents for one batch_seq is
    filtered exactly like a per-tx equivocation (stack.py binds each slot
    to the first content echoed)."""

    origin: bytes  # sign key of the batching node
    batch_seq: int  # u64; unique per origin (time-seeded, see service.py)
    entries_raw: bytes  # count x 140-byte payload bodies
    signature: bytes  # origin's ed25519 over signing_bytes()

    @property
    def slot(self) -> tuple:
        return (self.origin, self.batch_seq)

    @property
    def count(self) -> int:
        return len(self.entries_raw) // ENTRY_WIRE

    def entry(self, i: int) -> Payload:
        return Payload.decode_body(
            self.entries_raw[i * ENTRY_WIRE : (i + 1) * ENTRY_WIRE]
        )

    def entry_bytes(self, i: int) -> bytes:
        return self.entries_raw[i * ENTRY_WIRE : (i + 1) * ENTRY_WIRE]

    def entries(self) -> list:
        """All entries decoded (memoized: echo and delivery both need
        them; one decode pass per batch per node)."""
        cached = self.__dict__.get("_entries")
        if cached is None:
            cached = [
                Payload(sender, seq, ThinTransaction(recipient, amount), sig)
                for sender, seq, recipient, amount, sig in _PAYLOAD.iter_unpack(
                    self.entries_raw
                )
            ]
            object.__setattr__(self, "_entries", cached)
        return cached

    def signing_bytes(self) -> bytes:
        return (
            _BATCH_TAG
            + self.origin
            + struct.pack("<Q", self.batch_seq)
            + hashlib.sha256(self.entries_raw).digest()
        )

    @classmethod
    def create(
        cls, keypair, batch_seq: int, entries_raw: bytes
    ) -> "TxBatch":
        """Build and origin-sign a batch (the one construction path the
        ingress batcher and bench tools share)."""
        unsigned = cls(keypair.public, batch_seq, entries_raw, b"\0" * 64)
        return cls(
            keypair.public,
            batch_seq,
            entries_raw,
            keypair.sign(unsigned.signing_bytes()),
        )

    def content_hash(self) -> bytes:
        """The batch content identity Echo/Ready bitmaps attest to (the
        whole encoded body, signature included — same convention as
        Payload.content_hash)."""
        cached = self.__dict__.get("_chash")
        if cached is None:
            cached = hashlib.sha256(self.encode()[1:]).digest()
            object.__setattr__(self, "_chash", cached)
        return cached

    def encode(self) -> bytes:
        cached = self.__dict__.get("_encoded")
        if cached is None:
            cached = (
                bytes([BATCH])
                + _BATCH_HDR.pack(
                    self.origin, self.batch_seq, self.count, self.signature
                )
                + self.entries_raw
            )
            object.__setattr__(self, "_encoded", cached)
        return cached

    @staticmethod
    def decode_body(body: bytes) -> "TxBatch":
        origin, batch_seq, count, sig = _BATCH_HDR.unpack_from(body)
        entries = body[_BATCH_HDR.size :]
        if len(entries) != count * ENTRY_WIRE:
            raise WireError("batch entry count mismatch")
        return TxBatch(origin, batch_seq, entries, sig)


@dataclass(frozen=True)
class BatchAttestation:
    """An Echo or Ready over a batch: ONE signature endorsing a subset of
    the batch's entries, given by ``bitmap`` (little-endian bit i =
    entry i). Bitmaps let a node endorse exactly the entries that pass
    its per-(sender, sequence) equivocation registry, so one conflicting
    entry cannot poison the rest of the batch, and per-entry quorum
    counting preserves sieve/contagion semantics entry-by-entry
    (stack.py `_BatchState`). Ready bitmaps are monotone: an origin may
    re-attest with a superset as more entries reach Echo quorum."""

    phase: int  # BATCH_ECHO or BATCH_READY
    origin: bytes  # attesting node's sign key
    batch_origin: bytes
    batch_seq: int
    batch_hash: bytes  # TxBatch.content_hash()
    bitmap: bytes  # little-endian entry endorsement bits
    signature: bytes

    @staticmethod
    def signing_bytes(
        phase: int, batch_origin: bytes, batch_seq: int, batch_hash: bytes,
        bitmap: bytes,
    ) -> bytes:
        tag = _BECHO_TAG if phase == BATCH_ECHO else _BREADY_TAG
        return (
            tag
            + batch_origin
            + struct.pack("<Q", batch_seq)
            + batch_hash
            + bitmap
        )

    def to_sign(self) -> bytes:
        return self.signing_bytes(
            self.phase, self.batch_origin, self.batch_seq, self.batch_hash,
            self.bitmap,
        )

    def encode(self) -> bytes:
        return (
            bytes([self.phase])
            + _BATCH_ATT.pack(
                self.origin,
                self.batch_origin,
                self.batch_seq,
                self.batch_hash,
                len(self.bitmap),
            )
            + self.bitmap
            + self.signature
        )

    @staticmethod
    def decode_body(phase: int, body: bytes) -> "BatchAttestation":
        origin, b_origin, b_seq, b_hash, bm_len = _BATCH_ATT.unpack_from(body)
        bitmap = body[_BATCH_ATT.size : _BATCH_ATT.size + bm_len]
        sig = body[_BATCH_ATT.size + bm_len :]
        if len(bitmap) != bm_len or len(sig) != 64:
            raise WireError("truncated batch attestation")
        return BatchAttestation(phase, origin, b_origin, b_seq, b_hash, bitmap, sig)


@dataclass(frozen=True)
class BatchContentRequest:
    """Pull request for a batch whose Ready quorum was observed but whose
    gossip never arrived (the batch-plane twin of ContentRequest;
    unsigned, accepted only over authenticated channels)."""

    batch_origin: bytes
    batch_seq: int
    batch_hash: bytes

    def encode(self) -> bytes:
        return bytes([BATCH_REQ]) + _BATCH_REQ.pack(
            self.batch_origin, self.batch_seq, self.batch_hash
        )

    @staticmethod
    def decode_body(body: bytes) -> "BatchContentRequest":
        b_origin, b_seq, b_hash = _BATCH_REQ.unpack(body)
        return BatchContentRequest(b_origin, b_seq, b_hash)


@dataclass(frozen=True)
class DirectoryAnnounce:
    """Gossiped client-directory mappings: ``entries`` is a tuple of
    (client_id, pubkey) pairs assigned by ``origin`` (ids must fall in
    origin's stride — receivers check, node/directory.py ``apply``).
    Unsigned: accepted only over the mesh's authenticated channels, and
    a byzantine peer announcing wrong mappings can only make entries
    fail signature verification locally (liveness, never safety)."""

    origin: bytes  # announcing node's sign key
    entries: tuple  # of (client_id: int, pubkey: bytes)

    def encode(self) -> bytes:
        parts = [
            bytes([DIR_ANNOUNCE]),
            _DIR_HDR.pack(self.origin, len(self.entries)),
        ]
        parts.extend(_DIR_ENTRY.pack(cid, key) for cid, key in self.entries)
        return b"".join(parts)

    @staticmethod
    def decode_body(origin: bytes, body: bytes) -> "DirectoryAnnounce":
        n = len(body) // _DIR_ENTRY.size
        entries = tuple(
            _DIR_ENTRY.unpack_from(body, i * _DIR_ENTRY.size) for i in range(n)
        )
        return DirectoryAnnounce(origin, entries)


@dataclass(frozen=True)
class ConfigTx:
    """An epoch-based membership reconfiguration, signed by the fleet
    admin key (node/config.py ``admin_public``). ``body`` is canonical
    JSON (sorted keys, compact separators) describing the change:

    * ``add``    — rows of {address, exchange_hex, sign_hex} to join
    * ``remove`` — sign-key hexes to evict
    * ``echo_threshold`` / ``ready_threshold`` — optional re-weighting
    * ``grace``  — seconds old-epoch messages stay accepted

    The admin signature covers (tag || epoch || body), so a transaction
    can neither be replayed into a different epoch nor altered in
    flight. Validation (epoch must be exactly current+1, signature must
    verify against the configured admin key) lives in
    node/membership.py — the wire layer only carries it."""

    epoch: int
    body: bytes  # canonical JSON change description
    signature: bytes  # admin ed25519 over signing_bytes()

    @staticmethod
    def signing_bytes(epoch: int, body: bytes) -> bytes:
        return _CONFIG_TAG + struct.pack("<Q", epoch) + body

    def to_sign(self) -> bytes:
        return self.signing_bytes(self.epoch, self.body)

    @classmethod
    def create(cls, admin_keypair, epoch: int, change: dict) -> "ConfigTx":
        """Build and admin-sign a config transaction (the one
        construction path tools, sims, and tests share)."""
        body = json.dumps(
            change, separators=(",", ":"), sort_keys=True
        ).encode()
        return cls(epoch, body, admin_keypair.sign(cls.signing_bytes(epoch, body)))

    def change(self) -> dict:
        return json.loads(self.body)

    def encode(self) -> bytes:
        return (
            bytes([CONFIG_TX])
            + _CONFIG_HDR.pack(self.epoch, len(self.body), self.signature)
            + self.body
        )

    @staticmethod
    def decode_body(body: bytes) -> "ConfigTx":
        epoch, length, sig = _CONFIG_HDR.unpack_from(body)
        payload = body[_CONFIG_HDR.size :]
        if len(payload) != length:
            raise WireError("config tx body length mismatch")
        return ConfigTx(epoch, payload, sig)


@dataclass(frozen=True)
class StateBeacon:
    """A signed fleet-audit digest of one node's committed ledger state
    (obs/audit.py builds, compares, and attributes; TECHNICAL.md "Fleet
    audit & incident capture" documents the digest rules).

    All cross-node-comparable fields are *additive* digests — unordered
    sums over the state, so two correct nodes that committed the same
    set of transactions in different orders produce identical values:

    * ``wm_digest``  — 128-bit sum of H(sender, last_sequence) over the
      commit-watermark frontier; the comparison coordinate.
    * ``ranges``     — sixteen u64 lanes, one per account range
      (``key[0] >> 4``), each a sum of H(key, balance, sequence) over
      the accounts in that range; lane-granular attribution.
    * ``dir_digest`` — u64 sum of H(client_id, pubkey) over the client
      directory (informational: directory gossip is eventually
      consistent, so skew here is never treated as divergence).

    ``chain`` is the node's *local* sha256 digest-chain head — folded
    per beacon point and persisted in the store manifest as restart
    tamper evidence; it is order-dependent and never compared across
    peers. The origin signature makes a beacon non-repudiable evidence
    in incident bundles."""

    origin: bytes  # beaconing node's sign key
    epoch: int  # membership epoch the digest was taken under
    commits: int  # node-local committed-transfer count at the snapshot
    wm_digest: bytes  # 16B additive watermark digest (the coordinate)
    ranges: bytes  # 16 little-endian u64 account-range lanes (128B)
    dir_digest: bytes  # 8B additive client-directory digest
    chain: bytes  # 32B local digest-chain head (never compared)
    signature: bytes  # origin ed25519 over signing_bytes()

    @staticmethod
    def signing_bytes(
        origin: bytes,
        epoch: int,
        commits: int,
        wm_digest: bytes,
        ranges: bytes,
        dir_digest: bytes,
        chain: bytes,
    ) -> bytes:
        return _BEACON_TAG + _BEACON_BODY.pack(
            origin, epoch, commits, wm_digest, ranges, dir_digest, chain
        )

    def to_sign(self) -> bytes:
        return self.signing_bytes(
            self.origin,
            self.epoch,
            self.commits,
            self.wm_digest,
            self.ranges,
            self.dir_digest,
            self.chain,
        )

    @classmethod
    def create(
        cls,
        keypair,
        epoch: int,
        commits: int,
        wm_digest: bytes,
        ranges: bytes,
        dir_digest: bytes,
        chain: bytes,
    ) -> "StateBeacon":
        sig = keypair.sign(
            cls.signing_bytes(
                keypair.public, epoch, commits, wm_digest, ranges,
                dir_digest, chain,
            )
        )
        return cls(
            keypair.public, epoch, commits, wm_digest, ranges, dir_digest,
            chain, sig,
        )

    def encode(self) -> bytes:
        return (
            bytes([BEACON])
            + _BEACON_BODY.pack(
                self.origin,
                self.epoch,
                self.commits,
                self.wm_digest,
                self.ranges,
                self.dir_digest,
                self.chain,
            )
            + self.signature
        )

    @staticmethod
    def decode_body(body: bytes) -> "StateBeacon":
        origin, epoch, commits, wm, ranges, dird, chain = _BEACON_BODY.unpack(
            body[: _BEACON_BODY.size]
        )
        return StateBeacon(
            origin, epoch, commits, wm, ranges, dird, chain,
            body[_BEACON_BODY.size :],
        )


def cert_signing_bytes(
    epoch: int, wm_digest: bytes, ranges: bytes, dir_digest: bytes
) -> bytes:
    """The canonical certificate preimage: every correct node at the
    same committed frontier produces these exact bytes, so a quorum of
    signatures over them is portable finality evidence. Deliberately
    excludes the signer identity (bound by the verification key in the
    attestation scheme) and every node-local field (commits, chain)."""
    return _CERT_TAG + _CERT_PREIMAGE.pack(epoch, wm_digest, ranges, dir_digest)


@dataclass(frozen=True)
class CertSig:
    """One node's finality co-signature over a canonical commit
    frontier (finality/certs.py assembles 2f+1 of these into a quorum
    certificate; TECHNICAL.md "Finality certificates").

    ``epoch``/``wm_digest``/``ranges``/``dir_digest`` are the signed
    canonical tuple — additive digests identical across correct nodes
    at the same committed set (see StateBeacon). ``commits`` is the
    origin's node-local committed-transfer count at the frontier:
    informational (progress/lag coordinate), carried OUTSIDE the
    preimage because correct nodes disagree on it."""

    origin: bytes  # co-signing node's sign key
    epoch: int  # membership epoch the frontier was taken under
    commits: int  # node-local commit count (unsigned, informational)
    wm_digest: bytes  # 16B additive watermark digest (the coordinate)
    ranges: bytes  # 16 little-endian u64 account-range lanes (128B)
    dir_digest: bytes  # 8B additive client-directory digest
    signature: bytes  # origin ed25519 over cert_signing_bytes()

    def to_sign(self) -> bytes:
        return cert_signing_bytes(
            self.epoch, self.wm_digest, self.ranges, self.dir_digest
        )

    @classmethod
    def create(
        cls,
        keypair,
        epoch: int,
        commits: int,
        wm_digest: bytes,
        ranges: bytes,
        dir_digest: bytes,
    ) -> "CertSig":
        sig = keypair.sign(
            cert_signing_bytes(epoch, wm_digest, ranges, dir_digest)
        )
        return cls(
            keypair.public, epoch, commits, wm_digest, ranges, dir_digest, sig
        )

    def encode(self) -> bytes:
        return (
            bytes([CERT_SIG])
            + _CERT_BODY.pack(
                self.origin,
                self.epoch,
                self.commits,
                self.wm_digest,
                self.ranges,
                self.dir_digest,
            )
            + self.signature
        )

    @staticmethod
    def decode_body(body: bytes) -> "CertSig":
        origin, epoch, commits, wm, ranges, dird = _CERT_BODY.unpack(
            body[: _CERT_BODY.size]
        )
        return CertSig(
            origin, epoch, commits, wm, ranges, dird, body[_CERT_BODY.size :]
        )


def parse_frame(frame: bytes) -> list:
    """Split a frame into messages (frames may coalesce many)."""
    out = []
    view = memoryview(frame)
    while view:
        if len(out) >= MAX_MSGS_PER_FRAME:
            raise WireError("frame exceeds message cap")
        kind = view[0]
        if kind == GOSSIP:
            if len(view) < PAYLOAD_WIRE:
                raise WireError("truncated payload")
            out.append(Payload.decode_body(bytes(view[1:PAYLOAD_WIRE])))
            view = view[PAYLOAD_WIRE:]
        elif kind in (ECHO, READY):
            if len(view) < ATTEST_WIRE:
                raise WireError("truncated attestation")
            out.append(Attestation.decode_body(kind, bytes(view[1:ATTEST_WIRE])))
            view = view[ATTEST_WIRE:]
        elif kind == REQUEST:
            if len(view) < REQUEST_WIRE:
                raise WireError("truncated content request")
            out.append(ContentRequest.decode_body(bytes(view[1:REQUEST_WIRE])))
            view = view[REQUEST_WIRE:]
        elif kind == HIST_IDX_REQ:
            if len(view) < HIST_IDX_REQ_WIRE:
                raise WireError("truncated history index request")
            out.append(
                HistoryIndexRequest.decode_body(bytes(view[1:HIST_IDX_REQ_WIRE]))
            )
            view = view[HIST_IDX_REQ_WIRE:]
        elif kind == HIST_REQ:
            if len(view) < HIST_REQ_WIRE:
                raise WireError("truncated history request")
            out.append(HistoryRequest.decode_body(bytes(view[1:HIST_REQ_WIRE])))
            view = view[HIST_REQ_WIRE:]
        elif kind in (HIST_IDX, HIST_BATCH):
            if len(view) < HIST_HDR_WIRE:
                raise WireError("truncated history header")
            nonce, count = _HIST_HDR.unpack(bytes(view[1:HIST_HDR_WIRE]))
            entry = _HIST_IDX_ENTRY.size if kind == HIST_IDX else _PAYLOAD.size
            total = HIST_HDR_WIRE + count * entry
            if len(view) < total:
                raise WireError("truncated history entries")
            body = bytes(view[HIST_HDR_WIRE:total])
            if kind == HIST_IDX:
                out.append(HistoryIndex.decode_body(nonce, body))
            else:
                out.append(HistoryBatch.decode_body(nonce, body))
            view = view[total:]
        elif kind == BATCH:
            if len(view) < BATCH_HDR_WIRE:
                raise WireError("truncated batch header")
            _, _, count, _ = _BATCH_HDR.unpack_from(view, 1)
            if not 1 <= count <= MAX_BATCH_ENTRIES:
                raise WireError("batch entry count out of range")
            total = BATCH_HDR_WIRE + count * ENTRY_WIRE
            if len(view) < total:
                raise WireError("truncated batch entries")
            out.append(TxBatch.decode_body(bytes(view[1:total])))
            view = view[total:]
        elif kind in (BATCH_ECHO, BATCH_READY):
            if len(view) < BATCH_ATT_WIRE:
                raise WireError("truncated batch attestation")
            bm_len = int.from_bytes(
                bytes(view[1 + _BATCH_ATT.size - 4 : 1 + _BATCH_ATT.size]),
                "little",
            )
            if bm_len > MAX_BITMAP_BYTES:
                raise WireError("batch attestation bitmap too wide")
            total = BATCH_ATT_WIRE + bm_len
            if len(view) < total:
                raise WireError("truncated batch attestation bitmap")
            out.append(
                BatchAttestation.decode_body(kind, bytes(view[1:total]))
            )
            view = view[total:]
        elif kind == BATCH_REQ:
            if len(view) < BATCH_REQ_WIRE:
                raise WireError("truncated batch content request")
            out.append(
                BatchContentRequest.decode_body(bytes(view[1:BATCH_REQ_WIRE]))
            )
            view = view[BATCH_REQ_WIRE:]
        elif kind == DIR_ANNOUNCE:
            if len(view) < DIR_HDR_WIRE:
                raise WireError("truncated directory announce header")
            origin, count = _DIR_HDR.unpack(bytes(view[1:DIR_HDR_WIRE]))
            if count > MAX_DIR_ENTRIES:
                raise WireError("directory announce entry count out of range")
            total = DIR_HDR_WIRE + count * _DIR_ENTRY.size
            if len(view) < total:
                raise WireError("truncated directory announce entries")
            out.append(
                DirectoryAnnounce.decode_body(origin, bytes(view[DIR_HDR_WIRE:total]))
            )
            view = view[total:]
        elif kind == CONFIG_TX:
            if len(view) < CONFIG_HDR_WIRE:
                raise WireError("truncated config tx header")
            _, length, _ = _CONFIG_HDR.unpack(bytes(view[1:CONFIG_HDR_WIRE]))
            if length > MAX_CONFIG_BYTES:
                raise WireError("config tx body too large")
            total = CONFIG_HDR_WIRE + length
            if len(view) < total:
                raise WireError("truncated config tx body")
            out.append(ConfigTx.decode_body(bytes(view[1:total])))
            view = view[total:]
        elif kind == BEACON:
            if len(view) < BEACON_WIRE:
                raise WireError("truncated state beacon")
            out.append(StateBeacon.decode_body(bytes(view[1:BEACON_WIRE])))
            view = view[BEACON_WIRE:]
        elif kind == CERT_SIG:
            if len(view) < CERT_SIG_WIRE:
                raise WireError("truncated cert co-signature")
            out.append(CertSig.decode_body(bytes(view[1:CERT_SIG_WIRE])))
            view = view[CERT_SIG_WIRE:]
        else:
            raise WireError(f"unknown message kind {kind}")
    return out
