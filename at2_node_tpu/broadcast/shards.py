"""Sharded broadcast plane: per-origin slot shards behind one ingress.

The monolithic :class:`~.stack.Broadcast` runs every slot state machine
on the event loop, which caps plane-only capacity at one core no matter
how many the host has. This module partitions that state by ORIGIN KEY —
the first key of every slot: client sender for the per-tx plane, batch
origin for the batch plane — into N full :class:`Broadcast` cores, each
owning a disjoint slice of quorum bitmaps, dedup sets, slot GC, and
poison resolution. Partitioning by the slot's own key means every
message about a given slot lands on the same shard, so no per-slot state
is ever shared and the cores need no locks.

What stays on the owner loop (cross-shard concerns):

* ingress: ONE inbox, one parse pass (native ingest when available),
  and ONE bulk ``verify_many`` per drain cycle across all shards — the
  batched verifier keeps its amortization regardless of shard count;
* the delivered queue the service's commit tail consumes (commit-tail
  ordering is whatever order shard effects are applied in, exactly as
  the monolithic plane's was worker-chunk order);
* the entry registry — the (client sender, seq) -> first-endorsed-entry
  equivocation guard spans BOTH planes, and a client's per-tx slots and
  the node batches carrying that client's entries can hash to different
  shards, so the registry is one shared structure injected into every
  core;
* membership epochs, watermark export (merged), stats (one shared
  counter group), and the stall-kick signal.

Executor seam (parallel/plane.py): ``inline`` runs every shard closure
synchronously on the caller IN ARRIVAL ORDER — one logical worker, so
the wire behavior is byte-identical to the monolithic plane and the
same-seed sim campaign hash is IDENTICAL at shards=1 and shards=4
(tests/test_plane_shards.py). ``thread`` pins one OS thread per shard;
Python-level transitions still serialize on the GIL, so the real-host
scaling comes from the GIL-released native kernels (quorum counting,
parse, verify) overlapping across shards. Shard threads never touch the
mesh or the delivered queue directly: effects are handed back through
bounded SPSC queues and applied by the owner loop after each dispatch.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from ..parallel.plane import SPSCQueue, make_plane_executor
from .messages import (
    BATCH,
    Attestation,
    BatchAttestation,
    BatchContentRequest,
    ContentRequest,
    Payload,
    TxBatch,
)
from .stack import (
    GC_INTERVAL,
    INBOX_MAX_BYTES,
    RETRANSMIT_BUDGET_PER_PASS,
    STALL_KICK_MIN_INTERVAL,
    WORKER_CHUNK,
    Broadcast,
)

logger = logging.getLogger(__name__)

__all__ = ["ShardedPlane", "shard_of"]

# plane_shard_handoff_ns histogram ladder: 1µs .. ~33s, in ns.
_HANDOFF_BOUNDS = tuple(1e3 * 2.0**i for i in range(26))


def shard_of(key: bytes, shards: int) -> int:
    """Stable origin-key -> shard map. The first 8 bytes of an ed25519
    key are uniform, so a modulus spreads origins evenly; stability (no
    dependence on arrival order or shard load) is what makes the
    partition deterministic and the sim hash shard-count-invariant."""
    return int.from_bytes(key[:8], "little") % shards


class _ShardMesh:
    """Mesh facade for a THREADED shard core: reads delegate, sends are
    queued as effects for the owner loop (mesh transports are event-loop
    affine and must not be touched from shard threads)."""

    __slots__ = ("_real", "_effects")

    def __init__(self, real, effects: SPSCQueue) -> None:
        self._real = real
        self._effects = effects

    @property
    def peers(self):
        return self._real.peers

    @property
    def by_sign(self):
        return self._real.by_sign

    def send(self, peer, data: bytes) -> None:
        self._effects.put(("send", peer, data))

    def broadcast(self, data: bytes) -> None:
        self._effects.put(("broadcast", data))


class _ShardDelivered:
    """Delivered-queue facade for a THREADED shard core: deliveries are
    effects, re-put into the real asyncio queue by the owner."""

    __slots__ = ("_effects",)

    def __init__(self, effects: SPSCQueue) -> None:
        self._effects = effects

    def put_nowait(self, payload) -> None:
        self._effects.put(("deliver", payload))


class ShardedPlane:
    """N per-origin :class:`Broadcast` shard cores behind one ingress.

    Drop-in for :class:`Broadcast` at the service seam: same
    constructor shape (plus ``shards`` / ``executor``), same public
    surface (``on_frame``/``broadcast``/``broadcast_batch``/
    ``delivered``/``stats``/handler hooks/watermarks/thresholds).
    ``shards=1`` deployments should keep constructing ``Broadcast``
    directly (node/service.py does) — this class earns its overhead
    only when there are cores to spread across.
    """

    def __init__(
        self,
        keypair,
        mesh,
        verifier,
        *,
        shards: int = 2,
        executor: str = "thread",
        echo_threshold: Optional[int] = None,
        ready_threshold: Optional[int] = None,
        workers: int = 4,
        registry=None,
        trace=None,
        recorder=None,
        clock=None,
        phases=None,
        overlap_ready: bool = False,
    ) -> None:
        from ..clock import SYSTEM_CLOCK
        from ..obs.registry import Registry

        if shards <= 0:
            raise ValueError("ShardedPlane needs >= 1 shard")
        self.shards = shards
        self.keypair = keypair
        self.mesh = mesh
        self.verifier = verifier
        self.clock = SYSTEM_CLOCK if clock is None else clock
        self.workers = workers
        self.registry = Registry() if registry is None else registry
        self.trace = trace
        self.recorder = recorder
        self.phases = phases
        self.delivered: asyncio.Queue = asyncio.Queue()
        self._inbox: asyncio.Queue = asyncio.Queue(maxsize=65536)
        self._inbox_bytes = 0
        self._tasks: list = []
        self._executor = make_plane_executor(executor, shards)
        self._inline = self._executor.name == "inline"

        # one effects lane per shard (only drained in threaded mode, but
        # constructed always so instruments exist and stay cheap)
        self._effects: List[SPSCQueue] = [SPSCQueue() for _ in range(shards)]
        self._stall_pending = False
        # plane-level stall hysteresis for the inline global GC pass
        # (Broadcast._gc_resolve_stall duck-types against these)
        self._stall_last_kick = float("-inf")
        self._stall_backoff = STALL_KICK_MIN_INTERVAL

        # service-facing hooks, fanned into the cores below
        self.catchup_handler = None
        self.directory_handler = None
        self.config_handler = None
        self.beacon_handler = None
        self.stall_handler = None

        self.stats = self.registry.counter_group((
            "gossip_rx",
            "echo_rx",
            "ready_rx",
            "invalid_sig",
            "delivered",
            "slots_dropped",
            "content_req_tx",
            "content_req_rx",
            "content_served",
            "batch_rx",
            "batch_echo_rx",
            "batch_ready_rx",
            "batch_entries_delivered",
            "retransmits",
            "poison_resolved",
            "slots_retired",
            "stall_kicks_suppressed",
        ))

        self._cores: List[Broadcast] = []
        for sid in range(shards):
            core = Broadcast(
                keypair,
                mesh if self._inline else _ShardMesh(mesh, self._effects[sid]),
                verifier,  # unused by cores (owner runs the bulk verify)
                echo_threshold=echo_threshold,
                ready_threshold=ready_threshold,
                workers=0,
                registry=None,  # private registry; shared stats below
                trace=trace if self._inline else None,
                recorder=recorder if self._inline else None,
                clock=self.clock,
                phases=(
                    phases.shard_view(sid, self.registry)
                    if phases is not None
                    else None
                ),
                overlap_ready=overlap_ready,
            )
            core.stats = self.stats  # ONE aggregate counter group
            if self._inline:
                core.delivered = self.delivered
                core.stall_handler = self._fire_stall
            else:
                core.delivered = _ShardDelivered(self._effects[sid])
                core.stall_handler = self._make_thread_stall(sid)
            self._cores.append(core)
        # the equivocation registry spans shards (module docstring):
        # every core binds and reads through ONE shared instance
        shared_registry = self._cores[0]._entry_registry
        for core in self._cores[1:]:
            core._entry_registry = shared_registry
        # ONE slot-birth counter across cores: the global creation
        # ordinal reconstructs the monolithic plane's GC iteration order
        # (see _gc_pass_global)
        shared_births = self._cores[0]._birth_seq
        for core in self._cores[1:]:
            core._birth_seq = shared_births

        self.registry.gauge(
            "slots_undelivered", "live undelivered broadcast slots",
            fn=lambda: sum(c._undelivered for c in self._cores),
        )
        self.registry.gauge(
            "inbox_depth", "raw frames queued for the broadcast workers",
            fn=lambda: self._inbox.qsize(),
        )
        self.registry.gauge(
            "plane_shards", "broadcast plane shard count",
            fn=lambda: float(self.shards),
        )
        self.registry.gauge(
            "plane_shard_queue_depth",
            "deepest shard effects SPSC queue right now",
            fn=lambda: float(max(len(q) for q in self._effects)),
        )
        self._handoff_hist = self.registry.histogram(
            "plane_shard_handoff_ns",
            "shard effect enqueue-to-apply latency (ns)",
            bounds=_HANDOFF_BOUNDS,
        )

    # -- threshold fan-out (service reconfigures these on membership
    # epochs; every core must agree or quorum math diverges per shard) --

    @property
    def echo_threshold(self) -> int:
        return self._cores[0].echo_threshold

    @echo_threshold.setter
    def echo_threshold(self, value: int) -> None:
        for core in self._cores:
            core.echo_threshold = value

    @property
    def ready_threshold(self) -> int:
        return self._cores[0].ready_threshold

    @ready_threshold.setter
    def ready_threshold(self, value: int) -> None:
        for core in self._cores:
            core.ready_threshold = value

    @property
    def on_attest(self):
        return self._cores[0].on_attest

    @on_attest.setter
    def on_attest(self, hook) -> None:
        for core in self._cores:
            core.on_attest = hook

    @property
    def floor_refusals(self) -> int:
        return sum(c.floor_refusals for c in self._cores)

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        from ..native import ingest_available

        await asyncio.get_running_loop().run_in_executor(None, ingest_available)
        for _ in range(self.workers):
            self._tasks.append(asyncio.create_task(self._worker()))
        self._tasks.append(asyncio.create_task(self._gc_loop()))

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        self._executor.shutdown()

    # -- ingress (mirrors Broadcast.on_frame admission exactly) -----------

    async def on_frame(self, peer, frame: bytes) -> None:
        if self.recorder is not None and frame:
            self.recorder.record("rx", (frame[0], len(frame), peer.address))
        if self._inbox_bytes + len(frame) > INBOX_MAX_BYTES:
            logger.warning("inbox byte budget exhausted; dropping frame")
            if self.recorder is not None:
                self.recorder.record("rx_drop", ("bytes", len(frame)))
            return
        try:
            self._inbox.put_nowait((peer, frame))
        except asyncio.QueueFull:
            logger.warning("inbox overflow; dropping frame")
            if self.recorder is not None:
                self.recorder.record("rx_drop", ("depth", len(frame)))
        else:
            self._inbox_bytes += len(frame)

    async def broadcast(self, payload: Payload) -> None:
        await self._inbox.put((None, payload))

    async def broadcast_batch(self, batch: TxBatch) -> None:
        await self._inbox.put((None, batch))

    # -- routing ----------------------------------------------------------

    def _route(self, msg) -> int:
        """The owning shard id for a message — keyed by the SLOT's
        origin key so every message about one slot lands on one core."""
        if isinstance(msg, Payload):
            key = msg.sender
        elif isinstance(msg, Attestation):
            key = msg.sender
        elif isinstance(msg, TxBatch):
            key = msg.origin
        elif isinstance(msg, BatchAttestation):
            key = msg.batch_origin
        elif isinstance(msg, ContentRequest):
            key = msg.sender
        elif isinstance(msg, BatchContentRequest):
            key = msg.batch_origin
        else:
            # control plane (catchup / directory / config): stateless wrt
            # shard slots — handled wherever, keep it on core 0
            return 0
        return shard_of(key, self.shards)

    # -- drain cycle ------------------------------------------------------

    async def _worker(self) -> None:
        while True:
            item = await self._inbox.get()
            chunk = [item]
            while len(chunk) < WORKER_CHUNK:
                try:
                    chunk.append(self._inbox.get_nowait())
                except asyncio.QueueEmpty:
                    break
            for _, payload in chunk:
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    self._inbox_bytes -= len(payload)
            ph = self.phases
            t_plane = ph.begin_plane() if ph is not None else 0
            t0 = ph.t() if ph is not None else 0
            try:
                msgs = self._cores[0]._parse_chunk(chunk)
                if ph is not None:
                    ph.add("rx_decode", t0)
                await self._process_chunk(msgs)
            except Exception:
                logger.exception("sharded plane worker error")
            if ph is not None:
                ph.end_plane(t_plane)

    async def _process_chunk(self, msgs) -> None:
        """Stage 1 per message in ARRIVAL order on the owning core, ONE
        bulk verify for the whole chunk, stage 3 in arrival order
        (inline) or grouped per shard on the executor (threaded)."""
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        to_verify: list = []
        actions: list = []  # (shard_id, (kind, msg, n_sigs))
        scratch: list = []
        for peer, msg in msgs:
            sid = self._route(msg)
            self._cores[sid]._pre_msg(peer, msg, to_verify, scratch)
            if scratch:
                actions.append((sid, scratch[0]))
                scratch.clear()
        if ph is not None:
            t0 = ph.add("rx_decode", t0)
        if not to_verify:
            if not self._inline:
                self._flush_effects()
            self._maybe_fire_stall()
            return
        results = await self.verifier.verify_many(to_verify)
        if ph is not None:
            ph.add("verify_wait", t0)

        idx = 0
        if self._inline:
            for sid, (kind, msg, n_sigs) in actions:
                ok = results[idx]
                entry_oks = (
                    results[idx + 1 : idx + n_sigs] if kind == BATCH else None
                )
                idx += n_sigs
                self._cores[sid]._post_action(kind, msg, ok, entry_oks)
        else:
            per_shard: Dict[int, list] = {}
            for sid, (kind, msg, n_sigs) in actions:
                ok = results[idx]
                entry_oks = (
                    results[idx + 1 : idx + n_sigs] if kind == BATCH else None
                )
                idx += n_sigs
                per_shard.setdefault(sid, []).append(
                    (kind, msg, ok, entry_oks)
                )
            futs = [
                self._executor.submit(
                    sid, self._run_actions, self._cores[sid], alist
                )
                for sid, alist in per_shard.items()
            ]
            if futs:
                await asyncio.gather(
                    *(asyncio.wrap_future(f) for f in futs)
                )
            self._flush_effects()
        self._maybe_fire_stall()

    @staticmethod
    def _run_actions(core: Broadcast, alist) -> None:
        """Shard-thread entry point: apply this shard's verified actions
        in order. Exceptions stay on the shard (logged) so one poisoned
        message cannot take the owner's drain cycle down."""
        for kind, msg, ok, entry_oks in alist:
            try:
                core._post_action(kind, msg, ok, entry_oks)
            except Exception:
                logger.exception("shard action error")

    # -- effects + stall marshaling ---------------------------------------

    def _fire_stall(self) -> None:
        # inline cores call straight through on the owner loop
        self._stall_pending = True

    def _make_thread_stall(self, sid: int):
        effects = self._effects[sid]

        def _stall() -> None:
            effects.put(("stall",))

        return _stall

    def _flush_effects(self) -> None:
        """Apply queued shard effects on the owner loop (threaded mode).
        Per-queue FIFO keeps each shard's sends in its own order — the
        same guarantee the monolithic plane gave within a worker chunk."""
        worst = 0
        for q in self._effects:
            items, handoff = q.drain()
            if handoff > worst:
                worst = handoff
            for item in items:
                tag = item[0]
                if tag == "send":
                    self.mesh.send(item[1], item[2])
                elif tag == "broadcast":
                    self.mesh.broadcast(item[1])
                elif tag == "deliver":
                    self.delivered.put_nowait(item[1])
                elif tag == "stall":
                    self._stall_pending = True
        if worst > 0:
            self._handoff_hist.observe(worst)

    def _maybe_fire_stall(self) -> None:
        if not self._stall_pending:
            return
        self._stall_pending = False
        if self.stall_handler is not None:
            try:
                self.stall_handler()
            except Exception:
                logger.exception("stall handler error")

    # -- GC ---------------------------------------------------------------

    async def _gc_loop(self) -> None:
        while True:
            await self.clock.sleep(GC_INTERVAL)
            now = self.clock.monotonic()
            if self._inline:
                self._gc_pass_global(now)
            else:
                futs = [
                    self._executor.submit(sid, core._gc_pass, now)
                    for sid, core in enumerate(self._cores)
                ]
                await asyncio.gather(
                    *(asyncio.wrap_future(f) for f in futs),
                    return_exceptions=True,
                )
                self._flush_effects()
            self._maybe_fire_stall()

    def _gc_pass_global(self, now: float) -> None:
        """Inline (sim) GC: interleave EVERY shard's slots in global
        creation order under ONE retransmit budget and ONE plane-level
        stall hysteresis — exactly the pass the monolithic plane runs
        over its single insertion-ordered slot dict, so retransmission
        order (and with it the sim wire trace) is shard-count-invariant.
        Threaded mode keeps per-core passes instead: real-time hosts buy
        GC parallelism with a per-shard budget, a trade the sim never
        makes."""
        ph = self.phases
        t_gc = ph.t() if ph is not None else 0
        budget = [RETRANSMIT_BUDGET_PER_PASS]
        stalled = False
        tx = [
            (state.birth, core, slot)
            for core in self._cores
            for slot, state in core._slots.items()
        ]
        tx.sort(key=lambda e: e[0])
        for _, core, slot in tx:
            if core._gc_tx_slot(slot, now, budget):
                stalled = True
        batches = [
            (state.birth, core, slot)
            for core in self._cores
            for slot, state in core._batch_slots.items()
        ]
        batches.sort(key=lambda e: e[0])
        for _, core, slot in batches:
            if core._gc_batch_slot(slot, now, budget):
                stalled = True
        Broadcast._gc_resolve_stall(self, now, stalled)
        if ph is not None:
            ph.add("slot_gc", t_gc)

    # -- cross-shard service surface --------------------------------------

    def release_entry(self, sender: bytes, sequence: int) -> None:
        # the registry is shared: one pop releases the binding plane-wide
        self._cores[0].release_entry(sender, sequence)

    def export_watermarks(self) -> dict:
        """Merge per-shard watermark exports. Keys partition by shard for
        LIVE attestation bumps, but restored floors are fanned to every
        core, so merge with max to stay monotone either way."""
        tx: Dict[str, int] = {}
        batch: Dict[str, int] = {}
        for core in self._cores:
            doc = core.export_watermarks()
            for k, v in doc["tx"].items():
                tx[k] = max(tx.get(k, 0), v)
            for k, v in doc["batch"].items():
                batch[k] = max(batch.get(k, 0), v)
        return {"tx": tx, "batch": batch}

    def restore_watermarks(self, doc: dict) -> None:
        for core in self._cores:
            core.restore_watermarks(doc)

    def plane_info(self) -> dict:
        """The /statusz ``plane`` block (tools/top.py shards column)."""
        return {
            "shards": self.shards,
            "executor": self._executor.name,
            "effects_dropped": sum(q.dropped for q in self._effects),
        }

    # handler hooks are plain attributes on Broadcast; fan writes through
    # so cores see the service's callbacks (the sharded plane routes
    # control messages to core 0, but catchup replies can come from any
    # core's GC pass via stall, so keep them all consistent)
    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        if name in (
            "catchup_handler",
            "directory_handler",
            "config_handler",
            "beacon_handler",
        ):
            for core in getattr(self, "_cores", ()):  # pre-init writes
                setattr(core, name, value)
