"""Sharded broadcast plane: per-origin slot shards behind one ingress.

The monolithic :class:`~.stack.Broadcast` runs every slot state machine
on the event loop, which caps plane-only capacity at one core no matter
how many the host has. This module partitions that state by ORIGIN KEY —
the first key of every slot: client sender for the per-tx plane, batch
origin for the batch plane — into N full :class:`Broadcast` cores, each
owning a disjoint slice of quorum bitmaps, dedup sets, slot GC, and
poison resolution. Partitioning by the slot's own key means every
message about a given slot lands on the same shard, so no per-slot state
is ever shared and the cores need no locks.

What stays on the owner loop (cross-shard concerns):

* ingress: ONE inbox, one parse pass (native ingest when available),
  and ONE bulk ``verify_many`` per drain cycle across all shards — the
  batched verifier keeps its amortization regardless of shard count;
* the delivered queue the service's commit tail consumes (commit-tail
  ordering is whatever order shard effects are applied in, exactly as
  the monolithic plane's was worker-chunk order);
* the entry registry — the (client sender, seq) -> first-endorsed-entry
  equivocation guard spans BOTH planes, and a client's per-tx slots and
  the node batches carrying that client's entries can hash to different
  shards, so the registry is one shared structure injected into every
  core;
* membership epochs, watermark export (merged), stats (one shared
  counter group), and the stall-kick signal.

Executor seam (parallel/plane.py): ``inline`` runs every shard closure
synchronously on the caller IN ARRIVAL ORDER — one logical worker, so
the wire behavior is byte-identical to the monolithic plane and the
same-seed sim campaign hash is IDENTICAL at shards=1 and shards=4
(tests/test_plane_shards.py). ``thread`` pins one OS thread per shard;
Python-level transitions still serialize on the GIL, so the real-host
scaling comes from the GIL-released native kernels (quorum counting,
parse, verify) overlapping across shards. Shard threads never touch the
mesh or the delivered queue directly: effects are handed back through
bounded SPSC queues and applied by the owner loop after each dispatch.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import struct
from collections import deque
from typing import Dict, List, Optional

from ..parallel import plane_worker as pw
from ..parallel.plane import SPSCQueue, make_plane_executor
from ..parallel.plane_worker import STAT_KEYS, WorkerSpec
from .messages import (
    BATCH,
    BATCH_ECHO,
    BATCH_READY,
    BATCH_REQ,
    ECHO,
    GOSSIP,
    READY,
    REQUEST,
    Attestation,
    BatchAttestation,
    BatchContentRequest,
    ContentRequest,
    Payload,
    TxBatch,
)
from .stack import (
    GC_INTERVAL,
    INBOX_MAX_BYTES,
    RETRANSMIT_BUDGET_PER_PASS,
    STALL_KICK_MIN_INTERVAL,
    WORKER_CHUNK,
    Broadcast,
)

# wire kinds whose state lives on a shard core (everything else is
# control plane, dispatched on the owner through core 0's handlers)
_SLOT_KINDS = frozenset(
    (GOSSIP, ECHO, READY, REQUEST, BATCH, BATCH_ECHO, BATCH_READY, BATCH_REQ)
)
_SLOT_TYPES = (
    Payload,
    Attestation,
    TxBatch,
    BatchAttestation,
    ContentRequest,
    BatchContentRequest,
)

logger = logging.getLogger(__name__)

__all__ = ["ShardedPlane", "shard_of"]

# plane_shard_handoff_ns histogram ladder: 1µs .. ~33s, in ns.
_HANDOFF_BOUNDS = tuple(1e3 * 2.0**i for i in range(26))


def shard_of(key: bytes, shards: int) -> int:
    """Stable origin-key -> shard map. The first 8 bytes of an ed25519
    key are uniform, so a modulus spreads origins evenly; stability (no
    dependence on arrival order or shard load) is what makes the
    partition deterministic and the sim hash shard-count-invariant."""
    return int.from_bytes(key[:8], "little") % shards


class _ShardMesh:
    """Mesh facade for a THREADED shard core: reads delegate, sends are
    queued as effects for the owner loop (mesh transports are event-loop
    affine and must not be touched from shard threads)."""

    __slots__ = ("_real", "_effects")

    def __init__(self, real, effects: SPSCQueue) -> None:
        self._real = real
        self._effects = effects

    @property
    def peers(self):
        return self._real.peers

    @property
    def by_sign(self):
        return self._real.by_sign

    def send(self, peer, data: bytes) -> None:
        self._effects.put(("send", peer, data))

    def broadcast(self, data: bytes) -> None:
        self._effects.put(("broadcast", data))


class _ShardDelivered:
    """Delivered-queue facade for a THREADED shard core: deliveries are
    effects, re-put into the real asyncio queue by the owner."""

    __slots__ = ("_effects",)

    def __init__(self, effects: SPSCQueue) -> None:
        self._effects = effects

    def put_nowait(self, payload) -> None:
        self._effects.put(("deliver", payload))


class ShardedPlane:
    """N per-origin :class:`Broadcast` shard cores behind one ingress.

    Drop-in for :class:`Broadcast` at the service seam: same
    constructor shape (plus ``shards`` / ``executor``), same public
    surface (``on_frame``/``broadcast``/``broadcast_batch``/
    ``delivered``/``stats``/handler hooks/watermarks/thresholds).
    ``shards=1`` deployments should keep constructing ``Broadcast``
    directly (node/service.py does) — this class earns its overhead
    only when there are cores to spread across.
    """

    def __init__(
        self,
        keypair,
        mesh,
        verifier,
        *,
        shards: int = 2,
        executor: str = "thread",
        echo_threshold: Optional[int] = None,
        ready_threshold: Optional[int] = None,
        workers: int = 4,
        registry=None,
        trace=None,
        recorder=None,
        clock=None,
        phases=None,
        overlap_ready: bool = False,
        ring_slots: int = 4096,
        ring_slot_bytes: int = 1024,
        worker_profiler: bool = True,
        profiler_hz: float = 97.0,
        profiler_max_nodes: int = 20000,
        obs_flush_s: float = 0.05,
    ) -> None:
        from ..clock import SYSTEM_CLOCK
        from ..obs.registry import Registry

        if shards <= 0:
            raise ValueError("ShardedPlane needs >= 1 shard")
        self.shards = shards
        self.keypair = keypair
        self.mesh = mesh
        self.verifier = verifier
        self.clock = SYSTEM_CLOCK if clock is None else clock
        self.workers = workers
        self.registry = Registry() if registry is None else registry
        self.trace = trace
        self.recorder = recorder
        self.phases = phases
        self._overlap_ready = overlap_ready
        self.delivered: asyncio.Queue = asyncio.Queue()
        self._inbox: asyncio.Queue = asyncio.Queue(maxsize=65536)
        self._inbox_bytes = 0
        self._tasks: list = []
        self._executor = make_plane_executor(
            executor, shards,
            ring_slots=ring_slots, ring_slot_bytes=ring_slot_bytes,
        )
        self._inline = self._executor.name == "inline"
        self._proc = self._executor.name == "process"
        # process-mode owner-side state: merged watermarks, per-shard
        # gauge snapshots, and the crash ledger /healthz attributes
        self._proc_wm_tx: Dict[bytes, int] = {}
        self._proc_wm_batch: Dict[bytes, int] = {}
        self._proc_undeliv = [0] * shards
        self._proc_floor_refusals = [0] * shards
        self.worker_crashed: Dict[int, int] = {}
        self.on_worker_crash = None  # service hook: (shard_id, exitcode)
        self._pending_wm_restore: list = []

        # obs shipping lane (process mode): each worker runs its own
        # diagnosis-tier slice and streams delta records over a dedicated
        # per-shard obs ring; the owner folds them into THIS registry so
        # /metrics, /statusz, /profilez, /debugz see through the process
        # boundary. The lane exists whenever any instrument that would
        # ride it is enabled (worker gating mirrors the owner's).
        self._obs_ship = self._proc and (
            recorder is not None
            or trace is not None
            or phases is not None
            or worker_profiler
        )
        self._worker_profiler = worker_profiler
        self._profiler_hz = profiler_hz
        self._profiler_max_nodes = profiler_max_nodes
        self._obs_flush_s = obs_flush_s
        # per-shard fold state: raw phase-ns vectors (post-mortem + the
        # *_shardN counters derive from these increments), recorder
        # event tails, and folded-stack increments for /profilez merges
        self._obs_phase_ns: List[Dict[str, int]] = [
            dict() for _ in range(shards)
        ]
        self._obs_worker_events: List[deque] = [
            deque(maxlen=2048) for _ in range(shards)
        ]
        self._obs_folds: List[Dict[str, int]] = [dict() for _ in range(shards)]
        self._obs_fold_samples = [0] * shards

        # one effects lane per shard (only drained in threaded mode, but
        # constructed always so instruments exist and stay cheap)
        self._effects: List[SPSCQueue] = [SPSCQueue() for _ in range(shards)]
        self._stall_pending = False
        # plane-level stall hysteresis for the inline global GC pass
        # (Broadcast._gc_resolve_stall duck-types against these)
        self._stall_last_kick = float("-inf")
        self._stall_backoff = STALL_KICK_MIN_INTERVAL

        # service-facing hooks, fanned into the cores below
        self.catchup_handler = None
        self.directory_handler = None
        self.config_handler = None
        self.beacon_handler = None
        self.cert_handler = None
        self.stall_handler = None

        self.stats = self.registry.counter_group((
            "gossip_rx",
            "echo_rx",
            "ready_rx",
            "invalid_sig",
            "delivered",
            "slots_dropped",
            "content_req_tx",
            "content_req_rx",
            "content_served",
            "batch_rx",
            "batch_echo_rx",
            "batch_ready_rx",
            "batch_entries_delivered",
            "retransmits",
            "poison_resolved",
            "slots_retired",
            "stall_kicks_suppressed",
        ))

        # Process mode still builds the owner-side cores, but they stay
        # EMPTY forever: the real shard state lives in the worker
        # processes (parallel/plane_worker.py). What the owner cores
        # provide is the control-plane dispatch seam (core 0's _pre_msg
        # runs the catchup/directory/config/beacon handlers), the
        # threshold/floor bookkeeping the spec factory reads, and an
        # unchanged surface for every cross-shard accessor below.
        owner_side = self._inline or self._proc
        self._cores: List[Broadcast] = []
        for sid in range(shards):
            core = Broadcast(
                keypair,
                mesh if owner_side else _ShardMesh(mesh, self._effects[sid]),
                verifier,  # unused by cores (owner runs the bulk verify)
                echo_threshold=echo_threshold,
                ready_threshold=ready_threshold,
                workers=0,
                registry=None,  # private registry; shared stats below
                trace=trace if self._inline else None,
                recorder=recorder if owner_side else None,
                clock=self.clock,
                phases=(
                    phases.shard_view(sid, self.registry)
                    if phases is not None
                    else None
                ),
                overlap_ready=overlap_ready,
            )
            core.stats = self.stats  # ONE aggregate counter group
            if owner_side:
                core.delivered = self.delivered
                core.stall_handler = self._fire_stall
            else:
                core.delivered = _ShardDelivered(self._effects[sid])
                core.stall_handler = self._make_thread_stall(sid)
            self._cores.append(core)
        # the equivocation registry spans shards (module docstring):
        # every core binds and reads through ONE shared instance
        shared_registry = self._cores[0]._entry_registry
        for core in self._cores[1:]:
            core._entry_registry = shared_registry
        # ONE slot-birth counter across cores: the global creation
        # ordinal reconstructs the monolithic plane's GC iteration order
        # (see _gc_pass_global)
        shared_births = self._cores[0]._birth_seq
        for core in self._cores[1:]:
            core._birth_seq = shared_births

        self.registry.gauge(
            "slots_undelivered", "live undelivered broadcast slots",
            fn=lambda: (
                sum(c._undelivered for c in self._cores)
                + sum(self._proc_undeliv)
            ),
        )
        self.registry.gauge(
            "inbox_depth", "raw frames queued for the broadcast workers",
            fn=lambda: self._inbox.qsize(),
        )
        self.registry.gauge(
            "plane_shards", "broadcast plane shard count",
            fn=lambda: float(self.shards),
        )
        self.registry.gauge(
            "plane_shard_queue_depth",
            "deepest shard effects handoff lane right now (queue items "
            "for thread shards, ring slots for process shards)",
            fn=lambda: float(max(
                max(len(q) for q in self._effects),
                max((len(r) for r in self._live_rings()), default=0),
            )),
        )
        self.registry.gauge(
            "plane_shard_effects_dropped",
            "shard handoff records refused at lane capacity "
            "(producer-side drop accounting; should be 0)",
            fn=lambda: float(self.effects_dropped),
        )
        self.registry.gauge(
            "obs_records_dropped",
            "observability delta records shed at obs-ring capacity "
            "(accounted loss, never backpressure; distinct from "
            "plane_shard_effects_dropped)",
            fn=lambda: float(self.obs_dropped),
        )
        self._handoff_hist = self.registry.histogram(
            "plane_shard_handoff_ns",
            "shard effect enqueue-to-apply latency (ns)",
            bounds=_HANDOFF_BOUNDS,
        )

    # -- threshold fan-out (service reconfigures these on membership
    # epochs; every core must agree or quorum math diverges per shard) --

    @property
    def echo_threshold(self) -> int:
        return self._cores[0].echo_threshold

    @echo_threshold.setter
    def echo_threshold(self, value: int) -> None:
        for core in self._cores:
            core.echo_threshold = value
        self._proc_push_thresholds()

    @property
    def ready_threshold(self) -> int:
        return self._cores[0].ready_threshold

    @ready_threshold.setter
    def ready_threshold(self, value: int) -> None:
        for core in self._cores:
            core.ready_threshold = value
        self._proc_push_thresholds()

    def _proc_push_thresholds(self) -> None:
        if not self._proc or not self._executor._started:
            return
        payload = struct.pack(
            "<II",
            self._cores[0].echo_threshold,
            self._cores[0].ready_threshold,
        )
        for ring in self._executor.actions:
            ring.put(pw.C_THRESH, payload)

    @property
    def on_attest(self):
        return self._cores[0].on_attest

    @on_attest.setter
    def on_attest(self, hook) -> None:
        for core in self._cores:
            core.on_attest = hook

    @property
    def floor_refusals(self) -> int:
        return sum(c.floor_refusals for c in self._cores)

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        from ..native import ingest_available

        # pre-build BEFORE spawning workers: they load the cached .so
        await asyncio.get_running_loop().run_in_executor(None, ingest_available)
        if self._proc:
            self._executor.start(self._make_worker_spec)
            self._proc_push_thresholds()
            for doc in self._pending_wm_restore:
                payload = json.dumps(doc).encode()
                for ring in self._executor.actions:
                    ring.put(pw.C_WM_RESTORE, payload)
            self._pending_wm_restore.clear()
            self._tasks.append(asyncio.create_task(self._flusher()))
        for _ in range(self.workers):
            self._tasks.append(asyncio.create_task(self._worker()))
        self._tasks.append(asyncio.create_task(self._gc_loop()))

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self._proc:
            # stop workers FIRST (they flush state on shutdown), fold
            # their final effects in, then unlink the rings
            self._executor.stop_workers()
            try:
                self._flush_proc_effects()
                self._flush_proc_obs()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
        self._executor.shutdown()

    def _make_worker_spec(
        self, sid: int, actions_ring: str, effects_ring: str,
        obs_ring: str = "",
    ) -> WorkerSpec:
        return WorkerSpec(
            shard_id=sid,
            shards=self.shards,
            sign_seed=self.keypair.private_bytes,
            echo_threshold=self._cores[0].echo_threshold,
            ready_threshold=self._cores[0].ready_threshold,
            overlap_ready=self._overlap_ready,
            peers=tuple(
                (p.address, p.exchange_public, p.sign_public, p.region)
                for p in self.mesh.peers
            ),
            actions_ring=actions_ring,
            effects_ring=effects_ring,
            ring_slots=self._executor.ring_slots,
            ring_slot_bytes=self._executor.ring_slot_bytes,
            parent_pid=os.getpid(),
            # worker obs slice: gated by the SAME instruments the owner
            # runs, so thread-mode and process-mode observability agree
            obs_ring=obs_ring if self._obs_ship else "",
            recorder_cap=(
                self.recorder._cap if self.recorder is not None else 0
            ),
            trace_sample=(
                self.trace._sample_every if self.trace is not None else 0
            ),
            phase_accounting=self.phases is not None,
            profiler_hz=self._profiler_hz,
            profiler_max_nodes=self._profiler_max_nodes,
            obs_flush_s=self._obs_flush_s,
        )

    # -- ingress (mirrors Broadcast.on_frame admission exactly) -----------

    async def on_frame(self, peer, frame: bytes) -> None:
        if self.recorder is not None and frame:
            self.recorder.record("rx", (frame[0], len(frame), peer.address))
        if self._inbox_bytes + len(frame) > INBOX_MAX_BYTES:
            logger.warning("inbox byte budget exhausted; dropping frame")
            if self.recorder is not None:
                self.recorder.record("rx_drop", ("bytes", len(frame)))
            return
        try:
            self._inbox.put_nowait((peer, frame))
        except asyncio.QueueFull:
            logger.warning("inbox overflow; dropping frame")
            if self.recorder is not None:
                self.recorder.record("rx_drop", ("depth", len(frame)))
        else:
            self._inbox_bytes += len(frame)

    async def broadcast(self, payload: Payload) -> None:
        await self._inbox.put((None, payload))

    async def broadcast_batch(self, batch: TxBatch) -> None:
        await self._inbox.put((None, batch))

    # -- routing ----------------------------------------------------------

    def _route(self, msg) -> int:
        """The owning shard id for a message — keyed by the SLOT's
        origin key so every message about one slot lands on one core."""
        if isinstance(msg, Payload):
            key = msg.sender
        elif isinstance(msg, Attestation):
            key = msg.sender
        elif isinstance(msg, TxBatch):
            key = msg.origin
        elif isinstance(msg, BatchAttestation):
            key = msg.batch_origin
        elif isinstance(msg, ContentRequest):
            key = msg.sender
        elif isinstance(msg, BatchContentRequest):
            key = msg.batch_origin
        else:
            # control plane (catchup / directory / config): stateless wrt
            # shard slots — handled wherever, keep it on core 0
            return 0
        return shard_of(key, self.shards)

    # -- drain cycle ------------------------------------------------------

    async def _worker(self) -> None:
        while True:
            item = await self._inbox.get()
            chunk = [item]
            while len(chunk) < WORKER_CHUNK:
                try:
                    chunk.append(self._inbox.get_nowait())
                except asyncio.QueueEmpty:
                    break
            for _, payload in chunk:
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    self._inbox_bytes -= len(payload)
            ph = self.phases
            t_plane = ph.begin_plane() if ph is not None else 0
            t0 = ph.t() if ph is not None else 0
            try:
                if self._proc:
                    # process mode: the owner's whole hot path is ONE
                    # native parse+route call and ring copies — no
                    # message objects, no verify_wait on this loop
                    self._dispatch_chunk_proc(chunk)
                    if ph is not None:
                        ph.add("rx_decode", t0)
                else:
                    msgs = self._parse_chunk_routed(chunk)
                    if ph is not None:
                        ph.add("rx_decode", t0)
                    await self._process_chunk(msgs)
            except Exception:
                logger.exception("sharded plane worker error")
            if ph is not None:
                ph.end_plane(t_plane)

    def _parse_chunk_routed(self, chunk) -> list:
        """Parse a drained chunk into ``(peer, msg, shard_id)`` triples.

        The fused native call (at2_plane_drain) computes the owning
        shard for every message IN the GIL-released parse pass, so the
        owner loop never runs the per-message isinstance routing chain;
        the Python fallback derives the same ids via :func:`shard_of`
        (differentially pinned in tests/test_plane_shards.py). Ordering
        is exactly ``Broadcast._parse_chunk``'s: local objects first in
        chunk order, then frame messages in frame order."""
        from ..native import plane_drain_native, plane_drain_ready

        out = []
        frames: list = []
        frame_peers: list = []
        for peer, item in chunk:
            if isinstance(item, (bytes, bytearray, memoryview)):
                frames.append(bytes(item))
                frame_peers.append(peer)
            else:
                out.append((peer, item, self._route(item)))
        if not frames:
            return out
        total_bytes = sum(len(f) for f in frames)
        if total_bytes >= 4096 and plane_drain_ready():
            items, frame_ok, _counts = plane_drain_native(frames, self.shards)
            for i, ok in enumerate(frame_ok):
                if not ok:
                    peer = frame_peers[i]
                    logger.warning(
                        "bad frame from %s",
                        peer.address if peer is not None else "local",
                    )
            out.extend(
                (frame_peers[fi], msg, sid) for fi, sid, msg in items
            )
        else:
            parsed = self._cores[0]._parse_chunk(
                list(zip(frame_peers, frames))
            )
            out.extend((peer, msg, self._route(msg)) for peer, msg in parsed)
        return out

    async def _process_chunk(self, msgs) -> None:
        """Stage 1 per message in ARRIVAL order on the owning core, ONE
        bulk verify for the whole chunk, stage 3 in arrival order
        (inline) or grouped per shard on the executor (threaded).
        ``msgs`` are ``(peer, msg, shard_id)`` triples from
        :meth:`_parse_chunk_routed`."""
        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        to_verify: list = []
        actions: list = []  # (shard_id, (kind, msg, n_sigs))
        scratch: list = []
        for peer, msg, sid in msgs:
            self._cores[sid]._pre_msg(peer, msg, to_verify, scratch)
            if scratch:
                actions.append((sid, scratch[0]))
                scratch.clear()
        if ph is not None:
            t0 = ph.add("rx_decode", t0)
        if not to_verify:
            if not self._inline:
                self._flush_effects()
            self._maybe_fire_stall()
            return
        results = await self.verifier.verify_many(to_verify)
        if ph is not None:
            ph.add("verify_wait", t0)

        idx = 0
        if self._inline:
            for sid, (kind, msg, n_sigs) in actions:
                ok = results[idx]
                entry_oks = (
                    results[idx + 1 : idx + n_sigs] if kind == BATCH else None
                )
                idx += n_sigs
                self._cores[sid]._post_action(kind, msg, ok, entry_oks)
        else:
            per_shard: Dict[int, list] = {}
            for sid, (kind, msg, n_sigs) in actions:
                ok = results[idx]
                entry_oks = (
                    results[idx + 1 : idx + n_sigs] if kind == BATCH else None
                )
                idx += n_sigs
                per_shard.setdefault(sid, []).append(
                    (kind, msg, ok, entry_oks)
                )
            futs = [
                self._executor.submit(
                    sid, self._run_actions, self._cores[sid], alist
                )
                for sid, alist in per_shard.items()
            ]
            if futs:
                await asyncio.gather(
                    *(asyncio.wrap_future(f) for f in futs)
                )
            self._flush_effects()
        self._maybe_fire_stall()

    @staticmethod
    def _run_actions(core: Broadcast, alist) -> None:
        """Shard-thread entry point: apply this shard's verified actions
        in order. Exceptions stay on the shard (logged) so one poisoned
        message cannot take the owner's drain cycle down."""
        for kind, msg, ok, entry_oks in alist:
            try:
                core._post_action(kind, msg, ok, entry_oks)
            except Exception:
                logger.exception("shard action error")

    # -- process-mode owner loop ------------------------------------------

    def _dispatch_chunk_proc(self, chunk) -> None:
        """Process-mode stage 1: ONE native parse+route call over the
        chunk's frames, then flat ``peer_sign + wire`` records into each
        owning shard's actions ring. Slot-bound kinds never become
        Python objects on the owner; control kinds (catchup, directory,
        config, beacon) are peeled off and dispatched through core 0's
        handlers right here. A record that does not fit its ring is
        dropped with producer-side accounting (``effects_dropped``) —
        the same best-effort contract as every other plane lane."""
        from ..native import plane_drain_native, plane_drain_ready

        rings = self._executor.actions
        frames: list = []
        frame_peers: list = []
        for peer, item in chunk:
            if isinstance(item, (bytes, bytearray, memoryview)):
                frames.append(bytes(item))
                frame_peers.append(peer)
            else:
                # locally-submitted Payload/TxBatch: encode and ship to
                # the owning shard (the sentinel peer means "local")
                rings[self._route(item)].put(
                    pw.C_MSG, pw._LOCAL_SENTINEL + item.encode()
                )
        if not frames:
            return
        if plane_drain_ready():
            items, frame_ok, _counts = plane_drain_native(
                frames, self.shards, want_objects=False
            )
            for i, ok in enumerate(frame_ok):
                if not ok:
                    peer = frame_peers[i]
                    logger.warning(
                        "bad frame from %s",
                        peer.address if peer is not None else "local",
                    )
            for fidx, sid, kind, wire in items:
                if kind in _SLOT_KINDS:
                    peer = frame_peers[fidx]
                    pub = (
                        peer.sign_public if peer is not None
                        else pw._LOCAL_SENTINEL
                    )
                    rings[sid].put(pw.C_MSG, pub + wire)
                else:
                    self._ctrl_dispatch_wire(frame_peers[fidx], wire)
        else:
            parsed = self._cores[0]._parse_chunk(
                list(zip(frame_peers, frames))
            )
            for peer, msg in parsed:
                if isinstance(msg, _SLOT_TYPES):
                    pub = (
                        peer.sign_public if peer is not None
                        else pw._LOCAL_SENTINEL
                    )
                    rings[self._route(msg)].put(pw.C_MSG, pub + msg.encode())
                else:
                    self._ctrl_dispatch(peer, msg)

    def _ctrl_dispatch_wire(self, peer, wire: bytes) -> None:
        from .messages import WireError, parse_frame

        try:
            msgs = parse_frame(wire)
        except WireError:  # pragma: no cover - native already validated
            return
        for msg in msgs:
            self._ctrl_dispatch(peer, msg)

    def _ctrl_dispatch(self, peer, msg) -> None:
        """Owner-side control dispatch through core 0's handler seam
        (control kinds touch no shard slot state, only service hooks)."""
        scratch_v: list = []
        scratch_a: list = []
        try:
            self._cores[0]._pre_msg(peer, msg, scratch_v, scratch_a)
        except Exception:
            logger.exception("control dispatch error")

    async def _flusher(self) -> None:
        """Process-mode owner task: poll every shard's effects ring,
        apply records, and watch worker health. Adaptive cadence: tight
        while records flow, relaxed when idle (the handoff histogram
        keeps the latency honest either way)."""
        while True:
            try:
                n = self._flush_proc_effects()
                n += self._flush_proc_obs()
                self._poll_workers()
            except Exception:
                logger.exception("plane effects flush error")
                n = 0
            self._maybe_fire_stall()
            await asyncio.sleep(0.0005 if n else 0.002)

    def _flush_proc_effects(self) -> int:
        """Drain + apply every worker's effect records on the owner
        loop. Returns the number of records applied."""
        total = 0
        worst = 0
        by_sign = self.mesh.by_sign
        for sid, ring in enumerate(self._executor.effects):
            recs, handoff = ring.drain()
            if handoff > worst:
                worst = handoff
            for kind, payload in recs:
                if kind == pw.E_SEND:
                    peer = by_sign.get(payload[:32])
                    if peer is not None:
                        self.mesh.send(peer, payload[32:])
                elif kind == pw.E_BCAST:
                    self.mesh.broadcast(payload)
                elif kind == pw.E_DELIVER:
                    msg = Payload.decode_body(payload[:140])
                    object.__setattr__(msg, "_chash", payload[140:172])
                    self.delivered.put_nowait(msg)
                elif kind == pw.E_STALL:
                    self._stall_pending = True
                elif kind == pw.E_STATS:
                    for i, key in enumerate(STAT_KEYS):
                        delta = int.from_bytes(
                            payload[i * 8 : (i + 1) * 8], "little"
                        )
                        if delta:
                            self.stats[key] += delta
                elif kind == pw.E_WM:
                    key = payload[1:33]
                    seq = int.from_bytes(payload[33:41], "little")
                    wm = (
                        self._proc_wm_tx if payload[0] == 0
                        else self._proc_wm_batch
                    )
                    if wm.get(key, -1) < seq:
                        wm[key] = seq
                elif kind == pw.E_INFO:
                    undeliv, floors = struct.unpack("<IQ", payload)
                    self._proc_undeliv[sid] = undeliv
                    self._proc_floor_refusals[sid] = floors
            total += len(recs)
        if worst > 0:
            self._handoff_hist.observe(worst)
        return total

    # -- obs shipping lane: owner-side fold ------------------------------

    def _flush_proc_obs(self) -> int:
        """Drain every worker's obs ring and fold the delta records into
        the owner's registry / tracer / event tails. Returns the number
        of records folded (feeds the flusher's adaptive cadence)."""
        if not self._obs_ship or not self._executor._started:
            return 0
        total = 0
        for sid in range(len(self._executor.obs)):
            total += self._drain_obs_ring(sid)
        return total

    def _drain_obs_ring(self, sid: int) -> int:
        recs, _ = self._executor.obs[sid].drain()
        for kind, payload in recs:
            try:
                self._apply_obs_record(sid, kind, payload)
            except Exception:
                logger.exception("obs record fold error (shard %d)", sid)
        return len(recs)

    def _apply_obs_record(self, sid: int, kind: int, payload: bytes) -> None:
        from ..obs.profiler import (
            PHASE_BOUNDS,
            PHASES,
            PLANE_LEAF_PHASES,
            parse_folded,
        )

        if kind == pw.O_PHASE:
            # Fold rules mirror thread-mode ShardPhaseView: leaf phases
            # dual-write base + shardN; slot_gc (and any other non-leaf
            # a worker marks) goes to base only; plane_total goes ONLY
            # to its shardN counter — the worker's drain-cycle span and
            # the owner's dispatch span are DIFFERENT denominators, and
            # profile_collect sums them explicitly.
            head, nb = pw._ophase, len(PHASE_BOUNDS) + 1
            step = head.size + 4 * nb
            for off in range(0, len(payload), step):
                idx, ns, count, sum_s, max_s = head.unpack_from(payload, off)
                if idx >= len(PHASES):
                    continue  # vocabulary drift: shed rather than crash
                phase = PHASES[idx]
                buckets = struct.unpack_from(f"<{nb}I", payload, off + head.size)
                acc = self._obs_phase_ns[sid]
                acc[phase] = acc.get(phase, 0) + ns
                if phase == "plane_total":
                    self.registry.counter(
                        f"phase_plane_total_shard{sid}_ns",
                        "elapsed ns of plane shard worker drain cycles "
                        f"(shard {sid} process)",
                    ).inc(ns)
                    continue
                self.registry.counter(f"phase_{phase}_ns").inc(ns)
                if phase in PLANE_LEAF_PHASES:
                    self.registry.counter(
                        f"phase_{phase}_shard{sid}_ns",
                        f"elapsed ns accounted to phase {phase} on plane "
                        f"shard {sid}",
                    ).inc(ns)
                self.registry.histogram(
                    f"phase_{phase}", bounds=PHASE_BOUNDS
                ).merge_deltas(buckets, sum_s, count, max_s)
        elif kind == pw.O_REC:
            events = json.loads(payload.decode())
            self._obs_worker_events[sid].extend(events)
        elif kind == pw.O_TRACE:
            if self.trace is None:
                return
            rec = pw._otrace
            for off in range(0, len(payload), rec.size):
                sender, seq, stage_idx, mono = rec.unpack_from(payload, off)
                if stage_idx < len(pw.TRACE_STAGES):
                    self.trace.stamp(
                        (sender, seq), pw.TRACE_STAGES[stage_idx], now=mono
                    )
        elif kind == pw.O_FOLD:
            samples = int.from_bytes(payload[:8], "little")
            self._obs_fold_samples[sid] += samples
            fold = self._obs_folds[sid]
            for stack, count in parse_folded(payload[8:].decode()).items():
                fold[stack] = fold.get(stack, 0) + count

    @property
    def obs_dropped(self) -> int:
        """Producer-side drops on the obs lane only — exported as
        ``obs_records_dropped``, deliberately OUTSIDE
        ``plane_shard_effects_dropped`` (losing a phase delta is an
        observability gap; losing an effect record is protocol loss)."""
        if not self._proc or not self._executor._started:
            return 0
        total = 0
        for ring in self._executor.obs:
            try:
                total += ring.dropped
            except Exception:  # pragma: no cover - ring torn down
                pass
        return total

    def worker_events(self) -> list:
        """Worker-side recorder events shipped over the obs lane, in the
        /debugz event shape with codes prefixed ``shardN/``, sorted by
        mono timestamp (one CLOCK_MONOTONIC machine-wide, so they
        interleave truthfully with owner events)."""
        out = []
        for sid, dq in enumerate(self._obs_worker_events):
            pre = f"shard{sid}/"
            out.extend([t, pre + code, detail] for t, code, detail in dq)
        out.sort(key=lambda e: e[0])
        return out

    def profiler_start(self, duration: Optional[float] = None) -> bool:
        """Fan a StackSampler start to every worker (C_PROF) and reset
        the owner-side fold accumulators, so a /profilez session reports
        only its own window. Returns True if the fan-out happened."""
        if not (
            self._obs_ship
            and self._worker_profiler
            and self._executor._started
        ):
            return False
        for sid in range(self.shards):
            self._obs_folds[sid] = {}
            self._obs_fold_samples[sid] = 0
        payload = pw._prof.pack(1, float(duration if duration else 0.0))
        for ring in self._executor.actions:
            ring.put(pw.C_PROF, payload)
        return True

    def profiler_stop(self) -> bool:
        if not (
            self._obs_ship
            and self._worker_profiler
            and self._executor._started
        ):
            return False
        payload = pw._prof.pack(0, 0.0)
        for ring in self._executor.actions:
            ring.put(pw.C_PROF, payload)
        return True

    def worker_folds(self) -> list:
        """``(prefix, {stack: count})`` parts for
        :func:`~..obs.profiler.merge_folded` — one per shard that has
        shipped folded-stack increments."""
        return [
            (f"shard{sid}/", dict(self._obs_folds[sid]))
            for sid in range(self.shards)
            if self._obs_folds[sid]
        ]

    def worker_fold_samples(self) -> int:
        return sum(self._obs_fold_samples)

    def _poll_workers(self) -> None:
        """Surface worker deaths exactly once each: crash ledger for
        /healthz attribution, flight-recorder code, service hook. The
        plane keeps draining — surviving shards stay live, the dead
        shard's traffic drops with accounting until an operator
        restarts the node (degraded, never hung)."""
        for sid, code in self._executor.poll_crashed():
            self.worker_crashed[sid] = code
            logger.error(
                "plane shard %d worker died (exit %s)", sid, code
            )
            extra = None
            if self._obs_ship:
                # post-mortem: the dead worker can't flush again, but
                # whatever it already shipped is still in shared memory
                # — drain it FIRST so the crash snapshot carries the
                # worker's last recorder events and phase totals
                try:
                    self._drain_obs_ring(sid)
                except Exception:
                    logger.exception("post-mortem obs drain failed")
                extra = {
                    "shard": sid,
                    "exit": code,
                    "recorder_tail": list(self._obs_worker_events[sid])[-64:],
                    "phases": dict(self._obs_phase_ns[sid]),
                }
            if self.recorder is not None:
                try:
                    self.recorder.snapshot(
                        f"plane_worker_crash:shard={sid},exit={code}",
                        extra=extra,
                    )
                except Exception:
                    logger.exception("crash snapshot failed")
            hook = self.on_worker_crash
            if hook is not None:
                try:
                    hook(sid, code)
                except Exception:
                    logger.exception("worker-crash hook error")

    def _live_rings(self):
        if not self._proc or not self._executor._started:
            return ()
        return (*self._executor.actions, *self._executor.effects)

    @property
    def effects_dropped(self) -> int:
        """Producer-side handoff drops across EVERY lane: the in-process
        SPSC queues (thread mode) plus both ring directions (process
        mode). Exported as ``plane_shard_effects_dropped``."""
        total = sum(q.dropped for q in self._effects)
        for ring in self._live_rings():
            try:
                total += ring.dropped
            except Exception:  # pragma: no cover - ring torn down
                pass
        return total

    # -- effects + stall marshaling ---------------------------------------

    def _fire_stall(self) -> None:
        # inline cores call straight through on the owner loop
        self._stall_pending = True

    def _make_thread_stall(self, sid: int):
        effects = self._effects[sid]

        def _stall() -> None:
            effects.put(("stall",))

        return _stall

    def _flush_effects(self) -> None:
        """Apply queued shard effects on the owner loop (threaded mode).
        Per-queue FIFO keeps each shard's sends in its own order — the
        same guarantee the monolithic plane gave within a worker chunk."""
        worst = 0
        for q in self._effects:
            items, handoff = q.drain()
            if handoff > worst:
                worst = handoff
            for item in items:
                tag = item[0]
                if tag == "send":
                    self.mesh.send(item[1], item[2])
                elif tag == "broadcast":
                    self.mesh.broadcast(item[1])
                elif tag == "deliver":
                    self.delivered.put_nowait(item[1])
                elif tag == "stall":
                    self._stall_pending = True
        if worst > 0:
            self._handoff_hist.observe(worst)

    def _maybe_fire_stall(self) -> None:
        if not self._stall_pending:
            return
        self._stall_pending = False
        if self.stall_handler is not None:
            try:
                self.stall_handler()
            except Exception:
                logger.exception("stall handler error")

    # -- GC ---------------------------------------------------------------

    async def _gc_loop(self) -> None:
        while True:
            await self.clock.sleep(GC_INTERVAL)
            now = self.clock.monotonic()
            if self._inline:
                self._gc_pass_global(now)
            elif self._proc:
                # workers GC their own slots; CLOCK_MONOTONIC is one
                # clock machine-wide, so the owner's now is theirs
                payload = struct.pack("<d", now)
                for ring in self._executor.actions:
                    ring.put(pw.C_GC, payload)
            else:
                futs = [
                    self._executor.submit(sid, core._gc_pass, now)
                    for sid, core in enumerate(self._cores)
                ]
                await asyncio.gather(
                    *(asyncio.wrap_future(f) for f in futs),
                    return_exceptions=True,
                )
                self._flush_effects()
            self._maybe_fire_stall()

    def _gc_pass_global(self, now: float) -> None:
        """Inline (sim) GC: interleave EVERY shard's slots in global
        creation order under ONE retransmit budget and ONE plane-level
        stall hysteresis — exactly the pass the monolithic plane runs
        over its single insertion-ordered slot dict, so retransmission
        order (and with it the sim wire trace) is shard-count-invariant.
        Threaded mode keeps per-core passes instead: real-time hosts buy
        GC parallelism with a per-shard budget, a trade the sim never
        makes."""
        ph = self.phases
        t_gc = ph.t() if ph is not None else 0
        budget = [RETRANSMIT_BUDGET_PER_PASS]
        stalled = False
        tx = [
            (state.birth, core, slot)
            for core in self._cores
            for slot, state in core._slots.items()
        ]
        tx.sort(key=lambda e: e[0])
        for _, core, slot in tx:
            if core._gc_tx_slot(slot, now, budget):
                stalled = True
        batches = [
            (state.birth, core, slot)
            for core in self._cores
            for slot, state in core._batch_slots.items()
        ]
        batches.sort(key=lambda e: e[0])
        for _, core, slot in batches:
            if core._gc_batch_slot(slot, now, budget):
                stalled = True
        Broadcast._gc_resolve_stall(self, now, stalled)
        if ph is not None:
            ph.add("slot_gc", t_gc)

    # -- cross-shard service surface --------------------------------------

    def release_entry(self, sender: bytes, sequence: int) -> None:
        # the registry is shared: one pop releases the binding plane-wide
        self._cores[0].release_entry(sender, sequence)
        if self._proc and self._executor._started:
            # process workers each hold a registry; the binding lives on
            # whichever worker bound it — fan the release (no-op pops)
            payload = sender + struct.pack("<Q", sequence)
            for ring in self._executor.actions:
                ring.put(pw.C_RELEASE, payload)

    def export_watermarks(self) -> dict:
        """Merge per-shard watermark exports. Keys partition by shard for
        LIVE attestation bumps, but restored floors are fanned to every
        core, so merge with max to stay monotone either way. Process
        workers stream their bumps through the effects ring; the merged
        owner-side dicts are folded in here."""
        tx: Dict[str, int] = {}
        batch: Dict[str, int] = {}
        for core in self._cores:
            doc = core.export_watermarks()
            for k, v in doc["tx"].items():
                tx[k] = max(tx.get(k, 0), v)
            for k, v in doc["batch"].items():
                batch[k] = max(batch.get(k, 0), v)
        for key, v in self._proc_wm_tx.items():
            k = key.hex()
            tx[k] = max(tx.get(k, 0), v)
        for key, v in self._proc_wm_batch.items():
            k = key.hex()
            batch[k] = max(batch.get(k, 0), v)
        return {"tx": tx, "batch": batch}

    def restore_watermarks(self, doc: dict) -> None:
        for core in self._cores:
            core.restore_watermarks(doc)
        if self._proc:
            if self._executor._started:
                payload = json.dumps(doc).encode()
                for ring in self._executor.actions:
                    ring.put(pw.C_WM_RESTORE, payload)
            else:
                # the usual service order is restore-then-start: queue
                # the doc and replay it right after the workers spawn
                self._pending_wm_restore.append(doc)

    def plane_info(self) -> dict:
        """The /statusz ``plane`` block (tools/top.py shards column)."""
        info = {
            "shards": self.shards,
            "executor": self._executor.name,
            "effects_dropped": self.effects_dropped,
        }
        if self._proc:
            info["obs_records_dropped"] = self.obs_dropped
        if self.worker_crashed:
            info["worker_crashed"] = {
                str(sid): code for sid, code in self.worker_crashed.items()
            }
        return info

    # handler hooks are plain attributes on Broadcast; fan writes through
    # so cores see the service's callbacks (the sharded plane routes
    # control messages to core 0, but catchup replies can come from any
    # core's GC pass via stall, so keep them all consistent)
    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        if name in (
            "catchup_handler",
            "directory_handler",
            "config_handler",
            "beacon_handler",
            "cert_handler",
        ):
            for core in getattr(self, "_cores", ()):  # pre-init writes
                setattr(core, name, value)
