"""send-asset firehose load generator (BASELINE.json config 3).

Drives an AT2 network the way the reference's shell tests do — real gRPC
`SendAsset` calls from real client identities — but at benchmark
intensity: K concurrent clients, each with its own keypair, pipelining
transfers with incrementing sequences, spread round-robin over the
node RPC endpoints. Progress is measured by the ledger itself (polling
`GetLastSequence` per sender on a node that did NOT take the writes,
so a count only registers after broadcast totality commits it).

Usage:
    python -m at2_node_tpu.tools.loadgen \
        --rpc http://127.0.0.1:4001 --rpc http://127.0.0.1:4003 \
        --clients 16 --tx-per-client 100 [--window 8] [--json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from dataclasses import dataclass
from typing import List

from ..client import Client, RetryPolicy
from ..crypto.keys import SignKeyPair


@dataclass
class LoadResult:
    clients: int
    tx_per_client: int
    submitted: int
    committed: int
    submit_seconds: float
    commit_seconds: float

    @property
    def committed_tx_per_sec(self) -> float:
        return self.committed / self.commit_seconds if self.commit_seconds else 0.0


async def _client_worker(
    uri: str, keypair: SignKeyPair, n_tx: int, window: int, rpc_batch: int = 1,
    retry_budget: int = 0,
) -> int:
    """Issue n_tx self-transfers with sequences 1..n_tx, keeping up to
    ``window`` requests in flight (a firehose, not a lockstep loop).
    ``rpc_batch`` > 1 ships them ``rpc_batch`` per SendAssetBatch call
    (the beyond-parity bulk ingress) instead of one per SendAsset.
    ``retry_budget`` > 0 arms the client's jittered retry policy for
    RESOURCE_EXHAUSTED sheds (the server's [overload] ladder)."""
    sent = 0
    window = max(window, 1)
    retry = RetryPolicy(budget=retry_budget) if retry_budget > 0 else None
    async with Client(uri, retry=retry) as client:
        pending: set = set()

        async def _drain_one():
            nonlocal pending, sent
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for t in done:
                t.result()
                sent += t.tx_count

        if rpc_batch > 1:
            for lo in range(1, n_tx + 1, rpc_batch):
                seqs = range(lo, min(lo + rpc_batch, n_tx + 1))
                if len(pending) >= window:
                    await _drain_one()
                task = asyncio.create_task(
                    client.send_asset_many(
                        keypair, [(s, keypair.public, 1) for s in seqs]
                    )
                )
                task.tx_count = len(seqs)
                pending.add(task)
        else:
            for seq in range(1, n_tx + 1):
                if len(pending) >= window:
                    await _drain_one()
                task = asyncio.create_task(
                    client.send_asset(keypair, seq, keypair.public, 1)
                )
                task.tx_count = 1
                pending.add(task)
        for t in pending:
            await t
            sent += t.tx_count
    return sent


async def _wait_committed(
    uri: str, keypairs: List[SignKeyPair], n_tx: int, timeout: float
) -> int:
    """Poll a (read-side) node until every sender's last sequence reaches
    n_tx or the timeout expires; returns total committed transactions."""
    deadline = time.monotonic() + timeout
    async with Client(uri) as client:
        remaining = {kp.public: 0 for kp in keypairs}
        while time.monotonic() < deadline:
            for pk in list(remaining):
                seq = await client.get_last_sequence(pk)
                remaining[pk] = seq
                if seq >= n_tx:
                    del remaining[pk]
            if not remaining:
                return n_tx * len(keypairs)
            await asyncio.sleep(0.1)
        done = n_tx * len(keypairs) - sum(
            n_tx - seq for seq in remaining.values()
        )
        return done


async def run_load(
    rpcs: List[str],
    clients: int = 16,
    tx_per_client: int = 100,
    window: int = 8,
    commit_timeout: float = 120.0,
    rpc_batch: int = 1,
    broker: bool = False,
    retry_budget: int = 0,
) -> LoadResult:
    keypairs = [SignKeyPair.random() for _ in range(clients)]
    if broker:
        # Directory warmup: pre-register every client identity so the
        # measured window ships distilled frames with resolvable ids,
        # not Register round-trips. The endpoints serve the same at2.AT2
        # surface either way (the broker proxies reads through), so only
        # this warmup differs from direct-node mode.
        async def _register(uri: str, kp: SignKeyPair) -> None:
            async with Client(uri) as c:
                await c.register(kp.public)

        await asyncio.gather(
            *(
                _register(rpcs[i % len(rpcs)], kp)
                for i, kp in enumerate(keypairs)
            )
        )
    t0 = time.monotonic()
    sent = await asyncio.gather(
        *(
            _client_worker(
                rpcs[i % len(rpcs)], kp, tx_per_client, window, rpc_batch,
                retry_budget,
            )
            for i, kp in enumerate(keypairs)
        )
    )
    submit_s = time.monotonic() - t0
    # read from the LAST endpoint, round-robin ensured writes went elsewhere
    # too; totality means any node converges
    committed = await _wait_committed(
        rpcs[-1], keypairs, tx_per_client, commit_timeout
    )
    commit_s = time.monotonic() - t0
    return LoadResult(
        clients=clients,
        tx_per_client=tx_per_client,
        submitted=sum(sent),
        committed=committed,
        submit_seconds=submit_s,
        commit_seconds=commit_s,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rpc", action="append", required=True,
                    help="node RPC URL (repeat for round-robin)")
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--tx-per-client", type=int, default=100)
    ap.add_argument("--window", type=int, default=8)
    ap.add_argument("--commit-timeout", type=float, default=120.0)
    ap.add_argument("--rpc-batch", type=int, default=1,
                    help="transfers per SendAssetBatch call (1 = unary "
                    "SendAsset, reference-parity surface)")
    ap.add_argument("--broker", action="store_true",
                    help="the --rpc endpoints are broker ingress tiers "
                    "(tools/broker.py): pre-register every client into "
                    "the directory, then fire the same load — the broker "
                    "distills it into SendDistilledBatch frames")
    ap.add_argument("--retry-budget", type=int, default=0,
                    help="retries per call for RESOURCE_EXHAUSTED sheds "
                    "(jittered exponential backoff honoring the server's "
                    "retry_after_ms hint; 0 = fail fast)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    res = asyncio.run(
        run_load(
            args.rpc,
            clients=args.clients,
            tx_per_client=args.tx_per_client,
            window=args.window,
            commit_timeout=args.commit_timeout,
            rpc_batch=args.rpc_batch,
            broker=args.broker,
            retry_budget=args.retry_budget,
        )
    )
    if args.json:
        print(json.dumps({
            "clients": res.clients,
            "submitted": res.submitted,
            "committed": res.committed,
            "submit_seconds": round(res.submit_seconds, 3),
            "commit_seconds": round(res.commit_seconds, 3),
            "committed_tx_per_sec": round(res.committed_tx_per_sec, 1),
        }))
    else:
        print(
            f"{res.committed}/{res.clients * res.tx_per_client} tx committed "
            f"in {res.commit_seconds:.2f}s -> "
            f"{res.committed_tx_per_sec:.0f} tx/s"
        )
    return 0 if res.committed == res.clients * res.tx_per_client else 1


if __name__ == "__main__":
    sys.exit(main())
