"""Fleet plane-time decomposition: profile every node, name the serial term.

The continuous-profiler companion to trace_collect (ISSUE 11): for each
node it snapshots the phase-accounting counters from /statusz, starts a
sampling capture via /profilez?start&duration=D, waits out the window,
snapshots the counters again, and pulls the folded stacks. The counter
*deltas* over the window give an exact per-phase time decomposition of
the broadcast planes (shares of plane_total), and the hottest folded
stack attributes the top serial term to a file:line.

Usage:
    python -m at2_node_tpu.tools.profile_collect HOST:PORT [HOST:PORT ...]
        [--duration 5.0] [--min-coverage 0.0] [--json] [--out FILE]

Per node the report shows:
  - the phase table: share of plane_total per leaf phase (rx decode,
    verify wait, echo apply, quorum bitmap, ready/deliver, entry
    registry) plus the off-plane accounts (slot gc, commit tail,
    verifier flush) as absolute ms,
  - coverage: how much of plane wall time the leaf phases explain
    (sum of leaf shares; the remainder is unmarked glue),
  - the top serial term: the largest leaf share, attributed to the
    hottest sampled stack's leaf frame (file:line),
  - the node's build block (git SHA, Python/JAX versions, config hash)
    so reports are comparable across fleet versions,
  - in process mode, a per-shard row per worker (its plane wall time and
    hottest leaf phase from the ``phase_*_shardN_ns`` fold); the
    coverage denominator is the MERGED plane total — owner
    ``phase_plane_total_ns`` delta plus every worker's
    ``phase_plane_total_shardN_ns`` delta — so coverage stays honest
    when most plane time runs inside worker processes.

``--min-coverage PCT`` makes the exit code a gate: nonzero when any
node's leaf phases explain less than PCT% of its plane wall time —
that means a new serial term appeared that nothing accounts for.
Unreachable nodes always fail the run.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import re
import sys

from ..obs.profiler import PLANE_LEAF_PHASES, PHASES, build_info
from ._common import fetch_json, parse_addr as _parse_addr

_OFF_PLANE = tuple(
    p for p in PHASES if p not in PLANE_LEAF_PHASES and p != "plane_total"
)

# process-mode fold keys (broadcast/shards.py): per-shard phase counters
_SHARD_KEY = re.compile(r"^phase_([a-z_]+)_shard(\d+)_ns$")


def _phase_deltas(stats0: dict, stats1: dict) -> dict:
    """ns spent per phase over the capture window, from the exact
    counters the hot paths bump (phase_<name>_ns in /statusz stats)."""
    out = {}
    for p in PHASES:
        key = f"phase_{p}_ns"
        v0, v1 = stats0.get(key, 0), stats1.get(key, 0)
        if isinstance(v0, (int, float)) and isinstance(v1, (int, float)):
            out[p] = max(0, int(v1) - int(v0))
        else:
            out[p] = 0
    return out


def _shard_deltas(stats0: dict, stats1: dict) -> dict:
    """Per-shard phase ns deltas, ``{shard_id: {phase: ns}}``, from the
    ``phase_<p>_shard<k>_ns`` counters the process-mode obs fold
    maintains (broadcast/shards.py). Empty in thread/inline mode —
    those counters simply never exist there."""
    out: dict = {}
    for key, v1 in stats1.items():
        m = _SHARD_KEY.match(key) if isinstance(key, str) else None
        if not m or not isinstance(v1, (int, float)):
            continue
        v0 = stats0.get(key, 0)
        if not isinstance(v0, (int, float)):
            v0 = 0
        out.setdefault(int(m.group(2)), {})[m.group(1)] = max(
            0, int(v1) - int(v0)
        )
    return out


def _top_folded_leaf(folded_lines) -> str:
    """file:line attribution from the hottest sampled stack: the leaf
    frame of the highest-count folded line (labels are
    ``basename:func`` interior, ``basename:func:lineno`` leaf)."""
    best, best_count = None, -1
    for line in folded_lines or ():
        stack, _, count = line.rpartition(" ")
        if not stack or not count.isdigit():
            continue
        if int(count) > best_count:
            best_count = int(count)
            best = stack.rsplit(";", 1)[-1]
    return best or "(no samples)"


def decompose(stats0: dict, stats1: dict, profile: dict) -> dict:
    """One node's plane decomposition from two /statusz snapshots and
    the /profilez dump. Pure function of its inputs — unit-testable."""
    deltas = _phase_deltas(stats0, stats1)
    shard = _shard_deltas(stats0, stats1)
    # merged denominator: worker leaf time folds into the base counters,
    # but worker plane_total lives ONLY under the shard keys — counting
    # just the owner's plane_total would overstate coverage in process
    # mode (leaves from N workers over one owner's wall time)
    total = deltas.get("plane_total", 0) + sum(
        d.get("plane_total", 0) for d in shard.values()
    )
    shares = {
        p: (deltas[p] / total if total else 0.0) for p in PLANE_LEAF_PHASES
    }
    coverage = sum(shares.values())
    top_phase = max(
        PLANE_LEAF_PHASES, key=lambda p: shares[p]
    ) if total else None
    shards_out = {}
    for sid in sorted(shard):
        d = shard[sid]
        st = d.get("plane_total", 0)
        leaf = {p: d.get(p, 0) for p in PLANE_LEAF_PHASES}
        top = max(leaf, key=lambda p: leaf[p]) if any(leaf.values()) else None
        shards_out[sid] = {
            "plane_total_ms": st / 1e6,
            "phase_ms": {p: leaf[p] / 1e6 for p in PLANE_LEAF_PHASES},
            "shares": {
                p: (leaf[p] / st if st else 0.0) for p in PLANE_LEAF_PHASES
            },
            "top_phase": top,
        }
    return {
        "plane_total_ms": total / 1e6,
        "owner_plane_total_ms": deltas.get("plane_total", 0) / 1e6,
        "phase_ms": {p: deltas[p] / 1e6 for p in PHASES},
        "shares": shares,
        "shards": shards_out,
        "off_plane_ms": {p: deltas[p] / 1e6 for p in _OFF_PLANE},
        "coverage": coverage,
        "top_serial": {
            "phase": top_phase,
            "share": shares[top_phase] if top_phase else 0.0,
            "site": _top_folded_leaf(profile.get("folded")),
        },
        "sampler": profile.get("sampler", {}),
        "build": profile.get("build", {}),
    }


async def collect_node(host: str, port: int, duration: float) -> dict:
    """statusz -> start capture -> wait -> statusz + profilez."""
    sz0 = await fetch_json(host, port, "/statusz")
    started = await fetch_json(
        host, port, f"/profilez?start&duration={duration:g}"
    )
    # +0.5s slack so the sampler's own deadline stop lands first and
    # the folded dump covers the full window
    await asyncio.sleep(duration + 0.5)
    sz1 = await fetch_json(host, port, "/statusz")
    profile = await fetch_json(host, port, "/profilez")
    rec = decompose(sz0.get("stats", {}), sz1.get("stats", {}), profile)
    rec["capture_started"] = bool(started.get("started"))
    rec["node"] = sz1.get("node")
    return rec


def render(results, duration: float, min_coverage: float, out) -> int:
    """The human report; returns the exit code (the gate)."""
    info = build_info()
    print(
        f"profile_collect  duration={duration:g}s  "
        f"collector git={info['git_sha']} python={info['python']} "
        f"jax={info['jax']}",
        file=out,
    )
    rc = 0
    for addr, rec in results:
        if isinstance(rec, Exception):
            print(f"\n{addr}  DOWN {type(rec).__name__}: {rec}", file=out)
            rc = 1
            continue
        build = rec.get("build", {})
        print(
            f"\n{addr}  node={rec.get('node')}  "
            f"git={build.get('git_sha')} cfg={build.get('config_hash')} "
            f"uptime={build.get('uptime_s')}s",
            file=out,
        )
        total = rec["plane_total_ms"]
        shards = rec.get("shards") or {}
        if shards:
            print(
                f"  plane_total {total:.1f} ms over the window "
                f"(owner {rec.get('owner_plane_total_ms', 0.0):.1f} ms + "
                f"{len(shards)} worker shard"
                f"{'s' if len(shards) != 1 else ''})",
                file=out,
            )
        else:
            print(f"  plane_total {total:.1f} ms over the window", file=out)
        for p in PLANE_LEAF_PHASES:
            print(
                f"    {p:<16}{rec['phase_ms'][p]:>10.1f} ms"
                f"{100.0 * rec['shares'][p]:>8.1f} %",
                file=out,
            )
        cov = 100.0 * rec["coverage"]
        print(f"    {'coverage':<16}{'':>10}   {cov:>6.1f} %", file=out)
        off = "  ".join(
            f"{p}={rec['off_plane_ms'][p]:.1f}ms" for p in _OFF_PLANE
        )
        print(f"  off-plane: {off}", file=out)
        for sid in sorted(shards, key=int):
            srec = shards[sid]
            top = srec.get("top_phase")
            top_s = (
                f"{top} {100.0 * srec['shares'][top]:.1f}%"
                if top else "(idle)"
            )
            print(
                f"  shard{sid}: plane {srec['plane_total_ms']:>8.1f} ms"
                f"  top {top_s}",
                file=out,
            )
        top = rec["top_serial"]
        print(
            f"  top serial term: {top['phase']} "
            f"({100.0 * top['share']:.1f}% of plane) at {top['site']}",
            file=out,
        )
        samples = rec.get("sampler", {}).get("samples", 0)
        print(f"  sampler: {samples} samples", file=out)
        if min_coverage and cov < min_coverage:
            print(
                f"  COVERAGE BELOW GATE: {cov:.1f}% < {min_coverage:g}% "
                "— an unmarked serial term is eating plane time",
                file=out,
            )
            rc = 1
    return rc


async def run(addrs, duration: float) -> list:
    results = await asyncio.gather(
        *(collect_node(h, p, duration) for h, p in addrs),
        return_exceptions=True,
    )
    return [(f"{h}:{p}", r) for (h, p), r in zip(addrs, results)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("nodes", nargs="+", metavar="HOST:PORT")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="sampling window per node in seconds (default 5)")
    ap.add_argument("--min-coverage", type=float, default=0.0,
                    metavar="PCT",
                    help="fail (nonzero exit) when leaf phases explain "
                         "less than PCT%% of plane wall time")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw per-node decompositions as JSON")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this file")
    args = ap.parse_args(argv)
    addrs = [_parse_addr(a) for a in args.nodes]
    results = asyncio.run(run(addrs, args.duration))
    doc = {
        "collector_build": build_info(),
        "duration": args.duration,
        "nodes": {
            a: (str(r) if isinstance(r, Exception) else r)
            for a, r in results
        },
    }
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True, default=float)
            fh.write("\n")
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True, default=float))
        return render(results, args.duration, args.min_coverage,
                      out=sys.stderr)
    return render(results, args.duration, args.min_coverage,
                  out=sys.stdout)


if __name__ == "__main__":
    sys.exit(main())
