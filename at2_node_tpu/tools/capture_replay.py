"""Wire-capture → sim replay bridge: re-run a node's inbound traffic.

The capture ring (net/peers.py, ``[observability] capture_cap``) records
every inbound frame a node delivered — ``(mono_ns, peer, kind, frame)``
— and serves it on /capturez. This tool turns that capture into a sim
inject schedule (sim/campaign.py ``inject`` events) and replays it
under VIRTUAL time against a fresh simulated fleet: the relative
inter-frame timing is preserved, the wall-clock is not needed, and the
whole replay — delivery order, invariant sweep, fleet-audit verdict —
is a pure function of (capture, seed, knobs). Replaying the same
capture twice yields a byte-identical verdict, which is exactly what
the CI gate asserts.

What a replay is and is not: the simulated nodes have FRESH keys, so
signed traffic from the real fleet arrives as what it really is to an
outside observer — frames from an unknown origin. That exercises every
inbound defense (parse, signature, origin checks, quota, the fleet
auditor's beacon validation) against real-world bytes, making this a
deterministic fuzz-corpus bridge: any capture that crashes or diverges
a node becomes a seedable, minimizable sim reproducer.

``--minimize`` shrinks a failing replay to the shortest inject schedule
that still fails (sim/campaign.py minimize_events), turning a
thousand-frame capture into a handful-of-frames bug report.

Usage:
    python -m at2_node_tpu.tools.capture_replay CAPTURE.json
        [--seed 1] [--nodes 4] [--target 0] [--speed 1.0]
        [--repeat 2] [--minimize] [--json]
    python -m at2_node_tpu.tools.capture_replay --fetch HOST:PORT ...

CAPTURE.json is a /capturez dump (``{"cap", "captured", "records"}``,
with or without the route's ``node`` wrapper key) — e.g. the
``<node>/capturez.json`` file inside an incident bundle
(tools/incident.py).
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import sys
from typing import List, Optional

from ._common import fetch_json, parse_addr


def capture_to_events(
    doc: dict,
    *,
    target: int = 0,
    speed: float = 1.0,
    start: float = 0.5,
) -> List[list]:
    """Convert a /capturez dump into a sim inject schedule.

    Frames keep their relative spacing (``mono_ns`` deltas over
    ``speed``), re-anchored to virtual ``start``; all are injected into
    node ``target`` from the sim's hostile identity — the sim fleet has
    fresh keys, so to it the captured origin IS an unknown outsider.
    Pure in its inputs; ties on mono_ns keep capture order (stable
    sort), so the schedule — and the replay — is deterministic."""
    records = doc.get("records", [])
    if not records:
        return []
    ordered = sorted(records, key=lambda r: int(r[0]))
    t0 = int(ordered[0][0])
    events = []
    for mono_ns, _peer, _kind, frame_hex in ordered:
        t = start + (int(mono_ns) - t0) / 1e9 / max(speed, 1e-9)
        events.append(
            [t, "inject",
             {"src_hostile": 1, "target": target, "frame": frame_hex}]
        )
    return events


def replay_capture(
    doc: dict,
    seed: int = 1,
    *,
    nodes: int = 4,
    target: int = 0,
    speed: float = 1.0,
    events: Optional[List[list]] = None,
    settle_horizon: float = 60.0,
) -> dict:
    """Replay a capture in the sim and return the verdict.

    The verdict is every deterministic observable that matters:
    invariant violations, the episode trace hash, per-node committed
    counts, and the quiescent fleet-audit state (divergence + counters).
    Pure in (doc, seed, knobs) — hash it and compare across runs.
    ``events`` overrides the schedule derived from ``doc`` (used by
    minimization to replay candidate subsets)."""
    from ..sim.campaign import run_episode

    if events is None:
        events = capture_to_events(doc, target=target, speed=speed)
    result = run_episode(
        seed,
        nodes=nodes,
        f=1 if nodes >= 4 else 0,
        hostile=1,  # the hostile identity is the injected frames' source
        events=events,
        settle_horizon=settle_horizon,
        capture_obs=False,
    )
    return {
        "seed": seed,
        "nodes": nodes,
        "target": target,
        "injected": len(events),
        "violations": result.violations,
        "trace_hash": result.trace_hash,
        "committed": result.committed,
        "delivered": result.delivered,
        "audit": [
            {
                "divergence": a.get("divergence"),
                "counters": a.get("counters"),
            }
            for a in (result.audit or [])
        ],
    }


def verdict_hash(verdict: dict) -> str:
    """sha256 over the canonical-JSON verdict — the replay's identity."""
    return hashlib.sha256(
        json.dumps(
            verdict, sort_keys=True, separators=(",", ":"), default=str
        ).encode()
    ).hexdigest()


def minimize_capture(
    doc: dict,
    seed: int,
    *,
    nodes: int = 4,
    target: int = 0,
    speed: float = 1.0,
) -> Optional[List[list]]:
    """Shrink a failing capture to the shortest inject schedule that
    still fails invariants. Returns None when the replay passes (nothing
    to minimize)."""
    from ..sim.campaign import minimize_events

    events = capture_to_events(doc, target=target, speed=speed)

    def failing(candidate: List[list]) -> bool:
        v = replay_capture(
            doc, seed, nodes=nodes, target=target, events=candidate
        )
        return bool(v["violations"])

    if not failing(events):
        return None
    return minimize_events(events, failing)


def load_capture(path: str) -> dict:
    """Read a capture JSON file; tolerates the obs route's ``node``
    wrapper and an incident bundle's capturez.json equally."""
    with open(path) as fp:
        doc = json.load(fp)
    if "records" not in doc:
        raise ValueError(f"{path}: not a /capturez dump (no 'records')")
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("capture", nargs="?", default=None,
                    metavar="CAPTURE.json",
                    help="a /capturez dump (e.g. from an incident bundle)")
    ap.add_argument("--fetch", default=None, metavar="HOST:PORT",
                    help="fetch the capture live from a node's /capturez "
                         "instead of a file")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--target", type=int, default=0,
                    help="sim node index the frames are injected into")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="replay time compression (2.0 = twice as fast)")
    ap.add_argument("--repeat", type=int, default=2,
                    help="replay N times and compare verdict hashes "
                         "(default 2: the determinism check)")
    ap.add_argument("--minimize", action="store_true",
                    help="if the replay fails invariants, shrink the "
                         "schedule to the shortest failing subset")
    ap.add_argument("--json", action="store_true",
                    help="dump the verdict JSON to stdout")
    args = ap.parse_args(argv)
    if args.fetch:
        host, port = parse_addr(args.fetch)
        doc = asyncio.run(fetch_json(host, port, "/capturez"))
    elif args.capture:
        doc = load_capture(args.capture)
    else:
        print("pass CAPTURE.json or --fetch HOST:PORT", file=sys.stderr)
        return 2
    if not doc.get("records"):
        print("capture is empty (capture_cap=0 on the node?)",
              file=sys.stderr)
        return 2

    verdicts = [
        replay_capture(
            doc, args.seed, nodes=args.nodes, target=args.target,
            speed=args.speed,
        )
        for _ in range(max(args.repeat, 1))
    ]
    hashes = [verdict_hash(v) for v in verdicts]
    v = verdicts[0]
    deterministic = len(set(hashes)) == 1
    print(
        f"replayed {v['injected']} frames into node {args.target} "
        f"(seed {args.seed}, {args.nodes} nodes) x{len(verdicts)}",
        file=sys.stderr,
    )
    print(
        f"verdict {hashes[0][:16]}  violations={len(v['violations'])}  "
        f"committed={v['committed']}  "
        f"deterministic={'yes' if deterministic else 'NO'}",
        file=sys.stderr,
    )
    rc = 0
    if not deterministic:
        print(f"NON-DETERMINISTIC REPLAY: hashes {hashes}", file=sys.stderr)
        rc = 1
    if v["violations"]:
        for viol in v["violations"]:
            print(f"  violation: {viol}", file=sys.stderr)
        if args.minimize:
            minimized = minimize_capture(
                doc, args.seed, nodes=args.nodes, target=args.target,
                speed=args.speed,
            )
            if minimized is not None:
                v["minimized"] = minimized
                print(
                    f"minimized to {len(minimized)} frame(s)",
                    file=sys.stderr,
                )
    if args.json:
        v["verdict_sha256"] = hashes[0]
        v["deterministic"] = deterministic
        print(json.dumps(v, sort_keys=True, indent=1, default=str))
    return rc


if __name__ == "__main__":
    sys.exit(main())
