"""BASELINE config 4 — quorum-certificate aggregate verify (n=64, f=21).

Measures, on the local device, the two candidate routes for verifying a
64-attestation Echo-quorum certificate and records which one
``ops.aggregate.verify_certificate`` should take:

* **per-sig kernel** — the production batched verifier (Pallas on TPU,
  XLA graph elsewhere) on a 64-lane bucket: 64 independent RFC 8032
  checks in one dispatch, per-signature verdicts.
* **RLC aggregate** — the one-equation random-linear-combination check
  (`ops.aggregate.aggregate_verify`), including its small-order subgroup
  defense: certificate-level verdict only; culprits need a fallback pass.

Output: one JSON line (optionally written to a file with --out) with
steady-state latencies and verdicts — the data behind the routing choice
in `verify_certificate` (its docstring asserts the per-sig kernel wins on
TPU; this artifact is the proof or the refutation).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

N = 64
ROUNDS = 20


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=N)
    ap.add_argument("--rounds", type=int, default=ROUNDS)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax

    from ..crypto.keys import SignKeyPair
    from ..ops import ed25519 as kernel
    from ..ops.aggregate import aggregate_verify

    n = args.n
    keys = [SignKeyPair.random() for _ in range(n)]
    msgs = [b"attestation %d" % i for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    pks = [k.public for k in keys]
    # fixed coefficients: identical device graph every round (bench only —
    # production uses fresh secrets per call)
    z = [(2 * i + 3) | 1 for i in range(n)]

    # warm-up / compile both routes
    assert kernel.verify_batch(pks, msgs, sigs, batch_size=64).all()
    assert aggregate_verify(pks, msgs, sigs, _z_override=z) is True

    t0 = time.perf_counter()
    for _ in range(args.rounds):
        out = kernel.verify_batch(pks, msgs, sigs, batch_size=64)
    per_sig_ms = 1e3 * (time.perf_counter() - t0) / args.rounds
    assert out.all()

    t0 = time.perf_counter()
    for _ in range(args.rounds):
        ok = aggregate_verify(pks, msgs, sigs, _z_override=z)
    aggregate_ms = 1e3 * (time.perf_counter() - t0) / args.rounds
    assert ok is True

    winner = "per_sig_kernel" if per_sig_ms <= aggregate_ms else "rlc_aggregate"
    artifact = {
        "config": "BASELINE-4: n=64 quorum-certificate aggregate verify",
        "n": n,
        "device": str(jax.devices()[0].platform),
        "per_sig_kernel_ms": round(per_sig_ms, 2),
        "rlc_aggregate_ms": round(aggregate_ms, 2),
        "per_sig_certs_per_sec": round(1e3 / per_sig_ms, 1),
        "rlc_certs_per_sec": round(1e3 / aggregate_ms, 1),
        "winner": winner,
        "routing": (
            "verify_certificate routes certificates through the per-sig "
            "kernel on TPU and falls back to RLC off-TPU"
            if winner == "per_sig_kernel"
            else "RLC aggregate should become the TPU fast path"
        ),
    }
    out_line = json.dumps(artifact)
    print(out_line)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(out_line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
