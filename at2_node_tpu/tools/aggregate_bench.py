"""Amortized-verification benchmarks: the RLC engine's crossover grid.

Default mode (ISSUE 10) measures the CPU Verifier's three per-signature
routes IN PROCESS (native libraries only, no XLA) over a
(batch size x failure rate) grid:

* **per_sig_python** — one `verify_one` (OpenSSL via `cryptography`)
  call per signature: the ~2.4k sigs/s/core crypto floor every
  pre-ISSUE-10 e2e number paid (ROADMAP "what's left").
* **per_sig_native** — `verify_bulk_native` pinned to ONE thread: the
  bulk C path's per-core rate (thread fan-out scales it, but the grid
  is a per-core story).
* **rlc** — `RlcEngine.verify_batch`: ONE random-linear-combination
  check per batch with certification cache, randomized torsion rounds,
  and bisection fallback — the cost INCLUDES the bisections the
  injected failure rate forces, so the grid shows exactly where
  amortization stops paying (the router's min_batch/budget evidence).

Self-banking: every run merges a labeled row set into
BENCH_AGGREGATE.json (per-row captured_at + tunnel_live_at_write so
same-day A/B claims stay honest), and --bank-e2e adds the headline
crypto-floor row to BENCH_E2E.json.

``--cert-route`` keeps the original BASELINE-4 measurement (n=64
quorum-certificate: per-sig kernel vs one-equation aggregate, XLA
subprocesses) unchanged.

Usage:
    python -m at2_node_tpu.tools.aggregate_bench
        [--batches 64,256,1024] [--rates 0,0.004,0.05,0.5] [--rounds 3]
        [--probe-timeout 45] [--skip-probe] [--bank-e2e] [--label L]
    python -m at2_node_tpu.tools.aggregate_bench --cert-route [--n 64] ...
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

N = 64
ROUNDS = 20

_CHILD = """
import json, time, sys
if sys.argv[4] == "cpu":
    # env vars are clobbered by this environment's jax-preloading .pth
    # hook, so the backend must be retargeted via jax.config
    import jax
    jax.config.update("jax_platforms", "cpu")
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ops import ed25519 as kernel
from at2_node_tpu.ops.aggregate import aggregate_verify
import jax

route, n, rounds = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
keys = [SignKeyPair.from_hex(("%02x" % (i + 1)) * 32) for i in range(n)]
msgs = [b"attestation %d" % i for i in range(n)]
sigs = [k.sign(m) for k, m in zip(keys, msgs)]
pks = [k.public for k in keys]
z = [(2 * i + 3) | 1 for i in range(n)]

if route == "per_sig":
    assert kernel.verify_batch(pks, msgs, sigs, batch_size=64).all()
    t0 = time.perf_counter()
    for _ in range(rounds):
        out = kernel.verify_batch(pks, msgs, sigs, batch_size=64)
    ms = 1e3 * (time.perf_counter() - t0) / rounds
    assert out.all()
else:
    assert aggregate_verify(pks, msgs, sigs, _z_override=z) is True
    t0 = time.perf_counter()
    for _ in range(rounds):
        ok = aggregate_verify(pks, msgs, sigs, _z_override=z)
    ms = 1e3 * (time.perf_counter() - t0) / rounds
    assert ok is True
print(json.dumps({"ms": round(ms, 2), "device": jax.devices()[0].platform}))
"""


def _measure(route: str, n: int, rounds: int, cpu: bool, timeout: float) -> dict:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD, route, str(n), str(rounds),
             "cpu" if cpu else "default"],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
        )
    except subprocess.TimeoutExpired:
        return {"error": f"did not complete within {timeout:.0f}s (compile-bound)"}
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-400:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def cert_route_main(args) -> int:
    per_sig = _measure("per_sig", args.n, args.rounds, cpu=False,
                       timeout=args.timeout)
    aggregate = _measure("aggregate", args.n, args.rounds,
                         cpu=args.aggregate_on_cpu, timeout=args.timeout)

    ps_ms = per_sig.get("ms")
    ag_ms = aggregate.get("ms")
    if ps_ms is not None and (ag_ms is None or ps_ms <= ag_ms):
        winner = "per_sig_kernel"
    elif ag_ms is not None:
        winner = "rlc_aggregate"
    else:
        winner = "inconclusive"
    from ._common import host_context

    artifact = {
        "config": "BASELINE-4: n=64 quorum-certificate aggregate verify",
        "host_context": host_context(),
        "n": args.n,
        "per_sig_kernel": per_sig,
        "rlc_aggregate": aggregate,
        "winner": winner,
        "notes": (
            "The RLC route includes the mandatory small-order subgroup "
            "sweep ([L]R over 2n lanes), which alone exceeds the per-sig "
            "kernel's single Straus pass over n lanes at n=64 — the "
            "aggregate can only win when its one-equation saving beats "
            "that extra sweep, which structurally requires much larger n."
        ),
        "routing": (
            "verify_certificate routes certificates through the per-sig "
            "kernel on TPU; the RLC aggregate (with subgroup defense) "
            "remains the off-TPU screening path with per-sig fallback"
            if winner == "per_sig_kernel"
            else "RLC aggregate should become the TPU fast path"
        ),
    }
    out_line = json.dumps(artifact)
    print(out_line)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(out_line + "\n")
    return 0


# --------------------------------------------------------------------------
# ISSUE 10 default mode: the CPU engine's (batch x failure-rate) grid
# --------------------------------------------------------------------------

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
BANK_PATH = os.path.join(REPO, "BENCH_AGGREGATE.json")


def _probe_tunnel(timeout: float):
    """bench.py --probe in a subprocess: True when a real chip answers
    behind the tunnel, False when the backend comes up chipless or dies,
    None when probing was skipped. The grid itself never touches the
    device — the label only scopes WHICH numbers were obtainable the day
    a row was banked (dead-tunnel days can't re-bank device rows)."""
    if timeout <= 0:
        return None
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--probe"],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False
    for line in proc.stdout.splitlines():
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("probe") == "ok":
            return obj.get("device") == "tpu"
    return False


def _grid_batch(pool, n, n_bad, tag):
    """One measurement batch: ``n`` lanes over the deterministic key
    pool, ``n_bad`` evenly-spread lanes with a flipped s byte (exactly
    the salting adversary's cheapest shape — sim/hostile.py)."""
    items = []
    for i in range(n):
        kp = pool[i]
        msg = b"%s lane %d" % (tag, i)
        items.append((kp.public, msg, kp.sign(msg)))
    bad = set()
    if n_bad > 0:
        step = n / n_bad
        bad = {min(n - 1, int(i * step)) for i in range(n_bad)}
        for j in bad:
            pk, msg, sig = items[j]
            items[j] = (pk, msg, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:])
    return items, bad


def _rate(fn, items, rounds):
    """sigs/s over ``rounds`` timed runs (one untimed warm run first)."""
    fn(items)
    t0 = time.perf_counter()
    for _ in range(rounds):
        out = fn(items)
    dt = (time.perf_counter() - t0) / rounds
    return round(len(items) / dt, 1), out


def grid_main(args) -> int:
    import hashlib

    from ..crypto.keys import SignKeyPair, verify_one
    from ..crypto.verifier import RlcEngine
    from ..native import ingest_available, verify_bulk_native
    from ..native.rlc import rlc_available
    from ._common import host_context

    if not (ingest_available() and rlc_available()):
        print("native ingest/rlc libraries unavailable; grid needs both",
              file=sys.stderr)
        return 1

    batches = [int(b) for b in args.batches.split(",")]
    rates = [float(r) for r in args.rates.split(",")]
    captured_at = time.strftime("%Y-%m-%d", time.gmtime())
    tunnel_live = _probe_tunnel(0 if args.skip_probe else args.probe_timeout)
    row_labels = {"captured_at": captured_at,
                  "tunnel_live_at_write": tunnel_live}

    pool = [
        SignKeyPair.from_hex(
            hashlib.sha256(b"aggregate-grid key %d" % i).hexdigest()
        )
        for i in range(max(batches))
    ]
    # ONE engine across the whole grid: the certification cache warm
    # after the first cell is the steady state a node actually runs in
    # (cert_misses stays == pool size for the entire run)
    engine = RlcEngine()

    grid = []
    for n in batches:
        for rate in rates:
            n_bad = round(rate * n)
            items, bad = _grid_batch(pool, n, n_bad, b"r%d" % int(rate * 1e4))
            expected = [i not in bad for i in range(n)]
            checks0 = engine.stats()["rlc_checks"]
            rlc_rate, out = _rate(engine.verify_batch, items, args.rounds)
            assert out == expected, "rlc verdicts diverged from ground truth"
            native_rate, nout = _rate(
                lambda it: verify_bulk_native(it, 1), items, args.rounds
            )
            assert list(nout) == expected
            cell = {
                "batch": n,
                "failure_rate": rate,
                "bad_lanes": n_bad,
                "rlc_sigs_per_sec": rlc_rate,
                "per_sig_native_sigs_per_sec": native_rate,
                "rlc_speedup": round(rlc_rate / native_rate, 2),
                "rlc_checks_per_batch": round(
                    (engine.stats()["rlc_checks"] - checks0)
                    / (args.rounds + 1), 1
                ),
                **row_labels,
            }
            grid.append(cell)
            if not args.quiet:
                print(json.dumps(cell), flush=True)

    # the crypto floor: per-call OpenSSL, ONE timed round (it is ~10x
    # slower than everything else in the grid and perfectly stable)
    floor_n = max(batches)
    items, _ = _grid_batch(pool, floor_n, 0, b"floor")
    t0 = time.perf_counter()
    assert all(verify_one(pk, m, s) for pk, m, s in items)
    floor_rate = round(floor_n / (time.perf_counter() - t0), 1)

    head = next(
        c for c in grid
        if c["batch"] == floor_n and c["failure_rate"] == 0.0
    )
    # largest failure rate at the biggest batch where amortization still
    # beats the native per-sig path: the router budget's evidence
    tolerated = [
        c["failure_rate"] for c in grid
        if c["batch"] == floor_n and c["rlc_speedup"] >= 1.0
    ]
    summary = {
        "bucket": floor_n,
        "per_sig_python_sigs_per_sec": floor_rate,
        "per_sig_native_1thread_sigs_per_sec":
            head["per_sig_native_sigs_per_sec"],
        "rlc_sigs_per_sec": head["rlc_sigs_per_sec"],
        "rlc_vs_crypto_floor": round(head["rlc_sigs_per_sec"] / floor_rate, 2),
        "rlc_vs_native_per_sig": head["rlc_speedup"],
        "max_tolerated_failure_rate": max(tolerated) if tolerated else 0.0,
        "target": ">=5x the per-sig crypto floor at bucket %d, one core "
                  "(ISSUE 10)" % floor_n,
        "target_met": bool(head["rlc_sigs_per_sec"] >= 5 * floor_rate),
        **row_labels,
    }
    print(json.dumps(summary), flush=True)

    label = args.label or "grid_%s" % captured_at
    doc = {}
    if os.path.exists(BANK_PATH):
        with open(BANK_PATH) as fp:
            doc = json.load(fp)
    doc.setdefault(
        "config",
        "CPU amortized-verification grid: RLC engine vs per-sig routes "
        "(batch x failure rate), all rates sigs/s on one core",
    )
    doc["host_context"] = host_context()
    doc.setdefault("runs", {})[label] = {
        **row_labels,
        "rounds": args.rounds,
        "grid": grid,
        "summary": summary,
    }
    doc["latest"] = label
    tmp = BANK_PATH + ".tmp"
    with open(tmp, "w") as fp:
        json.dump(doc, fp, indent=1)
        fp.write("\n")
    os.replace(tmp, BANK_PATH)
    print("banked %s run %s" % (BANK_PATH, label), file=sys.stderr)

    if args.bank_e2e:
        from .e2e_bench import _bank_e2e_row

        _bank_e2e_row("crypto_floor_rlc", {
            **summary,
            "note": (
                "same-day A/B: all three routes measured in one process "
                "run on this host (see BENCH_AGGREGATE.json run %s for "
                "the full grid). This is the Verifier-seam crypto floor: "
                "CpuVerifier mode=auto routes qualifying flushes through "
                "the RLC engine at exactly these rates" % label
            ),
        })
        print("banked BENCH_E2E.json row crypto_floor_rlc", file=sys.stderr)
    return 0 if summary["target_met"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cert-route", action="store_true",
                    help="original BASELINE-4 certificate-route measurement")
    # cert-route knobs
    ap.add_argument("--n", type=int, default=N)
    ap.add_argument("--rounds", type=int, default=None,
                    help="timed rounds per cell (grid default 3, "
                    "cert-route default %d)" % ROUNDS)
    ap.add_argument("--aggregate-on-cpu", action="store_true", default=True,
                    help="measure the RLC route on the CPU backend (default; "
                    "its XLA-TPU compile exceeds any reasonable budget)")
    ap.add_argument("--aggregate-on-device", dest="aggregate_on_cpu",
                    action="store_false")
    ap.add_argument("--timeout", type=float, default=1200.0)
    ap.add_argument("--out", default=None)
    # grid knobs
    ap.add_argument("--batches", default="64,256,1024",
                    help="comma-separated batch sizes (default 64,256,1024)")
    ap.add_argument("--rates", default="0,0.004,0.05,0.5",
                    help="comma-separated failure rates (default "
                    "0,0.004,0.05,0.5 — clean / one-bad-ish / salted / "
                    "hostile-majority)")
    ap.add_argument("--probe-timeout", type=float, default=45.0,
                    help="seconds to wait on the device-tunnel probe used "
                    "only to LABEL banked rows (0 = skip)")
    ap.add_argument("--skip-probe", action="store_true",
                    help="label rows tunnel_live_at_write=null")
    ap.add_argument("--bank-e2e", action="store_true",
                    help="also bank the headline crypto-floor row into "
                    "BENCH_E2E.json")
    ap.add_argument("--label", default=None,
                    help="run label in BENCH_AGGREGATE.json "
                    "(default grid_<utc-date>)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    if args.cert_route:
        args.rounds = ROUNDS if args.rounds is None else args.rounds
        return cert_route_main(args)
    args.rounds = 3 if args.rounds is None else args.rounds
    return grid_main(args)


if __name__ == "__main__":
    sys.exit(main())
