"""BASELINE config 4 — quorum-certificate aggregate verify (n=64, f=21).

Measures the two candidate routes for verifying a 64-attestation
Echo-quorum certificate and records which one
``ops.aggregate.verify_certificate`` should take:

* **per-sig kernel** — the production batched verifier (Pallas on TPU,
  XLA graph elsewhere) on a 64-lane bucket: 64 independent RFC 8032
  checks in one dispatch, per-signature verdicts.
* **RLC aggregate** — the one-equation random-linear-combination check
  (`ops.aggregate.aggregate_verify`) INCLUDING its small-order subgroup
  defense (an extra fixed-window Straus pass over both point sets):
  certificate-level verdict only; culprits need a fallback pass.

Route measurements run in SUBPROCESSES so each gets a fresh backend and a
wall-clock bound (the round-2 attempt to compile the RLC graph on the
tunnelled TPU never completed, though the tunnel itself failed during
that window, so device-compile feasibility is unresolved). By default the
aggregate route is measured on the CPU backend while the per-sig route
runs on the default (TPU) backend; --aggregate-on-device overrides.

Output: one JSON line (optionally --out FILE) with steady-state
latencies, verdicts, and the routing decision that
`verify_certificate`'s docstring asserts.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

N = 64
ROUNDS = 20

_CHILD = """
import json, time, sys
if sys.argv[4] == "cpu":
    # env vars are clobbered by this environment's jax-preloading .pth
    # hook, so the backend must be retargeted via jax.config
    import jax
    jax.config.update("jax_platforms", "cpu")
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ops import ed25519 as kernel
from at2_node_tpu.ops.aggregate import aggregate_verify
import jax

route, n, rounds = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
keys = [SignKeyPair.from_hex(("%02x" % (i + 1)) * 32) for i in range(n)]
msgs = [b"attestation %d" % i for i in range(n)]
sigs = [k.sign(m) for k, m in zip(keys, msgs)]
pks = [k.public for k in keys]
z = [(2 * i + 3) | 1 for i in range(n)]

if route == "per_sig":
    assert kernel.verify_batch(pks, msgs, sigs, batch_size=64).all()
    t0 = time.perf_counter()
    for _ in range(rounds):
        out = kernel.verify_batch(pks, msgs, sigs, batch_size=64)
    ms = 1e3 * (time.perf_counter() - t0) / rounds
    assert out.all()
else:
    assert aggregate_verify(pks, msgs, sigs, _z_override=z) is True
    t0 = time.perf_counter()
    for _ in range(rounds):
        ok = aggregate_verify(pks, msgs, sigs, _z_override=z)
    ms = 1e3 * (time.perf_counter() - t0) / rounds
    assert ok is True
print(json.dumps({"ms": round(ms, 2), "device": jax.devices()[0].platform}))
"""


def _measure(route: str, n: int, rounds: int, cpu: bool, timeout: float) -> dict:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD, route, str(n), str(rounds),
             "cpu" if cpu else "default"],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
        )
    except subprocess.TimeoutExpired:
        return {"error": f"did not complete within {timeout:.0f}s (compile-bound)"}
    if proc.returncode != 0:
        return {"error": proc.stderr.strip()[-400:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=N)
    ap.add_argument("--rounds", type=int, default=ROUNDS)
    ap.add_argument("--aggregate-on-cpu", action="store_true", default=True,
                    help="measure the RLC route on the CPU backend (default; "
                    "its XLA-TPU compile exceeds any reasonable budget)")
    ap.add_argument("--aggregate-on-device", dest="aggregate_on_cpu",
                    action="store_false")
    ap.add_argument("--timeout", type=float, default=1200.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    per_sig = _measure("per_sig", args.n, args.rounds, cpu=False,
                       timeout=args.timeout)
    aggregate = _measure("aggregate", args.n, args.rounds,
                         cpu=args.aggregate_on_cpu, timeout=args.timeout)

    ps_ms = per_sig.get("ms")
    ag_ms = aggregate.get("ms")
    if ps_ms is not None and (ag_ms is None or ps_ms <= ag_ms):
        winner = "per_sig_kernel"
    elif ag_ms is not None:
        winner = "rlc_aggregate"
    else:
        winner = "inconclusive"
    from ._common import host_context

    artifact = {
        "config": "BASELINE-4: n=64 quorum-certificate aggregate verify",
        "host_context": host_context(),
        "n": args.n,
        "per_sig_kernel": per_sig,
        "rlc_aggregate": aggregate,
        "winner": winner,
        "notes": (
            "The RLC route includes the mandatory small-order subgroup "
            "sweep ([L]R over 2n lanes), which alone exceeds the per-sig "
            "kernel's single Straus pass over n lanes at n=64 — the "
            "aggregate can only win when its one-equation saving beats "
            "that extra sweep, which structurally requires much larger n."
        ),
        "routing": (
            "verify_certificate routes certificates through the per-sig "
            "kernel on TPU; the RLC aggregate (with subgroup defense) "
            "remains the off-TPU screening path with per-sig fallback"
            if winner == "per_sig_kernel"
            else "RLC aggregate should become the TPU fast path"
        ),
    }
    out_line = json.dumps(artifact)
    print(out_line)
    if args.out:
        with open(args.out, "w") as fp:
            fp.write(out_line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
