"""Durable-store benchmark: restart cost and the delta-bound flush.

The ISSUE 9 acceptance numbers, measured end to end and banked as
BENCH_DURABILITY.json:

* **restart at scale** — a store seeded with >= 100k accounts is opened
  the way a rebooting node opens it (load segments -> replay WAL), then
  a REAL Service is started on it and walked to a healthy verdict. No
  full-state transfer happens anywhere: the node's ledger comes off its
  own disk, catchup only reconciles the live frontier.
* **delta-bound flush** — after the initial full flush, an incremental
  flush's cost (segments written, bytes, wall time) must track the
  DELTA committed since the last flush, not the account count. Measured
  at two delta sizes so the scaling is visible in the artifact, with
  the full-flush cost alongside for the ratio.

Accounts are seeded through the legacy-migration path (a synthetic
monolithic checkpoint document) — the same code a real upgrade runs —
and the deltas are real signed payloads through ``note_commit``.

Usage:
    python -m at2_node_tpu.tools.bench_durability [--accounts 100000]
        [--shards 64] [--deltas 256,1024] [--out BENCH_DURABILITY.json]
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

from ..broadcast.messages import Payload
from ..crypto.keys import ExchangeKeyPair, SignKeyPair
from ..node.config import Config, StoreConfig
from ..node.service import Service
from ..store import ShardedStore
from ..types import ThinTransaction
from ._common import port_counter

_ports = port_counter(27600)


def _synthetic_accounts(n: int) -> dict:
    """n deterministic account rows in legacy-checkpoint form. Keys are
    sha256-derived so they spread across shards like real ed25519 keys."""
    return {
        hashlib.sha256(f"bench-acct-{i}".encode()).hexdigest(): [1, 100_000]
        for i in range(n)
    }


def _delta_commits(store: ShardedStore, senders: list, count: int,
                   seq0: int) -> None:
    for k in range(count):
        kp = senders[k % len(senders)]
        seq = seq0 + k // len(senders)
        p = Payload.create(kp, seq, ThinTransaction(b"r" * 32, 1))
        store.note_commit(p, seq, 100_000 - seq, 100_000 + seq)


async def _service_restart(store_dir: str, shards: int) -> dict:
    """Start a real node on the pre-populated store and time the walk
    to a healthy verdict. Peerless on purpose: with nobody to transfer
    state FROM, reaching healthy proves the ledger came off disk."""
    cfg = Config(
        node_address=f"127.0.0.1:{next(_ports)}",
        rpc_address=f"127.0.0.1:{next(_ports)}",
        sign_key=SignKeyPair.random(),
        network_key=ExchangeKeyPair.random(),
        store=StoreConfig(dir=store_dir, shards=shards),
    )
    t0 = time.monotonic()
    service = await Service.start(cfg)
    try:
        verdict = service.health_verdict()
        deadline = time.monotonic() + 30.0
        while (
            verdict["status"] != "ok" and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.05)
            verdict = service.health_verdict()
        elapsed = time.monotonic() - t0
        return {
            "healthy_after_s": round(elapsed, 3),
            "status": verdict["status"],
            "recovery": service.recovery.to_dict(
                service.clock.monotonic()
            ),
            "accounts": service.store.account_count(),
            "catchup_transfers": service._catchup_commits,
        }
    finally:
        await service.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--accounts", type=int, default=100_000)
    ap.add_argument("--shards", type=int, default=64)
    ap.add_argument("--deltas", default="256,1024",
                    help="comma-separated incremental delta sizes")
    ap.add_argument("--out", default="BENCH_DURABILITY.json",
                    help="output path ('-' for stdout)")
    args = ap.parse_args(argv)
    deltas = [int(d) for d in args.deltas.split(",") if d]

    root = tempfile.mkdtemp(prefix="at2-bench-store-")
    store_dir = os.path.join(root, "node")
    result = {
        "accounts": args.accounts,
        "shards": args.shards,
        "host_cpus": os.cpu_count(),
    }
    try:
        # -- seed via the migration path, then the initial FULL flush
        legacy = {
            "version": 1,
            "accounts": _synthetic_accounts(args.accounts),
            "recent": [],
        }
        t0 = time.monotonic()
        store = ShardedStore.open(
            store_dir, n_shards=args.shards, legacy_checkpoint=legacy
        )
        migrate_s = time.monotonic() - t0
        # a LOCALIZED delta: two senders + one recipient touch at most
        # three shards, so the incremental flush's dirty-shard cost is
        # visibly decoupled from the 100k-account total
        senders = [
            SignKeyPair(hashlib.sha256(f"bench-sender-{i}".encode()).digest())
            for i in range(2)
        ]
        # a second full flush: every shard dirty (worst case), for the
        # incremental ratio's denominator
        _delta_commits(store, senders, args.accounts // 1000, seq0=1)
        for shard in range(args.shards):
            store._dirty.add(shard)
        t0 = time.monotonic()
        full = store.flush(force=True)
        full_s = time.monotonic() - t0
        result["migrate_s"] = round(migrate_s, 3)
        result["full_flush"] = {
            "segments_written": full["segments_written"],
            "bytes": full["segment_bytes"],
            "wall_s": round(full_s, 3),
        }

        # -- incremental flushes at increasing delta sizes
        result["incremental_flush"] = []
        seq0 = 1000
        for delta in deltas:
            wal_before = os.path.getsize(store._wal.path)
            t_commit = time.monotonic()
            _delta_commits(store, senders, delta, seq0=seq0)
            commit_s = time.monotonic() - t_commit
            wal_bytes = os.path.getsize(store._wal.path) - wal_before
            seq0 += delta
            t0 = time.monotonic()
            stats = store.flush()
            wall = time.monotonic() - t0
            result["incremental_flush"].append({
                "delta_commits": delta,
                "segments_written": stats["segments_written"],
                "bytes": stats["segment_bytes"],
                "wall_s": round(wall, 3),
                "bytes_vs_full": round(
                    stats["segment_bytes"] / max(1, full["segment_bytes"]), 4
                ),
                # the strictly delta-sized durability cost: WAL append
                # bytes per commit, independent of account count
                "wal_bytes": wal_bytes,
                "wal_bytes_per_commit": round(wal_bytes / delta, 1),
                "commit_wall_s": round(commit_s, 3),
            })
        store.close()

        # -- the restart: open timing at store level, then a real node
        t0 = time.monotonic()
        reopened = ShardedStore.open(store_dir, n_shards=args.shards)
        result["store_open"] = {
            "wall_s": round(time.monotonic() - t0, 3),
            "segments_loaded": reopened.segments_loaded,
            "wal_replayed": reopened.wal_replayed,
            "accounts": reopened.account_count(),
        }
        reopened.close()
        result["service_restart"] = asyncio.run(
            _service_restart(store_dir, args.shards)
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # the acceptance claims, asserted so the bench doubles as a gate
    inc = result["incremental_flush"]
    ok = (
        result["service_restart"]["status"] == "ok"
        and result["service_restart"]["catchup_transfers"] == 0
        and result["store_open"]["accounts"] >= args.accounts
        and all(row["bytes_vs_full"] < 0.10 for row in inc)
    )
    result["delta_bounded"] = all(row["bytes_vs_full"] < 0.10 for row in inc)
    result["ok"] = ok

    blob = json.dumps(result, indent=1)
    if args.out == "-":
        print(blob)
    else:
        with open(args.out, "w") as fp:
            fp.write(blob + "\n")
        print(f"banked {args.out}", file=sys.stderr)
        print(blob)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
