"""Shared helpers for the benchmark/demo tools."""

from __future__ import annotations

import itertools
import os
from typing import Iterator, List

from ..crypto.keys import ExchangeKeyPair, SignKeyPair
from ..net.peers import Peer
from ..node.config import Config


def host_context() -> dict:
    """The ONE statement of this host's measurement ceiling, embedded by
    every tool artifact (e2e_bench / scale_demo / aggregate_bench) so a
    reader can't mistake harness floors for design ceilings."""
    return {
        "cpus": os.cpu_count(),
        "note": (
            "all servers, clients, load generators, and the XLA runtime "
            "share this host's core(s); absolute tx/s figures on a "
            "1-core VM are harness floors, not design ceilings — "
            "cross-config DELTAS and device-side rates are the signal. "
            "Run-to-run noise on this class of host is ~±10%."
        ),
    }


def make_net_configs(
    n: int, ports: Iterator[int], **config_overrides
) -> List[Config]:
    """N full-mesh node Configs with fresh keys: THE one builder for the
    tools' in-process nets (plane_bench / scale_demo / e2e_bench), so
    Config/Peer construction changes land in one place."""
    cfgs = [
        Config(
            node_address=f"127.0.0.1:{next(ports)}",
            rpc_address=f"127.0.0.1:{next(ports)}",
            sign_key=SignKeyPair.random(),
            network_key=ExchangeKeyPair.random(),
            **config_overrides,
        )
        for _ in range(n)
    ]
    for i, cfg in enumerate(cfgs):
        cfg.nodes = [
            Peer(o.node_address, o.network_key.public, o.sign_key.public)
            for j, o in enumerate(cfgs)
            if j != i
        ]
    return cfgs


def port_counter(start: int) -> Iterator[int]:
    return itertools.count(start)
